"""Throughput benchmark — prints ONE JSON line.

Measures the full MoCo v2 ResNet-50 pretraining step (two encoder
forwards, one backward, EMA, Shuffle-BN handling, InfoNCE vs the 65536-key
queue, optimizer) on the available accelerator, in imgs/sec/chip.

Baseline: the reference trains 200 epochs of ImageNet (1.281M imgs) in
~53h on 8×V100 ⇒ ≈168 imgs/s/GPU (SURVEY.md §6, BASELINE.md).
`vs_baseline` is the ratio of our per-chip rate to that 168 imgs/s/GPU;
the north star is ≥2.0.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp

REFERENCE_IMGS_PER_SEC_PER_GPU = 168.0


def main() -> None:
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    from moco_tpu.core import build_encoder, create_state, make_train_step, place_state
    from moco_tpu.parallel import create_mesh, shard_batch
    from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig
    from moco_tpu.utils.schedules import build_optimizer

    if on_tpu:
        arch, img, batch, k, steps, dtype = "resnet50", 224, 256, 65536, 20, "bfloat16"
    else:  # CPU fallback so the bench always emits a line
        arch, img, batch, k, steps, dtype = "resnet18", 32, 64, 4096, 3, "float32"

    n_dev = len(jax.devices())
    mesh = create_mesh(num_data=n_dev, num_model=1)
    config = TrainConfig(
        moco=MocoConfig(
            arch=arch,
            dim=128,
            num_negatives=k,
            temperature=0.2,
            mlp=True,
            shuffle="gather_perm" if n_dev > 1 else "none",
            cifar_stem=not on_tpu,
            compute_dtype=dtype,
        ),
        optim=OptimConfig(lr=0.03, epochs=200, cos=True),
        data=DataConfig(dataset="synthetic", image_size=img, global_batch=batch),
    )
    encoder = build_encoder(config.moco, num_data=n_dev)
    tx = build_optimizer(config.optim, steps_per_epoch=5004)
    rng = jax.random.PRNGKey(0)
    state = create_state(rng, config, encoder, tx, jnp.zeros((1, img, img, 3), jnp.float32))
    state = place_state(state, mesh)
    step = make_train_step(config, encoder, tx, mesh, donate=False)

    ims = jax.random.normal(jax.random.PRNGKey(1), (2, batch, img, img, 3), jnp.float32)
    batch_dict = shard_batch(mesh, {"im_q": ims[0], "im_k": ims[1]})
    root_rng = jax.device_put(
        jax.random.PRNGKey(2), jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    )

    # Warmup (compile) + 2 steady-state steps. NB: sync via a host
    # transfer, not block_until_ready — on the experimental axon TPU
    # platform block_until_ready returns before device completion
    # (measured: 20 R50 steps "in" 0.07s), silently inflating the number.
    for _ in range(3):
        state, metrics = step(state, batch_dict, root_rng)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_dict, root_rng)
    float(metrics["loss"])  # chained state deps force all `steps` steps
    dt = time.perf_counter() - t0

    imgs_per_sec = batch * steps / dt
    per_chip = imgs_per_sec / n_dev
    print(
        f"platform={platform} chips={n_dev} arch={arch} batch={batch} "
        f"steps={steps} wall={dt:.2f}s total={imgs_per_sec:.1f} imgs/s",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "moco_v2_r50_pretrain_imgs_per_sec_per_chip"
                if on_tpu
                else "moco_v1_r18_cpu_smoke_imgs_per_sec",
                "value": round(per_chip, 2),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(per_chip / REFERENCE_IMGS_PER_SEC_PER_GPU, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
