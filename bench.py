"""Throughput benchmark — prints ONE JSON line.

Measures the full MoCo v2 ResNet-50 pretraining step (two encoder
forwards, one backward, EMA, Shuffle-BN handling, InfoNCE vs the 65536-key
queue, optimizer) on the available accelerator, in imgs/sec/chip. Two
rates are reported:

- ``value`` (the headline): device-only steady-state rate, pre-staged
  batches — isolates the compiled step, comparable across rounds.
- ``with_data_imgs_per_sec_per_chip``: sustained rate with the real input
  pipeline in the loop (JPEG ImageFolder decode via the native C++ pool +
  on-device two-crop augmentation), per VERDICT round-1 item 4. NB: this
  host exposes a single CPU core (the reference assumed 32 DataLoader
  workers/GPU), so this number is host-decode-bound here; the split
  between the two rates is exactly the signal it exists to expose.

Also reported: ``mfu`` (model FLOP utilization; FLOPs from XLA cost
analysis when available, else an analytic R50 estimate) against the
chip's peak bf16 TFLOPS.

Baseline: the reference trains 200 epochs of ImageNet (1.281M imgs) in
~53h on 8×V100 ⇒ ≈168 imgs/s/GPU (SURVEY.md §6, BASELINE.md).
`vs_baseline` is the ratio of our per-chip rate to that 168 imgs/s/GPU
(null on the CPU-fallback smoke, where the ratio would be meaningless);
the north star is ≥2.0.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

REFERENCE_IMGS_PER_SEC_PER_GPU = 168.0

# Peak bf16 matmul TFLOPS per chip, for the MFU denominator.
PEAK_TFLOPS = {
    "tpu v5 lite": 197.0,  # v5e
    "tpu v5": 459.0,  # v5p
    "tpu v4": 275.0,
    "tpu v6 lite": 918.0,  # v6e
}


def _peak_tflops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_TFLOPS.items():
        if key in kind:
            return val
    return None


def _step_flops(jitted_step, state, batch_dict, rng) -> float | None:
    """Per-step FLOPs from XLA cost analysis; None if unsupported."""
    try:
        cost = jitted_step.lower(state, batch_dict, rng).compile().cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def _analytic_step_flops(batch: int, img: int) -> float:
    """Fallback estimate for the GLOBAL batch: R50 fwd ≈ 4.1 GFLOPs @224²
    (scales ~quadratically with side); step = q fwd+bwd (3×) + k fwd (1×)
    ⇒ ~16.4 GFLOPs/img. Divided by n_dev at use to get per-device FLOPs
    (XLA's cost_analysis already reports the per-device SPMD module)."""
    r50_fwd = 4.1e9 * (img / 224.0) ** 2
    return 4.0 * r50_fwd * batch


def _ensure_jpeg_folder(root: str, n: int, size: int, classes: int = 8) -> str:
    """Synthetic JPEG ImageFolder for the with-data bench (no datasets on
    disk in this environment). Deterministic, built once, reused."""
    from PIL import Image

    stamp = os.path.join(root, f".complete_{n}_{size}")
    if os.path.exists(stamp):
        return root
    rng = np.random.default_rng(0)
    for c in range(classes):
        os.makedirs(os.path.join(root, f"class_{c}"), exist_ok=True)
    for i in range(n):
        c = i % classes
        # low-frequency field + noise ≈ natural-image JPEG work profile
        coarse = rng.uniform(0, 255, (8, 8, 3))
        img = np.asarray(
            Image.fromarray(coarse.astype(np.uint8)).resize((size, size), Image.BILINEAR),
            np.float32,
        )
        img += rng.normal(0, 12, img.shape)
        Image.fromarray(np.clip(img, 0, 255).astype(np.uint8)).save(
            os.path.join(root, f"class_{c}", f"img_{i:05d}.jpg"), quality=90
        )
    open(stamp, "w").close()
    return root


def main() -> None:
    from moco_tpu.utils.platform import (
        backend_probe,
        enable_persistent_compilation_cache,
        pin_platform_from_env,
    )

    # Per-leg skip ledger (BENCH r02–r05 lesson: the bench silently
    # degraded to the CPU smoke for four rounds and nobody could say
    # why from the JSON alone). Every leg records ran/skip_reason; the
    # ledger ships inside the one-line JSON as `legs`.
    legs: dict[str, dict] = {
        name: {"ran": False, "skip_reason": None}
        for name in (
            "accelerator",
            "numerics_crosscheck",
            "obs_overhead",
            "with_data",
            "zero_ab",
            "serving",
            "ann_ab",
        )
    }

    def _skip(leg: str, reason: str) -> None:
        legs[leg]["skip_reason"] = reason
        print(f"leg {leg} skipped: {reason}", file=sys.stderr)

    pin_platform_from_env()  # honor an explicit JAX_PLATFORMS request
    # A bench that crashes or hangs on a down/wedged tunnel emits NO
    # metric line at all — degrading to the CPU smoke is strictly better.
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        _skip("accelerator", "JAX_PLATFORMS=cpu pinned by the environment")
    else:
        usable, probe_reason = backend_probe()
        if not usable:
            print("accelerator backend unavailable/hung; CPU fallback", file=sys.stderr)
            _skip("accelerator", probe_reason or "backend probe failed")
            jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    if on_tpu:
        legs["accelerator"]["ran"] = True
    elif legs["accelerator"]["skip_reason"] is None:
        _skip(
            "accelerator",
            f"default backend is {platform!r}, not TPU (no probe failure)",
        )
    if on_tpu:
        # AFTER the fallback decision on purpose: the degraded CPU smoke
        # must not write XLA:CPU AOT entries (see the guard's docstring)
        enable_persistent_compilation_cache()  # battery legs share compiles

    from moco_tpu.core import (
        build_encoder,
        build_predictor,
        create_state,
        make_train_step,
        place_state,
    )
    from moco_tpu.parallel import create_mesh, shard_batch
    from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig
    from moco_tpu.utils.schedules import build_optimizer

    if on_tpu:
        arch, img, batch, k, steps, dtype = "resnet50", 224, 256, 65536, 20, "bfloat16"
    else:  # CPU fallback so the bench always emits a line
        arch, img, batch, k, steps, dtype = "resnet18", 32, 64, 4096, 3, "float32"
    # BENCH_ARCH=vit_b16 benches the v3 ViT step instead (queue-free
    # symmetric loss, AdamW; BENCH_FLASH=1 adds the Pallas flash kernel)
    arch = os.environ.get("BENCH_ARCH", arch)
    is_vit = arch.startswith("vit")
    batch = int(os.environ.get("BENCH_BATCH", batch))
    steps = int(os.environ.get("BENCH_STEPS", steps))

    n_dev = len(jax.devices())
    mesh = create_mesh(num_data=n_dev, num_model=1)
    if is_vit:
        moco = MocoConfig(
            arch=arch,
            dim=256,
            num_negatives=0,
            momentum=0.99,
            momentum_cos=True,
            temperature=0.2,
            v3=True,
            shuffle="none",
            compute_dtype=dtype,
            vit_flash_attention=os.environ.get("BENCH_FLASH", "0") == "1",
        )
        optim = OptimConfig(optimizer="adamw", lr=2.4e-3, weight_decay=0.1,
                            epochs=300, cos=True, warmup_epochs=40)
    else:
        moco = MocoConfig(
            arch=arch,
            dim=128,
            num_negatives=k,
            temperature=0.2,
            mlp=True,
            # virtual groups need the in-batch key permutation, so the
            # single-device bench switches to gather_perm when the
            # BENCH_BN_VIRTUAL_GROUPS A/B leg is active; the EMAN leg
            # (BENCH_KEY_BN_EVAL=1) instead REQUIRES shuffle='none'
            # (running-stats keys have nothing to decorrelate)
            shuffle="none"
            if os.environ.get("BENCH_KEY_BN_EVAL") == "1"
            else "gather_perm"
            if n_dev > 1 or int(os.environ.get("BENCH_BN_VIRTUAL_GROUPS", 0)) > 1
            else "none",
            # BENCH_KEY_BN_EVAL=1 A/Bs the EMAN-style key forward
            # (eval-mode BN from EMA'd running stats — drops the key-side
            # statistics pass, one third of the BN-bytes cost center)
            key_bn_running_stats=os.environ.get("BENCH_KEY_BN_EVAL") == "1",
            cifar_stem=not on_tpu,
            compute_dtype=dtype,
            # BENCH_BN_STATS_ROWS=32 A/Bs the subset-statistics BN (the
            # PROFILE.md byte-reduction lever); BENCH_BN_VIRTUAL_GROUPS=8
            # the virtual Shuffle-BN mode — both without code changes
            bn_stats_rows=int(os.environ.get("BENCH_BN_STATS_ROWS", 0)),
            # BENCH_BN_STATS_BARRIER=1 adds the fusion barrier around the
            # subset slice (the bn_compile_repro candidate workaround)
            bn_stats_barrier=os.environ.get("BENCH_BN_STATS_BARRIER") == "1",
            bn_virtual_groups=int(os.environ.get("BENCH_BN_VIRTUAL_GROUPS", 0)),
            # BENCH_FUSED=0/1 pins the streaming Pallas InfoNCE off/on
            # (unset = the config's auto default) for the fused-vs-dense A/B
            fused_infonce=(
                None
                if os.environ.get("BENCH_FUSED") is None
                else os.environ["BENCH_FUSED"] == "1"
            ),
        )
        optim = OptimConfig(lr=0.03, epochs=200, cos=True)
    config = TrainConfig(
        moco=moco,
        optim=optim,
        data=DataConfig(dataset="synthetic", image_size=img, global_batch=batch),
    )
    encoder = build_encoder(config.moco, num_data=n_dev)
    predictor = build_predictor(config.moco, num_data=n_dev)
    tx = build_optimizer(config.optim, steps_per_epoch=5004)
    rng = jax.random.PRNGKey(0)
    state = create_state(
        rng, config, encoder, tx, jnp.zeros((1, img, img, 3), jnp.float32),
        predictor=predictor,
    )
    state = place_state(state, mesh)
    # donate=False: donation costs ~80ms/call through the axon remote-TPU
    # tunnel (measured, see make_train_step) and state is small vs HBM.
    step = make_train_step(
        config, encoder, tx, mesh, donate=False, predictor=predictor,
        total_steps=5004 * config.optim.epochs,
    )

    ims = jax.random.normal(jax.random.PRNGKey(1), (2, batch, img, img, 3), jnp.float32)
    batch_dict = shard_batch(mesh, {"im_q": ims[0], "im_k": ims[1]})
    root_rng = jax.device_put(
        jax.random.PRNGKey(2), jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    )

    # ---- fused-vs-dense numerics cross-check (BENCH_NUMERICS=1) -------
    # One compiled step per path from the IDENTICAL initial state and
    # batch. The streaming Pallas InfoNCE is default-ON for TPU
    # (core/moco.py fused auto-resolution); a Mosaic lowering bug there
    # would corrupt training silently while benching fast — this prints
    # on-chip correctness evidence without needing the pytest session.
    # Opt-in (two extra full-step compiles, ~2×3.5 min on the chip).
    crosscheck_ok = True
    if os.environ.get("BENCH_NUMERICS") != "1":
        _skip("numerics_crosscheck", "opt-in leg (set BENCH_NUMERICS=1; two extra full-step compiles)")
    elif is_vit or moco.num_negatives == 0:
        _skip("numerics_crosscheck", "fused-vs-dense InfoNCE A/B needs the queue-based (non-ViT) step")
    if (
        os.environ.get("BENCH_NUMERICS") == "1"
        and not is_vit
        and moco.num_negatives > 0
    ):
        legs["numerics_crosscheck"]["ran"] = True
        import dataclasses

        outs = {}
        for name, fused in (("fused", True), ("dense", False)):
            cfg_n = dataclasses.replace(
                config, moco=dataclasses.replace(moco, fused_infonce=fused)
            )
            step_n = make_train_step(
                cfg_n, encoder, tx, mesh, donate=False,
                total_steps=5004 * config.optim.epochs,
            )
            _, m = step_n(state, batch_dict, root_rng)
            outs[name] = (float(m["loss"]), float(m["acc1"]))
        d_loss = abs(outs["fused"][0] - outs["dense"][0])
        d_acc = abs(outs["fused"][1] - outs["dense"][1])
        # Both paths share the (bf16) encoder forwards bit-for-bit; they
        # differ only in the logits/log-sum-exp arithmetic (f32 in both),
        # so tolerance is tight relative to the ~ln(1+K)≈11 loss scale.
        crosscheck_ok = d_loss <= 5e-2 and d_acc <= 1.0
        print(
            "numerics crosscheck: "
            f"fused loss={outs['fused'][0]:.6f} acc1={outs['fused'][1]:.3f} "
            f"dense loss={outs['dense'][0]:.6f} acc1={outs['dense'][1]:.3f} "
            f"dloss={d_loss:.2e} dacc1={d_acc:.3f} "
            f"{'PASS' if crosscheck_ok else 'FAIL'}",
            file=sys.stderr,
        )
        # a FAIL must still let the bench finish (a chip window is
        # precious; the headline JSON and the FAIL line are both
        # evidence) — the nonzero exit happens after the JSON prints

    # Warmup (compile) + steady state. NB: sync via a host transfer, not
    # block_until_ready — on the experimental axon TPU platform
    # block_until_ready returns before device completion (measured: 20 R50
    # steps "in" 0.07s), silently inflating the number.
    for _ in range(3):
        state, metrics = step(state, batch_dict, root_rng)
    float(metrics["loss"])

    trace_dir = os.environ.get("BENCH_TRACE_DIR")
    trace = None
    if trace_dir:
        try:
            trace = jax.profiler.trace(trace_dir)
            trace.__enter__()
        except Exception as e:  # profiler may not exist on the axon tunnel
            print(f"profiler unavailable: {e}", file=sys.stderr)
            trace = None

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_dict, root_rng)
    float(metrics["loss"])  # chained state deps force all `steps` steps
    dt = time.perf_counter() - t0
    if trace is not None:
        trace.__exit__(None, None, None)

    imgs_per_sec = batch * steps / dt
    per_chip = imgs_per_sec / n_dev

    # ---- obs overhead (the cost of the telemetry layer itself) --------
    # Same step count twice: FULL obs (in-step health gauges + installed
    # span tracer + a JSONL sink write at logging cadence) vs BARE
    # (--no-health-metrics equivalent, sinks disabled, no tracer). The
    # headline `value` above stays the untouched steady-state loop,
    # comparable with prior BENCH_r*.json rounds; this field tracks what
    # observability costs so a regression in the telemetry layer is a
    # visible number, not a silent throughput tax.
    obs_overhead_pct = None
    if os.environ.get("BENCH_SKIP_OBS_OVERHEAD"):
        _skip("obs_overhead", "BENCH_SKIP_OBS_OVERHEAD set")
    else:
        try:
            import dataclasses as _dc
            import tempfile as _tf

            from moco_tpu import obs as _obs
            from moco_tpu.obs.sinks import JsonlSink

            def _timed_leg(step_fn, sink=None, tracer=None):
                st = state
                prev = _obs.set_tracer(tracer)
                try:
                    for _ in range(2):  # warm this variant's compile
                        st, m = step_fn(st, batch_dict, root_rng)
                    float(m["loss"])
                    t0 = time.perf_counter()
                    for i in range(steps):
                        with _obs.span("step", step=i):
                            st, m = step_fn(st, batch_dict, root_rng)
                        if sink is not None and i % 10 == 0:
                            sink.write(i, m)
                    float(m["loss"])
                    return time.perf_counter() - t0
                finally:
                    _obs.set_tracer(prev)

            sink = JsonlSink(_tf.mkdtemp(prefix="bench_obs_"))
            dt_full = _timed_leg(step, sink=sink, tracer=_obs.Tracer())
            sink.close()
            step_bare = make_train_step(
                _dc.replace(config, health_metrics=False),
                encoder, tx, mesh, donate=False, predictor=predictor,
                total_steps=5004 * config.optim.epochs,
            )
            dt_bare = _timed_leg(step_bare)
            if dt_bare > 0:
                obs_overhead_pct = round((dt_full - dt_bare) / dt_bare * 100.0, 2)
            legs["obs_overhead"]["ran"] = True
            print(
                f"obs overhead: full={dt_full:.2f}s bare={dt_bare:.2f}s "
                f"-> {obs_overhead_pct}%",
                file=sys.stderr,
            )
        except Exception as e:
            _skip("obs_overhead", f"leg crashed: {e!r:.200}")

    # ---- ZeRO weight-update sharding A/B (zero1 vs zero23) ------------
    # Same model/batch, two extra compiled steps: stage 1 (sharded opt
    # state, params re-gathered in-step) vs stage 2/3 (persistently
    # sharded params, bucketed collectives). Recorded per leg: rate,
    # device hbm peak where the backend reports it (NB: peak is a
    # process-lifetime high-water mark, tainted by the main loop above —
    # the analytic at-rest state bytes are the clean A/B signal), and
    # the analytic comms bytes/step from the per-bucket ledger.
    zero_ab = None
    zero_legs = (("zero1", 1, False), ("zero23", 3, False), ("zero_layer", 3, True))
    if os.environ.get("BENCH_SKIP_ZERO"):
        _skip("zero_ab", "BENCH_SKIP_ZERO set")
    elif n_dev < 2:
        reason = (
            f"single-device mesh ({n_dev} chip): ZeRO shards over the data axis "
            "(scripts/fleet_smoke.py covers the fake-8-device A/B)"
        )
        _skip("zero_ab", reason)
        # Sub-leg-granular skip record: CPU-smoke rounds previously wrote
        # a bare null here, so the perf trajectory could not say WHICH
        # zero legs a round was missing once the leg set grew.
        zero_ab = {
            name: {"ran": False, "skip_reason": reason} for name, _, _ in zero_legs
        }
    else:
        try:
            import dataclasses as _dcz

            from moco_tpu.obs import comms as _comms
            from moco_tpu.obs.stepstats import device_memory_stats, tree_shard_bytes

            zero_ab = {}
            zsteps = max(steps // 2, 2)
            for name, stage, layer in zero_legs:
                cfg_z = _dcz.replace(
                    config,
                    parallel=_dcz.replace(
                        config.parallel,
                        shard_weight_update=True,
                        zero_stage=stage,
                        zero_layer_granular=layer,
                    ),
                )
                state_z = create_state(  # mocolint: disable=JX003  (A/B legs share the main run's init seed on purpose: identical weights across zero1/zero23)
                    rng, cfg_z, encoder, tx,
                    jnp.zeros((1, img, img, 3), jnp.float32),
                    predictor=predictor, zero_num_data=n_dev,
                )
                step_z = make_train_step(
                    cfg_z, encoder, tx, mesh, donate=False, predictor=predictor,
                    total_steps=5004 * config.optim.epochs, state_template=state_z,
                )
                state_z = place_state(
                    state_z, mesh, zero=True, zero_params=stage >= 2
                )
                _comms.reset()  # per-leg ledger; tags re-fire on the fresh trace
                st = state_z
                for _ in range(2):
                    st, m = step_z(st, batch_dict, root_rng)
                float(m["loss"])
                t0z = time.perf_counter()
                for _ in range(zsteps):
                    st, m = step_z(st, batch_dict, root_rng)
                float(m["loss"])
                dtz = time.perf_counter() - t0z
                mem = device_memory_stats() or {}
                ledger = _comms.payload()
                zero_ab[name] = {
                    "ran": True,
                    "imgs_per_sec_per_chip": round(batch * zsteps / dtz / n_dev, 2),
                    "hbm_peak_bytes": mem.get("hbm_peak_bytes"),
                    "hbm_state_bytes_per_chip": tree_shard_bytes(st),
                    # analytic shards + live-gather transient: the PEAK
                    # model bytes (not just at-rest) — the number the
                    # layer-granular stage actually moves, trackable on
                    # CPU-smoke rounds where device memory_stats is null
                    "hbm_model_peak_bytes_analytic": getattr(
                        step_z, "hbm_model_peak_bytes", None
                    ),
                    "comms_bytes_per_step": ledger.get("comms/total", 0),
                }
                # Max-feasible-batch probe (analytic, not an OOM search):
                # capacity left after the leg's peak model bytes + state,
                # divided by the measured per-image activation footprint.
                # Null on hosts without memory_stats; the device peak is
                # a process-lifetime watermark, so treat it as a floor
                # estimate, not a guarantee.
                probe = None
                live = mem.get("hbm_live_bytes")
                headroom = mem.get("hbm_headroom_bytes")
                peak_dev = mem.get("hbm_peak_bytes")
                model_peak = zero_ab[name]["hbm_model_peak_bytes_analytic"]
                state_b = zero_ab[name]["hbm_state_bytes_per_chip"]
                if None not in (live, headroom, peak_dev, model_peak):
                    limit = headroom + live
                    act_per_img = max(peak_dev - model_peak - state_b, 1) / batch
                    probe = int(max(limit - model_peak - state_b, 0) // act_per_img)
                zero_ab[name]["max_feasible_batch_probe"] = probe
            legs["zero_ab"]["ran"] = True
            saved = (
                zero_ab["zero1"]["hbm_state_bytes_per_chip"]
                - zero_ab["zero23"]["hbm_state_bytes_per_chip"]
            )
            peak23 = zero_ab["zero23"]["hbm_model_peak_bytes_analytic"]
            peakl = zero_ab["zero_layer"]["hbm_model_peak_bytes_analytic"]
            ratio = round(peak23 / peakl, 2) if peak23 and peakl else None
            print(
                f"zero A/B: zero1={zero_ab['zero1']} zero23={zero_ab['zero23']} "
                f"zero_layer={zero_ab['zero_layer']} "
                f"(at-rest state saved/chip: {saved / 1e6:.1f} MB, "
                f"layer-granular peak-model ratio: {ratio})",
                file=sys.stderr,
            )
        except Exception as e:
            _skip("zero_ab", f"leg crashed: {e!r:.200}")

    # ---- serving leg (queries/s/chip at a fixed SLO) ------------------
    # The platform-independent second headline (ISSUE 8): the key (EMA)
    # encoder behind the continuous batcher, closed-loop clients firing
    # mixed-size requests, measured queries/s at a fixed latency SLO
    # plus padded-bucket occupancy. Runs on the CPU fallback too — the
    # perf trajectory keeps a serving series even when the TPU tunnel
    # is down (the BENCH r02–r05 lesson, applied to the new subsystem).
    serving = None
    if os.environ.get("BENCH_SKIP_SERVE"):
        _skip("serving", "BENCH_SKIP_SERVE set")
    else:
        try:
            import threading

            from moco_tpu.serve.batcher import ContinuousBatcher
            from moco_tpu.serve.engine import InferenceEngine
            from moco_tpu.serve.index import EmbeddingIndex

            # CPU smoke: shrink the bucket ladder and widen the SLO —
            # the point off-TPU is a nonzero tracked series, not an
            # achievable latency target (same degradation philosophy as
            # the headline's resnet18/32px fallback)
            slo_ms = float(
                os.environ.get("BENCH_SERVE_SLO_MS", 25.0 if on_tpu else 2000.0)
            )
            # the FULL key encoder (backbone + head): serving embeds in
            # the dictionary's space, so the step's own queue rows are
            # the /neighbors corpus
            eng = InferenceEngine(
                encoder,
                jax.device_get(state.params_k),
                jax.device_get(state.batch_stats_k),
                image_size=img,
                buckets=(1, 8, 32, 128) if on_tpu else (1, 8, 32),
            )
            eng.warmup()
            index = None
            if moco.num_negatives > 0:
                index = EmbeddingIndex.from_train_queue(jax.device_get(state.queue))
                index.prepare(eng.buckets, k=5)
                index.freeze()

            def run_batch(images, want_neighbors, *, stages=None):
                if want_neighbors and index is not None:
                    emb, scores, nidx, executed = eng.embed_and_query(
                        images, index, 5, stages=stages
                    )
                    return {"embedding": emb, "scores": scores, "indices": nidx}, executed
                emb, executed = eng.embed(images, stages=stages)
                return {"embedding": emb}, executed

            sizes = tuple(
                s for s in (1, 2, 4, 8, 16, 32) if s <= eng.buckets[-1]
            )
            canned = {
                n: np.random.default_rng(n).integers(0, 255, (n, img, img, 3), np.uint8)
                for n in sizes
            }
            warm_s = float(os.environ.get("BENCH_SERVE_WARM_S", 1.0 if on_tpu else 3.0))
            measure_s = float(
                os.environ.get("BENCH_SERVE_MEASURE_S", 3.0 if on_tpu else 8.0)
            )

            def measure(reqtrace: bool, run_batch_fn=None, warm=None, meas=None):
                """One closed-loop pass: fresh batcher + clients over a
                warm engine's run_batch; returns (qps/chip, payload)."""
                run_batch_fn = run_batch_fn or run_batch
                warm = warm_s if warm is None else warm
                meas = measure_s if meas is None else meas
                batcher = ContinuousBatcher(
                    run_batch_fn, max_batch=eng.buckets[-1], slo_ms=slo_ms,
                    reqtrace=reqtrace,
                )
                measuring = threading.Event()
                stop_clients = threading.Event()
                counts = [0] * 8

                def client(ci: int) -> None:
                    crng = np.random.default_rng(100 + ci)
                    while not stop_clients.is_set():
                        n = int(crng.choice(sizes))
                        try:
                            fut = batcher.submit(
                                canned[n], want_neighbors=index is not None
                            )
                            fut.result(timeout=30.0)
                        except Exception:
                            return
                        if measuring.is_set():
                            counts[ci] += 1

                clients = [
                    threading.Thread(target=client, args=(i,), daemon=True)
                    for i in range(len(counts))
                ]
                for c in clients:
                    c.start()
                time.sleep(warm)
                measuring.set()
                t0s = time.perf_counter()
                time.sleep(meas)
                measuring.clear()
                dts = time.perf_counter() - t0s
                stop_clients.set()
                batcher.close()
                for c in clients:
                    c.join(timeout=5.0)
                payload = batcher.metrics.payload()
                completed = sum(counts)
                if completed == 0:
                    raise RuntimeError(
                        f"no request completed inside the {meas}s measure "
                        "window — raise BENCH_SERVE_MEASURE_S on very slow hosts"
                    )
                return completed / dts / n_dev, payload

            # A/B: the tracked headline stays the tracing-OFF pass (the
            # r06+ series must remain comparable); the tracing-ON pass
            # measures the request-trace overhead the ISSUE-10 acceptance
            # caps (perf_ledger gates trace_overhead_pct)
            qps_chip, payload = measure(reqtrace=False)
            qps_traced, payload_traced = measure(reqtrace=True)
            trace_overhead_pct = (qps_chip - qps_traced) / qps_chip * 100.0

            # ---- router tracing A/B (ISSUE 18) --------------------------
            # The distributed-tracing cost at the fleet front door: the
            # same warm engine behind ONE HTTP replica, a FleetRouter in
            # front, closed-loop clients through real sockets; tracing
            # OFF vs ON (context injection, per-attempt spans, the
            # stitcher, the flight ring). perf_ledger gates the delta
            # under the same trace-overhead caps as the replica-side A/B.
            router_qps = router_qps_traced = None
            if not os.environ.get("BENCH_SKIP_ROUTER"):
                import urllib.request as _urlreq

                from moco_tpu.serve.router import FleetRouter
                from moco_tpu.serve.server import ServeServer

                replica = ServeServer(
                    eng, index=index, port=0, slo_ms=slo_ms,
                    neighbors_k=5, warmup=False,
                )
                router_meas = float(os.environ.get(
                    "BENCH_ROUTER_MEASURE_S", max(measure_s / 2, 2.0)
                ))

                def router_pass(rt: bool) -> float:
                    router = FleetRouter(
                        replica_urls=[f"http://127.0.0.1:{replica.port}"],
                        port=0, slo_ms=slo_ms, hedge=False, reqtrace=rt,
                    )
                    rbase = f"http://127.0.0.1:{router.port}"
                    measuring = threading.Event()
                    stop_r = threading.Event()
                    rcounts = [0] * 4

                    def rclient(ci: int) -> None:
                        crng = np.random.default_rng(200 + ci)
                        while not stop_r.is_set():
                            n = int(crng.choice(sizes))
                            req = _urlreq.Request(
                                rbase + "/embed",
                                data=canned[n].tobytes(),
                                headers={"X-Image-Shape": ",".join(
                                    map(str, canned[n].shape)
                                )},
                            )
                            try:
                                with _urlreq.urlopen(req, timeout=30) as r:
                                    r.read()
                            except Exception:
                                if measuring.is_set():
                                    return
                                # pre-measure 503s while the health loop
                                # admits the replica are expected
                                time.sleep(0.05)
                                continue
                            if measuring.is_set():
                                rcounts[ci] += 1

                    try:
                        rclients = [
                            threading.Thread(
                                target=rclient, args=(i,), daemon=True
                            )
                            for i in range(len(rcounts))
                        ]
                        for c in rclients:
                            c.start()
                        time.sleep(max(warm_s, 1.0))
                        measuring.set()
                        t0r = time.perf_counter()
                        time.sleep(router_meas)
                        measuring.clear()
                        dtr = time.perf_counter() - t0r
                        stop_r.set()
                        for c in rclients:
                            c.join(timeout=10.0)
                    finally:
                        router.close()
                    completed = sum(rcounts)
                    if completed == 0:
                        raise RuntimeError(
                            f"no request completed inside the router "
                            f"{router_meas}s measure window (reqtrace={rt})"
                        )
                    return completed / dtr / n_dev

                try:
                    router_qps = router_pass(False)
                    router_qps_traced = router_pass(True)
                finally:
                    replica.close()
            router_trace_overhead_pct = (
                (router_qps - router_qps_traced) / router_qps * 100.0
                if router_qps
                else None
            )

            # ---- promotion-swap overhead (ISSUE 19) ---------------------
            # What one staged-rollout step costs the fleet front door:
            # two in-process replicas behind a FleetRouter, closed-loop
            # clients running throughout, and replica 0 promoted
            # (drain -> swap -> re-admit with a new model identity).
            # promote_pause_ms = wall time from promote_replica() until
            # /admin/replicas shows the replica back (healthy, not
            # draining, new digest); promote_swap_p99_ms = client p99 of
            # requests overlapping that window; promote_swap_failures
            # must be 0 (the drain path's whole point). The swap rebinds
            # the same port around the already-warm engine, so the pause
            # measures the router-side drain/readmit machinery and
            # EXCLUDES checkpoint restore + AOT re-warm (the fleet smoke
            # exercises the full cold swap). perf_ledger.py check gates
            # all three fields.
            promote_pause_ms = promote_swap_p99 = promote_failures = None
            if not os.environ.get("BENCH_SKIP_PROMOTE"):
                import urllib.request as _urlreq2

                from moco_tpu.serve.router import FleetRouter
                from moco_tpu.serve.server import ServeServer

                class _SwapSupervisor:
                    """Duck-typed ReplicaSupervisor stand-in: the
                    router's promotion path only ever calls
                    set_ckpt_dir() and restart_replica(). A restart
                    rebuilds the in-process replica on the SAME port
                    around the warm engine, bumping the model identity
                    so the digest landing is observable."""

                    def __init__(self, servers):
                        self.servers = servers
                        self.ckpt_dir = None

                    def urls(self):
                        return [
                            f"http://127.0.0.1:{s.port}" for s in self.servers
                        ]

                    def set_ckpt_dir(self, path):
                        self.ckpt_dir = str(path)

                    def restart_replica(self, i):
                        old = self.servers[i]
                        port, step = old.port, (old.model_step or 0) + 1
                        old.close()
                        self.servers[i] = ServeServer(
                            eng, index=index, port=port, slo_ms=slo_ms,
                            neighbors_k=5, warmup=False, model_step=step,
                            model_digest=f"benchswap{step:03d}",
                        )

                duck = _SwapSupervisor([
                    ServeServer(
                        eng, index=index, port=0, slo_ms=slo_ms,
                        neighbors_k=5, warmup=False, model_step=0,
                        model_digest=f"benchlive{i:03d}",
                    )
                    for i in range(2)
                ])
                prouter = FleetRouter(
                    replica_urls=duck.urls(), supervisor=duck, port=0,
                    slo_ms=slo_ms, hedge=False, health_interval_s=0.1,
                )
                pbase = f"http://127.0.0.1:{prouter.port}"
                admitted = threading.Event()
                stop_p = threading.Event()
                p_lock = threading.Lock()
                p_samples = []  # (t_start, t_end, ms) post-admission
                p_failures = []

                def pclient(ci: int) -> None:
                    crng = np.random.default_rng(300 + ci)
                    while not stop_p.is_set():
                        n = int(crng.choice(sizes))
                        req = _urlreq2.Request(
                            pbase + "/embed",
                            data=canned[n].tobytes(),
                            headers={"X-Image-Shape": ",".join(
                                map(str, canned[n].shape)
                            )},
                        )
                        t0 = time.perf_counter()
                        try:
                            with _urlreq2.urlopen(req, timeout=30) as r:
                                r.read()
                        except Exception as e:
                            if admitted.is_set():
                                with p_lock:
                                    p_failures.append(repr(e))
                            else:
                                # pre-admission 503s while the health
                                # loop admits the replicas are expected
                                time.sleep(0.05)
                            continue
                        t1 = time.perf_counter()
                        if admitted.is_set():
                            with p_lock:
                                p_samples.append((t0, t1, (t1 - t0) * 1e3))

                def _fleet_snap():
                    with _urlreq2.urlopen(
                        pbase + "/admin/replicas", timeout=5
                    ) as r:
                        return json.loads(r.read())["replicas"]

                try:
                    deadline = time.monotonic() + 30.0
                    while time.monotonic() < deadline:
                        snaps = _fleet_snap()
                        if all(s["healthy"] and s["warm"] for s in snaps):
                            break
                        time.sleep(0.1)
                    pclients = [
                        threading.Thread(target=pclient, args=(i,), daemon=True)
                        for i in range(4)
                    ]
                    for c in pclients:
                        c.start()
                    admitted.set()
                    time.sleep(max(warm_s, 1.0))
                    t_sw0 = time.perf_counter()
                    if not prouter.promote_replica(0, "bench-candidate"):
                        raise RuntimeError("promotion step refused: replica busy")
                    deadline = time.monotonic() + 60.0
                    landed = False
                    while time.monotonic() < deadline:
                        s0 = _fleet_snap()[0]
                        if (
                            s0["healthy"]
                            and not s0["draining"]
                            and s0["model_digest"] == "benchswap001"
                        ):
                            landed = True
                            break
                        time.sleep(0.05)
                    t_sw1 = time.perf_counter()
                    if not landed:
                        raise RuntimeError(
                            f"promotion swap never landed: {_fleet_snap()[0]}"
                        )
                    time.sleep(0.5)  # tail traffic past the swap window
                    stop_p.set()
                    for c in pclients:
                        c.join(timeout=10.0)
                finally:
                    stop_p.set()
                    prouter.close()
                    for s in duck.servers:
                        s.close()
                promote_pause_ms = (t_sw1 - t_sw0) * 1e3
                with p_lock:
                    window = sorted(
                        ms for (a, b, ms) in p_samples
                        if b >= t_sw0 and a <= t_sw1
                    )
                    promote_failures = len(p_failures)
                promote_swap_p99 = (
                    window[min(len(window) - 1, int(len(window) * 0.99))]
                    if window
                    else None
                )

            # ---- quantized-engine A/B (ISSUE 11): w8 vs w8a8 ----------
            # Same params, same buckets, same index; qps measured in
            # short INTERLEAVED slices (the tiers alternate inside one
            # wall window, so host drift hits both equally) plus each
            # tier's embedding cosine vs the f32 engine on a fixed probe
            # batch. `int8_kernels` records whether true int8×int8→int32
            # actually ran (tpu/gpu) or the bit-faithful CPU emulation
            # did (quant.py docstring: XLA:CPU has no int8 conv kernels,
            # measured ~45x slower — so on the CPU smoke the w8a8-vs-w8
            # speed signal is conv-bound ~parity and the arithmetic
            # factor is an accelerator claim; the cosine floor gates
            # everywhere).
            quant_ab = None
            if not os.environ.get("BENCH_SKIP_QUANT"):
                probe = np.concatenate([canned[n] for n in sizes])
                emb_f32, _ = eng.embed(probe)

                def _mean_cos(a, b):  # rows are L2-normalized
                    return float(np.mean(np.sum(
                        np.asarray(a, np.float64) * np.asarray(b, np.float64),
                        axis=-1,
                    )))

                calib_sample = np.concatenate([
                    np.random.default_rng(50 + n).integers(
                        0, 255, (n, img, img, 3), np.uint8
                    )
                    for n in sizes
                ])
                qengines = {}
                for tier in ("w8", "w8a8"):
                    kw = {"calib_sample": calib_sample} if tier == "w8a8" else {}
                    qe = InferenceEngine(
                        encoder,
                        jax.device_get(state.params_k),
                        jax.device_get(state.batch_stats_k),
                        image_size=img,
                        buckets=eng.buckets,
                        engine_quant=tier,
                        **kw,
                    )
                    qe.warmup()
                    qengines[tier] = qe

                def _quant_run_batch(qe):
                    def rb(images, want_neighbors, *, stages=None):
                        if want_neighbors and index is not None:
                            emb, scores, nidx, executed = qe.embed_and_query(
                                images, index, 5, stages=stages
                            )
                            return {
                                "embedding": emb, "scores": scores, "indices": nidx,
                            }, executed
                        emb, executed = qe.embed(images, stages=stages)
                        return {"embedding": emb}, executed
                    return rb

                slices = int(os.environ.get("BENCH_QUANT_SLICES", 3))
                slice_s = float(
                    os.environ.get("BENCH_QUANT_SLICE_S", max(measure_s / 3, 1.0))
                )
                acc = {t: [] for t in qengines}
                for _ in range(slices):
                    for tier, qe in qengines.items():
                        q_t, _ = measure(
                            reqtrace=False, run_batch_fn=_quant_run_batch(qe),
                            warm=min(warm_s, 1.0), meas=slice_s,
                        )
                        acc[tier].append(q_t)
                quant_ab = {}
                for tier, qe in qengines.items():
                    if qe.recompiles_after_warmup:
                        raise RuntimeError(
                            f"{tier} engine recompiled after warmup"
                        )
                    emb_q, _ = qe.embed(probe)
                    audit = qe.donation_audit()
                    quant_ab[tier] = {
                        "qps": round(sum(acc[tier]) / len(acc[tier]), 2),
                        "cosine_vs_f32": round(_mean_cos(emb_q, emb_f32), 5),
                        "donation_audit_ok": not any(
                            v is False for v in audit.values()
                        ),
                    }
                quant_ab["w8a8"]["int8_kernels"] = bool(
                    qengines["w8a8"].int8_compute
                )
                quant_ab["speedup_w8a8_vs_w8"] = round(
                    quant_ab["w8a8"]["qps"] / quant_ab["w8"]["qps"], 3
                )
                print(
                    f"serving quant A/B: w8={quant_ab['w8']['qps']:.1f} q/s "
                    f"(cos={quant_ab['w8']['cosine_vs_f32']:.5f}) "
                    f"w8a8={quant_ab['w8a8']['qps']:.1f} q/s "
                    f"(cos={quant_ab['w8a8']['cosine_vs_f32']:.5f}, "
                    f"int8_kernels={quant_ab['w8a8']['int8_kernels']}) "
                    f"-> {quant_ab['speedup_w8a8_vs_w8']}x",
                    file=sys.stderr,
                )

            recompiles = eng.recompiles_after_warmup + (
                index.recompiles_after_warmup if index is not None else 0
            )
            if recompiles:
                raise RuntimeError(
                    f"serving leg recompiled {recompiles}x after warmup"
                )
            serving = {
                "metric": (
                    f"moco_serve_{arch}_queries_per_sec_per_chip"
                    if on_tpu
                    else f"moco_serve_{arch}_cpu_smoke_queries_per_sec"
                ),
                "value": round(qps_chip, 2),
                "unit": "queries/sec/chip",
                "slo_ms": slo_ms,
                "p50_ms": round(payload["serve/p50_ms"], 2),
                "p99_ms": round(payload["serve/p99_ms"], 2),
                "occupancy": round(payload["serve/occupancy"], 4),
                "slo_violation_rate": (
                    round(payload["serve/slo_violations"] / payload["serve/requests"], 4)
                    if payload["serve/requests"]
                    else None
                ),
                "bucket_histogram": {
                    k.split("_", 1)[1]: v
                    for k, v in payload.items()
                    if k.startswith("serve/bucket_")
                },
                "neighbors": index is not None,
                # request-tracing A/B (ISSUE 10): qps with per-request
                # waterfalls ON, the measured overhead (gated by
                # perf_ledger.py check), and the traced pass's mean
                # stage split
                "qps_traced": round(qps_traced, 2),
                "trace_overhead_pct": round(trace_overhead_pct, 2),
                # distributed-tracing A/B at the fleet front door
                # (ISSUE 18): qps through a FleetRouter + one HTTP
                # replica with router tracing OFF vs ON; the overhead is
                # gated by perf_ledger.py check under the same caps
                "router_qps": (
                    round(router_qps, 2) if router_qps is not None else None
                ),
                "router_qps_traced": (
                    round(router_qps_traced, 2)
                    if router_qps_traced is not None
                    else None
                ),
                "router_trace_overhead_pct": (
                    round(router_trace_overhead_pct, 2)
                    if router_trace_overhead_pct is not None
                    else None
                ),
                "trace_stage_ms": {
                    k[len("serve/trace_"):-len("_ms")]: v
                    for k, v in payload_traced.items()
                    if k.startswith("serve/trace_") and k.endswith("_ms")
                },
                # promotion-swap overhead (ISSUE 19): one staged-rollout
                # step through the router under live closed-loop load —
                # the pause until the swapped replica re-admits with its
                # new digest, the client p99 across the swap window, and
                # the failure count (gated at 0 by perf_ledger.py check)
                "promote_pause_ms": (
                    round(promote_pause_ms, 2)
                    if promote_pause_ms is not None
                    else None
                ),
                "promote_swap_p99_ms": (
                    round(promote_swap_p99, 2)
                    if promote_swap_p99 is not None
                    else None
                ),
                "promote_swap_failures": promote_failures,
                # quantized-engine tiers (ISSUE 11): w8/w8a8 qps from the
                # interleaved slices + embedding cosine vs f32 (gated at
                # QUANT_COSINE_FLOOR by perf_ledger.py check), and
                # whether true int8 kernels ran
                "quant": quant_ab,
            }
            legs["serving"]["ran"] = True
            print(
                f"serving: {qps_chip:.1f} queries/s/chip @ SLO {slo_ms}ms "
                f"(p50={payload['serve/p50_ms']}ms p99={payload['serve/p99_ms']}ms "
                f"occupancy={payload['serve/occupancy']} "
                f"violations={serving['slo_violation_rate']} "
                f"traced={qps_traced:.1f} q/s "
                f"overhead={trace_overhead_pct:+.1f}%)",
                file=sys.stderr,
            )
            if router_trace_overhead_pct is not None:
                print(
                    f"router tracing A/B: {router_qps:.1f} q/s untraced, "
                    f"{router_qps_traced:.1f} q/s traced "
                    f"(overhead={router_trace_overhead_pct:+.1f}%)",
                    file=sys.stderr,
                )
            if promote_pause_ms is not None:
                print(
                    f"promotion swap: pause={promote_pause_ms:.0f}ms "
                    f"p99-during-swap="
                    + (
                        f"{promote_swap_p99:.0f}ms"
                        if promote_swap_p99 is not None
                        else "n/a"
                    )
                    + f" failures={promote_failures}",
                    file=sys.stderr,
                )
        except Exception as e:
            serving = None  # never ship a half-built serving record
            legs["serving"]["ran"] = False
            _skip("serving", f"leg crashed: {e!r:.200}")

    # ---- ANN A/B: exact scan vs IVF behind EmbeddingIndex (ISSUE 9) ---
    # The sub-linear serving claim, measured: a K-row dictionary (2^20
    # by default — past the point where the exact scan's O(K) matmul
    # dominates a query), exact vs IVF (nprobe cells of ~K/nlist rows)
    # vs int8-IVF queries/s at the same top-k, plus recall@k of each
    # approximate tier against the exact oracle on the same queries.
    # Platform-independent like the serving leg: the CPU smoke keeps
    # the series alive when the TPU tunnel is down, and the algorithmic
    # win (O(K) -> O(nprobe*K/nlist)) shows up on any backend.
    ann_ab = None
    if os.environ.get("BENCH_SKIP_ANN"):
        _skip("ann_ab", "BENCH_SKIP_ANN set")
    else:
        try:
            from moco_tpu.serve.index import EmbeddingIndex

            ann_rows = int(os.environ.get("BENCH_ANN_ROWS", 1 << 20))
            ann_dim = int(os.environ.get("BENCH_ANN_DIM", 64))
            ann_nlist = int(os.environ.get("BENCH_ANN_NLIST", 1024))
            ann_nprobe = int(os.environ.get("BENCH_ANN_NPROBE", 8))
            ann_m = int(os.environ.get("BENCH_ANN_BATCH", 8))
            ann_batches = int(os.environ.get("BENCH_ANN_QUERY_BATCHES", 8))
            ks = (1, 10)
            # clustered synthetic corpus (mixture of Gaussians on the
            # sphere) — the geometry trained embedding dictionaries
            # actually have; uniform random rows have no neighbor
            # structure for ANY index to exploit
            arng = np.random.default_rng(7)
            n_centers = max(4 * ann_nlist, 64)
            centers = arng.normal(size=(n_centers, ann_dim)).astype(np.float32)
            corpus = centers[arng.integers(0, n_centers, ann_rows)]
            corpus += 0.25 * arng.normal(size=corpus.shape).astype(np.float32)
            corpus /= np.linalg.norm(corpus, axis=1, keepdims=True)
            picks = arng.integers(0, ann_rows, ann_batches * ann_m)
            queries = corpus[picks] + 0.05 * arng.normal(
                size=(len(picks), ann_dim)
            ).astype(np.float32)
            queries /= np.linalg.norm(queries, axis=1, keepdims=True)
            qbatches = queries.reshape(ann_batches, ann_m, ann_dim)

            aidx = EmbeddingIndex(ann_rows, ann_dim)
            aidx.snapshot(corpus)
            t0a = time.perf_counter()
            aidx.train_ivf(
                nlist=ann_nlist,
                iters=int(os.environ.get("BENCH_ANN_KMEANS_ITERS", 8)),
                nprobe=ann_nprobe,
            )
            aidx.enable_int8()
            build_s = time.perf_counter() - t0a
            aidx.prepare([ann_m], k=max(ks), nprobe=ann_nprobe,
                         modes=("exact", "ivf", "ivf_i8",
                                "ivf_fused", "ivf_fused_i8"))
            aidx.freeze()

            def _ann_leg(mode):
                outs = []
                t0 = time.perf_counter()
                for qb in qbatches:
                    outs.append(aidx.query(qb, max(ks), mode=mode)[1])
                dt = time.perf_counter() - t0
                return ann_batches * ann_m / dt, np.concatenate(outs)

            exact_qps, exact_idx = _ann_leg("exact")
            ivf_qps, ivf_idx = _ann_leg("ivf")
            i8_qps, i8_idx = _ann_leg("ivf_i8")
            # the fused gather-scan tiers (ISSUE 11): same probe/top-k
            # semantics as the composed scan, one kernel, no
            # (m, nprobe*cell_cap, d) candidate materialization
            fused_qps, fused_idx = _ann_leg("ivf_fused")
            fused_i8_qps, fused_i8_idx = _ann_leg("ivf_fused_i8")
            if aidx.recompiles_after_warmup:
                raise RuntimeError(
                    f"ann leg recompiled {aidx.recompiles_after_warmup}x after freeze"
                )

            def _recall(approx, oracle, k):
                return float(np.mean([
                    len(set(approx[i, :k]) & set(oracle[i, :k])) / k
                    for i in range(oracle.shape[0])
                ]))

            stats = aidx.ivf_stats()
            ann_ab = {
                "metric": (
                    "moco_ann_ivf_queries_per_sec"
                    if on_tpu
                    else "moco_ann_ivf_cpu_smoke_queries_per_sec"
                ),
                "value": round(ivf_qps, 2),
                "unit": "queries/sec",
                "rows": ann_rows,
                "dim": ann_dim,
                "nlist": stats["nlist"],
                "nprobe": ann_nprobe,
                "cell_cap": stats["cell_cap"],
                "spilled": stats["spilled"],
                "batch": ann_m,
                "build_s": round(build_s, 2),
                "exact_qps": round(exact_qps, 2),
                "speedup": round(ivf_qps / exact_qps, 2),
                "recall_at_1": _recall(ivf_idx, exact_idx, 1),
                "recall_at_10": _recall(ivf_idx, exact_idx, 10),
                "int8": {
                    "qps": round(i8_qps, 2),
                    "speedup_vs_exact": round(i8_qps / exact_qps, 2),
                    # honest recall vs the f32 oracle AND vs the int8
                    # exact oracle (isolates IVF loss from quantization
                    # reordering of near-ties)
                    "recall_at_10": _recall(i8_idx, exact_idx, 10),
                },
                # fused gather-scan tier (ISSUE 11): the composed scan's
                # three hops as one kernel — recall-gated like every
                # tier (perf_ledger check: recall floor + fused must
                # beat the composed tier it replaces)
                "fused": {
                    "qps": round(fused_qps, 2),
                    "speedup_vs_ivf": round(fused_qps / ivf_qps, 2),
                    "recall_at_10": _recall(fused_idx, exact_idx, 10),
                    # same candidate set by construction — ids match the
                    # composed scan exactly on ties-free data
                    "ids_match_composed": bool((fused_idx == ivf_idx).all()),
                    "int8": {
                        "qps": round(fused_i8_qps, 2),
                        "speedup_vs_ivf_i8": round(fused_i8_qps / i8_qps, 2),
                        "recall_at_10": _recall(fused_i8_idx, exact_idx, 10),
                    },
                },
            }
            legs["ann_ab"]["ran"] = True
            print(
                f"ann A/B: K={ann_rows} exact={exact_qps:.1f} q/s "
                f"ivf={ivf_qps:.1f} q/s ({ann_ab['speedup']}x, "
                f"recall@10={ann_ab['recall_at_10']:.3f}) "
                f"ivf_i8={i8_qps:.1f} q/s | fused={fused_qps:.1f} q/s "
                f"({ann_ab['fused']['speedup_vs_ivf']}x vs composed, "
                f"recall@10={ann_ab['fused']['recall_at_10']:.3f}, "
                f"ids_match={ann_ab['fused']['ids_match_composed']}) "
                f"fused_i8={fused_i8_qps:.1f} q/s (build {build_s:.1f}s, "
                f"spilled={stats['spilled']})",
                file=sys.stderr,
            )
        except Exception as e:
            ann_ab = None
            legs["ann_ab"]["ran"] = False
            _skip("ann_ab", f"leg crashed: {e!r:.200}")

    # ---- MFU (per-device FLOPs over per-device peak) ------------------
    flops_per_dev = _step_flops(step, state, batch_dict, root_rng) or (
        None if is_vit else _analytic_step_flops(batch, img) / n_dev
    )
    peak = _peak_tflops(jax.devices()[0])
    mfu = (
        (flops_per_dev * steps / dt) / (peak * 1e12)
        if peak and flops_per_dev
        else None
    )

    # ---- with-data rate (real pipeline in the loop) -------------------
    # Two legs since the device prefetch ring landed (ISSUE 5): the
    # synchronous path (decode → transfer → dispatch take turns on one
    # producer thread) vs the overlapped path (epoch(device=True):
    # decode thread + transfer ring + pipelined steps). Both run on the
    # CPU fallback too — the perf trajectory needs a non-null with-data
    # series and an overlap A/B even when the TPU tunnel is down
    # (BENCH_r05.json carried `with_data: null` for exactly that reason).
    with_data = with_data_sync = overlap_efficiency = None
    if os.environ.get("BENCH_SKIP_DATA"):
        _skip("with_data", "BENCH_SKIP_DATA set")
    else:
        try:
            from moco_tpu.data.pipeline import TwoCropPipeline

            # drop-last pipeline: an epoch smaller than one batch yields
            # ZERO batches and the epoch roller below would spin forever
            if on_tpu:
                n_imgs, src_size = max(1024, batch), 256
            else:  # CPU smoke: small synthetic folder, small geometry
                n_imgs, src_size = max(256, batch), 64
            folder = _ensure_jpeg_folder("/tmp/moco_bench_imgfolder", n_imgs, src_size)
            dconf = DataConfig(
                dataset="imagefolder",
                data_dir=folder,
                image_size=img,
                global_batch=batch,
                aug_plus=True,
                num_workers=8,
                # decode-once packed RGB cache on by default (best-practice
                # config; BENCH_CACHE_DIR="" disables, see PROFILE.md for
                # the uncached/canvas-mode ladder); BENCH_HOST_RRC=0 moves
                # the crop on-device (canvas mode — a pure mmap row read)
                cache_dir=os.environ.get("BENCH_CACHE_DIR", "/tmp/moco_bench_cache")
                or None,
                host_rrc=os.environ.get("BENCH_HOST_RRC", "1") != "0",
            )
            pipe = TwoCropPipeline(dconf, mesh, seed=0)

            def _with_data_leg(device: bool, warm_steps: int):
                """Sustained imgs/s (global) of `steps` real-pipeline
                steps, plus the ring's TransferStats on the overlapped
                leg. Rolls over epochs; closes abandoned iterators so
                ring/producer threads never leak between legs."""
                st, done, epoch = state, 0, 0
                it = iter(pipe.epoch(epoch, device=device))

                def _next():
                    nonlocal it, epoch
                    while True:
                        b = next(it, None)
                        if b is not None:
                            return b
                        getattr(it, "close", lambda: None)()
                        epoch += 1
                        it = iter(pipe.epoch(epoch, device=device))

                for _ in range(warm_steps):
                    b = _next()
                st, m = step(st, b, root_rng)
                float(m["loss"])
                t0 = time.perf_counter()
                for _ in range(steps):
                    st, m = step(st, _next(), root_rng)
                float(m["loss"])  # chained state deps force all steps
                dt = time.perf_counter() - t0
                stats = getattr(it, "stats", None)
                getattr(it, "close", lambda: None)()
                return batch * steps / dt, stats

            # warm a FULL first epoch before timing: the first pass over
            # a cold cache dir decodes every JPEG and writes the packed
            # cache — a one-time cost that otherwise lands inside the
            # timed loop and misreports the steady-state rate (the
            # ladder in PROFILE.md is steady-state)
            warm_steps = max(n_imgs // batch, 1)
            sync_rate, _ = _with_data_leg(device=False, warm_steps=warm_steps)
            over_rate, ring_stats = _with_data_leg(device=True, warm_steps=1)
            with_data_sync = sync_rate / n_dev
            with_data = over_rate / n_dev

            # overlap_efficiency = achieved / min(host, device, wire):
            # 1.0 means the overlapped loop runs at the binding stage's
            # rate — nothing left to hide. Host rate drains the decode
            # generator alone; device rate is the headline steady-state;
            # wire rate converts the ring's measured MB/s to imgs/s.
            t0 = time.perf_counter()
            host_n = 0
            for _ in pipe._host_gen(97):
                host_n += 1
                if host_n >= steps:
                    break
            host_rate = batch * host_n / (time.perf_counter() - t0)
            bounds = [host_rate, imgs_per_sec]
            if ring_stats is not None and ring_stats.batches:
                wire_bps = ring_stats.wire_rate_bytes_per_sec()
                bytes_per_img = ring_stats.total_bytes / ring_stats.batches / batch
                if wire_bps and bytes_per_img:
                    bounds.append(wire_bps / bytes_per_img)
            overlap_efficiency = over_rate / min(bounds)
            legs["with_data"]["ran"] = True
            print(
                f"with-data: sync={sync_rate:.1f} overlapped={over_rate:.1f} imgs/s "
                f"(bounds host={host_rate:.1f} device={imgs_per_sec:.1f}"
                + (f" wire={bounds[2]:.1f}" if len(bounds) > 2 else "")
                + f") overlap_efficiency={overlap_efficiency:.3f}",
                file=sys.stderr,
            )
        except Exception as e:
            _skip("with_data", f"leg crashed: {e!r:.200}")

    print(
        f"platform={platform} chips={n_dev} arch={arch} batch={batch} "
        f"steps={steps} wall={dt:.2f}s total={imgs_per_sec:.1f} imgs/s "
        f"mfu={mfu if mfu is None else round(mfu, 4)} with_data={with_data}",
        file=sys.stderr,
    )
    if is_vit:
        flash = "_flash" if config.moco.vit_flash_attention else ""
        metric = (
            f"moco_v3_{arch}{flash}_pretrain_imgs_per_sec_per_chip"
            if on_tpu
            else f"moco_v3_{arch}{flash}_cpu_smoke_imgs_per_sec"
        )
    elif on_tpu:
        metric = "moco_v2_r50_pretrain_imgs_per_sec_per_chip"
    else:
        metric = "moco_v1_r18_cpu_smoke_imgs_per_sec"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(per_chip, 2),
                "unit": "imgs/sec/chip",
                # apples-to-apples only on the real R50/224 TPU metric
                # (the 168 imgs/s/GPU baseline is the reference's R50 run)
                "vs_baseline": round(per_chip / REFERENCE_IMGS_PER_SEC_PER_GPU, 3)
                if on_tpu and not is_vit
                else None,
                "mfu": None if mfu is None else round(mfu, 4),
                # overlapped (device prefetch ring) with-data rate; the
                # sync leg and the efficiency ratio ride along so every
                # BENCH record carries the overlap A/B (CPU smoke too)
                "with_data_imgs_per_sec_per_chip": None
                if with_data is None
                else round(with_data, 2),
                "with_data_sync_imgs_per_sec_per_chip": None
                if with_data_sync is None
                else round(with_data_sync, 2),
                "overlap_efficiency": None
                if overlap_efficiency is None
                else round(overlap_efficiency, 3),
                # telemetry-layer cost: full obs (health gauges + tracer
                # + sink writes) vs bare, same compiled shapes
                "obs_overhead_pct": obs_overhead_pct,
                # ZeRO-1 vs ZeRO-2/3 A/B (multi-chip legs only): per-leg
                # rate, device hbm peak, analytic at-rest state bytes,
                # and bucketed-collective bytes/step
                "zero_ab": zero_ab,
                # serving leg (ISSUE 8): the second headline series —
                # queries/s/chip through the continuous batcher at a
                # fixed SLO, with its own metric name so the perf
                # ledger gates it independently of the training rate
                "serving": serving,
                # ANN A/B (ISSUE 9): exact-vs-IVF-vs-int8 queries/s +
                # recall@k on a 2^20-row dictionary — the third gated
                # series (sub-linear retrieval must stay sub-linear)
                "ann_ab": ann_ab,
                # per-leg skip ledger: WHY a leg didn't run, in-band —
                # a BENCH_*.json degraded to the CPU smoke now says so
                # itself (accelerator.skip_reason) instead of relying on
                # someone reading four rounds of stderr
                "legs": legs,
            }
        )
    )
    if not crosscheck_ok:
        raise SystemExit("fused-vs-dense numerics crosscheck FAILED")


if __name__ == "__main__":
    main()
