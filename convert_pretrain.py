#!/usr/bin/env python
"""Convert a pretraining checkpoint for transfer evaluation — the
TPU-native `detection/convert-pretrain-to-detectron2.py` (plus a torch
state-dict export for the wider ecosystem).

Usage:
    python convert_pretrain.py WORKDIR out.pkl   # detectron2 pickle (ResNet)
    python convert_pretrain.py WORKDIR out.pth   # torch state_dict
                                                 # (ResNet->torchvision names,
                                                 #  ViT->timm names)

The backbone architecture is read from the config stored in the
checkpoint."""

from __future__ import annotations

import argparse

from moco_tpu.export import (
    STAGE_SIZES,
    resnet_to_torchvision,
    save_detectron2_pickle,
    save_torch_state_dict,
    vit_to_timm,
)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("workdir", help="pretraining workdir (Orbax checkpoints)")
    p.add_argument("output", help="output .pkl (detectron2) or .pth (torch)")
    p.add_argument("--format", choices=("d2", "torch"), default=None,
                   help="default: inferred from the output extension")
    args = p.parse_args()

    from moco_tpu.lincls import load_pretrained_backbone

    # arch and template come from the config stored in the checkpoint
    params, stats, config = load_pretrained_backbone(args.workdir)
    arch = config.moco.arch
    fmt = args.format or ("torch" if args.output.endswith(".pth") else "d2")
    if arch in STAGE_SIZES:
        state = resnet_to_torchvision(params, stats, stage_sizes=STAGE_SIZES[arch])
    elif arch.startswith("vit"):
        if fmt == "d2":
            raise SystemExit(
                "detectron2 export is the R50-C4 detection recipe (ResNet only); "
                "ViT checkpoints export as a timm state dict (.pth)"
            )
        state = vit_to_timm(
            params,
            patch_size=config.moco.vit_patch_size or 16,
            image_size=config.data.image_size,
        )
    else:
        raise SystemExit(f"unsupported arch for export: {arch!r}")

    if fmt == "d2":
        save_detectron2_pickle(state, args.output)
    else:
        save_torch_state_dict(state, args.output)
    print(f"wrote {len(state)} tensors -> {args.output} ({fmt})")


if __name__ == "__main__":
    main()
