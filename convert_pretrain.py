#!/usr/bin/env python
"""Convert a pretraining checkpoint for transfer evaluation — the
TPU-native `detection/convert-pretrain-to-detectron2.py` (plus a torch
state-dict export for the wider ecosystem).

Usage:
    python convert_pretrain.py WORKDIR out.pkl   # detectron2 pickle
    python convert_pretrain.py WORKDIR out.pth   # torch state_dict

The backbone architecture is read from the config stored in the
checkpoint."""

from __future__ import annotations

import argparse

from moco_tpu.export import (
    STAGE_SIZES,
    resnet_to_torchvision,
    save_detectron2_pickle,
    save_torch_state_dict,
)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("workdir", help="pretraining workdir (Orbax checkpoints)")
    p.add_argument("output", help="output .pkl (detectron2) or .pth (torch)")
    p.add_argument("--format", choices=("d2", "torch"), default=None,
                   help="default: inferred from the output extension")
    args = p.parse_args()

    from moco_tpu.lincls import load_pretrained_backbone

    # arch and template come from the config stored in the checkpoint
    params, stats, config = load_pretrained_backbone(args.workdir)
    arch = config.moco.arch
    if arch not in STAGE_SIZES:
        raise SystemExit(f"export supports the ResNet family only, got {arch!r}")
    state = resnet_to_torchvision(params, stats, stage_sizes=STAGE_SIZES[arch])

    fmt = args.format or ("torch" if args.output.endswith(".pth") else "d2")
    if fmt == "d2":
        save_detectron2_pickle(state, args.output)
    else:
        save_torch_state_dict(state, args.output)
    print(f"wrote {len(state)} tensors -> {args.output} ({fmt})")


if __name__ == "__main__":
    main()
