#!/usr/bin/env python
"""Detection fine-tuning harness — the reference's `detection/train_net.py`
(SURVEY.md §2.2 row 12, ~80 LoC): a thin Detectron2 `DefaultTrainer`
whose only customization is evaluator selection (PascalVOC vs COCO).

Runs on GPU with detectron2 installed (not in the TPU image — this file
is the bridge's far side; `convert_pretrain.py` produces the weights it
consumes)."""

from __future__ import annotations

import os

try:
    import detectron2.utils.comm as comm
    from detectron2.checkpoint import DetectionCheckpointer
    from detectron2.config import get_cfg
    from detectron2.engine import DefaultTrainer, default_argument_parser, default_setup, launch
    from detectron2.evaluation import COCOEvaluator, PascalVOCDetectionEvaluator
    from detectron2.layers import get_norm
except ImportError as e:  # pragma: no cover - detectron2 is GPU-side only
    raise SystemExit(
        "detectron2 is required for detection fine-tuning (GPU side). "
        "Install it per https://github.com/facebookresearch/detectron2 — "
        f"import failed with: {e}"
    )


class Trainer(DefaultTrainer):
    """DefaultTrainer + dataset-appropriate evaluator, as the reference."""

    @classmethod
    def build_evaluator(cls, cfg, dataset_name, output_folder=None):
        if output_folder is None:
            output_folder = os.path.join(cfg.OUTPUT_DIR, "inference")
        if "voc" in dataset_name:
            return PascalVOCDetectionEvaluator(dataset_name)
        return COCOEvaluator(dataset_name, output_dir=output_folder)


def setup(args):
    cfg = get_cfg()
    cfg.merge_from_file(args.config_file)
    cfg.merge_from_list(args.opts)
    cfg.freeze()
    default_setup(cfg, args)
    return cfg


def main(args):
    cfg = setup(args)
    if args.eval_only:
        model = Trainer.build_model(cfg)
        DetectionCheckpointer(model, save_dir=cfg.OUTPUT_DIR).resume_or_load(
            cfg.MODEL.WEIGHTS, resume=args.resume
        )
        return Trainer.test(cfg, model)
    trainer = Trainer(cfg)
    trainer.resume_or_load(resume=args.resume)
    return trainer.train()


if __name__ == "__main__":
    args = default_argument_parser().parse_args()
    launch(
        main,
        args.num_gpus,
        num_machines=args.num_machines,
        machine_rank=args.machine_rank,
        dist_url=args.dist_url,
        args=(args,),
    )
