// moco_tpu native data loader.
//
// TPU-native equivalent of the reference's DataLoader worker processes
// (`main_moco.py:~L255-260`: 32 fork'd workers doing PIL decode,
// SURVEY.md §3.4). Python threads around PIL leave decode throughput
// hostage to the GIL and per-image Python overhead; at the north-star
// rate (>2x 168 imgs/s/chip, multi-chip) the host input path must
// sustain thousands of decoded images per second. This library keeps
// the whole hot path in C++:
//
//   paths -> [worker threads: read file -> libjpeg/libpng decode ->
//             bilinear shortest-side resize -> center-crop to a fixed
//             S x S x 3 canvas] -> caller-provided contiguous batch
//
// The Python side (moco_tpu/data/native_loader.py, ctypes) hands in a
// batch of sample indices and a numpy uint8 buffer; workers fill it in
// parallel with zero Python involvement per image.
//
// C ABI (ctypes-friendly):
//   mtl_create(paths, n, canvas, threads) -> handle
//   mtl_load_batch(handle, indices, bs, out, status) -> 0 | error count
//       (decode -> shortest-side resize -> center-crop to canvas²)
//   mtl_get_dims(handle, indices, bs, dims) -> 0 | error count
//       (header-only original (h, w) per sample, cached — lets the host
//        sample torchvision-exact RandomResizedCrop boxes against the
//        ORIGINAL geometry, VERDICT r1 weak-item 6)
//   mtl_load_batch_crops(handle, indices, bs, boxes, n_crops, out_size,
//                        out, status) -> 0 | error count
//       (decode ONCE, then for each of n_crops boxes (y0,x0,ch,cw in
//        original coords) antialiased-resize the region to out_size² —
//        the two-crop pipeline's decode-once/crop-twice fast path)
//   mtl_create_raw(data_path, offsets, dims, n, canvas, threads) -> handle
//       (packed-RGB-cache backend, moco_tpu/data/cache.py: samples are
//        raw HWC uint8 blobs mmap'd from one file — same batch/crop/dims
//        surface as the path backend with the codec stage removed, and
//        crop+resize runs in these worker threads instead of PIL)
//   mtl_destroy(handle)
//   mtl_version() -> int

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

#include <csetjmp>
#include <fcntl.h>
#include <jpeglib.h>
#include <png.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ------------------------------------------------------------- decode

struct Image {
  std::vector<uint8_t> data;  // HWC, RGB
  int h = 0, w = 0;
};

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

bool decode_jpeg(const uint8_t* buf, size_t len, Image* out) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  out->w = cinfo.output_width;
  out->h = cinfo.output_height;
  out->data.resize(size_t(out->w) * out->h * 3);
  const size_t stride = size_t(out->w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data.data() + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

struct PngReadState {
  const uint8_t* data;
  size_t len, off;
};

void png_read_cb(png_structp png, png_bytep out, png_size_t n) {
  auto* s = static_cast<PngReadState*>(png_get_io_ptr(png));
  if (s->off + n > s->len) {
    png_error(png, "png: read past end");
    return;
  }
  memcpy(out, s->data + s->off, n);
  s->off += n;
}

bool decode_png(const uint8_t* buf, size_t len, Image* out) {
  if (len < 8 || png_sig_cmp(buf, 0, 8)) return false;
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) return false;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return false;
  }
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  PngReadState state{buf, len, 0};
  png_set_read_fn(png, &state, png_read_cb);
  png_read_info(png, info);
  // normalize everything to 8-bit RGB
  png_set_strip_16(png);
  png_set_palette_to_rgb(png);
  png_set_expand_gray_1_2_4_to_8(png);
  if (png_get_valid(png, info, PNG_INFO_tRNS)) png_set_tRNS_to_alpha(png);
  png_set_strip_alpha(png);
  png_set_gray_to_rgb(png);
  png_read_update_info(png, info);
  out->w = png_get_image_width(png, info);
  out->h = png_get_image_height(png, info);
  out->data.resize(size_t(out->w) * out->h * 3);
  std::vector<png_bytep> rows(out->h);
  const size_t stride = size_t(out->w) * 3;
  for (int y = 0; y < out->h; ++y) rows[y] = out->data.data() + y * stride;
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  return true;
}

bool decode_any(const uint8_t* buf, size_t len, Image* out) {
  if (len >= 3 && buf[0] == 0xFF && buf[1] == 0xD8) return decode_jpeg(buf, len, out);
  if (len >= 8 && !png_sig_cmp(buf, 0, 8)) return decode_png(buf, len, out);
  return decode_jpeg(buf, len, out) || decode_png(buf, len, out);
}

// Header-only (h, w): no pixel decode — feeds host-side RandomResizedCrop
// box sampling against the ORIGINAL image geometry.
bool peek_dims_jpeg(const uint8_t* buf, size_t len, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  *w = cinfo.image_width;
  *h = cinfo.image_height;
  jpeg_destroy_decompress(&cinfo);
  return true;
}

bool peek_dims_png(const uint8_t* buf, size_t len, int* h, int* w) {
  if (len < 8 || png_sig_cmp(buf, 0, 8)) return false;
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) return false;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return false;
  }
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  PngReadState state{buf, len, 0};
  png_set_read_fn(png, &state, png_read_cb);
  png_read_info(png, info);
  *w = png_get_image_width(png, info);
  *h = png_get_image_height(png, info);
  png_destroy_read_struct(&png, &info, nullptr);
  return true;
}

bool peek_dims(const uint8_t* buf, size_t len, int* h, int* w) {
  if (len >= 3 && buf[0] == 0xFF && buf[1] == 0xD8) return peek_dims_jpeg(buf, len, h, w);
  if (len >= 8 && !png_sig_cmp(buf, 0, 8)) return peek_dims_png(buf, len, h, w);
  return peek_dims_jpeg(buf, len, h, w) || peek_dims_png(buf, len, h, w);
}

// ------------------------------------------------- resize + crop

// PIL-style antialiased separable triangle (BILINEAR) resample along one
// axis: for downscale the filter support widens by the scale factor, so
// every source pixel inside the footprint contributes (PIL Resample.c
// semantics; a fixed 2-tap bilinear would alias on downscale and diverge
// from the Python/PIL path by ~15 gray levels).
struct ResampleWeights {
  std::vector<double> weights;  // flattened (out_size, max_taps)
  std::vector<int> bounds;      // (out_size, 2): xmin, count
  int max_taps = 0;
};

ResampleWeights triangle_weights(int in_size, int out_size) {
  ResampleWeights rw;
  const double scale = double(in_size) / out_size;
  const double filterscale = std::max(scale, 1.0);
  const double support = 1.0 * filterscale;  // triangle support = 1
  rw.max_taps = int(support * 2 + 1);
  rw.weights.assign(size_t(out_size) * rw.max_taps, 0.0);
  rw.bounds.assign(size_t(out_size) * 2, 0);
  for (int i = 0; i < out_size; ++i) {
    const double center = (i + 0.5) * scale;
    int xmin = std::max(0, int(center - support + 0.5));
    int xmax = std::min(in_size, int(center + support + 0.5));
    double total = 0.0;
    for (int x = xmin; x < xmax; ++x) {
      double arg = std::abs((x + 0.5 - center) / filterscale);
      double w = arg < 1.0 ? 1.0 - arg : 0.0;
      rw.weights[size_t(i) * rw.max_taps + (x - xmin)] = w;
      total += w;
    }
    if (total > 0)
      for (int x = xmin; x < xmax; ++x)
        rw.weights[size_t(i) * rw.max_taps + (x - xmin)] /= total;
    rw.bounds[i * 2] = xmin;
    rw.bounds[i * 2 + 1] = xmax - xmin;
  }
  return rw;
}

// Shortest-side antialiased resize to `canvas` then center-crop to
// (canvas, canvas) — the semantics of ImageFolderDataset.load
// (moco_tpu/data/datasets.py) with PIL BILINEAR.
void resize_center_crop(const Image& src, int canvas, uint8_t* out) {
  const double scale = double(canvas) / std::min(src.w, src.h);
  // lrint (ties-to-even) matches Python round() in datasets.py — a plain
  // int(x + 0.5) would diverge by 1px on exact-half products
  const int nw = std::max(canvas, int(std::lrint(src.w * scale)));
  const int nh = std::max(canvas, int(std::lrint(src.h * scale)));
  ResampleWeights wx = triangle_weights(src.w, nw);
  ResampleWeights wy = triangle_weights(src.h, nh);

  // horizontal pass: (h, w) -> (h, nw), float intermediate
  std::vector<float> tmp(size_t(src.h) * nw * 3);
  const size_t sstride = size_t(src.w) * 3;
  for (int y = 0; y < src.h; ++y) {
    const uint8_t* srow = src.data.data() + y * sstride;
    float* drow = tmp.data() + size_t(y) * nw * 3;
    for (int x = 0; x < nw; ++x) {
      const int xmin = wx.bounds[x * 2], cnt = wx.bounds[x * 2 + 1];
      const double* w = wx.weights.data() + size_t(x) * wx.max_taps;
      double acc[3] = {0, 0, 0};
      for (int k = 0; k < cnt; ++k) {
        const uint8_t* p = srow + size_t(xmin + k) * 3;
        acc[0] += w[k] * p[0];
        acc[1] += w[k] * p[1];
        acc[2] += w[k] * p[2];
      }
      drow[x * 3] = float(acc[0]);
      drow[x * 3 + 1] = float(acc[1]);
      drow[x * 3 + 2] = float(acc[2]);
    }
  }

  // vertical pass fused with the center crop: emit only canvas rows/cols
  const int x_off = (nw - canvas) / 2, y_off = (nh - canvas) / 2;
  for (int y = 0; y < canvas; ++y) {
    const int yy = y + y_off;
    const int ymin = wy.bounds[yy * 2], cnt = wy.bounds[yy * 2 + 1];
    const double* w = wy.weights.data() + size_t(yy) * wy.max_taps;
    uint8_t* drow = out + size_t(y) * canvas * 3;
    for (int x = 0; x < canvas; ++x) {
      const int xx = x + x_off;
      double acc[3] = {0, 0, 0};
      for (int k = 0; k < cnt; ++k) {
        const float* p = tmp.data() + (size_t(ymin + k) * nw + xx) * 3;
        acc[0] += w[k] * p[0];
        acc[1] += w[k] * p[1];
        acc[2] += w[k] * p[2];
      }
      for (int c = 0; c < 3; ++c) {
        double v = acc[c] + 0.5;
        drow[x * 3 + c] = uint8_t(v < 0 ? 0 : (v > 255 ? 255 : v));
      }
    }
  }
}

// Antialiased resample of the region [y0, y0+ch) x [x0, x0+cw) of `src`
// to (out_size, out_size) — crop-then-resize without materializing the
// crop (weights are built for the region size, bounds offset into the
// full image). Exactly torchvision's `resized_crop(img, box, out)` with
// PIL BILINEAR. Assumes the box is inside the image (the host clamps).
void resize_region(const Image& src, int y0, int x0, int ch, int cw, int out_size,
                   uint8_t* out) {
  ResampleWeights wx = triangle_weights(cw, out_size);
  ResampleWeights wy = triangle_weights(ch, out_size);

  // rows of the source needed by the vertical pass
  int r0 = src.h, r1 = 0;
  for (int y = 0; y < out_size; ++y) {
    const int ymin = y0 + wy.bounds[y * 2];
    r0 = std::min(r0, ymin);
    r1 = std::max(r1, ymin + wy.bounds[y * 2 + 1]);
  }
  r0 = std::max(0, r0);
  r1 = std::min(src.h, std::max(r1, r0 + 1));

  // horizontal pass: (r1-r0, w) -> (r1-r0, out_size)
  std::vector<float> tmp(size_t(r1 - r0) * out_size * 3);
  const size_t sstride = size_t(src.w) * 3;
  for (int y = r0; y < r1; ++y) {
    const uint8_t* srow = src.data.data() + y * sstride;
    float* drow = tmp.data() + size_t(y - r0) * out_size * 3;
    for (int x = 0; x < out_size; ++x) {
      const int xmin = x0 + wx.bounds[x * 2], cnt = wx.bounds[x * 2 + 1];
      const double* w = wx.weights.data() + size_t(x) * wx.max_taps;
      double acc[3] = {0, 0, 0};
      for (int k = 0; k < cnt; ++k) {
        const int xx = std::min(std::max(xmin + k, 0), src.w - 1);
        const uint8_t* p = srow + size_t(xx) * 3;
        acc[0] += w[k] * p[0];
        acc[1] += w[k] * p[1];
        acc[2] += w[k] * p[2];
      }
      drow[x * 3] = float(acc[0]);
      drow[x * 3 + 1] = float(acc[1]);
      drow[x * 3 + 2] = float(acc[2]);
    }
  }

  // vertical pass
  for (int y = 0; y < out_size; ++y) {
    const int ymin = y0 + wy.bounds[y * 2], cnt = wy.bounds[y * 2 + 1];
    const double* w = wy.weights.data() + size_t(y) * wy.max_taps;
    uint8_t* drow = out + size_t(y) * out_size * 3;
    for (int x = 0; x < out_size; ++x) {
      double acc[3] = {0, 0, 0};
      for (int k = 0; k < cnt; ++k) {
        const int yy = std::min(std::max(ymin + k, r0), r1 - 1) - r0;
        const float* p = tmp.data() + (size_t(yy) * out_size + x) * 3;
        acc[0] += w[k] * p[0];
        acc[1] += w[k] * p[1];
        acc[2] += w[k] * p[2];
      }
      for (int c = 0; c < 3; ++c) {
        double v = acc[c] + 0.5;
        drow[x * 3 + c] = uint8_t(v < 0 ? 0 : (v > 255 ? 255 : v));
      }
    }
  }
}

// --------------------------------------------------- thread pool

class Loader {
 public:
  Loader(std::vector<std::string> paths, int canvas, int threads)
      : paths_(std::move(paths)), canvas_(canvas), stop_(false) {
    start_workers(threads);
  }

  // Raw packed-RGB backend: blob i is dims[i*2] x dims[i*2+1] x 3 uint8
  // at byte offset offsets[i] of the mmap'd file. `ok_` stays false on
  // any mapping/consistency failure (caller destroys the handle).
  Loader(const char* data_path, const int64_t* offsets, const int32_t* dims,
         int64_t n, int canvas, int threads)
      : canvas_(canvas), stop_(false) {
    raw_mode_ = true;
    raw_offsets_.assign(offsets, offsets + n + 1);
    raw_dims_.assign(dims, dims + n * 2);
    int fd = open(data_path, O_RDONLY);
    if (fd < 0) return;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < raw_offsets_[n]) {
      close(fd);
      return;
    }
    raw_len_ = size_t(st.st_size);
    void* p = mmap(nullptr, raw_len_, PROT_READ, MAP_SHARED, fd, 0);
    close(fd);  // the mapping holds its own reference
    if (p == MAP_FAILED) return;
    raw_base_ = static_cast<const uint8_t*>(p);
    ok_ = true;
    start_workers(threads);
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
    if (raw_base_) munmap(const_cast<uint8_t*>(raw_base_), raw_len_);
  }

  // Path backend is always usable after construction; the raw backend
  // is only usable if its mapping succeeded (raw_mode_ distinguishes a
  // FAILED raw open — raw_base_ null — from the path backend).
  bool ok() const { return raw_mode_ ? ok_ : true; }

  enum class Mode { kCenterCrop, kCrops, kDims };

  struct BatchCtx {
    const int64_t* indices;
    int bs;
    uint8_t* out;
    uint8_t* status;  // per-slot: 1 = ok, 0 = failed (caller falls back)
    Mode mode = Mode::kCenterCrop;
    const int32_t* boxes = nullptr;  // (bs, n_crops, 4): y0, x0, ch, cw
    int n_crops = 0;
    int out_size = 0;
    int32_t* dims = nullptr;  // (bs, 2): h, w (kDims)
    std::atomic<int> next{0}, errors{0}, done{0};
  };

  // Fill out[(bs, canvas, canvas, 3)] with samples `indices`; returns the
  // number of failed loads (failed slots are zero-filled). The shared_ptr
  // keeps the batch context alive for any worker still draining it after
  // this call returns.
  int load_batch(const int64_t* indices, int bs, uint8_t* out, uint8_t* status) {
    auto ctx = std::make_shared<BatchCtx>();
    ctx->indices = indices;
    ctx->bs = bs;
    ctx->out = out;
    ctx->status = status;
    return run(ctx);
  }

  // out[(bs, n_crops, out_size, out_size, 3)]: decode each sample once,
  // resize each of its n_crops boxes.
  int load_batch_crops(const int64_t* indices, int bs, const int32_t* boxes,
                       int n_crops, int out_size, uint8_t* out, uint8_t* status) {
    auto ctx = std::make_shared<BatchCtx>();
    ctx->indices = indices;
    ctx->bs = bs;
    ctx->out = out;
    ctx->status = status;
    ctx->mode = Mode::kCrops;
    ctx->boxes = boxes;
    ctx->n_crops = n_crops;
    ctx->out_size = out_size;
    return run(ctx);
  }

  // dims[(bs, 2)] = original (h, w); header parse only, cached per path.
  int get_dims(const int64_t* indices, int bs, int32_t* dims, uint8_t* status) {
    auto ctx = std::make_shared<BatchCtx>();
    ctx->indices = indices;
    ctx->bs = bs;
    ctx->out = nullptr;
    ctx->status = status;
    ctx->mode = Mode::kDims;
    ctx->dims = dims;
    return run(ctx);
  }

  int canvas() const { return canvas_; }
  size_t size() const {
    return raw_base_ ? raw_dims_.size() / 2 : paths_.size();
  }

 private:
  int run(const std::shared_ptr<BatchCtx>& ctx) {
    // one batch at a time per handle: concurrent callers (e.g. a Python
    // thread pool mapping single-image loads) would otherwise race on
    // the batch_ slot
    std::lock_guard<std::mutex> batch_lk(batch_mu_);
    {
      std::lock_guard<std::mutex> lk(mu_);
      batch_ = ctx;
      batch_gen_++;
    }
    cv_.notify_all();
    run_batch(ctx);  // caller thread participates
    while (ctx->done.load() < ctx->bs) std::this_thread::yield();
    {
      std::lock_guard<std::mutex> lk(mu_);
      batch_ = nullptr;
    }
    return ctx->errors.load();
  }

  bool read_file(int64_t idx, std::vector<uint8_t>* buf) {
    if (idx < 0 || size_t(idx) >= paths_.size()) return false;
    FILE* f = fopen(paths_[idx].c_str(), "rb");
    if (!f) return false;
    fseek(f, 0, SEEK_END);
    long len = ftell(f);
    fseek(f, 0, SEEK_SET);
    buf->resize(len > 0 ? len : 0);
    const bool ok = len > 0 && fread(buf->data(), 1, len, f) == size_t(len);
    fclose(f);
    return ok;
  }

  // Raw backend: blob copy out of the mmap (a ~100 us memcpy, dwarfed by
  // the resize it feeds); path backend: read + codec decode.
  bool fetch_image(int64_t idx, Image* img) {
    if (raw_base_) {
      if (idx < 0 || size_t(idx) * 2 >= raw_dims_.size()) return false;
      const int h = raw_dims_[idx * 2], w = raw_dims_[idx * 2 + 1];
      const int64_t start = raw_offsets_[idx];
      const size_t count = size_t(h) * w * 3;
      if (h < 1 || w < 1 || start < 0 || start + int64_t(count) > int64_t(raw_len_))
        return false;
      img->h = h;
      img->w = w;
      img->data.assign(raw_base_ + start, raw_base_ + start + count);
      return true;
    }
    std::vector<uint8_t> buf;
    if (!read_file(idx, &buf)) return false;
    return decode_any(buf.data(), buf.size(), img) && img->w >= 1 && img->h >= 1;
  }

  bool load_one(int64_t idx, uint8_t* dst) {
    Image img;
    if (!fetch_image(idx, &img)) return false;
    resize_center_crop(img, canvas_, dst);
    return true;
  }

  bool load_one_crops(int64_t idx, const int32_t* boxes, int n_crops, int out_size,
                      uint8_t* dst) {
    Image img;
    if (!fetch_image(idx, &img)) return false;
    if (!raw_base_) {
      // opportunistically fill the dims cache (a later get_dims is
      // free); raw mode answers dims from its table — filling here
      // would only add hot-path lock traffic and unbounded map growth
      std::lock_guard<std::mutex> lk(dims_mu_);
      dims_cache_[idx] = {img.h, img.w};
    }
    const size_t frame = size_t(out_size) * out_size * 3;
    for (int c = 0; c < n_crops; ++c) {
      const int32_t* b = boxes + size_t(c) * 4;  // y0, x0, ch, cw
      int y0 = std::max(0, std::min(int(b[0]), img.h - 1));
      int x0 = std::max(0, std::min(int(b[1]), img.w - 1));
      int ch = std::max(1, std::min(int(b[2]), img.h - y0));
      int cw = std::max(1, std::min(int(b[3]), img.w - x0));
      resize_region(img, y0, x0, ch, cw, out_size, dst + frame * c);
    }
    return true;
  }

  bool dims_one(int64_t idx, int32_t* hw) {
    if (raw_base_) {
      if (idx < 0 || size_t(idx) * 2 >= raw_dims_.size()) return false;
      hw[0] = raw_dims_[idx * 2];
      hw[1] = raw_dims_[idx * 2 + 1];
      return hw[0] > 0 && hw[1] > 0;
    }
    {
      std::lock_guard<std::mutex> lk(dims_mu_);
      auto it = dims_cache_.find(idx);
      if (it != dims_cache_.end()) {
        hw[0] = it->second.first;
        hw[1] = it->second.second;
        return true;
      }
    }
    std::vector<uint8_t> buf;
    if (!read_file(idx, &buf)) return false;
    int h = 0, w = 0;
    if (!peek_dims(buf.data(), buf.size(), &h, &w) || h < 1 || w < 1) return false;
    hw[0] = h;
    hw[1] = w;
    std::lock_guard<std::mutex> lk(dims_mu_);
    dims_cache_[idx] = {h, w};
    return true;
  }

  void run_batch(const std::shared_ptr<BatchCtx>& ctx) {
    for (;;) {
      int i = ctx->next.fetch_add(1);
      if (i >= ctx->bs) break;
      bool ok = false;
      size_t frame = 0;
      uint8_t* dst = nullptr;
      switch (ctx->mode) {
        case Mode::kCenterCrop:
          frame = size_t(canvas_) * canvas_ * 3;
          dst = ctx->out + i * frame;
          ok = load_one(ctx->indices[i], dst);
          break;
        case Mode::kCrops:
          frame = size_t(ctx->out_size) * ctx->out_size * 3 * ctx->n_crops;
          dst = ctx->out + i * frame;
          ok = load_one_crops(ctx->indices[i],
                              ctx->boxes + size_t(i) * ctx->n_crops * 4,
                              ctx->n_crops, ctx->out_size, dst);
          break;
        case Mode::kDims:
          ok = dims_one(ctx->indices[i], ctx->dims + size_t(i) * 2);
          if (!ok) ctx->dims[size_t(i) * 2] = ctx->dims[size_t(i) * 2 + 1] = 0;
          break;
      }
      if (ctx->status) ctx->status[i] = ok ? 1 : 0;
      if (!ok) {
        if (dst) memset(dst, 0, frame);
        ctx->errors.fetch_add(1);
      }
      ctx->done.fetch_add(1);
    }
  }

  void worker() {
    uint64_t seen_gen = 0;
    for (;;) {
      std::shared_ptr<BatchCtx> ctx;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || (batch_ && batch_gen_ != seen_gen); });
        if (stop_) return;
        seen_gen = batch_gen_;
        ctx = batch_;
      }
      if (ctx) run_batch(ctx);
    }
  }

  void start_workers(int threads) {
    const int n = std::max(1, threads);
    for (int i = 0; i < n; ++i) workers_.emplace_back([this] { worker(); });
  }

  std::vector<std::string> paths_;
  bool raw_mode_ = false;              // packed-RGB backend requested
  const uint8_t* raw_base_ = nullptr;  // non-null once its mmap succeeded
  size_t raw_len_ = 0;
  std::vector<int64_t> raw_offsets_;
  std::vector<int32_t> raw_dims_;
  bool ok_ = false;
  int canvas_;
  std::mutex dims_mu_;
  std::unordered_map<int64_t, std::pair<int, int>> dims_cache_;  // idx -> (h, w)
  std::vector<std::thread> workers_;
  std::mutex batch_mu_;  // serializes load_batch callers
  std::mutex mu_;
  std::condition_variable cv_;
  std::shared_ptr<BatchCtx> batch_;
  uint64_t batch_gen_ = 0;
  bool stop_;
};

}  // namespace

extern "C" {

void* mtl_create(const char** paths, int64_t n, int canvas, int threads) {
  std::vector<std::string> v;
  v.reserve(n);
  for (int64_t i = 0; i < n; ++i) v.emplace_back(paths[i]);
  return new Loader(std::move(v), canvas, threads);
}

void* mtl_create_raw(const char* data_path, const int64_t* offsets,
                     const int32_t* dims, int64_t n, int canvas, int threads) {
  auto* l = new Loader(data_path, offsets, dims, n, canvas, threads);
  if (!l->ok()) {
    delete l;
    return nullptr;
  }
  return l;
}

int mtl_load_batch(void* handle, const int64_t* indices, int bs, uint8_t* out,
                   uint8_t* status) {
  return static_cast<Loader*>(handle)->load_batch(indices, bs, out, status);
}

int mtl_load_batch_crops(void* handle, const int64_t* indices, int bs,
                         const int32_t* boxes, int n_crops, int out_size,
                         uint8_t* out, uint8_t* status) {
  return static_cast<Loader*>(handle)->load_batch_crops(indices, bs, boxes, n_crops,
                                                        out_size, out, status);
}

int mtl_get_dims(void* handle, const int64_t* indices, int bs, int32_t* dims,
                 uint8_t* status) {
  return static_cast<Loader*>(handle)->get_dims(indices, bs, dims, status);
}

void mtl_destroy(void* handle) { delete static_cast<Loader*>(handle); }

int mtl_version() { return 4; }

}  // extern "C"
