#!/usr/bin/env python
"""Chaos smoke: prove the fault-tolerance layer end-to-end on synthetic
data (CPU, single device, a few minutes) — run by CI on every PR.

Legs (each worker is a fresh subprocess, like a real crash/restart):

  A. fault-free reference ........ 3 epochs, final checkpoint id = 3*spe
  B1. ckpt_truncate .............. 2 epochs; the LAST epoch's checkpoint
                                   write is truncated on disk (torn write)
  B2. resume through corruption .. 3 epochs; resume must quarantine the
      + transient loader IOError    corrupt step, fall back one interval,
      + one NaN loss step          retry the injected read, skip the NaN
                                   update — and still reach EXACTLY the
                                   fault-free final step count
  C1. stall + watchdog ........... a 120 s sleep is injected mid-epoch;
                                   the 6 s watchdog must dump stacks,
                                   write an emergency checkpoint, and
                                   exit with the stall code (42)
  C2. resume after stall ......... completes all epochs; the stall cost
                                   at most one checkpoint interval extra

Usage:
    bash scripts/chaos_smoke.sh          # or: python scripts/chaos_smoke.py
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

# scripts/ is not a package; make the repo root importable for both the
# orchestrator and the re-invoked workers
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.utils.contracts import STALL_EXIT_CODE  # noqa: E402

EPOCHS = 3
SPE = 2  # 32 synthetic examples / global batch 16


def worker(args: argparse.Namespace) -> None:
    """One training process (the unit a preemption/crash kills)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    # collective-free RNG lowering (see tests/conftest.py); single-device
    # CPU here, set for parity with the test harness
    jax.config.update("jax_threefry_partitionable", True)

    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train
    from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig

    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=32, mlp=True,
            shuffle="none", cifar_stem=True, compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=args.epochs, cos=True),
        data=DataConfig(
            dataset="synthetic", image_size=16, global_batch=16, num_workers=2
        ),
        workdir=args.workdir,
        log_every=1,
        watchdog_timeout=args.watchdog_timeout,
    )
    dataset = SyntheticDataset(num_examples=32, image_size=16)
    result = train(config, dataset=dataset)
    print(f"WORKER_RESULT {json.dumps(result)}")


def latest_step(workdir: str):
    from moco_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(workdir)
    step = mgr.latest_step()
    extra = mgr.read_extra() if step is not None else {}
    mgr.close()
    return step, extra


def run_leg(name, workdir, epochs, faults=None, watchdog=0.0, expect_rc=0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MOCO_FAULTS", None)
    if faults:
        env["MOCO_FAULTS"] = faults
    cmd = [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--workdir", workdir, "--epochs", str(epochs),
        "--watchdog-timeout", str(watchdog),
    ]
    print(f"\n=== {name}: epochs={epochs} faults={faults!r} watchdog={watchdog} ===")
    proc = subprocess.run(cmd, env=env, timeout=900)
    if proc.returncode != expect_rc:
        raise SystemExit(
            f"{name}: exit code {proc.returncode}, expected {expect_rc}"
        )
    print(f"=== {name}: exit {proc.returncode} (expected) ===")


def check(cond, msg):
    if not cond:
        raise SystemExit(f"CHAOS SMOKE FAILED: {msg}")
    print(f"ok: {msg}")


def orchestrate(base: str) -> None:
    a, b, c = (os.path.join(base, d) for d in ("fault_free", "chaos", "stall"))

    # A. fault-free reference
    run_leg("A fault-free", a, EPOCHS)
    steps_a, extra_a = latest_step(a)
    check(steps_a == EPOCHS * SPE, f"fault-free run reached step {steps_a} == {EPOCHS * SPE}")
    check(extra_a["epoch"] == EPOCHS - 1, "fault-free run completed all epochs")

    # B1: truncate the final (epoch-1) checkpoint write
    run_leg("B1 ckpt_truncate", b, EPOCHS - 1, faults=f"ckpt_truncate@step={(EPOCHS - 1) * SPE}")
    # B2: resume through the corruption, plus a transient loader error
    # and one NaN step during the redone epochs
    run_leg(
        "B2 resume+io+nan", b, EPOCHS,
        faults=f"io@site=data.read:at=2,nan@step={(EPOCHS - 1) * SPE + 1}",
    )
    steps_b, extra_b = latest_step(b)
    check(
        os.path.isdir(os.path.join(b, "quarantine")),
        "corrupt checkpoint was quarantined, not fatal",
    )
    check(extra_b["epoch"] == EPOCHS - 1, "chaos run completed all epochs")
    check(
        steps_b == steps_a,
        f"chaos final step {steps_b} == fault-free final step {steps_a}",
    )
    metrics = [json.loads(l) for l in open(os.path.join(b, "metrics.jsonl"))]
    check(
        any(m.get("event") == "nonfinite_loss" for m in metrics),
        "NaN step was counted in metrics.jsonl",
    )
    check(
        any(m.get("io_retries") for m in metrics),
        "loader retry was surfaced in metrics.jsonl",
    )

    # C1: stall mid-epoch; the watchdog must kill the process nonzero
    # after an emergency checkpoint (stall >> watchdog timeout)
    # watchdog 20 s: far above a healthy CPU step (~seconds) so no false
    # fire, far below the 120 s injected stall so the leg stays fast
    run_leg(
        "C1 stall+watchdog", c, EPOCHS,
        faults="stall@step=3:seconds=120", watchdog=20.0,
        expect_rc=STALL_EXIT_CODE,
    )
    steps_c1, _ = latest_step(c)
    check(steps_c1 is not None, "watchdog wrote an emergency checkpoint")
    check(
        os.path.exists(os.path.join(c, "stall_stacks.txt")),
        "watchdog dumped all-thread stacks",
    )
    # C2: resume to completion
    run_leg("C2 resume after stall", c, EPOCHS)
    steps_c2, extra_c = latest_step(c)
    check(extra_c["epoch"] == EPOCHS - 1, "post-stall resume completed all epochs")
    check(
        0 <= steps_c2 - steps_a <= SPE,
        f"stall cost {steps_c2 - steps_a} extra steps <= one interval ({SPE})",
    )

    print("\nCHAOS SMOKE PASSED")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--worker", action="store_true")
    p.add_argument("--workdir", default=None)
    p.add_argument("--epochs", type=int, default=EPOCHS)
    p.add_argument("--watchdog-timeout", type=float, default=0.0)
    args = p.parse_args()
    if args.worker:
        worker(args)
        return
    base = args.workdir or tempfile.mkdtemp(prefix="chaos_smoke_")
    print(f"chaos smoke workdir: {base}")
    orchestrate(base)


if __name__ == "__main__":
    main()
