# Shared TPU-battery helper: block until the accelerator backend is
# usable. Sourced by scripts/tpu_battery_r4b.sh and
# scripts/tpu_chains_r4.sh (callers set $L to their log dir first).
#
# The probe (moco_tpu.utils.platform.backend_usable) runs jax.devices()
# in a SUBPROCESS with a timeout and ABANDONS it on expiry — never
# kills it: SIGKILLing a TPU client mid-init wedges the chip lease for
# 1h+ (the round-4 battery incident). Waiting here instead of burning
# leg timeouts against a wedged lease is what lets a battery survive
# tunnel outages.
wait_backend() {
  until python - <<'EOF'
import sys
sys.path.insert(0, ".")
from moco_tpu.utils.platform import backend_usable
sys.exit(0 if backend_usable(timeout=150) else 1)
EOF
  do
    echo "backend not usable; waiting 180s ($(date +%H:%M:%S))" | tee -a "$L/battery_wait.log"
    sleep 180
  done
}
