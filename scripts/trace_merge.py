#!/usr/bin/env python
"""Merge per-process span streams into ONE Perfetto trace.

    python scripts/trace_merge.py WORKDIR [--output merged_trace.json]

A multi-process run writes one `trace_events.jsonl` per process
(`trace_events.p<i>.jsonl` for process > 0) and one heartbeat file per
process. Each stream's timestamps are relative to ITS tracer's start —
on a pod the processes start seconds apart, so naive concatenation
skews every track. This tool:

1. discovers every per-process span stream under the workdir (the
   records carry the process index in `p`; the filename is a fallback);
2. reads the heartbeats' `trace_wall_t0` wall-clock anchors and shifts
   each process's timestamps by `(wall_t0_p - min(wall_t0)) * 1e6` µs —
   clock-offset correction, so "step 40 on host 3" lines up under
   "step 40 on host 0" in the merged view;
3. emits one Chrome trace-event JSON with pid = process index, a
   `process_name` track label per host (hostname from the heartbeat
   when known), and every thread preserved.

Serving replicas join the same timeline (PR 10): a `ServeServer`
given a workdir streams its request spans (obs/reqtrace.py waterfalls
on virtual "requests" lanes) to `trace_events.s<replica>.jsonl` with a
`heartbeat.s<replica>.json` wall anchor. Those streams merge with
pid = `SERVE_PID_BASE + replica` (offset so a serve replica co-hosted
with training process 0 gets its own track group) against the SAME
clock origin — so "the p99 request on replica 2" lines up under "step
40 on host 0" and a balanced fleet stays debuggable. Fleet replicas
spawned by serve/fleet.py keep their streams in per-replica workdirs
(`<workdir>/replica<i>/`); discovery looks one level deep for them.

The fleet ROUTER (serve/router.py, PR 18) joins as its own track group
at pid = `ROUTER_PID_BASE + router_index` from
`trace_events.r<i>.jsonl` + `heartbeat.r<i>.json`, clock-corrected the
same way. Router dispatch-attempt spans carry the propagated span ids
(obs/ctxprop.py), so the merge also emits Chrome FLOW events
(`ph:"s"`/`ph:"f"`, one per attempt→replica-request pair joined on
`X-Parent-Span`): Perfetto draws the cross-process arrow from each
router attempt into the replica request it carried.

`stitch_traces()` is the offline twin of the router's in-band
stitching: it joins the router + replica streams by trace id (clock-
aligned via the heartbeat anchors) into one obs/critpath.py
stitched-trace record per request — what the fleet smoke gates on.

Open the output in https://ui.perfetto.dev — one track group per host
plus one per serving replica and one per router. A process with no
heartbeat (it died before its first beat, or a pre-fleet run) merges
with zero offset and a warning in `otherData`.

Needs only the stdlib + moco_tpu.obs (no jax), so it runs wherever the
files were copied.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from moco_tpu.obs.fleet import read_heartbeats  # noqa: E402
from moco_tpu.obs.trace import spans_to_chrome_events  # noqa: E402

_PROC_RE = re.compile(r"trace_events\.p(\d+)\.jsonl$")
_SERVE_RE = re.compile(r"trace_events\.s(\d+)\.jsonl$")
_ROUTER_RE = re.compile(r"trace_events\.r(\d+)\.jsonl$")

# Serving-replica track-group offset: replica i renders as pid
# SERVE_PID_BASE + i, clear of any plausible training host index.
SERVE_PID_BASE = 100
# The fleet router's track group, clear of the replica band.
ROUTER_PID_BASE = 200


def discover_streams(workdir: str) -> dict[int, str]:
    """{process_index: span-stream path} for every per-process stream
    under `workdir`. `trace_events.jsonl` is process 0."""
    streams: dict[int, str] = {}
    base = os.path.join(workdir, "trace_events.jsonl")
    if os.path.exists(base):
        streams[0] = base
    for path in glob.glob(os.path.join(workdir, "trace_events.p*.jsonl")):
        m = _PROC_RE.search(path)
        if m:
            streams[int(m.group(1))] = path
    return streams


def _glob_shallow_and_one_deep(workdir: str, pattern: str) -> list[str]:
    """Matches at the workdir top level plus one directory deep — fleet
    replicas (serve/fleet.py) keep their files in
    `<workdir>/replica<i>/`."""
    return glob.glob(os.path.join(workdir, pattern)) + glob.glob(
        os.path.join(workdir, "*", pattern)
    )


def discover_serve_streams(workdir: str) -> dict[int, str]:
    """{replica_index: span-stream path} for every serving replica's
    `trace_events.s<i>.jsonl` under `workdir` (top level or one
    subdirectory deep)."""
    streams: dict[int, str] = {}
    for path in _glob_shallow_and_one_deep(workdir, "trace_events.s*.jsonl"):
        m = _SERVE_RE.search(path)
        if m:
            streams[int(m.group(1))] = path
    return streams


def discover_router_streams(workdir: str) -> dict[int, str]:
    """{router_index: span-stream path} for every fleet router's
    `trace_events.r<i>.jsonl` under `workdir`."""
    streams: dict[int, str] = {}
    for path in _glob_shallow_and_one_deep(workdir, "trace_events.r*.jsonl"):
        m = _ROUTER_RE.search(path)
        if m:
            streams[int(m.group(1))] = path
    return streams


def read_serve_anchors(workdir: str) -> dict[int, dict]:
    """{replica_index: anchor record} from the per-replica
    `heartbeat.s<i>.json` files ServeServer writes (same shape as the
    fleet heartbeats, plus role="serve"); unparseable files skipped."""
    out: dict[int, dict] = {}
    for path in _glob_shallow_and_one_deep(workdir, "heartbeat.s*.json"):
        try:
            with open(path) as f:
                rec = json.load(f)
            out[int(rec["process"])] = rec
        except (ValueError, KeyError, OSError):
            continue
    return out


def read_router_anchors(workdir: str) -> dict[int, dict]:
    """{router_index: anchor record} from the `heartbeat.r<i>.json`
    files FleetRouter writes (role="router")."""
    out: dict[int, dict] = {}
    for path in _glob_shallow_and_one_deep(workdir, "heartbeat.r*.json"):
        try:
            with open(path) as f:
                rec = json.load(f)
            if rec.get("role") == "router":
                out[int(rec["process"])] = rec
        except (ValueError, KeyError, OSError):
            continue
    return out


def read_spans(path: str) -> list[dict]:
    """Parsed span records; a truncated tail line (crash mid-write) is
    skipped, not fatal — merging a crashed run is the point."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def _flow_events(attempt_spans: list[dict], request_spans: list[dict]) -> list[dict]:
    """Chrome flow events linking each router `router/attempt` span to
    the replica `request` span it dispatched — joined on the propagated
    span id (the attempt's `span_id` arrives at the replica as
    `X-Parent-Span` and comes back in its request span's
    `parent_span`). Perfetto renders one arrow per pair.

    Inputs are pre-positioned events: each carries the clock-corrected
    `ts` plus the pid/tid of the track it renders on."""
    by_parent: dict[str, dict] = {}
    for ev in request_spans:
        parent = (ev.get("args") or {}).get("parent_span")
        if parent:
            by_parent[parent] = ev
    flows: list[dict] = []
    for ev in attempt_spans:
        span_id = (ev.get("args") or {}).get("span_id")
        target = by_parent.get(span_id)
        if target is None:
            continue
        common = {"name": "dispatch", "cat": "fleet", "id": span_id}
        flows.append(
            {**common, "ph": "s", "pid": ev["pid"], "tid": ev["tid"], "ts": ev["ts"]}
        )
        flows.append(
            {
                **common,
                "ph": "f",
                "bp": "e",
                "pid": target["pid"],
                "tid": target["tid"],
                "ts": target["ts"],
            }
        )
    return flows


def merge_traces(workdir: str, output: str) -> dict:
    """Merge every per-process span stream under `workdir` into one
    Chrome trace at `output`; returns a summary dict (process count,
    span counts, applied offsets)."""
    streams = discover_streams(workdir)
    serve_streams = discover_serve_streams(workdir)
    router_streams = discover_router_streams(workdir)
    if not streams and not serve_streams and not router_streams:
        raise FileNotFoundError(f"no trace_events*.jsonl under {workdir}")
    beats = read_heartbeats(workdir)
    serve_beats = read_serve_anchors(workdir)
    router_beats = read_router_anchors(workdir)
    anchors = {
        p: rec["trace_wall_t0"]
        for p, rec in beats.items()
        if isinstance(rec.get("trace_wall_t0"), (int, float))
    }
    serve_anchors = {
        r: rec["trace_wall_t0"]
        for r, rec in serve_beats.items()
        if isinstance(rec.get("trace_wall_t0"), (int, float))
    }
    router_anchors = {
        r: rec["trace_wall_t0"]
        for r, rec in router_beats.items()
        if isinstance(rec.get("trace_wall_t0"), (int, float))
    }
    # ONE clock origin across training hosts, serving replicas AND the
    # fleet router, so a request span lines up under the training step
    # it rode alongside and the router->replica arrows point forward
    all_anchors = (
        list(anchors.values())
        + list(serve_anchors.values())
        + list(router_anchors.values())
    )
    origin = min(all_anchors) if all_anchors else 0.0
    events: list[dict] = []
    summary = {"processes": {}, "serve_replicas": {}, "routers": {}, "unanchored": []}
    # positioned spans collected for the flow-event join
    attempt_events: list[dict] = []
    request_events: list[dict] = []
    for p in sorted(streams):
        spans = read_spans(streams[p])
        offset_us = (anchors[p] - origin) * 1e6 if p in anchors else 0.0
        if p not in anchors:
            summary["unanchored"].append(p)
        host = beats.get(p, {}).get("host")
        name = f"host {p}" + (f" ({host})" if host else "")
        events.extend(
            spans_to_chrome_events(
                spans, pid=p, process_name=name, ts_offset_us=offset_us
            )
        )
        summary["processes"][p] = {
            "spans": len(spans),
            "offset_us": round(offset_us, 1),
            "host": host,
        }
    for r in sorted(serve_streams):
        spans = read_spans(serve_streams[r])
        offset_us = (serve_anchors[r] - origin) * 1e6 if r in serve_anchors else 0.0
        if r not in serve_anchors:
            summary["unanchored"].append(f"s{r}")
        host = serve_beats.get(r, {}).get("host")
        name = f"serve replica {r}" + (f" ({host})" if host else "")
        chrome = spans_to_chrome_events(
            spans,
            pid=SERVE_PID_BASE + r,
            process_name=name,
            ts_offset_us=offset_us,
        )
        events.extend(chrome)
        request_events.extend(
            ev
            for ev in chrome
            if ev.get("ph") == "X"
            and ev.get("name") == "request"
            and (ev.get("args") or {}).get("parent_span")
        )
        summary["serve_replicas"][r] = {
            "spans": len(spans),
            "offset_us": round(offset_us, 1),
            "host": host,
        }
    for r in sorted(router_streams):
        spans = read_spans(router_streams[r])
        offset_us = (router_anchors[r] - origin) * 1e6 if r in router_anchors else 0.0
        if r not in router_anchors:
            summary["unanchored"].append(f"r{r}")
        host = router_beats.get(r, {}).get("host")
        name = f"fleet router {r}" + (f" ({host})" if host else "")
        chrome = spans_to_chrome_events(
            spans,
            pid=ROUTER_PID_BASE + r,
            process_name=name,
            ts_offset_us=offset_us,
        )
        events.extend(chrome)
        attempt_events.extend(
            ev
            for ev in chrome
            if ev.get("ph") == "X" and ev.get("name") == "router/attempt"
        )
        summary["routers"][r] = {
            "spans": len(spans),
            "offset_us": round(offset_us, 1),
            "host": host,
        }
    flows = _flow_events(attempt_events, request_events)
    events.extend(flows)
    summary["flow_events"] = len(flows) // 2
    meta = {
        "merged_from": len(streams) + len(serve_streams) + len(router_streams),
        "serve_replicas": sorted(serve_streams),
        "routers": sorted(router_streams),
        "flow_pairs": len(flows) // 2,
        "clock_origin_wall": origin,
        "unanchored_processes": summary["unanchored"],
    }
    os.makedirs(os.path.dirname(os.path.abspath(output)), exist_ok=True)
    with open(output, "w") as f:
        json.dump(
            {"traceEvents": events, "displayTimeUnit": "ms", "otherData": meta}, f
        )
    summary["output"] = output
    return summary


# router stage spans -> the stitched record's `router` section keys
# (span names, not metric keys — derived so the metric-schema pass
# doesn't read the table as a payload emission)
_ROUTER_STAGE_KEYS = {
    "router/" + stage: stage + "_ms"
    for stage in ("ingress", "admission", "respond")
}


def stitch_traces(workdir: str) -> dict[str, dict]:
    """Join the router + replica span streams by trace id into one
    obs/critpath.py stitched-trace record per request (see that module's
    docstring for the schema) — the OFFLINE twin of the router's in-band
    stitching, built purely from the on-disk artifacts.

    Clock alignment is the heartbeat-anchor correction merge_traces
    applies: every timestamp shifts into one wall origin, so the network
    split (`net_send_ms`/`net_recv_ms`) falls out of the aligned gap
    between a router attempt span and the replica request span it
    dispatched (joined on the propagated span id). Attempts whose
    replica stream never recorded a request span (the replica died, or
    the attempt failed before dispatch completed) keep `remote: None` —
    the stitch is partial, not absent.

    Returns {trace_id: stitched record}."""
    router_streams = discover_router_streams(workdir)
    serve_streams = discover_serve_streams(workdir)
    router_beats = read_router_anchors(workdir)
    serve_beats = read_serve_anchors(workdir)
    router_anchors = {
        r: rec["trace_wall_t0"]
        for r, rec in router_beats.items()
        if isinstance(rec.get("trace_wall_t0"), (int, float))
    }
    serve_anchors = {
        r: rec["trace_wall_t0"]
        for r, rec in serve_beats.items()
        if isinstance(rec.get("trace_wall_t0"), (int, float))
    }
    all_anchors = list(router_anchors.values()) + list(serve_anchors.values())
    origin = min(all_anchors) if all_anchors else 0.0

    # -- replica side: request spans keyed by the propagated parent span
    remote_by_parent: dict[str, dict] = {}
    for r in sorted(serve_streams):
        offset_us = (serve_anchors[r] - origin) * 1e6 if r in serve_anchors else 0.0
        reqs: dict[str, tuple] = {}
        stage_spans: dict[str, list] = {}
        for s in read_spans(serve_streams[r]):
            args = s.get("args") or {}
            ts = float(s.get("ts") or 0.0) + offset_us
            name = s.get("name") or ""
            if name == "request" and args.get("parent_span"):
                reqs[args.get("request_id")] = (ts, float(s.get("dur") or 0.0), args)
            elif name.startswith("req/") and args.get("request_id"):
                stage_spans.setdefault(args["request_id"], []).append(
                    (name[len("req/"):], ts, float(s.get("dur") or 0.0))
                )
        for rid, (ts, dur, args) in reqs.items():
            remote_by_parent[args["parent_span"]] = {
                "request_id": rid,
                "replica": r,
                "span_id": args.get("span_id"),
                "ts_us": ts,
                "total_ms": dur / 1e3,
                "stages": [
                    {
                        "stage": stage,
                        "start_ms": round((sts - ts) / 1e3, 3),
                        "dur_ms": round(sdur / 1e3, 3),
                    }
                    for stage, sts, sdur in sorted(
                        stage_spans.get(rid, ()), key=lambda x: x[1]
                    )
                ],
            }

    # -- router side: one stitched record per request span, attempts
    #    joined to the replica requests they dispatched
    stitched: dict[str, dict] = {}
    for i in sorted(router_streams):
        offset_us = (router_anchors[i] - origin) * 1e6 if i in router_anchors else 0.0
        reqs = {}
        stage_ms: dict[str, dict] = {}
        attempt_spans: dict[str, list] = {}
        for s in read_spans(router_streams[i]):
            args = s.get("args") or {}
            trace_id = args.get("trace_id")
            if not trace_id:
                continue
            ts = float(s.get("ts") or 0.0) + offset_us
            dur = float(s.get("dur") or 0.0)
            name = s.get("name")
            if name == "request":
                reqs[trace_id] = (ts, dur, args)
            elif name in _ROUTER_STAGE_KEYS:
                key = _ROUTER_STAGE_KEYS[name]
                d = stage_ms.setdefault(trace_id, {})
                d[key] = d.get(key, 0.0) + dur / 1e3
            elif name == "router/attempt":
                attempt_spans.setdefault(trace_id, []).append((ts, dur, args))
        for trace_id, (ts, dur, args) in reqs.items():
            attempts = []
            for ats, adur, aargs in sorted(
                attempt_spans.get(trace_id, ()), key=lambda x: x[0]
            ):
                att = {
                    "span_id": aargs.get("span_id"),
                    "replica": aargs.get("replica"),
                    "retry_index": aargs.get("retry_index"),
                    "lane": aargs.get("lane"),
                    "breaker": aargs.get("breaker"),
                    "outcome": aargs.get("outcome"),
                    "winner": bool(aargs.get("winner")),
                    "start_ms": round((ats - ts) / 1e3, 3),
                    "dur_ms": round(adur / 1e3, 3),
                    "net_send_ms": None,
                    "net_recv_ms": None,
                    "wasted_ms": aargs.get("wasted_ms"),
                    "error": aargs.get("error"),
                    "remote": None,
                }
                remote = remote_by_parent.get(att["span_id"])
                if remote is not None:
                    # clock-aligned network split: dispatch-to-replica-
                    # ingress gap is send, the attempt's tail past the
                    # replica's own wall is receive
                    send = max(0.0, (remote["ts_us"] - ats) / 1e3)
                    recv = max(0.0, adur / 1e3 - send - remote["total_ms"])
                    att["net_send_ms"] = round(send, 3)
                    att["net_recv_ms"] = round(recv, 3)
                    att["remote"] = {
                        "request_id": remote["request_id"],
                        "replica": remote["replica"],
                        "span_id": remote["span_id"],
                        "stages": remote["stages"],
                    }
                attempts.append(att)
            rounded = {
                k: round(v, 3) for k, v in stage_ms.get(trace_id, {}).items()
            }
            stitched[trace_id] = {
                "trace_id": trace_id,
                "request_id": args.get("request_id"),
                "path": args.get("path"),
                "status": args.get("status"),
                "wall_t0": origin + ts / 1e6,
                "total_ms": round(dur / 1e3, 3),
                "router": rounded,
                "attempts": attempts,
            }
    return stitched


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("workdir", help="run workdir holding trace_events*.jsonl (+ heartbeats)")
    ap.add_argument(
        "--output", "-o", default=None,
        help="merged trace path (default: <workdir>/merged_trace.json)",
    )
    args = ap.parse_args()
    output = args.output or os.path.join(args.workdir, "merged_trace.json")
    try:
        summary = merge_traces(args.workdir, output)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for p, info in sorted(summary["processes"].items()):
        host = f" host={info['host']}" if info["host"] else ""
        print(
            f"process {p}: {info['spans']} spans, clock offset "
            f"{info['offset_us'] / 1e3:.1f} ms{host}"
        )
    for r, info in sorted(summary.get("serve_replicas", {}).items()):
        host = f" host={info['host']}" if info["host"] else ""
        print(
            f"serve replica {r} (pid {SERVE_PID_BASE + r}): {info['spans']} "
            f"spans, clock offset {info['offset_us'] / 1e3:.1f} ms{host}"
        )
    for r, info in sorted(summary.get("routers", {}).items()):
        host = f" host={info['host']}" if info["host"] else ""
        print(
            f"fleet router {r} (pid {ROUTER_PID_BASE + r}): {info['spans']} "
            f"spans, clock offset {info['offset_us'] / 1e3:.1f} ms{host}"
        )
    if summary.get("flow_events"):
        print(f"linked {summary['flow_events']} router attempt -> replica request flows")
    if summary["unanchored"]:
        print(
            f"warning: no heartbeat clock anchor for processes "
            f"{summary['unanchored']} — merged with zero offset",
            file=sys.stderr,
        )
    print(f"wrote {output} (open in https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
