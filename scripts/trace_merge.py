#!/usr/bin/env python
"""Merge per-process span streams into ONE Perfetto trace.

    python scripts/trace_merge.py WORKDIR [--output merged_trace.json]

A multi-process run writes one `trace_events.jsonl` per process
(`trace_events.p<i>.jsonl` for process > 0) and one heartbeat file per
process. Each stream's timestamps are relative to ITS tracer's start —
on a pod the processes start seconds apart, so naive concatenation
skews every track. This tool:

1. discovers every per-process span stream under the workdir (the
   records carry the process index in `p`; the filename is a fallback);
2. reads the heartbeats' `trace_wall_t0` wall-clock anchors and shifts
   each process's timestamps by `(wall_t0_p - min(wall_t0)) * 1e6` µs —
   clock-offset correction, so "step 40 on host 3" lines up under
   "step 40 on host 0" in the merged view;
3. emits one Chrome trace-event JSON with pid = process index, a
   `process_name` track label per host (hostname from the heartbeat
   when known), and every thread preserved.

Serving replicas join the same timeline (PR 10): a `ServeServer`
given a workdir streams its request spans (obs/reqtrace.py waterfalls
on virtual "requests" lanes) to `trace_events.s<replica>.jsonl` with a
`heartbeat.s<replica>.json` wall anchor. Those streams merge with
pid = `SERVE_PID_BASE + replica` (offset so a serve replica co-hosted
with training process 0 gets its own track group) against the SAME
clock origin — so "the p99 request on replica 2" lines up under "step
40 on host 0" and a balanced fleet stays debuggable.

Open the output in https://ui.perfetto.dev — one track group per host
plus one per serving replica. A process with no heartbeat (it died
before its first beat, or a pre-fleet run) merges with zero offset and
a warning in `otherData`.

Needs only the stdlib + moco_tpu.obs (no jax), so it runs wherever the
files were copied.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from moco_tpu.obs.fleet import read_heartbeats  # noqa: E402
from moco_tpu.obs.trace import spans_to_chrome_events  # noqa: E402

_PROC_RE = re.compile(r"trace_events\.p(\d+)\.jsonl$")
_SERVE_RE = re.compile(r"trace_events\.s(\d+)\.jsonl$")

# Serving-replica track-group offset: replica i renders as pid
# SERVE_PID_BASE + i, clear of any plausible training host index.
SERVE_PID_BASE = 100


def discover_streams(workdir: str) -> dict[int, str]:
    """{process_index: span-stream path} for every per-process stream
    under `workdir`. `trace_events.jsonl` is process 0."""
    streams: dict[int, str] = {}
    base = os.path.join(workdir, "trace_events.jsonl")
    if os.path.exists(base):
        streams[0] = base
    for path in glob.glob(os.path.join(workdir, "trace_events.p*.jsonl")):
        m = _PROC_RE.search(path)
        if m:
            streams[int(m.group(1))] = path
    return streams


def discover_serve_streams(workdir: str) -> dict[int, str]:
    """{replica_index: span-stream path} for every serving replica's
    `trace_events.s<i>.jsonl` under `workdir`."""
    streams: dict[int, str] = {}
    for path in glob.glob(os.path.join(workdir, "trace_events.s*.jsonl")):
        m = _SERVE_RE.search(path)
        if m:
            streams[int(m.group(1))] = path
    return streams


def read_serve_anchors(workdir: str) -> dict[int, dict]:
    """{replica_index: anchor record} from the per-replica
    `heartbeat.s<i>.json` files ServeServer writes (same shape as the
    fleet heartbeats, plus role="serve"); unparseable files skipped."""
    out: dict[int, dict] = {}
    for path in glob.glob(os.path.join(workdir, "heartbeat.s*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
            out[int(rec["process"])] = rec
        except (ValueError, KeyError, OSError):
            continue
    return out


def read_spans(path: str) -> list[dict]:
    """Parsed span records; a truncated tail line (crash mid-write) is
    skipped, not fatal — merging a crashed run is the point."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def merge_traces(workdir: str, output: str) -> dict:
    """Merge every per-process span stream under `workdir` into one
    Chrome trace at `output`; returns a summary dict (process count,
    span counts, applied offsets)."""
    streams = discover_streams(workdir)
    serve_streams = discover_serve_streams(workdir)
    if not streams and not serve_streams:
        raise FileNotFoundError(f"no trace_events*.jsonl under {workdir}")
    beats = read_heartbeats(workdir)
    serve_beats = read_serve_anchors(workdir)
    anchors = {
        p: rec["trace_wall_t0"]
        for p, rec in beats.items()
        if isinstance(rec.get("trace_wall_t0"), (int, float))
    }
    serve_anchors = {
        r: rec["trace_wall_t0"]
        for r, rec in serve_beats.items()
        if isinstance(rec.get("trace_wall_t0"), (int, float))
    }
    # ONE clock origin across training hosts AND serving replicas, so a
    # request span lines up under the training step it rode alongside
    all_anchors = list(anchors.values()) + list(serve_anchors.values())
    origin = min(all_anchors) if all_anchors else 0.0
    events: list[dict] = []
    summary = {"processes": {}, "serve_replicas": {}, "unanchored": []}
    for p in sorted(streams):
        spans = read_spans(streams[p])
        offset_us = (anchors[p] - origin) * 1e6 if p in anchors else 0.0
        if p not in anchors:
            summary["unanchored"].append(p)
        host = beats.get(p, {}).get("host")
        name = f"host {p}" + (f" ({host})" if host else "")
        events.extend(
            spans_to_chrome_events(
                spans, pid=p, process_name=name, ts_offset_us=offset_us
            )
        )
        summary["processes"][p] = {
            "spans": len(spans),
            "offset_us": round(offset_us, 1),
            "host": host,
        }
    for r in sorted(serve_streams):
        spans = read_spans(serve_streams[r])
        offset_us = (serve_anchors[r] - origin) * 1e6 if r in serve_anchors else 0.0
        if r not in serve_anchors:
            summary["unanchored"].append(f"s{r}")
        host = serve_beats.get(r, {}).get("host")
        name = f"serve replica {r}" + (f" ({host})" if host else "")
        events.extend(
            spans_to_chrome_events(
                spans,
                pid=SERVE_PID_BASE + r,
                process_name=name,
                ts_offset_us=offset_us,
            )
        )
        summary["serve_replicas"][r] = {
            "spans": len(spans),
            "offset_us": round(offset_us, 1),
            "host": host,
        }
    meta = {
        "merged_from": len(streams) + len(serve_streams),
        "serve_replicas": sorted(serve_streams),
        "clock_origin_wall": origin,
        "unanchored_processes": summary["unanchored"],
    }
    os.makedirs(os.path.dirname(os.path.abspath(output)), exist_ok=True)
    with open(output, "w") as f:
        json.dump(
            {"traceEvents": events, "displayTimeUnit": "ms", "otherData": meta}, f
        )
    summary["output"] = output
    return summary


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("workdir", help="run workdir holding trace_events*.jsonl (+ heartbeats)")
    ap.add_argument(
        "--output", "-o", default=None,
        help="merged trace path (default: <workdir>/merged_trace.json)",
    )
    args = ap.parse_args()
    output = args.output or os.path.join(args.workdir, "merged_trace.json")
    try:
        summary = merge_traces(args.workdir, output)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for p, info in sorted(summary["processes"].items()):
        host = f" host={info['host']}" if info["host"] else ""
        print(
            f"process {p}: {info['spans']} spans, clock offset "
            f"{info['offset_us'] / 1e3:.1f} ms{host}"
        )
    for r, info in sorted(summary.get("serve_replicas", {}).items()):
        host = f" host={info['host']}" if info["host"] else ""
        print(
            f"serve replica {r} (pid {SERVE_PID_BASE + r}): {info['spans']} "
            f"spans, clock offset {info['offset_us'] / 1e3:.1f} ms{host}"
        )
    if summary["unanchored"]:
        print(
            f"warning: no heartbeat clock anchor for processes "
            f"{summary['unanchored']} — merged with zero offset",
            file=sys.stderr,
        )
    print(f"wrote {output} (open in https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
