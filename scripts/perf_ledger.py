#!/usr/bin/env python
"""Tracked perf ledger + regression gate — the end of benchmark blindness.

BENCH r02–r05 silently degraded to the CPU smoke and nobody could say
from the JSON alone whether the perf trajectory regressed. PR 6's
per-leg skip ledger answered "why didn't this leg run"; this module
answers "is this run slower than the last comparable one" — and makes
the answer a CI failure instead of an archaeology project.

`PERF_LEDGER.json` (tracked in-repo) holds one entry per bench run:
headline imgs/s/chip, MFU, overlap_efficiency, obs overhead, hbm peak,
comms/total share and the ZeRO A/B when bench recorded them, plus the
per-leg skip ledger verbatim.

    python scripts/perf_ledger.py append --input bench_out.json --run-id r06
    python scripts/perf_ledger.py check  --input bench_out.json
    python scripts/perf_ledger.py show

`check` compares the candidate's headline `value` against the MOST
RECENT ledger entry with the same metric name (same platform + model
family by construction — CPU-smoke and TPU numbers never cross). No
comparable entry -> pass, with the reason printed (the gate only bites
when a comparable platform leg exists). Regression beyond the threshold
-> exit 1. Thresholds: 10% on accelerator metrics; CPU-smoke metrics
default to 50% because shared CI runners jitter far beyond 10% — still
a gate against catastrophic regressions, never a flake source.

Stdlib-only, like obs/schema.py, so CI can run it before heavy deps.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_LEDGER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "PERF_LEDGER.json")
ACCEL_THRESHOLD = 0.10
CPU_SMOKE_THRESHOLD = 0.50
# recall@10 floor for the ANN series (CONTRIBUTING: the review gate) —
# qps wins bought by recall losses fail the build. Applies to EVERY
# approximate tier in the record (composed IVF and the fused scan).
ANN_RECALL_FLOOR = 0.95
# embedding-cosine floor for the quantized engine tiers (w8/w8a8 vs the
# f32 engine on the same probe batch): quantization that moves the
# embedding space does not merge, on any platform
QUANT_COSINE_FLOOR = 0.99
# tier-vs-tier speed gates (ISSUE 11): a new tier must beat the tier it
# replaces — the fused scan vs the composed scan, w8a8 vs w8. On
# accelerator metrics the ratio is a hard >= 1.0 (the tier exists to be
# faster); the CPU smoke gets slack for two reasons the repo has
# already measured: shared-runner jitter (the 0.50 regression
# threshold's reason), and for w8a8 specifically the absence of int8
# conv kernels in XLA:CPU (~45x slower than f32, so the CPU path runs
# the bit-faithful f32 emulation and the arithmetic win only exists on
# a chip — serve/quant.py docstring). The ratios are measured within
# ONE bench record (interleaved slices), so no cross-run drift applies.
TIER_MIN_RATIO_ACCEL = 1.0
TIER_MIN_RATIO_CPU = 0.75
# request-tracing overhead caps for the serving series (ISSUE 10
# acceptance: tracing ON must cost < 5% qps). The CPU smoke gets the
# same widened treatment as its regression threshold — the two
# closed-loop passes run minutes apart on a shared 1-core runner, so
# their qps delta carries scheduler jitter far beyond the tracing cost.
TRACE_OVERHEAD_CAP_ACCEL = 5.0
TRACE_OVERHEAD_CAP_CPU = 25.0
# promotion-swap caps for the serving series (ISSUE 19): the measured
# pause of one staged-rollout step (drain -> same-port swap -> readmit;
# checkpoint restore/AOT re-warm excluded — see bench.py) and the
# client p99 across the swap window. The CPU smoke's closed-loop
# clients queue on one core while a replica is out of rotation, so its
# caps are about catching pathology (a wedged drain, a readmit
# timeout), not about the accelerator claim.
PROMOTE_PAUSE_CAP_ACCEL = 5000.0
PROMOTE_PAUSE_CAP_CPU = 15000.0
PROMOTE_SWAP_P99_CAP_ACCEL = 5000.0
PROMOTE_SWAP_P99_CAP_CPU = 30000.0
# layer-granular ZeRO-3 memory claim (ISSUE 20): the per-layer-group
# gather/free schedule exists to cut the PEAK model bytes from the
# whole gathered tree to shards + one live group — its analytic peak
# must stay at or below half the whole-tree zero23 peak. Analytic on
# both platforms (no memory_stats dependence), so the gate is hard
# everywhere, including CPU-smoke rounds. The step RATE is reported
# informationally only: rematerialized backward re-gathers trade
# compute for memory by design.
ZERO_LAYER_PEAK_MAX_RATIO = 0.5

# bench-JSON fields copied into a ledger entry when present
TRACKED_FIELDS = (
    "value",
    "unit",
    "vs_baseline",
    "mfu",
    "with_data_imgs_per_sec_per_chip",
    "with_data_sync_imgs_per_sec_per_chip",
    "overlap_efficiency",
    "obs_overhead_pct",
    "hbm_peak_bytes",
    "comms_total_bytes_per_step",
    "zero_ab",
    "serving",
    "ann_ab",
    "legs",
)


def default_threshold(metric: str) -> float:
    return CPU_SMOKE_THRESHOLD if "cpu_smoke" in metric else ACCEL_THRESHOLD


def load_ledger(path: str) -> dict:
    if not os.path.exists(path):
        return {"schema": 1, "entries": []}
    with open(path) as f:
        ledger = json.load(f)
    if "entries" not in ledger:
        raise ValueError(f"{path} is not a perf ledger (no 'entries')")
    return ledger


def load_bench_record(path: str) -> dict:
    """A bench record: either bench.py's one-line JSON itself, or a
    BENCH_r*.json driver wrapper carrying it under 'parsed'."""
    with open(path) as f:
        rec = json.load(f)
    if "parsed" in rec and isinstance(rec["parsed"], dict):
        rec = rec["parsed"]
    if "metric" not in rec or "value" not in rec:
        raise ValueError(f"{path} carries no metric/value pair")
    return rec


def make_entry(rec: dict, run_id: str, note: str | None = None) -> dict:
    entry = {"run_id": run_id, "metric": rec["metric"]}
    entry["platform"] = "cpu" if "cpu_smoke" in rec["metric"] else "tpu"
    for k in TRACKED_FIELDS:
        if k in rec:
            entry[k] = rec[k]
    if note:
        entry["note"] = note
    return entry


def append(ledger_path: str, input_path: str, run_id: str, note: str | None = None) -> dict:
    ledger = load_ledger(ledger_path)
    entry = make_entry(load_bench_record(input_path), run_id, note)
    ledger["entries"].append(entry)
    with open(ledger_path, "w") as f:
        json.dump(ledger, f, indent=2)
        f.write("\n")
    return entry


def _gate_series(
    ledger: dict,
    metric: str,
    value,
    threshold: float | None,
    get_series,
) -> int:
    """Gate one (metric, value) series against the most recent ledger
    entry for which `get_series(entry)` yields the same metric. 0 =
    pass or no comparable entry; 1 = regression beyond threshold."""
    if value is None:
        print(f"perf gate: candidate has no value for {metric} — nothing to gate")
        return 0
    baseline = base_entry = None
    for e in reversed(ledger["entries"]):
        s = get_series(e)
        if s and s.get("metric") == metric and s.get("value") is not None:
            baseline, base_entry = s, e
            break
    if baseline is None:
        print(
            f"perf gate: no comparable ledger entry for {metric!r} — pass "
            "(gate applies only when a comparable platform leg exists)"
        )
        return 0
    thr = default_threshold(metric) if threshold is None else threshold
    floor = baseline["value"] * (1.0 - thr)
    delta = (value - baseline["value"]) / baseline["value"] * 100.0
    verdict = "PASS" if value >= floor else "FAIL"
    print(
        f"perf gate [{verdict}] {metric}: {value:.2f} vs {baseline['value']:.2f} "
        f"(run {base_entry.get('run_id')}, {delta:+.1f}%, threshold -{thr * 100:.0f}%)"
    )
    return 0 if verdict == "PASS" else 1


def check(ledger_path: str, input_path: str, threshold: float | None = None) -> int:
    """0 = every series passes (or has no comparable leg); 1 = any
    regression beyond threshold. Three gated series per record: the
    training headline (`metric`/`value`), the serving headline
    (`serving.metric`/`serving.value`, queries/s/chip at the fixed
    SLO), and the ANN headline (`ann_ab.metric`/`ann_ab.value`, IVF
    queries/s — plus a hard recall@10 floor), each against the most
    recent ledger entry carrying the same metric name."""
    ledger = load_ledger(ledger_path)
    rec = load_bench_record(input_path)
    rc = _gate_series(ledger, rec["metric"], rec.get("value"), threshold, lambda e: e)

    def _tier_ratio_gate(metric: str, name: str, new_qps, old_qps) -> int:
        """In-record tier gate: the new tier's qps vs the tier it
        replaces, platform-appropriate minimum ratio (constants above)."""
        if new_qps is None or not old_qps:
            return 0
        floor_ratio = (
            TIER_MIN_RATIO_CPU if "cpu_smoke" in metric else TIER_MIN_RATIO_ACCEL
        )
        ratio = new_qps / old_qps
        verdict = "PASS" if ratio >= floor_ratio else "FAIL"
        print(
            f"perf gate [{verdict}] {metric}: {name} {ratio:.2f}x "
            f"(floor {floor_ratio:g}x)"
        )
        return 0 if verdict == "PASS" else 1

    def _floor_gate(metric: str, name: str, value, floor: float) -> int:
        if value is None:
            return 0
        verdict = "PASS" if value >= floor else "FAIL"
        print(
            f"perf gate [{verdict}] {metric}: {name} {value:.4f} "
            f"(floor {floor:g})"
        )
        return 0 if verdict == "PASS" else 1

    serving = rec.get("serving")
    if serving and serving.get("metric"):
        rc |= _gate_series(
            ledger,
            serving["metric"],
            serving.get("value"),
            threshold,
            lambda e: e.get("serving"),
        )
        # hard cap on the request-tracing overhead (per-request
        # waterfalls must stay ~free or serving runs them off in prod).
        # Two series under the same caps: the replica-side batcher A/B
        # (ISSUE 10) and the router-side distributed-tracing A/B
        # (ISSUE 18 — context injection, attempt spans, flight ring).
        for field, label in (
            ("trace_overhead_pct", "request-tracing"),
            ("router_trace_overhead_pct", "router distributed-tracing"),
        ):
            overhead = serving.get(field)
            if overhead is None:
                continue
            cap = (
                TRACE_OVERHEAD_CAP_CPU
                if "cpu_smoke" in serving["metric"]
                else TRACE_OVERHEAD_CAP_ACCEL
            )
            if overhead > cap:
                print(
                    f"perf gate [FAIL] {serving['metric']}: {label} "
                    f"overhead {overhead:.1f}% above the {cap:g}% cap"
                )
                rc |= 1
            else:
                print(
                    f"perf gate [PASS] {serving['metric']}: {label} "
                    f"overhead {overhead:.1f}% (cap {cap:g}%)"
                )
        # promotion-swap overhead (ISSUE 19): one staged-rollout step
        # through the router must stay cheap — a bounded pause until
        # the swapped replica re-admits with its new digest, a bounded
        # client p99 across the swap window, and ZERO failed requests
        # (one dropped request during a swap is the exact failure the
        # drain path exists to prevent)
        on_cpu = "cpu_smoke" in serving["metric"]
        for field, label, cap in (
            (
                "promote_pause_ms",
                "promotion-swap pause",
                PROMOTE_PAUSE_CAP_CPU if on_cpu else PROMOTE_PAUSE_CAP_ACCEL,
            ),
            (
                "promote_swap_p99_ms",
                "p99 during swap",
                PROMOTE_SWAP_P99_CAP_CPU if on_cpu else PROMOTE_SWAP_P99_CAP_ACCEL,
            ),
            ("promote_swap_failures", "swap-window failures", 0.0),
        ):
            value = serving.get(field)
            if value is None:
                continue
            if value > cap:
                print(
                    f"perf gate [FAIL] {serving['metric']}: {label} "
                    f"{value:g} above the {cap:g} cap"
                )
                rc |= 1
            else:
                print(
                    f"perf gate [PASS] {serving['metric']}: {label} "
                    f"{value:g} (cap {cap:g})"
                )
        # quantized-engine tiers (ISSUE 11): both tiers must hold the
        # embedding-cosine floor vs f32 (hard, every platform — speed
        # bought by moving the embedding space is a regression), and
        # w8a8 must beat w8 at the platform ratio (see constants: the
        # arithmetic factor is an accelerator claim; the CPU smoke
        # gates against catastrophic slowdowns only)
        quant = serving.get("quant") or {}
        for tier in ("w8", "w8a8"):
            rc |= _floor_gate(
                serving["metric"],
                f"{tier} cosine_vs_f32",
                (quant.get(tier) or {}).get("cosine_vs_f32"),
                QUANT_COSINE_FLOOR,
            )
        rc |= _tier_ratio_gate(
            serving["metric"],
            "w8a8 qps vs w8",
            (quant.get("w8a8") or {}).get("qps"),
            (quant.get("w8") or {}).get("qps"),
        )
    # third gated series since the IVF tier: approximate-NN queries/s
    # (the sub-linear retrieval headline) — same most-recent-comparable
    # rule; additionally a recall@10 FLOOR (an ANN index that got fast
    # by dropping recall is a regression, not a win)
    ann = rec.get("ann_ab")
    if ann and ann.get("metric"):
        rc |= _gate_series(
            ledger, ann["metric"], ann.get("value"), threshold,
            lambda e: e.get("ann_ab"),
        )
        recall = ann.get("recall_at_10")
        if recall is not None and recall < ANN_RECALL_FLOOR:
            print(
                f"perf gate [FAIL] {ann['metric']}: recall@10 {recall:.3f} "
                f"below the {ANN_RECALL_FLOOR} floor"
            )
            rc |= 1
        # fused gather-scan tier (ISSUE 11): recall floor like every
        # approximate tier, plus the in-record ratio gate — the fused
        # kernel exists to beat the composed scan it replaces
        fused = ann.get("fused") or {}
        rc |= _floor_gate(
            ann["metric"], "fused recall@10",
            fused.get("recall_at_10"), ANN_RECALL_FLOOR,
        )
        rc |= _tier_ratio_gate(
            ann["metric"], "fused qps vs composed ivf",
            fused.get("qps"), ann.get("value"),
        )
    # layer-granular ZeRO-3 memory gate (in-record, like the tier-ratio
    # gates): analytic peak model bytes of the zero_layer leg vs the
    # whole-tree zero23 leg. Skip-record legs carry ran=False and no
    # peaks, so single-device rounds pass through with the reason
    # already in the skip ledger.
    zero_ab = rec.get("zero_ab") or {}
    peak23 = (zero_ab.get("zero23") or {}).get("hbm_model_peak_bytes_analytic")
    peakl = (zero_ab.get("zero_layer") or {}).get("hbm_model_peak_bytes_analytic")
    if peak23 and peakl:
        ratio = peakl / peak23
        verdict = "PASS" if ratio <= ZERO_LAYER_PEAK_MAX_RATIO else "FAIL"
        print(
            f"perf gate [{verdict}] {rec['metric']}: zero_layer peak model bytes "
            f"{ratio:.2f}x of zero23 (cap {ZERO_LAYER_PEAK_MAX_RATIO:g}x)"
        )
        rc |= 0 if verdict == "PASS" else 1
        rate23 = (zero_ab.get("zero23") or {}).get("imgs_per_sec_per_chip")
        ratel = (zero_ab.get("zero_layer") or {}).get("imgs_per_sec_per_chip")
        if rate23 and ratel:
            print(
                f"  zero_layer rate vs zero23: {ratel / rate23:.2f}x "
                "(informational: remat re-gathers trade rate for peak memory)"
            )
    # informational deltas for the secondary series (never gating —
    # they gate the day they prove stable enough)
    baseline = None
    for e in reversed(ledger["entries"]):
        if e.get("metric") == rec["metric"] and e.get("value") is not None:
            baseline = e
            break
    if baseline is not None:
        for k in ("mfu", "with_data_imgs_per_sec_per_chip", "overlap_efficiency"):
            a, b = rec.get(k), baseline.get(k)
            if a is not None and b is not None and b:
                print(f"  {k}: {a} vs {b} ({(a - b) / b * 100.0:+.1f}%)")
    return rc


def _all_legs_skipped(entry: dict) -> bool:
    legs = entry.get("legs")
    if not isinstance(legs, dict) or not legs:
        return False
    return all(isinstance(l, dict) and not l.get("ran") for l in legs.values())


def tracked_series(entries: list[dict]) -> dict:
    """metric -> (run_id, value, unit): the latest REAL (non-None) point
    per tracked series — the training headline plus the serving and
    ann_ab sub-records. A round whose legs all hit the skip ledger
    appends None values; the series keeps its last measured point."""
    latest: dict = {}
    for e in entries:
        for sub in (e, e.get("serving") or {}, e.get("ann_ab") or {}):
            metric, value = sub.get("metric"), sub.get("value")
            if metric and value is not None:
                latest[metric] = (e.get("run_id", "?"), value, sub.get("unit"))
    return latest


def show(ledger_path: str) -> int:
    ledger = load_ledger(ledger_path)
    entries = ledger["entries"]
    for e in entries:
        tag = "  (all legs skipped)" if _all_legs_skipped(e) else ""
        print(
            f"{e.get('run_id', '?'):>6}  {e.get('platform', '?'):>4}  "
            f"{e.get('value')}  {e.get('metric')}{tag}"
        )
    # Without this block a tail of skip-only rounds makes the whole
    # trajectory read empty even though every series has data a round or
    # two back — `show` must always answer "where does each series
    # stand" from the latest real point.
    latest = tracked_series(entries)
    if latest:
        print("tracked series (latest real point):")
        for metric in sorted(latest):
            run_id, value, unit = latest[metric]
            suffix = f" {unit}" if unit else ""
            print(f"  {metric} = {value}{suffix}  (run {run_id})")
    print(f"{len(entries)} entries in {os.path.abspath(ledger_path)}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_append = sub.add_parser("append", help="append a bench record to the ledger")
    p_append.add_argument("--input", required=True, help="bench JSON (raw line or BENCH_r*.json)")
    p_append.add_argument("--ledger", default=DEFAULT_LEDGER)
    p_append.add_argument("--run-id", required=True, help="e.g. r06")
    p_append.add_argument("--note", default=None)
    p_check = sub.add_parser("check", help="gate a candidate against the last comparable entry")
    p_check.add_argument("--input", required=True)
    p_check.add_argument("--ledger", default=DEFAULT_LEDGER)
    p_check.add_argument(
        "--threshold", type=float, default=None,
        help="fractional regression allowed (default 0.10; 0.50 for cpu_smoke metrics)",
    )
    p_show = sub.add_parser("show", help="list ledger entries")
    p_show.add_argument("--ledger", default=DEFAULT_LEDGER)
    args = ap.parse_args()
    if args.cmd == "append":
        entry = append(args.ledger, args.input, args.run_id, args.note)
        print(f"appended {entry['run_id']} ({entry['metric']}={entry.get('value')})")
        return 0
    if args.cmd == "check":
        return check(args.ledger, args.input, args.threshold)
    return show(args.ledger)


if __name__ == "__main__":
    sys.exit(main())
