#!/usr/bin/env python
"""Post-run telemetry report: one readable summary from a run's JSONL.

    python scripts/obs_report.py WORKDIR            # or a metrics.jsonl path
    python scripts/obs_report.py WORKDIR --output report.md
    python scripts/obs_report.py WORKDIR --strict   # exit 1 on schema errors

Renders, from `metrics.jsonl` (+ per-process `metrics.p<i>.jsonl`
siblings, `trace.json`, `alerts.jsonl`, and `heartbeat.p*.json` when
present):

- run shape: steps/epochs covered, wall time, logging cadence;
- step-time breakdown: where the average step went (data wait vs
  dispatch vs device compute), as an ASCII "pie";
- fleet view: straggler skew trend, the fleet-max step time vs the
  mean, the most-blamed host, and a per-host heartbeat table that
  flags hosts whose heartbeat went stale (died mid-run) — merged from
  the out-of-band heartbeat files, so a dead host still appears;
- comms: per-collective-site analytic wire bytes per step (from the
  `comms/*` counters) with a share-of-total bar;
- serving: the request-stage waterfall as an ASCII pie (from the
  `serve/trace_<stage>_ms` window means), qps/p99 trends, the SLO
  burn-rate curve per window (`serve/burn_rate_*` sparkline), and the
  top-N slowest requests with their full stage waterfalls from the
  newest flight-recorder dump (`flight_*.json`) when one exists;
- model quality & freshness: the served model's identity (checkpoint
  step + params digest + last ingested step), compatibility gauges
  (`serve/compat_cosine`, `serve/recall_overlap`), the index row-age
  trend vs the declared freshness objective with the
  `serve/fresh_burn_rate_*` sparklines, the fleet's version-skew
  trend, and every `promotions.jsonl` verdict with its failing gate;
- alerts: every fired alert from alerts.jsonl, grouped by rule;
- training-health trends: loss/accuracy, EMA drift, InfoNCE pos/neg
  logit margin, feature-collapse gauges, queue staleness — first→last
  with min/max, so a drifting gauge is visible without plotting;
- device memory: peak HBM seen (or "not reported by backend");
- fault ledger: NaN steps, decode failures, per-site I/O retries,
  compile-cache misses, and every event line verbatim;
- trace summary: total/self time by span name from the Chrome trace.

When the source is a workdir, co-hosted processes' metrics files are
globbed and merged (the per-process-filename satellite); `--strict`
validates EVERY file against the schema.

Needs only the stdlib + moco_tpu.obs.schema (no jax import, so it runs
on any machine the JSONL was copied to). CI's obs-smoke step runs this
against the driver smoke's artifacts on every PR, so report rendering
cannot rot.
"""

from __future__ import annotations

import argparse
import glob as globmod
import json
import os
import sys

# allow running from a checkout without installation
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from moco_tpu.obs import schema  # noqa: E402


BAR_WIDTH = 36


def _bar(frac: float, width: int = BAR_WIDTH) -> str:
    n = max(0, min(width, round(frac * width)))
    return "#" * n + "." * (width - n)


def _fmt(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


_SPARK_CHARS = " .:-=+*#%@"


def _spark(vals: list, width: int = 32) -> str:
    """Tiny ASCII sparkline of a series (downsampled to `width`), scaled
    to its own max — the burn-rate curve without a plotting dep."""
    if not vals:
        return ""
    if len(vals) > width:
        step = len(vals) / width
        vals = [vals[int(i * step)] for i in range(width)]
    top = max(max(vals), 1e-12)
    idx = [min(int(v / top * (len(_SPARK_CHARS) - 1) + 0.5), len(_SPARK_CHARS) - 1)
           for v in vals]
    return "[" + "".join(_SPARK_CHARS[i] for i in idx) + "]"


def _trend(lines: list[dict], key: str) -> str | None:
    vals = [(r["step"], r[key]) for r in lines if isinstance(r.get(key), (int, float))]
    if not vals:
        return None
    nums = [v for _, v in vals]
    first, last = vals[0][1], vals[-1][1]
    return (
        f"{_fmt(first)} -> {_fmt(last)}"
        f"  (min {_fmt(min(nums))}, max {_fmt(max(nums))}, n={len(nums)})"
    )


def _flight_dumps(workdir: str | None, role: str | None) -> list[tuple[str, dict]]:
    """(path, dump) for every parseable flight_*.json under `workdir`,
    oldest first, filtered by the dump's `role` stamp — `"router"` for
    the fleet router's stitched-waterfall dumps, None for a replica's
    own (unstamped or role="serve") dumps."""
    out = []
    for path in sorted(globmod.glob(os.path.join(workdir, "flight_*.json"))) if workdir else []:
        try:
            with open(path) as f:
                dump = json.load(f)
        except (ValueError, OSError):
            continue
        if (dump.get("role") == "router") == (role == "router"):
            out.append((path, dump))
    return out


def _promotion_ledger(workdir: str | None) -> list[dict]:
    """Parsed `promotions.jsonl` verdict lines (oldest first), [] when
    the run has no promotion ledger. Tolerant parse — the report must
    render even next to a half-written ledger."""
    if not workdir:
        return []
    path = os.path.join(workdir, "promotions.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("event") == "promotion":
                out.append(rec)
    return out


def metrics_paths_for(source: str) -> list[str]:
    """All per-process metrics files of a workdir (process 0's
    `metrics.jsonl` first), or the single file the caller named."""
    if not os.path.isdir(source):
        return [source]
    paths = []
    base = os.path.join(source, "metrics.jsonl")
    if os.path.exists(base):
        paths.append(base)
    paths.extend(sorted(globmod.glob(os.path.join(source, "metrics.p*.jsonl"))))
    return paths


def render_report(
    metrics_path: str | list[str],
    trace_path: str | None = None,
    workdir: str | None = None,
) -> str:
    paths = [metrics_path] if isinstance(metrics_path, str) else list(metrics_path)
    records = []
    for p in paths:
        records.extend(schema.read_metrics(p, strict=False))
    if len(paths) > 1:  # merged multi-process view: one timeline
        records.sort(key=lambda r: (r.get("time", 0.0), r.get("step", 0)))
    train_lines = [r for r in records if "loss" in r and "event" not in r]
    events = [r for r in records if "event" in r]
    out: list[str] = []
    w = out.append

    src = paths[0] if len(paths) == 1 else f"{len(paths)} per-process files"
    w("# Telemetry report")
    w("")
    w(f"source: `{src}` — {len(records)} lines "
      f"({len(train_lines)} training, {len(events)} events)")
    if not records:
        w("")
        w("(empty metrics file — nothing to report)")
        return "\n".join(out)
    steps = [r["step"] for r in records]
    wall = records[-1]["time"] - records[0]["time"]
    epochs = sorted({r["epoch"] for r in records if "epoch" in r})
    w(f"steps {min(steps)}..{max(steps)}"
      + (f", epochs {epochs[0]}..{epochs[-1]}" if epochs else "")
      + f", {wall:.1f}s of wall time between first and last line")
    w("")

    # -- step-time breakdown --------------------------------------------
    w("## Step-time breakdown")
    w("")
    t_data = [r["t_data"] for r in train_lines if isinstance(r.get("t_data"), (int, float))]
    t_step = [r["t_step"] for r in train_lines if isinstance(r.get("t_step"), (int, float))]
    if t_step:
        mean_step = sum(t_step) / len(t_step)
        mean_data = sum(t_data) / len(t_data) if t_data else 0.0
        other = max(mean_step - mean_data, 0.0)
        w(f"mean logged step: {mean_step * 1e3:.1f} ms")
        for name, sec in (("data wait", mean_data), ("dispatch+device", other)):
            frac = sec / mean_step if mean_step else 0.0
            w(f"  {name:<16} {_bar(frac)} {frac * 100:5.1f}%  ({sec * 1e3:.1f} ms)")
        disp = [r["t_dispatch"] for r in train_lines
                if isinstance(r.get("t_dispatch"), (int, float))]
        dev = [r["t_device"] for r in train_lines
               if isinstance(r.get("t_device"), (int, float))]
        if dev:
            w(f"  probe samples: dispatch {sum(disp) / len(disp) * 1e3:.1f} ms, "
              f"device {sum(dev) / len(dev) * 1e3:.1f} ms "
              f"(block_until_ready on {len(dev)} sampled lines)")
    else:
        w("(no t_step fields — run predates the telemetry layer?)")
    w("")

    # -- input wire (device prefetch ring) -------------------------------
    t_xfer = [r["t_transfer"] for r in train_lines
              if isinstance(r.get("t_transfer"), (int, float))]
    if t_xfer:
        xbytes = [r["transfer_bytes"] for r in train_lines
                  if isinstance(r.get("transfer_bytes"), (int, float))]
        depth = [r["prefetch_depth_live"] for r in train_lines
                 if isinstance(r.get("prefetch_depth_live"), (int, float))]
        mean_xfer = sum(t_xfer) / len(t_xfer)
        w("## Input wire (device prefetch ring)")
        w("")
        w(f"mean transfer: {mean_xfer * 1e3:.1f} ms/batch"
          + (f" ({sum(xbytes) / len(xbytes) / 1e6:.1f} MB -> "
             f"{sum(xbytes) / len(xbytes) / 1e6 / max(mean_xfer, 1e-9):.0f} MB/s"
             if xbytes else "")
          + ")")
        if depth:
            starved = sum(1 for d in depth if d == 0)
            w(f"staged depth at consume: mean {sum(depth) / len(depth):.1f}, "
              f"empty on {starved}/{len(depth)} lines "
              "(empty = the wire or the host is the bottleneck; "
              "full = the device is)")
        if t_step:
            frac = mean_xfer / mean_step if mean_step else 0.0
            w(f"wire/step ratio: {frac * 100:.0f}% "
              "(>100% means transfer bounds throughput even when overlapped)")
        w("")

    # -- fleet view ------------------------------------------------------
    skew = _trend(train_lines, "straggler_skew")
    hosts = [r["fleet_hosts"] for r in train_lines if isinstance(r.get("fleet_hosts"), int)]
    beats = {}
    if workdir:
        from moco_tpu.obs.fleet import read_heartbeats

        beats = read_heartbeats(workdir)
    if skew or hosts or beats:
        w("## Fleet")
        w("")
        if hosts:
            w(f"hosts reporting: {max(hosts)}")
        if skew:
            w(f"- `straggler_skew`: {skew}")
        tmax = _trend(train_lines, "fleet/t_step_max")
        tmean = _trend(train_lines, "fleet/t_step_mean")
        if tmax:
            w(f"- `fleet/t_step_max`: {tmax}")
        if tmean:
            w(f"- `fleet/t_step_mean`: {tmean}")
        blames = [r["fleet/t_step_argmax"] for r in train_lines
                  if isinstance(r.get("fleet/t_step_argmax"), int)]
        if blames:
            worst = max(set(blames), key=blames.count)
            w(f"- slowest host (mode of `fleet/t_step_argmax`): "
              f"host {worst} on {blames.count(worst)}/{len(blames)} lines")
        if beats:
            newest = max(b.get("time", 0.0) for b in beats.values())
            w("")
            w("heartbeats (out-of-band; a stale one means the host died mid-run):")
            for p in sorted(beats):
                b = beats[p]
                lag = newest - b.get("time", 0.0)
                flag = "  ** STALE — host died mid-run? **" if lag > 60.0 else ""
                w(f"- host {p} ({b.get('host', '?')}): last beat at step "
                  f"{b.get('step', '?')}, {lag:.0f}s behind the newest{flag}")
        w("")

    # -- comms (analytic wire bytes per collective site) -----------------
    comms_line = next(
        (r for r in reversed(train_lines)
         if any(k.startswith("comms/") and k != "comms/total" for k in r)),
        None,
    )
    if comms_line:
        w("## Comms (analytic wire bytes per device, per step)")
        w("")
        sites = {
            k[len("comms/"):]: v for k, v in comms_line.items()
            if k.startswith("comms/") and k != "comms/total"
            and isinstance(v, (int, float))
        }
        total = sum(sites.values()) or 1.0
        for name, nbytes in sorted(sites.items(), key=lambda kv: -kv[1]):
            frac = nbytes / total
            w(f"  {name:<28} {_bar(frac)} {frac * 100:5.1f}%  "
              f"({nbytes / 2**20:.2f} MiB/step)")
        w(f"  total: {total / 2**20:.2f} MiB/step per device "
          f"(collective cost model: moco_tpu/obs/comms.py)")
        w("")

    # -- serving (request-scoped observability) --------------------------
    serve_lines = [r for r in records if any(k.startswith("serve/") for k in r)]
    if serve_lines:
        w("## Serving")
        w("")
        last = serve_lines[-1]
        reqs = last.get("serve/requests")
        if isinstance(reqs, (int, float)):
            w(f"requests: {int(reqs)}, slo {_fmt(last.get('serve/slo_ms'))} ms "
              f"(objective {_fmt(last.get('serve/slo_objective'))}), "
              f"violations {_fmt(last.get('serve/slo_violations'))}")
        for key in ("serve/qps", "serve/p99_ms", "serve/p50_ms", "serve/occupancy"):
            t = _trend(serve_lines, key)
            if t is not None:
                w(f"- `{key}`: {t}")
        ex = next(
            (r["serve/p99_exemplar"] for r in reversed(serve_lines)
             if isinstance(r.get("serve/p99_exemplar"), str)),
            None,
        )
        if ex is not None:
            w(f"- worst recent request (p99 exemplar): `{ex}`")
        # stage waterfall pie: the latest line carrying trace means
        stage_line = next(
            (r for r in reversed(serve_lines)
             if any(k.startswith("serve/trace_") and k.endswith("_ms") for k in r)),
            None,
        )
        if stage_line:
            stages = {
                k[len("serve/trace_"):-len("_ms")]: v
                for k, v in stage_line.items()
                if k.startswith("serve/trace_") and k.endswith("_ms")
                and isinstance(v, (int, float))
            }
            total = sum(stages.values()) or 1.0
            w("")
            w("stage waterfall (mean ms/request, latest window):")
            for name, ms in sorted(stages.items(), key=lambda kv: -kv[1]):
                frac = ms / total
                w(f"  {name:<16} {_bar(frac)} {frac * 100:5.1f}%  ({ms:.1f} ms)")
        # burn-rate curve: one sparkline per window
        burn_keys = sorted(
            {k for r in serve_lines for k in r if k.startswith("serve/burn_rate_")}
        )
        for key in burn_keys:
            vals = [r[key] for r in serve_lines if isinstance(r.get(key), (int, float))]
            if vals:
                w(f"- `{key}`: {_spark(vals)}  last {_fmt(vals[-1])} "
                  f"(max {_fmt(max(vals))}; >1 = burning budget faster "
                  "than the SLO period sustains)")
        # top-N slowest requests from the newest REPLICA flight dump
        # (router dumps carry role="router" and render in Fleet tracing)
        if _flight_dumps(workdir, role=None):
            path, dump = _flight_dumps(workdir, role=None)[-1]
            if dump.get("slowest"):
                w("")
                w(f"slowest requests (flight recorder `{os.path.basename(path)}`, "
                  f"reason: {dump.get('reason', '?')}):")
                for wf in dump["slowest"][:5]:
                    stages_str = " ".join(
                        f"{s['stage']}={s['dur_ms']:.0f}ms"
                        for s in wf.get("stages", [])
                    )
                    w(f"- `{wf.get('request_id', '?')}` "
                      f"({wf.get('total_ms', 0):.0f} ms, {wf.get('rows', '?')} rows): "
                      f"{stages_str}")
        w("")

    # -- model quality & freshness (the train->serve loop) ----------------
    quality_lines = [
        r for r in records
        if any(
            k in r
            for k in (
                "serve/model_step", "serve/compat_cosine", "serve/fresh_max_age_s",
            )
        )
    ]
    promotions = _promotion_ledger(workdir)
    if quality_lines or promotions:
        w("## Model quality & freshness")
        w("")
        last = quality_lines[-1] if quality_lines else {}
        if last.get("serve/model_step") is not None or last.get("serve/model_digest"):
            w(f"served model: step {_fmt(last.get('serve/model_step'))}, "
              f"digest `{_fmt(last.get('serve/model_digest'))}`, "
              f"last ingested block from step "
              f"{_fmt(last.get('serve/ingest_ckpt_step'))}")
        for key in ("serve/compat_cosine", "serve/recall_overlap"):
            t = _trend(quality_lines, key)
            if t is not None:
                w(f"- `{key}`: {t}")
        fresh_obj = last.get("serve/fresh_max_age_s")
        if isinstance(fresh_obj, (int, float)):
            w(f"- freshness objective: rows no older than {_fmt(fresh_obj)}s")
            for key in ("serve/row_age_max_s", "serve/row_age_mean_s"):
                t = _trend(quality_lines, key)
                if t is not None:
                    w(f"- `{key}`: {t}")
        fresh_keys = sorted(
            {k for r in quality_lines for k in r
             if k.startswith("serve/fresh_burn_rate_")}
        )
        for key in fresh_keys:
            vals = [r[key] for r in quality_lines
                    if isinstance(r.get(key), (int, float))]
            if vals:
                w(f"- `{key}`: {_spark(vals)}  last {_fmt(vals[-1])} "
                  f"(max {_fmt(max(vals))}; >1 = the index is going stale "
                  "faster than the objective sustains)")
        skew = _trend(
            [r for r in records if "fleet_serve/model_skew" in r],
            "fleet_serve/model_skew",
        )
        if skew is not None:
            w(f"- `fleet_serve/model_skew`: {skew} "
              "(0 = every replica serves the same encoder)")
        if promotions:
            w("")
            w("promotion ledger (append-only, newest last):")
            for p in promotions[-10:]:
                gate = p.get("promotion/failed_gate")
                detail = ""
                if gate:
                    val = p.get(f"promotion/gate/{gate}")
                    floor = p.get(f"promotion/floor/{gate}")
                    detail = f" — failed `{gate}`" + (
                        f" ({_fmt(val)} vs floor {_fmt(floor)})"
                        if val is not None else ""
                    )
                w(f"- step {p.get('promotion/step', '?')} "
                  f"`{_fmt(p.get('promotion/digest'))}`: "
                  f"**{p.get('promotion/verdict', '?')}** "
                  f"at {p.get('promotion/stage', '?')}{detail}")
            if len(promotions) > 10:
                w(f"- ... {len(promotions) - 10} earlier entries in "
                  "promotions.jsonl")
        w("")

    # -- fleet tracing (stitched distributed waterfalls) ------------------
    fleet_lines = [
        r for r in records if any(k.startswith("fleet_serve/") for k in r)
    ]
    if fleet_lines:
        w("## Fleet tracing")
        w("")
        last = fleet_lines[-1]
        reqs = last.get("fleet_serve/requests")
        if isinstance(reqs, (int, float)):
            w(f"requests through the front door: {int(reqs)}, "
              f"slo {_fmt(last.get('fleet_serve/slo_ms'))} ms, "
              f"p99 {_fmt(last.get('fleet_serve/p99_ms'))} ms")
        # critical-path pie: which hop of the distributed request ate
        # the milliseconds (obs/critpath.py attribution, latest window)
        crit_line = next(
            (r for r in reversed(fleet_lines)
             if any(k.startswith("fleet_serve/critpath_") for k in r)),
            None,
        )
        if crit_line:
            hops = {
                k[len("fleet_serve/critpath_"):-len("_ms")]: v
                for k, v in crit_line.items()
                if k.startswith("fleet_serve/critpath_") and k.endswith("_ms")
                and isinstance(v, (int, float))
            }
            total = sum(hops.values()) or 1.0
            w("")
            w("critical path (mean ms/request, latest window):")
            for name, ms in sorted(hops.items(), key=lambda kv: -kv[1]):
                frac = ms / total
                w(f"  {name:<22} {_bar(frac)} {frac * 100:5.1f}%  ({ms:.1f} ms)")
        hedges = last.get("fleet_serve/hedges")
        if isinstance(hedges, (int, float)) and hedges:
            wins = last.get("fleet_serve/hedge_wins") or 0
            w(f"- hedges: {int(hedges)} (win rate {wins / hedges * 100:.0f}%); "
              f"{_fmt(last.get('fleet_serve/hedge_wasted_ms'))} ms burned in "
              "cancelled loser lanes")
        retries = last.get("fleet_serve/retries")
        if isinstance(retries, (int, float)) and retries:
            retry_ms = (
                crit_line.get("fleet_serve/critpath_retry_failed_ms")
                if crit_line else None
            )
            w(f"- retries: {int(retries)}; failed-attempt wait on the "
              f"critical path: {_fmt(retry_ms)} ms (mean over traced requests)")
        # top-5 slowest stitched multi-hop waterfalls
        router_dumps = _flight_dumps(workdir, role="router")
        if router_dumps and router_dumps[-1][1].get("slowest"):
            path, dump = router_dumps[-1]
            w("")
            w(f"slowest distributed waterfalls (router flight "
              f"`{os.path.basename(path)}`, reason: {dump.get('reason', '?')}):")
            for wf in dump["slowest"][:5]:
                stages_str = " ".join(
                    f"{s['stage']}={s['dur_ms']:.0f}ms"
                    for s in wf.get("stages", [])
                )
                w(f"- `{wf.get('trace_id', '?')}` -> "
                  f"`{wf.get('request_id', '?')}` "
                  f"({wf.get('total_ms', 0):.0f} ms, "
                  f"status {wf.get('status', '?')}, "
                  f"{len(wf.get('attempts') or ())} attempt(s)): {stages_str}")
        w("")

    # -- alerts ----------------------------------------------------------
    alerts = []
    if workdir:
        from moco_tpu.obs.alerts import read_alerts

        alerts = read_alerts(os.path.join(workdir, "alerts.jsonl"))
    if alerts:
        w("## Alerts")
        w("")
        by_rule: dict[str, int] = {}
        for a in alerts:
            by_rule[a.get("rule", "?")] = by_rule.get(a.get("rule", "?"), 0) + 1
        w("fired: " + ", ".join(f"`{r}` x{n}" for r, n in sorted(by_rule.items())))
        for a in alerts[:20]:
            w(f"- [{a.get('severity', '?')}] step {a.get('step', '?')} "
              f"`{a.get('rule', '?')}`: {a.get('message', '')}")
        if len(alerts) > 20:
            w(f"- ... {len(alerts) - 20} more in alerts.jsonl")
        w("")

    # -- device memory ---------------------------------------------------
    w("## Device memory")
    w("")
    hbm = [r["hbm_peak_bytes"] for r in train_lines
           if isinstance(r.get("hbm_peak_bytes"), (int, float))]
    live = [r["hbm_live_bytes"] for r in train_lines
            if isinstance(r.get("hbm_live_bytes"), (int, float))]
    if hbm or live:
        if hbm:
            w(f"peak HBM: {max(hbm) / 2**30:.2f} GiB")
        if live:
            w(f"live bytes, last line: {live[-1] / 2**30:.2f} GiB")
    else:
        w("not reported by backend (hbm gauges are null — CPU host or "
          "tunnel without memory_stats)")
    w("")

    # -- health trends ---------------------------------------------------
    w("## Training health (first -> last)")
    w("")
    for key in (
        "loss", "acc1", "acc5", "lr", "knn_top1",
        "ema_drift", "logit_pos_mean", "logit_neg_mean",
        "logit_pos_std", "logit_neg_std",
        "feature_std", "feature_dim_active",
        "queue_age_mean", "queue_age_max",
    ):
        # knn_top1 rides aux lines, not train lines
        src = records if key == "knn_top1" else train_lines
        t = _trend(src, key)
        if t is not None:
            w(f"- `{key}`: {t}")
    groups = sorted(
        {k for r in train_lines for k in r if k.startswith("ema_drift/")}
    )
    for g in groups:
        t = _trend(train_lines, g)
        if t is not None:
            w(f"- `{g}`: {t}")
    pos = _trend(train_lines, "logit_pos_mean")
    if pos is None:
        w("- (no health gauges on these lines — --no-health-metrics run?)")
    w("")

    # -- fault ledger ----------------------------------------------------
    w("## Fault ledger")
    w("")
    ledger = []
    nan = [r["nan_steps"] for r in records if "nan_steps" in r]
    if nan:
        ledger.append(f"- non-finite loss steps: {max(nan)}")
    dec = [r["decode_failures"] for r in records if "decode_failures" in r]
    if dec:
        ledger.append(f"- decode failures (cumulative): {max(dec)}")
    io: dict[str, int] = {}
    for r in records:
        for site, n in (r.get("io_retries") or {}).items():
            io[site] = max(io.get(site, 0), n)
    if io:
        ledger.append(f"- io retries by site: {io}")
    ccm = [r["compile_cache_misses"] for r in records if "compile_cache_misses" in r]
    if ccm:
        flat = " (flat after warmup)" if len(set(ccm[1:])) <= 1 else " (STILL RISING)"
        ledger.append(f"- compile cache misses: last={ccm[-1]}{flat}")
    for e in events:
        ledger.append(f"- event @ step {e['step']}: {e['event']}")
    w("\n".join(ledger) if ledger else "clean run — no faults, no events.")
    w("")

    # -- trace summary ---------------------------------------------------
    if trace_path and os.path.exists(trace_path):
        w("## Trace summary (Chrome trace; open in ui.perfetto.dev)")
        w("")
        with open(trace_path) as f:
            trace = json.load(f)
        totals: dict[str, tuple[float, int]] = {}
        for ev in trace.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            t, n = totals.get(ev["name"], (0.0, 0))
            totals[ev["name"]] = (t + ev.get("dur", 0.0), n + 1)
        for name, (dur, n) in sorted(totals.items(), key=lambda kv: -kv[1][0])[:12]:
            w(f"- `{name}`: {dur / 1e6:.2f}s total over {n} spans")
        w("")
    return "\n".join(out)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("source", help="run workdir, or a metrics.jsonl path")
    ap.add_argument("--trace", default=None, help="chrome trace json (default: <workdir>/trace.json)")
    ap.add_argument("--output", "-o", default=None, help="write the report here (default: stdout)")
    ap.add_argument(
        "--strict", action="store_true",
        help="validate every line against the schema; exit 1 on violations",
    )
    args = ap.parse_args()

    trace_path = args.trace
    workdir = None
    if os.path.isdir(args.source):
        workdir = args.source
        if trace_path is None:
            # prefer the multi-process merged trace when one was built
            for cand in ("merged_trace.json", "trace.json"):
                cand = os.path.join(workdir, cand)
                if os.path.exists(cand):
                    trace_path = cand
                    break
        metrics_paths = metrics_paths_for(workdir)
    else:
        metrics_paths = [args.source]
    missing = [p for p in metrics_paths if not os.path.exists(p)]
    if missing or not metrics_paths:
        print(f"error: {missing or args.source} not found", file=sys.stderr)
        return 2

    errors = []
    for p in metrics_paths:
        tag = f"{os.path.basename(p)}: " if len(metrics_paths) > 1 else ""
        errors.extend(tag + e for e in schema.validate_file(p))
    report = render_report(metrics_paths, trace_path, workdir=workdir)
    if errors:
        report += "\n## Schema violations\n\n" + "\n".join(f"- {e}" for e in errors) + "\n"
    if args.output:
        with open(args.output, "w") as f:
            f.write(report + "\n")
        print(f"wrote {args.output}")
    else:
        print(report)
    if errors:
        print(f"{len(errors)} schema violation(s)", file=sys.stderr)
        return 1 if args.strict else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
