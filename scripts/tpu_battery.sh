#!/bin/bash
# Full TPU evidence battery (VERDICT r2 items 1, 3, 4) — run when the
# axon tunnel is healthy. Sequential: the TPU admits ONE client; a
# second process silently blocks. Each leg gets a generous timeout —
# hitting it means the tunnel wedged (a 3-minute workload does not take
# 30), at which point the SIGTERM is moot anyway. Pallas kernel tests
# run LAST (a killed client mid-Mosaic-compile can wedge the lease).
#
# Output: artifacts/tpu_r4/*.json + logs; trace under /tmp/moco_trace_r4.
set -u
cd "$(dirname "$0")/.."
L=artifacts/tpu_r4
mkdir -p "$L"
date > "$L/battery_started"

run() { # name timeout_s env... -- cmd...
  local name=$1 t=$2; shift 2
  local envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done
  shift
  echo "=== $name ($(date +%H:%M:%S)) ===" | tee -a "$L/battery.log"
  env "${envs[@]}" timeout "$t" "$@" > "$L/$name.out" 2> "$L/$name.log"
  echo "rc=$? $name" | tee -a "$L/battery.log"
}

# 1. headline bench: device rate + MFU + with-data ladder + trace
run bench_r50 2700 BENCH_TRACE_DIR=/tmp/moco_trace_r4 -- python bench.py

# 2. fused-vs-dense InfoNCE A/B (device-only for clean numbers)
run bench_r50_fused 900 BENCH_SKIP_DATA=1 BENCH_FUSED=1 -- python bench.py
run bench_r50_dense 900 BENCH_SKIP_DATA=1 BENCH_FUSED=0 -- python bench.py

# 3. BN-bytes lever A/B: subset-row statistics (PROFILE.md, 32 rows =
#    the reference's per-GPU granularity) and virtual-group mode cost
run bench_r50_bn32 900 BENCH_SKIP_DATA=1 BENCH_BN_STATS_ROWS=32 -- python bench.py
run bench_r50_bn64 900 BENCH_SKIP_DATA=1 BENCH_BN_STATS_ROWS=64 -- python bench.py
run bench_r50_vg8 900 BENCH_SKIP_DATA=1 BENCH_BN_VIRTUAL_GROUPS=8 -- python bench.py

# 4. ViT v3 step bench, flash off/on
run bench_vit 1200 BENCH_ARCH=vit_b16 BENCH_SKIP_DATA=1 -- python bench.py
run bench_vit_flash 1200 BENCH_ARCH=vit_b16 BENCH_FLASH=1 BENCH_SKIP_DATA=1 -- python bench.py

# 5. compiled (non-interpret) Pallas kernel tests — LAST (riskiest)
run kernel_tests 1800 MOCO_TPU_TESTS=1 -- python -m pytest tests/test_tpu_kernels.py -q

# 5b. TPU-tunnel host->device transfer anchor (PROFILE.md input section:
#    the 765 MB/s loopback number needs its real-tunnel counterpart;
#    small geometry keeps host-side stages quick on the 1-core box)
rm -rf /tmp/moco_input_profile_cache   # cache stamps are listing-exact
run input_transfer 1200 -- python scripts/profile_input.py --batch 64 --n-images 1024 \
  --reps 2 --threads 1 --out-size 224 --src-size 256 \
  --profile-md artifacts/tpu_r4/input_profile_tpu.md --artifact artifacts/tpu_r4/input_profile_tpu.json

# 6. trace analysis (host-side, no TPU use)
if [ -d /tmp/moco_trace_r4 ]; then
  JAX_PLATFORMS=cpu timeout 600 python scripts/analyze_trace.py /tmp/moco_trace_r4 \
    --flops 8.18e12 --bytes 100e9 > "$L/trace_analysis.txt" 2>&1
fi
date > "$L/battery_finished"
echo "battery complete" | tee -a "$L/battery.log"
