#!/bin/bash
# Part 2 of the round-4 TPU battery — the legs the first run never
# reached. Lesson from part 1 (artifacts/tpu_r4/battery.log): the
# bn_stats_rows r50/224 program compiles for >15 min on the TPU
# backend, the 900 s leg timeout SIGTERMed it mid-compile, and the
# killed leaseholder wedged the chip lease (bn64's init then hung with
# an empty log until the battery was stopped by hand). Changes here:
#   - every leg waits for a HEALTHY backend first (subprocess probe,
#     abandoned not killed on timeout) instead of serially burning
#     timeouts against a wedged lease;
#   - pathological-compile suspects (bn32/bn64/vg8) run LAST with
#     45-minute timeouts;
#   - timeouts escalate SIGTERM -> SIGKILL (-k 60): part 1's failure
#     mode was a leg wedged in C++ TPU-runtime threads that survives
#     SIGTERM — without escalation the battery would hang on it
#     forever. The point remains that they should never fire on a
#     healthy leg.
set -u
cd "$(dirname "$0")/.."
L=artifacts/tpu_r4
mkdir -p "$L"
date > "$L/battery_b_started"

source "$(dirname "$0")/lib_backend.sh"  # wait_backend

run() { # name timeout_s env... -- cmd...
  local name=$1 t=$2; shift 2
  local envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done
  shift
  wait_backend
  echo "=== $name ($(date +%H:%M:%S)) ===" | tee -a "$L/battery.log"
  env "${envs[@]}" timeout -k 60 "$t" "$@" > "$L/$name.out" 2> "$L/$name.log"
  echo "rc=$? $name" | tee -a "$L/battery.log"
}

# r50 headline + fused-vs-dense NUMERICS cross-check (VERDICT r4 #3):
# one compiled step per path from identical state/batch, loss/acc
# compared to tolerance in the leg output — on-chip correctness
# evidence for the default-on Pallas InfoNCE, independent of pytest.
run bench_r50_numerics 2700 BENCH_SKIP_DATA=1 BENCH_NUMERICS=1 -- python bench.py

# ViT v3 step bench, flash off/on (battery item 4)
run bench_vit 2700 BENCH_ARCH=vit_b16 BENCH_SKIP_DATA=1 -- python bench.py
run bench_vit_flash 2700 BENCH_ARCH=vit_b16 BENCH_FLASH=1 BENCH_SKIP_DATA=1 -- python bench.py

# compiled (non-interpret) Pallas kernel tests
run kernel_tests 2700 MOCO_TPU_TESTS=1 -- python -m pytest tests/test_tpu_kernels.py -q

# TPU-tunnel host->device transfer anchor (input-path evidence)
rm -rf /tmp/moco_input_profile_cache
run input_transfer 1800 -- python scripts/profile_input.py --batch 64 --n-images 1024 \
  --reps 2 --threads 1 --out-size 224 --src-size 256 \
  --profile-md artifacts/tpu_r4/input_profile_tpu.md --artifact artifacts/tpu_r4/input_profile_tpu.json

# EMAN key forward A/B (key_bn_running_stats): drops the key-side BN
# statistics pass — one third of the 55%-of-step BN-bytes cost center
# (PROFILE.md). Expected to COMPILE FINE (it removes reduces).
run bench_r50_eman 2700 BENCH_SKIP_DATA=1 BENCH_KEY_BN_EVAL=1 -- python bench.py

# Input-wire overlap A/B (ISSUE 5 tentpole) at the anchor geometry:
# bench.py now runs the with-data leg BOTH ways — sync iterator vs the
# device prefetch ring — and reports with_data{,_sync} per chip plus
# overlap_efficiency = achieved / min(host, device, wire). This is the
# on-hardware measurement of the round-5 with-data ceiling move
# (~288 imgs/s serial -> wire-bound ~2500 on this tunnel, device-bound
# on a pod host). Obs-overhead leg skipped: this leg is about the wire.
run input_overlap 2700 BENCH_SKIP_OBS_OVERHEAD=1 -- python bench.py

# bn_stats_rows compile-pathology bisect (VERDICT r4 #2): small ConvBN
# stacks, rows x variant grid, per-cell subprocess compiles timed.
# Runs BEFORE the full-step bn32 bench legs so the diagnosis lands even
# if those wedge; abandons (never kills) a timed-out compiling cell.
# 14400s > worst case (15 cells x 900s = 13500s): the OUTER timeout
# must never fire mid-grid — a TERM/KILL there orphans a compiling
# child against the single-client chip (the r4 wedge); the harness
# bounds itself per-cell and stops on the first abandoned cell.
run bn_compile_repro 14400 -- python scripts/bn_compile_repro.py \
  --depths 1 4 8 --rows 0 32 --variants mask fwd barrier slice \
  --cell-timeout 900 --abandon-on-timeout \
  --out artifacts/tpu_r4/bn_compile_repro.json

# BN-bytes lever A/Bs — the slow-compile suspects, LAST, 45 min each
run bench_r50_bn32 2700 BENCH_SKIP_DATA=1 BENCH_BN_STATS_ROWS=32 -- python bench.py
run bench_r50_bn64 2700 BENCH_SKIP_DATA=1 BENCH_BN_STATS_ROWS=64 -- python bench.py
run bench_r50_vg8 2700 BENCH_SKIP_DATA=1 BENCH_BN_VIRTUAL_GROUPS=8 -- python bench.py

date > "$L/battery_b_finished"
echo "battery part 2 complete" | tee -a "$L/battery.log"
