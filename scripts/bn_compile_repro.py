"""Minimal repro / bisect harness for the bn_stats_rows TPU compile
pathology (VERDICT r4 #2, PROFILE.md round-4 notes).

Observed: the r50/224 MoCo step with `--bn-stats-rows 32` compiles in
>15 min on the TPU backend vs ~3.5 min for the full-batch-BN baseline,
while the SAME program compiles FASTER than baseline on CPU — i.e. a
TPU-backend (Mosaic/layout/fusion) compile-time behavior, not a
graph-size explosion. This script isolates WHICH ingredient triggers it
by timing `jit(f).lower()` and `.compile()` separately over a grid:

  axis 1 — depth: a stack of D ConvBN(+ReLU) cells at r50 stage-1
           geometry (56x56x256-ish activations), D in --depths;
  axis 2 — rows: BN statistics subset size, in --rows (0 = full batch,
           the baseline arm);
  axis 3 — variant:
      slice      x[:r] subset statistics (the shipped implementation,
                 models/resnet.py BatchNorm);
      mask       full-row read with a row mask (same RESULT, no slice /
                 no pad-transpose in the backward — reads all bytes, so
                 it forfeits the lever; DIAGNOSIS control only);
      fwd        `slice` without value_and_grad (no backward pad): did
                 the transpose introduce it?
      align      `slice` with r rounded up to a multiple of 8 before
                 slicing (sublane alignment probe; only differs for
                 r not already 8-aligned);
      barrier    `slice` with an optimization_barrier around the
                 subset — breaks the slice out of XLA's fusion
                 clustering (candidate workaround if the pathology is
                 fusion/layout interaction, at the cost of one small
                 materialization per BN).

Each (depth, rows, variant) cell is compiled in a fresh subprocess so a
pathological cell can be timed out (--cell-timeout) without wedging the
parent or poisoning later cells, and so each cell pays its own clean
compile (the persistent compilation cache is DISABLED in children —
cache hits would report 0s and hide the pathology).

With --abandon-on-timeout (the TPU battery mode), a timed-out cell is
ABANDONED — never killed — and the harness STOPS: SIGKILLing a TPU
client mid-compile wedges the chip lease for 1h+ (the round-4 battery
incident), and later cells would only hang against the single-client
chip the abandoned child still holds. Order --rows/--depths so the
suspected-pathological cells come last.

Run on CPU (sanity: everything fast) or against the TPU tunnel (the
diagnosis; scripts/tpu_battery_r4b.sh stages it). Output: one table row
per cell to stdout + a JSON artifact with all timings.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHILD_ENV_FLAG = "BN_REPRO_CHILD"


def depth_cells(rows, variants):
    """Cell order within a depth: the rows=0 baseline FIRST (its timing
    anchors the bisect), control variants next, the shipped slice-subset
    suspects LAST — so an abandoned pathological cell forfeits the least
    information."""
    sub_rows = [r for r in rows if r]
    cells = [("slice", 0)] if 0 in rows and "slice" in variants else []
    cells += [(v, r) for v in variants if v != "slice" for r in sub_rows]
    if "slice" in variants:
        cells += [("slice", r) for r in sub_rows]
    return cells


def child_main() -> None:
    """Time lower+compile of one grid cell; print one JSON line."""
    from moco_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()
    import jax
    import jax.numpy as jnp
    import flax.linen as nn

    from moco_tpu.models.resnet import BatchNorm, conv_kernel_init

    spec = json.loads(os.environ["BN_REPRO_SPEC"])
    depth = spec["depth"]
    rows = spec["rows"]
    variant = spec["variant"]
    batch = spec["batch"]
    hw = spec["hw"]
    feats = spec["feats"]
    dtype = jnp.bfloat16 if spec["dtype"] == "bfloat16" else jnp.float32

    def _track_running_stats(mod, mean, var, feats):
        """Every variant must compile the SAME running-average EMA
        writes the real BatchNorm does (mutable batch_stats outputs
        change XLA's program structure) — otherwise a mask-vs-slice
        compile-time gap could be the stats writes, not the slice."""
        ra_mean = mod.variable(
            "batch_stats", "mean", lambda: jnp.zeros((feats,), jnp.float32)
        )
        ra_var = mod.variable(
            "batch_stats", "var", lambda: jnp.ones((feats,), jnp.float32)
        )
        if not mod.is_initializing():
            ra_mean.value = 0.9 * ra_mean.value + 0.1 * mean
            ra_var.value = 0.9 * ra_var.value + 0.1 * var

    class MaskBN(nn.Module):
        """Row-mask subset statistics: identical result to x[:r] stats,
        but the reduction reads every row (no slice, no backward pad)."""

        stats_rows: int
        dtype: jnp.dtype

        @nn.compact
        def __call__(self, x):
            feats = x.shape[-1]
            scale = self.param("scale", nn.initializers.ones, (feats,), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (feats,), jnp.float32)
            r = self.stats_rows or x.shape[0]
            mask = (jnp.arange(x.shape[0]) < r).astype(jnp.float32)
            bcast = (x.shape[0],) + (1,) * (x.ndim - 1)
            xf = x.astype(jnp.float32) * mask.reshape(bcast)
            denom = r * x.shape[1] * x.shape[2]
            axes = tuple(range(x.ndim - 1))
            mean = jnp.sum(xf, axis=axes) / denom
            mean2 = jnp.sum(jnp.square(xf), axis=axes) / denom
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            _track_running_stats(self, mean, var, feats)
            mul = scale * jax.lax.rsqrt(var + 1e-5)
            shift = bias - mean * mul
            return x * mul.astype(self.dtype) + shift.astype(self.dtype)

    class BarrierBN(nn.Module):
        """x[:r] subset statistics with an optimization_barrier around
        the sliced subset: same math as `slice`, but the barrier stops
        XLA fusing the slice into the surrounding conv/reduce clusters
        — the candidate workaround if the compile pathology is a
        fusion/layout interaction."""

        stats_rows: int
        dtype: jnp.dtype

        @nn.compact
        def __call__(self, x):
            feats = x.shape[-1]
            scale = self.param("scale", nn.initializers.ones, (feats,), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (feats,), jnp.float32)
            r = self.stats_rows or x.shape[0]
            sub = jax.lax.optimization_barrier(x[:r]).astype(jnp.float32)
            axes = tuple(range(sub.ndim - 1))
            mean = jnp.mean(sub, axis=axes)
            mean2 = jnp.mean(jnp.square(sub), axis=axes)
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            _track_running_stats(self, mean, var, feats)
            mul = scale * jax.lax.rsqrt(var + 1e-5)
            shift = bias - mean * mul
            return x * mul.astype(self.dtype) + shift.astype(self.dtype)

    class Stack(nn.Module):
        depth: int
        norm_rows: int
        variant: str
        dtype: jnp.dtype

        @nn.compact
        def __call__(self, x):
            x = x.astype(self.dtype)
            for _ in range(self.depth):
                x = nn.Conv(
                    feats, (3, 3), padding=[(1, 1), (1, 1)], use_bias=False,
                    kernel_init=conv_kernel_init, dtype=x.dtype,
                )(x)
                if self.variant == "mask":
                    x = MaskBN(stats_rows=self.norm_rows, dtype=self.dtype)(x)
                elif self.variant == "barrier":
                    x = BarrierBN(stats_rows=self.norm_rows, dtype=self.dtype)(x)
                else:
                    r = self.norm_rows
                    if self.variant == "align" and r:
                        r = (r + 7) // 8 * 8
                    x = BatchNorm(stats_rows=r, dtype=self.dtype)(x)
                x = nn.relu(x)
            return jnp.mean(x.astype(jnp.float32))

    model = Stack(depth=depth, norm_rows=rows, variant=variant, dtype=dtype)
    x = jnp.zeros((batch, hw, hw, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    params = variables["params"]
    stats = variables.get("batch_stats", {})

    def apply(p, x):
        # mutable batch_stats mirrors the real train step (BatchNorm
        # writes its running-average variables every training call)
        out, _ = model.apply(
            {"params": p, "batch_stats": stats}, x, mutable=["batch_stats"]
        )
        return out

    if variant == "fwd":
        f = apply
    else:
        def f(p, x):
            return jax.value_and_grad(lambda q: apply(q, x))(p)

    t0 = time.perf_counter()
    lowered = jax.jit(f).lower(params, x)
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    lowered.compile()
    t_compile = time.perf_counter() - t0
    print(json.dumps({
        "depth": depth, "rows": rows, "variant": variant,
        "lower_s": round(t_lower, 2), "compile_s": round(t_compile, 2),
        "backend": jax.default_backend(),
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--depths", type=int, nargs="*", default=[1, 4, 8, 16])
    ap.add_argument("--rows", type=int, nargs="*", default=[0, 32, 8])
    ap.add_argument("--variants", nargs="*",
                    default=["slice", "mask", "fwd"],
                    choices=("slice", "mask", "fwd", "align", "barrier"))
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--hw", type=int, default=56,
                    help="activation side (56 = r50 stage-1 at 224px input)")
    ap.add_argument("--feats", type=int, default=256)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--cell-timeout", type=int, default=1200)
    ap.add_argument("--abandon-on-timeout", action="store_true",
                    help="on a cell timeout, abandon (don't kill) the child "
                         "and stop — the TPU-battery mode (see docstring)")
    ap.add_argument("--out", default="artifacts/bn_compile_repro.json")
    args = ap.parse_args()

    results = []
    stop = False
    cells = depth_cells(args.rows, args.variants)
    print(f"{'depth':>5} {'rows':>5} {'variant':>8} {'lower_s':>8} {'compile_s':>10}")
    for depth in args.depths:
        if stop:
            break
        for variant, rows in cells:
            if stop:
                break
            spec = dict(
                depth=depth, rows=rows, variant=variant, batch=args.batch,
                hw=args.hw, feats=args.feats, dtype=args.dtype,
            )
            env = dict(os.environ)
            env[CHILD_ENV_FLAG] = "1"
            env["BN_REPRO_SPEC"] = json.dumps(spec)
            # a clean compile per cell: cache hits would hide the bug
            env["MOCO_NO_COMPILE_CACHE"] = "1"
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
            try:
                out, err = proc.communicate(timeout=args.cell_timeout)
                line = out.strip().splitlines()[-1] if out.strip() else ""
                try:
                    cell = json.loads(line) if proc.returncode == 0 and line else {
                        **spec, "error": f"rc={proc.returncode}",
                        "stderr_tail": err[-400:],
                    }
                except json.JSONDecodeError:
                    # a stray runtime notice on the child's last stdout
                    # line must cost one cell, not the grid
                    cell = {**spec, "error": "unparseable child output",
                            "stdout_tail": out[-400:]}
            except subprocess.TimeoutExpired:
                cell = {**spec, "error": f"timeout>{args.cell_timeout}s"}
                if args.abandon_on_timeout:
                    # leave the child compiling; it frees the chip lease
                    # when it finishes on its own (killing wedges it)
                    cell["abandoned"] = True
                    stop = True
                else:
                    proc.kill()
                    proc.communicate()
            results.append(cell)
            print(
                f"{depth:>5} {rows:>5} {variant:>8} "
                f"{cell.get('lower_s', '—'):>8} "
                f"{str(cell.get('compile_s', cell.get('error', '—'))):>10}",
                flush=True,
            )
            # incremental artifact: an outer kill must not discard
            # hours of already-timed chip compiles
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(results, f, indent=2)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"wrote {args.out}")
    if stop:
        print("stopped after an abandoned cell (see docstring); "
              "remaining grid cells not attempted")


if __name__ == "__main__":
    if os.environ.get(CHILD_ENV_FLAG):
        child_main()
    else:
        main()
