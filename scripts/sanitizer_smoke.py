#!/usr/bin/env python
"""Collective-schedule sanitizer smoke: prove the runtime divergence
detector end-to-end on a fake-8-device mesh, asserted hard.

    python scripts/sanitizer_smoke.py [--workdir DIR]

Two legs over the SAME real collective schedule (the a2a Shuffle-BN
exchange + a grad-style psum + the queue's key all_gather, traced
through `obs/comms.py` tags on an 8-virtual-device mesh):

1. **control** — two simulated processes record the schedule cleanly;
   their hashes agree, `ScheduleSanitizer.check()` passes, and the
   driver-level run (`--sanitize-collectives` equivalent) writes
   `collective_schedule_hash` on its metrics lines. Exit contribution:
   0.
2. **chaos** — process 1 re-records under an injected
   `diverge@site=shuffle.a2a` fault (`utils/faults.py`). Its hash must
   differ, `check()` must raise `ScheduleDivergenceError`, the message
   must carry a PER-SITE diff naming `shuffle.a2a`, and
   `schedule_diff.json` must land on disk (the CI artifact).
3. **zero23** — the ZeRO-2/3 bucketed collective schedule
   (parallel/zero.py `BucketPlan`: per-bucket `zero.gather_q.b<i>` /
   `zero.scatter.b<i>` sites): two clean processes agree on the
   bucketed schedule, and an injected `diverge@site=zero.gather_q.b0`
   is caught with the bucket named in the per-site diff.

The smoke exits nonzero if the detector misses the divergence OR
false-positives on the clean leg.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# 8 virtual CPU devices, pinned BEFORE jax initializes (same trick as
# tests/conftest.py and scripts/fleet_smoke.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

DIVERGE_SITE = "shuffle.a2a"


def trace_schedule(process_index: int) -> "ScheduleRecorder":
    """Trace the real collective schedule into a fresh recorder
    simulating one process: shuffle a2a + unshuffle + key all_gather +
    grad psum, all comms-tagged, on the 8-device mesh. A fresh
    shard_map closure per call forces a fresh trace so the tags
    re-fire."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from moco_tpu.analysis.sanitizer import ScheduleRecorder, install_recorder
    from moco_tpu.obs import comms
    from moco_tpu.parallel.compat import shard_map
    from moco_tpu.parallel.shuffle import (
        balanced_shuffle,
        balanced_unshuffle,
        unshuffle_gather,
    )

    recorder = ScheduleRecorder(process_index=process_index)
    prev = install_recorder(recorder)
    try:
        import numpy as np

        devices = jax.devices()
        mesh = Mesh(np.array(devices), ("data",))
        n = len(devices)

        def step(x, rng):
            y = balanced_shuffle(rng, x, "data")
            k = y * 2.0
            k = balanced_unshuffle(rng, k, "data")  # mocolint: disable=JX003  (involution reuses the key on purpose, same contract as parallel/shuffle.py)
            _, k_global = unshuffle_gather(k, jnp.argsort(jnp.arange(x.shape[0] * n)), "data")
            with comms.tag("grad.psum", "psum", k, n):
                g = lax.psum(k, "data")
            return g + k_global.sum()

        fn = shard_map(
            step, mesh=mesh,
            in_specs=(P("data"), P()), out_specs=P("data"),
            check_vma=False,  # nested-pjit rep inference trips on 0.4.x
        )
        x = jnp.arange(16 * n * 4, dtype=jnp.float32).reshape(16 * n, 4)
        rng = jax.random.PRNGKey(0)
        jax.block_until_ready(jax.jit(fn)(x, rng))
    finally:
        install_recorder(prev)
    return recorder


ZERO_DIVERGE_SITE = "zero.gather_q.b0"


def trace_zero_schedule(process_index: int) -> "ScheduleRecorder":
    """Trace the ZeRO-2/3 bucketed collective schedule into a fresh
    recorder simulating one process: a BucketPlan gather + scatter over
    a toy two-leaf tree (small bucket size forces >1 bucket) on the
    8-device mesh, every bucket comms-tagged."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from moco_tpu.analysis.sanitizer import ScheduleRecorder, install_recorder
    from moco_tpu.parallel.compat import shard_map
    from moco_tpu.parallel.zero import BucketPlan, shard_tree

    recorder = ScheduleRecorder(process_index=process_index)
    prev = install_recorder(recorder)
    try:
        devices = jax.devices()
        mesh = Mesh(np.array(devices), ("data",))
        n = len(devices)
        tree = {
            "a": jnp.arange(4096, dtype=jnp.float32).reshape(64, 64),
            "b": jnp.arange(100, dtype=jnp.float32),
        }
        plan = BucketPlan(jax.tree.leaves(tree), n, bucket_bytes=1024)
        sharded = shard_tree(tree, n)

        def fn(sh):
            local = jax.tree.map(lambda x: x[0], sh)
            leaves, treedef = jax.tree.flatten(local)
            full = jax.tree.unflatten(
                treedef, plan.gather(leaves, site="zero.gather_q")
            )
            grads_sh = plan.scatter_mean(jax.tree.leaves(full), site="zero.scatter")
            return sum(jnp.sum(g) for g in grads_sh)

        mapped = shard_map(
            fn, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("data", None), sharded),),
            out_specs=P(), check_vma=False,
        )
        jax.block_until_ready(jax.jit(mapped)(sharded))
    finally:
        install_recorder(prev)
    return recorder


def run_smoke(workdir: str) -> dict:
    from moco_tpu.analysis.sanitizer import (
        ScheduleDivergenceError,
        ScheduleSanitizer,
    )
    from moco_tpu.utils import faults

    report: dict = {"workdir": workdir}

    # ---- leg 1: clean control ----------------------------------------
    faults.clear()
    rec0 = trace_schedule(0)
    rec1 = trace_schedule(1)
    assert rec0.entries(), "no collective sites recorded — tag hook broken"
    sites = [e[0] for e in rec0.entries()]
    assert DIVERGE_SITE in sites, f"expected {DIVERGE_SITE!r} in {sites}"
    assert rec0.schedule_hash() == rec1.schedule_hash(), (
        "clean re-trace hashed differently — recorder is not deterministic"
    )
    san0 = ScheduleSanitizer(workdir, process_index=0, num_processes=2, recorder=rec0)
    san1 = ScheduleSanitizer(workdir, process_index=1, num_processes=2, recorder=rec1)
    san1.publish(step=0)
    san0.check(step=0)  # must NOT raise
    san1.check(step=0)
    report["control"] = {
        "hash": rec0.schedule_hash()[:12],
        "sites": sites,
        "ok": True,
    }
    print(f"control: {len(sites)} sites agree, hash {rec0.schedule_hash()[:12]}")

    # ---- leg 2: injected divergence ----------------------------------
    faults.install(f"diverge@site={DIVERGE_SITE}")
    try:
        rec1_div = trace_schedule(1)
    finally:
        faults.clear()
    assert rec1_div.schedule_hash() != rec0.schedule_hash(), (
        "diverge@ fault did not change the schedule hash"
    )
    san1_div = ScheduleSanitizer(
        workdir, process_index=1, num_processes=2, recorder=rec1_div
    )
    caught = None
    try:
        san1_div.check(step=1)
    except ScheduleDivergenceError as e:
        caught = str(e)
    assert caught is not None, "sanitizer MISSED the injected divergence"
    assert DIVERGE_SITE in caught, (
        f"divergence message lacks the per-site diff naming {DIVERGE_SITE!r}:\n{caught}"
    )
    diff_path = os.path.join(workdir, "schedule_diff.json")
    assert os.path.exists(diff_path), "schedule_diff.json artifact missing"
    with open(diff_path) as f:
        diff = json.load(f)
    assert diff["divergent_peers"] == [0], diff["divergent_peers"]
    assert any(DIVERGE_SITE in line for line in diff["diff"]), diff["diff"]
    report["chaos"] = {
        "hash": rec1_div.schedule_hash()[:12],
        "caught": True,
        "diff_lines": diff["diff"],
    }
    print(f"chaos: divergence at {DIVERGE_SITE!r} caught with per-site diff:")
    for line in diff["diff"]:
        print(f"  {line}")

    # ---- leg 3: ZeRO-2/3 bucketed collective schedule ----------------
    faults.clear()
    zdir = os.path.join(workdir, "zero23")
    os.makedirs(zdir, exist_ok=True)
    z0 = trace_zero_schedule(0)
    z1 = trace_zero_schedule(1)
    zsites = [e[0] for e in z0.entries()]
    gather_sites = [s for s in zsites if s.startswith("zero.gather_q.b")]
    assert len(gather_sites) > 1, (
        f"bucketed schedule should carry >1 gather bucket site, got {zsites}"
    )
    assert ZERO_DIVERGE_SITE in zsites, f"{ZERO_DIVERGE_SITE!r} not in {zsites}"
    assert z0.schedule_hash() == z1.schedule_hash(), (
        "clean zero23 re-trace hashed differently"
    )
    szan0 = ScheduleSanitizer(zdir, process_index=0, num_processes=2, recorder=z0)
    szan1 = ScheduleSanitizer(zdir, process_index=1, num_processes=2, recorder=z1)
    szan1.publish(step=0)
    szan0.check(step=0)  # must NOT raise on the bucketed schedule
    szan1.check(step=0)
    faults.install(f"diverge@site={ZERO_DIVERGE_SITE}")
    try:
        z1_div = trace_zero_schedule(1)
    finally:
        faults.clear()
    szan1_div = ScheduleSanitizer(
        zdir, process_index=1, num_processes=2, recorder=z1_div
    )
    caught = None
    try:
        szan1_div.check(step=1)
    except ScheduleDivergenceError as e:
        caught = str(e)
    assert caught is not None, "sanitizer MISSED the bucketed-gather divergence"
    assert ZERO_DIVERGE_SITE in caught, (
        f"divergence message lacks the bucket site {ZERO_DIVERGE_SITE!r}:\n{caught}"
    )
    report["zero23"] = {"sites": zsites, "caught": True}
    print(
        f"zero23: bucketed schedule agrees ({len(zsites)} sites); "
        f"diverge at {ZERO_DIVERGE_SITE!r} caught"
    )
    return report


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--workdir", default=None,
        help="artifact directory (default: a fresh temp dir)",
    )
    args = ap.parse_args()
    workdir = args.workdir or tempfile.mkdtemp(prefix="sanitizer_smoke_")
    os.makedirs(workdir, exist_ok=True)
    report = run_smoke(workdir)
    with open(os.path.join(workdir, "sanitizer_smoke.json"), "w") as f:
        json.dump(report, f, indent=2)
    print(f"sanitizer smoke OK — artifacts in {workdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
