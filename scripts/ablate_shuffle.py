"""Shuffle-BN cheat + component-sensitivity ablation (VERDICT r2 #2).

The reference exists because of two design answers: Shuffle-BN stops
per-device BatchNorm statistics from leaking which key is the positive
(`moco/builder.py:~L79-126` — BASELINE.json's "signature leakage"), and
the EMA key encoder keeps the dictionary consistent (`~L52-60`). This
script reproduces the *phenomena* those designs answer, on the in-repo
synthetic learning-signal task, with one arm per strategy:

  none         — no decorrelation: the cheat arm. Expected: inflated
                 (K+1)-way contrast accuracy, degraded frozen-feature
                 kNN (the model keys on BN statistics, not content).
  gather_perm  — reference-exact Shuffle-BN (same-seed permutation
                 replacing the NCCL broadcast).
  a2a          — balanced all_to_all permutation; the cheaper mode whose
                 "statistically equivalent decorrelation" claim
                 (moco_tpu/parallel/shuffle.py) this run tests.
  syncbn       — no shuffle, cross-replica BN over the data axis (the
                 alternative the reference only uses in detection).
  m0           — gather_perm but EMA momentum 0 (key encoder = query
                 encoder every step): the no-momentum arm of the MoCo
                 paper's ablation (arXiv:1911.05722 §4.1, where m=0
                 fails to converge at ImageNet scale).

All arms share seeds, data, schedule, and budget; the only difference is
the strategy flag. Per-device batch is kept small (global 64 over 8
devices = 8/device) because BN statistics over few samples leak MORE —
the regime where the cheat is easiest to see.

Run (8 virtual CPU devices — per-device BN needs a multi-device mesh):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/ablate_shuffle.py

Each arm writes artifacts/ablation/<arm>.json as it finishes (re-running
skips finished arms; delete the JSON to redo). The summary table is
written into REPORT.md between marker comments (idempotent).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.utils.platform import enable_persistent_compilation_cache, pin_platform_from_env

pin_platform_from_env()
enable_persistent_compilation_cache()

ABLATION_DIR = "artifacts/ablation"

ARMS = ("none", "gather_perm", "a2a", "syncbn", "m0", "eman", "eman_warmup")


def run_arm(arm: str, args) -> dict:
    import jax
    import numpy as np

    from moco_tpu.data.datasets import build_dataset
    from moco_tpu.train import train
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        ParallelConfig,
        TrainConfig,
    )

    n_dev = len(jax.devices())
    # 'm0' isolates the EMA encoder on the reference shuffle; 'eman'
    # replaces Shuffle-BN entirely with the running-stats key forward
    # (key_bn_running_stats) — its accuracy arm at this budget
    # 'eman_warmup' adds the round-5 key-stats fast-tracking schedule
    # (key_bn_stats_warmup); 'eman' PINS it off so re-runs stay
    # artifact-comparable with the r4 no-warmup seeds.
    eman = arm in ("eman", "eman_warmup")
    shuffle = "gather_perm" if arm == "m0" else "none" if eman else arm
    momentum = 0.0 if arm == "m0" else args.momentum
    # --virtual-groups G emulates the G-device per-device-BN topology
    # inside however many real devices exist (oracle-tested equivalent,
    # tests/test_resnet.py) — the TPU-single-chip path for this matrix.
    # syncbn is cross-replica by construction and does not compose.
    # eman keeps vg on its QUERY side so the matrix stays
    # single-variable (its key path reads no batch statistics either
    # way; the encoder gate exempts key_bn_running_stats).
    vg = 0 if arm == "syncbn" else args.virtual_groups
    if vg > 1:
        per_dev = args.batch // n_dev
        if per_dev % vg or per_dev // vg < 2:
            # 1-row groups degenerate (x - mean == 0: BN outputs its bias
            # and every arm collapses to chance — a silently wrong matrix,
            # not an error); non-divisible values fail opaquely inside jit
            raise SystemExit(
                f"--virtual-groups {vg} needs per-device batch {per_dev} "
                f"divisible into groups of >= 2 rows"
            )
    workdir = os.path.join(args.workdir, arm)
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18",
            dim=128,
            num_negatives=args.queue,
            momentum=momentum,
            temperature=0.2,
            mlp=True,
            shuffle=shuffle,
            cifar_stem=True,
            compute_dtype="bfloat16" if jax.default_backend() == "tpu" else "float32",
            bn_virtual_groups=vg,
            # the cheat arm NEEDS the leak build_encoder loudly rejects:
            # per-group statistics with unpermuted keys, opted into
            # explicitly and only here (this is the positive control)
            allow_leaky_bn=(arm == "none" and vg > 1),
            key_bn_running_stats=eman,
            key_bn_stats_warmup=(arm == "eman_warmup"),
        ),
        optim=OptimConfig(lr=args.lr, epochs=args.epochs, cos=True, warmup_epochs=1),
        data=DataConfig(
            dataset=args.dataset,
            image_size=32,
            global_batch=args.batch,
            aug_plus=True,
            crops_only=args.crops_only,
        ),
        parallel=ParallelConfig(num_data=n_dev),
        workdir=workdir,
        knn_every_epochs=args.knn_every,
        knn_k=20,
        log_every=8,
        seed=args.seed,
    )

    bank = build_dataset(args.dataset, None, 32, train=True)
    test = build_dataset(args.dataset, None, 32, train=False)
    # same train slice for every arm; kNN bank = the train slice itself
    bank.num_examples = args.examples
    test.num_examples = max(args.examples // 4, 256)

    dataset = build_dataset(args.dataset, None, 32, train=True)
    dataset.num_examples = args.examples

    final = train(config, dataset=dataset, knn_datasets=(bank, test))

    # pull the full trajectories back out of the run's metrics.jsonl
    rows = []
    with open(os.path.join(workdir, "metrics.jsonl")) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    knns = [(r["epoch"], r["knn_top1"]) for r in rows if "knn_top1" in r]
    accs = [(r["step"], r["acc1"]) for r in rows if "acc1" in r]
    losses = [(r["step"], r["loss"]) for r in rows if "loss" in r]
    # contrast acc averaged over the last quarter of logged steps: the
    # cheat signature is PERSISTENTLY high contrast acc late in training
    # (honest arms get harder as the queue fills with real keys)
    tail = max(len(accs) // 4, 1)
    return {
        "arm": arm,
        "shuffle": shuffle,
        "ema_momentum": momentum,
        "dataset": args.dataset,
        "num_devices": n_dev,
        "virtual_groups": vg,
        "global_batch": args.batch,
        "per_device_batch": args.batch // n_dev,
        # rows per BN-statistics group: syncbn spans the whole global
        # batch; virtual groups split each device's shard into vg groups
        "bn_group_rows": (
            args.batch if arm == "syncbn"
            else args.batch // (n_dev * vg) if vg > 1
            else args.batch // n_dev
        ),
        "queue": args.queue,
        "epochs": args.epochs,
        "examples": args.examples,
        "seed": args.seed,
        "backend": jax.default_backend(),
        "final_loss": final.get("loss"),
        "contrast_acc_tail_mean": float(np.mean([a for _, a in accs[-tail:]])),
        "contrast_acc_trajectory": accs,
        "loss_trajectory": losses,
        "knn_trajectory": knns,
        "final_knn_top1": knns[-1][1] if knns else None,
    }


def render_section(ablation_dir: str = ABLATION_DIR) -> str | None:
    """Markdown section from whatever arm JSONs exist; None if none do."""
    results = {}
    if not os.path.isdir(ablation_dir):
        return None
    for name in sorted(os.listdir(ablation_dir)):
        if name.endswith(".json"):
            with open(os.path.join(ablation_dir, name)) as f:
                data = json.load(f)
            if isinstance(data, dict) and "queue" in data:  # arm JSONs only
                results[name[:-5]] = data
    if not results:
        return None
    any_r = next(iter(results.values()))
    # a re-run at different flags silently skips finished arms, so a
    # mixed-budget table is easy to produce by accident — and its
    # header would then claim "identical data/schedule across arms"
    # over arms trained on different budgets. Fail loudly instead.
    budgets = {
        (r["epochs"], r["examples"], r["global_batch"], r["queue"])
        for r in results.values()
    }
    # vg is intentionally per-arm (syncbn forces 0), so check it only
    # across the arms that accept it
    vgs = {
        r.get("virtual_groups", 0)
        for r in results.values()
        if r["arm"] != "syncbn"
    }
    if len(budgets) != 1 or len(vgs) > 1:
        raise ValueError(
            f"arm JSONs in {ablation_dir} were produced at different "
            f"budgets {sorted(budgets)} / virtual_groups {sorted(vgs)} — "
            "delete the stale ones (or use a separate --out dir) before "
            "rendering one table"
        )
    k = any_r["queue"]
    contrast_chance = 100.0 / (1 + k)
    chance = 100.0 / 32 if any_r["dataset"] == "synthetic_hard" else 100.0 / 8
    lines = [
        f"## Shuffle-BN cheat + component ablation (`{any_r['dataset']}`)",
        "",
        f"`scripts/ablate_shuffle.py` on `{any_r['dataset']}` ({any_r['backend']}, "
        f"{any_r['num_devices']} devices, global batch {any_r['global_batch']} = "
        f"{any_r['per_device_batch']}/device"
        + f", K={k}, {any_r['epochs']} epochs, "
        f"seed {any_r['seed']}; identical data/schedule across arms; "
        "BN rows/group is per-arm below — syncbn's statistics span the "
        "whole batch by construction).",
        "",
        "| Arm | BN decorrelation | BN rows/group | EMA m | contrast acc (tail mean) | kNN top-1 (final) |",
        "|---|---|---|---|---|---|",
    ]
    for arm in ARMS:
        r = results.get(arm)
        if r is None:
            continue
        label = {
            "none": "**none (cheat arm)**",
            "gather_perm": "Shuffle-BN (reference-exact)",
            "a2a": "balanced all_to_all",
            "syncbn": "cross-replica BN",
            "m0": "Shuffle-BN, no EMA",
            "eman": "EMAN key (running-stats BN, no shuffle)",
            "eman_warmup": "EMAN key + stats-EMA warmup schedule",
        }[arm]
        knn = r["final_knn_top1"]
        rows = r.get("bn_group_rows")
        rows_cell = str(rows) if rows is not None else "—"
        knn_cell = f"{knn:.2f}%" if knn is not None else "n/a"
        lines.append(
            f"| `{arm}` | {label} | {rows_cell} | {r['ema_momentum']} | "
            f"{r['contrast_acc_tail_mean']:.2f}% | {knn_cell} |"
        )
    lines += [
        "",
        f"(contrast-acc chance {contrast_chance:.3f}%; kNN chance {chance:.1f}%.)",
        "",
        "What each arm answers: `none` is the cheat arm (the BN-statistics",
        "leak the reference was built to prevent, `moco/builder.py:~L79-126`",
        "— its signature, when it develops, is contrast accuracy above the",
        "honest arms with degraded kNN); `a2a` vs `gather_perm` tests the",
        "cheaper balanced-permutation mode's equivalence claim",
        "(moco_tpu/parallel/shuffle.py); `syncbn` is the no-shuffle",
        "alternative; `m0` isolates the EMA encoder (arXiv:1911.05722",
        "§4.1). Arms within each other's noise band mean the phenomenon",
        "has not developed at this budget — the mechanism-level",
        "leak-probe section is the sharper instrument either way. Raw",
        "per-arm trajectories: the arm JSONs next to this table's data.",
    ]
    return "\n".join(lines)


def write_into_report(
    report_path: str = "REPORT.md",
    ablation_dir: str = ABLATION_DIR,
    marker: str = "ablation",
) -> None:
    """Insert/replace the marker-delimited ablation section in REPORT.md."""
    section = render_section(ablation_dir)
    if section is None:
        return
    from moco_tpu.utils.report import replace_marker_block

    replace_marker_block(report_path, marker, section)
    print(f"ablation section ({marker}) written into {report_path}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arms", nargs="*", default=list(ARMS), choices=ARMS)
    ap.add_argument("--dataset", default="synthetic_learnable",
                    choices=("synthetic_learnable", "synthetic_hard",
                             "synthetic_leak_control"))
    ap.add_argument("--crops-only", action="store_true",
                    help="geometric-only augmentation (RRC+flip+normalize) — "
                    "required for the leak-control task, whose weak global "
                    "tint photometric jitter would swamp")
    ap.add_argument("--workdir", default="/tmp/moco_ablate")
    ap.add_argument("--out", default=ABLATION_DIR)
    ap.add_argument("--examples", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--queue", type=int, default=2048)
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--knn-every", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.03)
    ap.add_argument("--momentum", type=float, default=0.99)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--virtual-groups", type=int, default=0,
                    help="emulate a G-device per-device-BN topology with "
                    "BatchNorm virtual groups (runs the matrix on a single "
                    "TPU chip ~2 orders of magnitude faster than the "
                    "8-virtual-CPU-device mesh); syncbn arm ignores it")
    ap.add_argument("--report", default="REPORT.md")
    ap.add_argument("--marker", default="ablation",
                    help="report section marker; a second matrix (e.g. on "
                    "synthetic_hard) needs its own marker AND its own --out "
                    "dir, else the arm JSONs overwrite each other")
    args = ap.parse_args()

    os.makedirs(args.out, exist_ok=True)
    for arm in args.arms:
        out_path = os.path.join(args.out, f"{arm}.json")
        if os.path.exists(out_path):
            print(f"[{arm}] done already ({out_path}); skipping")
            continue
        print(f"[{arm}] running...", flush=True)
        result = run_arm(arm, args)
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[{arm}] contrast tail {result['contrast_acc_tail_mean']:.2f}%  "
              f"kNN {result['final_knn_top1']}")
    write_into_report(args.report, args.out, marker=args.marker)


if __name__ == "__main__":
    main()
