#!/bin/bash
# EMAN accuracy arm, RE-RUN with the key-stats EMA warmup schedule
# (MocoConfig.key_bn_stats_warmup, round-5): 3 seeds at the EXACT
# seed-variance budget so the result pools against the r4 table
# (REPORT.md "EMAN key forward": 35.55 ± 4.49 vs gather_perm's
# 53.65 ± 0.59 without the warmup). If the staleness mechanism the r4
# analysis proposed is right, fast-tracked early statistics should
# close most of the deficit; if not, the preset gets demoted.
set -u
cd "$(dirname "$0")/.."
mkdir -p artifacts/eman_warmup
for seed in 0 1 2; do
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python scripts/ablate_shuffle.py \
    --arms eman_warmup \
    --epochs 10 --examples 1024 --batch 64 --queue 2048 \
    --seed "$seed" \
    --workdir "/tmp/moco_eman_warmup_seed$seed" \
    --out "artifacts/eman_warmup/seed$seed" \
    --report "/tmp/eman_warmup_scratch.md" --marker "eman-warmup-scratch" \
    >> artifacts/eman_warmup/run.log 2>&1
done
echo done > artifacts/eman_warmup/finished
