#!/bin/bash
# Round-4 TPU learning-chain battery (VERDICT r3 items 2, 3, 5, 6, 7)
# — run AFTER scripts/tpu_battery.sh (the TPU admits ONE client).
# Sequential; every leg is resumable (arm JSONs / checkpoints skip
# finished work). Ordered by verdict priority so a mid-run tunnel
# death leaves the most important evidence behind first.
#
# Output: artifacts/tpu_chains_r4/*.log + per-leg artifact dirs.
set -u
cd "$(dirname "$0")/.."
L=artifacts/tpu_chains_r4
mkdir -p "$L"
date > "$L/chains_started"

source "$(dirname "$0")/lib_backend.sh"  # wait_backend (shared guard)

run() { # name timeout_s -- cmd...
  local name=$1 t=$2; shift 2; shift # consume "--"
  wait_backend
  echo "=== $name ($(date +%H:%M:%S)) ===" | tee -a "$L/chains.log"
  timeout -k 60 "$t" "$@" > "$L/$name.out" 2> "$L/$name.log"
  echo "rc=$? $name" | tee -a "$L/chains.log"
}

# 1. the 32-class gate at the headline chain's budget (VERDICT r3 #3:
#    "budget is binding" is the claim under test — 30 ep x 4096 ex x
#    batch 256 is ~12x the CI budget where every variant failed).
#    Scratch report: the hard-signal REPORT.md section is folded by
#    hand from signal_summary.json (the main body belongs to the
#    8-class headline chain).
run signal32 7200 -- python scripts/learning_signal.py \
  --dataset synthetic_learnable32 --epochs 30 --batch 256 \
  --examples 4096 --queue 4096 \
  --workdir /tmp/moco_signal32_tpu --report "$L/signal32_report.md"

# 2. headline 8-class chain ON TPU — the platform upgrade of the main
#    REPORT.md body (until now CPU-only) and the CONTROL arm for the
#    bn_stats_rows accuracy comparison (identical budget + platform).
run signal8 7200 -- python scripts/learning_signal.py \
  --epochs 30 --batch 256 --examples 4096 --queue 4096 \
  --workdir /tmp/moco_signal8_tpu --report REPORT.md

# 3. the BN-bytes lever's accuracy arm (VERDICT r3 #2): same budget,
#    statistics from the first 32 of 256 rows. A step-time win that
#    degrades the probe is not a win; this is the degradation check.
run signal8_bn32 7200 -- python scripts/learning_signal.py \
  --epochs 30 --batch 256 --examples 4096 --queue 4096 --bn-stats-rows 32 \
  --workdir /tmp/moco_signal8_bn32_tpu --report "$L/bn32_report.md"

# 3b. the EMAN lever's accuracy arm: key forward on eval-mode BN with
#     EMA'd running stats (key_bn_running_stats) at the same budget —
#     companion to the BENCH_KEY_BN_EVAL step-time A/B.
run signal8_eman 7200 -- python scripts/learning_signal.py \
  --epochs 30 --batch 256 --examples 4096 --queue 4096 --key-bn-eval \
  --workdir /tmp/moco_signal8_eman_tpu --report "$L/eman_report.md"

# 4. BN-cheat positive control (VERDICT r3 #5): the leak-control task
#    (weak global tint, iid noise otherwise), geometric-only crops,
#    2-row BN groups (batch 64 / 32 virtual groups — the corr-0.74
#    fingerprint regime), 30 epochs. Arm 'none' opts into the leak
#    via allow_leaky_bn; gather_perm/a2a must remove it.
run leak_ablate 10800 -- python scripts/ablate_shuffle.py \
  --arms none gather_perm a2a --dataset synthetic_leak_control --crops-only \
  --virtual-groups 32 --batch 64 --examples 2048 --queue 2048 \
  --epochs 30 --knn-every 5 \
  --workdir /tmp/moco_leak_tpu --out artifacts/leak_control \
  --marker ablation-leak

# 5. mechanism probe on those checkpoints: aligned-vs-shuffled contrast
#    accuracy under the trained 2-row grouping (the sharper instrument;
#    arm 'none' should finally show a drop, the honest arms ~0)
run leak_probe 3600 -- python scripts/leak_probe.py \
  --arms none gather_perm a2a --workdir /tmp/moco_leak_tpu \
  --batches 8 --out artifacts/leak_probe_control.json \
  --marker leak-probe-control

# 6. v3/ViT at larger-than-tiny scale (VERDICT r3 #6): vit_s16
#    (384-wide, 12-deep) on the TPU chip, same budget as the headline
#    chain; replaces the vit_tiny/CPU v3-signal section in REPORT.md.
run v3_vit_s16 10800 -- python scripts/learning_signal.py \
  --v3 --arch vit_s16 --epochs 30 --batch 256 --examples 4096 \
  --workdir /tmp/moco_signal_v3s16_tpu --report REPORT.md

# 7. LARS large-batch path (VERDICT r3 #7): one measured data point,
#    batch 512, LARS vs SGD, same budget; writes the lars-check
#    REPORT.md section with median step time per arm.
run lars 7200 -- python scripts/lars_check.py

# durable copies of the /tmp run summaries (workdirs are scratch)
for d in moco_signal32_tpu moco_signal8_tpu moco_signal8_bn32_tpu \
         moco_signal8_eman_tpu moco_signal_v3s16_tpu; do
  for f in signal_summary.json signal_summary_v3.json metrics.jsonl; do
    [ -f "/tmp/$d/$f" ] && cp "/tmp/$d/$f" "$L/${d}_${f}"
  done
done

date > "$L/chains_finished"
echo "chains complete" | tee -a "$L/chains.log"
