#!/usr/bin/env python
"""Elastic-training chaos smoke: deterministic `kill@host` on a fake-8
mesh must trigger the full checkpoint-and-rescale loop — detection →
consensus → emergency checkpoint → reshard → rescale → in-process
resume — with loss continuity against an uninterrupted control.

    python scripts/elastic_smoke.py [--workdir DIR]

(The script pins an 8-virtual-device CPU platform itself; each virtual
device doubles as a simulated "host" — the FleetAggregator's
one-device-per-host convention.)

Two ZeRO-2/3 driver runs (layer-granular per-group gather schedule —
the rescale leg for ISSUE 20's new stage) on the same seed:

  control  uninterrupted fake-8 run (3 epochs × 2 steps, batch 64)
  chaos    same config + `--elastic`, with `kill@host=2:at=3` injected:
           simulated host 2 stops beating at global step 3

The chaos run must, without a from-scratch restart:

1. fire the `heartbeat_loss` alert (obs/alerts.py default rule at the
   configurable `--heartbeat-timeout`) AND the elastic trigger on the
   same stale heartbeat;
2. agree on the rescale (consensus file published), take an emergency
   checkpoint whose extras carry `reason: "rescale"` + the plan;
3. emit a schema'd `event: "rescale"` metrics line with the old/new mesh
   shape (8 → 4: the widest surviving width preserving the queue's
   `K % global_batch == 0` invariant at constant per-device batch) and
   the re-derived hyperparameters (κ = 1/2: LR halves, EMA momentum
   becomes m^κ — "How to Scale Your EMA", arXiv:2307.13813);
4. reshard the ZeRO flat shards onto the 4-wide mesh through the
   layout-aware resume (`reshard_state`), visible as the per-device
   at-rest state footprint DOUBLING across the rescale;
5. finish all epochs in-process with a final-epoch loss within
   tolerance of the control.

CI runs this in the tier-1 job and uploads metrics.jsonl, alerts.jsonl,
the heartbeat files (the dead host's stale one included), and the
summary as artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

EPOCHS = 3
SPE = 2  # steps per epoch (pinned, so the schedule is batch-independent)
KILL_STEP = 3  # global step at which simulated host 2 stops beating
KILL_HOST = 2
# 8 hosts, per-device batch 8, K=128: the widest surviving width with
# 128 % (8·n) == 0 at n <= 7 is n = 4 (see elastic.feasible_width)
EXPECT_NEW_NUM_DATA = 4
LOSS_TOL = 0.10  # relative final-epoch loss tolerance vs the control


def _config(workdir: str, elastic: bool, sanitize_threads: bool = False):
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        ParallelConfig,
        TrainConfig,
    )

    return TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=128, momentum=0.99,
            temperature=0.2, mlp=True, shuffle="none", cifar_stem=True,
            compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=EPOCHS, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=64, num_workers=2),
        # ZeRO-2/3 with the layer-granular schedule (ISSUE 20): the
        # rescale must route the persistent flat shards through
        # reshard_state, not just replicated params — and the per-group
        # gather pipeline must survive an 8 -> 4 mesh rebuild mid-run
        parallel=ParallelConfig(
            num_data=8, shard_weight_update=True, zero_stage=3,
            zero_layer_granular=True,
        ),
        workdir=workdir,
        log_every=1,
        steps_per_epoch=SPE,
        checkpoint_keep=0,  # keep every step: the rescale save is inspected
        obs_probe_every=2,
        fleet_metrics=True,
        alert_rules="default",
        elastic=elastic,
        heartbeat_timeout=5.0,
        # mocolint v3 runtime arm: trace lock-acquisition order through
        # the whole checkpoint-and-rescale storm (heartbeat writers,
        # prefetch ring, async gatherer); a cycle aborts the run, a
        # clean pass writes lock_order.json next to the schedule files
        sanitize_threads=sanitize_threads,
    )


def run_control(workdir: str, sanitize_threads: bool = False) -> dict:
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train

    return train(
        _config(workdir, elastic=False, sanitize_threads=sanitize_threads),
        dataset=SyntheticDataset(num_examples=4 * 64, image_size=16),
    )


def run_chaos(workdir: str, sanitize_threads: bool = False) -> dict:
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train
    from moco_tpu.utils import faults

    faults.install(f"kill@host={KILL_HOST}:at={KILL_STEP}")
    try:
        return train(
            _config(workdir, elastic=True, sanitize_threads=sanitize_threads),
            dataset=SyntheticDataset(num_examples=4 * 64, image_size=16),
        )
    finally:
        faults.clear()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


def assert_surface(workdir: str, result: dict, control: dict) -> dict:
    from moco_tpu.obs import schema
    from moco_tpu.utils.checkpoint import CheckpointManager

    metrics_path = os.path.join(workdir, "metrics.jsonl")
    errors = schema.validate_file(metrics_path)
    assert not errors, f"schema violations: {errors[:5]}"
    records = schema.read_metrics(metrics_path)

    # -- 1. the run finished all epochs in-process, losses finite -------
    assert result["epoch"] == EPOCHS - 1, f"chaos run ended at epoch {result['epoch']}"
    train_lines = [r for r in records if "loss" in r and "event" not in r]
    assert all(r["loss"] is not None for r in train_lines), "non-finite loss logged"

    # -- 2. exactly one rescale event with the derived plan -------------
    rescales = [r for r in records if r.get("event") == "rescale"]
    assert len(rescales) == 1, f"expected 1 rescale event, got {len(rescales)}"
    ev = rescales[0]
    assert ev["rescale/dead_hosts"] == [KILL_HOST], ev
    assert ev["rescale/old_num_data"] == 8, ev
    assert ev["rescale/new_num_data"] == EXPECT_NEW_NUM_DATA, ev
    assert ev["rescale/old_global_batch"] == 64, ev
    assert ev["rescale/new_global_batch"] == 8 * EXPECT_NEW_NUM_DATA, ev
    kappa = ev["rescale/kappa"]
    assert abs(kappa - 0.5) < 1e-9, f"kappa {kappa} != 0.5"
    # the EMA-scaling rule: momentum re-derives as m^kappa, LR linearly
    assert abs(ev["rescale/momentum"] - 0.99**0.5) < 1e-9, ev
    assert abs(ev["rescale/lr"] - 0.03 * 0.5) < 1e-9, ev

    # -- 3. the heartbeat_loss alert fired on the same staleness --------
    alerts = _read_jsonl(os.path.join(workdir, "alerts.jsonl"))
    assert any(a["rule"] == "heartbeat_loss" for a in alerts), alerts
    assert os.path.exists(os.path.join(workdir, f"heartbeat.p{KILL_HOST}.json")), (
        "dead host's stale heartbeat file missing — the merged-heartbeat "
        "table could not name it"
    )
    assert os.path.exists(os.path.join(workdir, "rescale.p0.json")), (
        "no consensus file published"
    )

    # -- 4. the emergency checkpoint carries the rescale reason + plan --
    mgr = CheckpointManager(workdir, keep=0)
    extras = {s: mgr.read_extra(s) for s in mgr.all_steps()}
    rescue = [e for e in extras.values() if e.get("reason") == "rescale"]
    assert rescue, f"no rescale emergency checkpoint: { {s: e.get('reason') for s, e in extras.items()} }"
    plan = rescue[0]["rescale"]
    assert plan["dead_hosts"] == [KILL_HOST] and plan["new_num_data"] == EXPECT_NEW_NUM_DATA
    # the final checkpoint was written by the SURVIVING mesh
    final_extra = extras[max(extras)]
    assert final_extra["num_data"] == EXPECT_NEW_NUM_DATA, final_extra
    assert final_extra["epoch"] == EPOCHS - 1, final_extra
    mgr.close()
    assert not os.path.isdir(os.path.join(workdir, "quarantine")), (
        "the rescale resume quarantined a checkpoint — the reshard path "
        "misread a layout change as corruption"
    )

    # -- 5. the reshard is visible: per-device at-rest state doubles ----
    rescale_step = ev["step"]
    pre = [r for r in train_lines if r["step"] <= rescale_step]
    post = [r for r in train_lines if r["step"] > rescale_step]
    assert len(post) >= 2 * SPE, f"only {len(post)} post-rescale training lines"
    s_pre, s_post = pre[-1]["hbm_state_bytes"], post[-1]["hbm_state_bytes"]
    assert s_post > 1.5 * s_pre, (
        f"per-device state {s_pre} -> {s_post}: the 8->4 reshard should "
        "roughly double the flat-shard footprint"
    )

    # -- 6. loss continuity vs the uninterrupted control ----------------
    rel = abs(result["loss"] - control["loss"]) / abs(control["loss"])
    assert rel <= LOSS_TOL, (
        f"post-rescale final-epoch loss {result['loss']:.4f} deviates "
        f"{rel:.1%} from control {control['loss']:.4f} (> {LOSS_TOL:.0%})"
    )
    return {
        "rescale_event": ev,
        "final_loss": result["loss"],
        "control_loss": control["loss"],
        "loss_rel_dev": rel,
        "state_bytes_pre": s_pre,
        "state_bytes_post": s_post,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description="elastic checkpoint-and-rescale chaos smoke")
    ap.add_argument("--workdir", default=None, help="default: a fresh temp dir")
    ap.add_argument(
        "--sanitize-threads", action="store_true",
        help="run both legs under the mocolint v3 lock-order sanitizer "
        "(strict: an order cycle anywhere in the rescale storm aborts); "
        "asserts the clean lock_order.json artifact exists",
    )
    ap.add_argument(
        "--contract-coverage", action="store_true",
        help="mocolint v4 runtime arm: record which fault hooks and "
        "schema validators actually fire across both legs, write "
        "contract_coverage.json, and FAIL if the kill@host hook or the "
        "rescale/* validators never ran",
    )
    args = ap.parse_args()
    base = args.workdir or tempfile.mkdtemp(prefix="elastic_smoke_")
    control_dir = os.path.join(base, "control")
    chaos_dir = os.path.join(base, "chaos")
    os.makedirs(control_dir, exist_ok=True)
    os.makedirs(chaos_dir, exist_ok=True)

    recorder = None
    if args.contract_coverage:
        from moco_tpu.analysis import contracts as contract_cov

        recorder = contract_cov.install_recorder()

    control = run_control(control_dir, sanitize_threads=args.sanitize_threads)
    chaos = run_chaos(chaos_dir, sanitize_threads=args.sanitize_threads)
    # assert_surface re-validates metrics.jsonl — with the recorder
    # wired into obs/schema that doubles as validator coverage
    summary = assert_surface(chaos_dir, chaos, control)

    if recorder is not None:
        cov = recorder.snapshot()
        contract_cov.uninstall_recorder()
        gate_faults = ["kill@host"]
        gate_validators = ["rescale/dead_hosts", "rescale/new_num_data"]
        missing = contract_cov.check_coverage(
            cov, fault_sites=gate_faults, validators=gate_validators
        )
        with open(os.path.join(base, "contract_coverage.json"), "w") as f:
            json.dump({
                "coverage": cov,
                "gates": {
                    "fault_sites": gate_faults,
                    "validators": gate_validators,
                },
                "missing": missing,
            }, f, indent=2, sort_keys=True)
        assert not missing, (
            f"newly-dead contracts (registered but never fired): {missing}"
        )
        summary["contract_coverage"] = {
            "fault_hooks": len(cov["fault_hooks"]),
            "validators": len(cov["validators"]),
            "missing": 0,
        }
    if args.sanitize_threads:
        # the runs completed (no LockOrderError) AND left their reports:
        # the clean --sanitize-threads pass the CI leg asserts
        for leg_dir in (control_dir, chaos_dir):
            rep_path = os.path.join(leg_dir, "lock_order.json")
            assert os.path.isfile(rep_path), f"missing {rep_path}"
            with open(rep_path) as f:
                rep = json.load(f)
            assert not rep["cycles"], rep["cycles"]
        summary["sanitize_threads"] = {"clean": True}
    with open(os.path.join(base, "elastic_smoke.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(
        f"elastic smoke OK: mesh 8 -> {EXPECT_NEW_NUM_DATA} at step "
        f"{summary['rescale_event']['step']}, final loss "
        f"{summary['final_loss']:.4f} vs control {summary['control_loss']:.4f} "
        f"({summary['loss_rel_dev']:.1%} dev) — artifacts in {base}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
