"""Aggregate the shuffle-mode ablation across seeds (VERDICT r3 #4).

Pools the seed-0 arms under `artifacts/ablation/` with the seed-N arms
under `artifacts/ablation_seeds/seed<N>/` (all run at the identical
budget: epochs 10, 1024 examples, batch 64, K=2048) into one
mean ± range table per arm, and rewrites the `ablation-seeds` marker
section of REPORT.md. The question it answers is weak #3: does the
a2a-vs-gather_perm gap (2.7 pts on one seed) survive a noise band, or
does it close — i.e. is `parallel/shuffle.py`'s "statistically
equivalent decorrelation" claim empirically backed?

Run (host-side only, no training):
    python scripts/seed_variance_report.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ARMS = ("gather_perm", "a2a", "syncbn", "eman", "eman_warmup")


def collect(base_dir: str = "artifacts") -> dict[str, list[dict]]:
    """arm -> list of per-seed result dicts, seed-sorted."""
    dirs = [os.path.join(base_dir, "ablation")]
    for seeds_root in (
        os.path.join(base_dir, "ablation_seeds"),
        # round-5: the eman_warmup arm's seeds (scripts/run_eman_warmup.sh)
        # live in their own root so the r4 no-warmup artifacts stay intact
        os.path.join(base_dir, "eman_warmup"),
    ):
        if os.path.isdir(seeds_root):
            dirs += sorted(
                os.path.join(seeds_root, d)
                for d in os.listdir(seeds_root)
                if d.startswith("seed")
            )
    out: dict[str, list[dict]] = {a: [] for a in ARMS}
    for d in dirs:
        for arm in ARMS:
            p = os.path.join(d, f"{arm}.json")
            if os.path.exists(p):
                with open(p) as f:
                    out[arm].append(json.load(f))
    for arm in out:
        out[arm].sort(key=lambda r: r["seed"])
    return out


def render_section(results: dict[str, list[dict]]) -> str | None:
    import numpy as np

    present = {a: rs for a, rs in results.items() if rs}
    if not present:
        return None
    # pool only the majority budget: a stray arm produced at different
    # flags must not block regeneration of the whole table — it is
    # dropped and named instead
    from collections import Counter

    budget_of = lambda r: (  # noqa: E731
        r["epochs"], r["examples"], r["global_batch"], r["queue"]
    )
    counts = Counter(budget_of(r) for rs in present.values() for r in rs)
    ranked = counts.most_common()
    if len(ranked) > 1 and ranked[0][1] == ranked[1][1]:
        # A 50/50 split must not silently crown whichever budget was
        # inserted first (Counter.most_common tie = insertion order) —
        # the stale half could win. Fail loudly with both listed.
        tied = sorted(b for b, c in ranked if c == ranked[0][1])
        raise SystemExit(
            "seed_variance_report: tied majority budgets "
            f"{tied} ({ranked[0][1]} runs each) — re-run the stray "
            "arms at one budget or delete the stale artifact dirs"
        )
    majority = ranked[0][0]
    excluded = []
    for arm in list(present):
        keep = [r for r in present[arm] if budget_of(r) == majority]
        dropped = [r for r in present[arm] if budget_of(r) != majority]
        excluded += [f"{arm}/s{r['seed']} @ {budget_of(r)}" for r in dropped]
        if keep:
            present[arm] = keep
        else:
            del present[arm]
    if not present:
        return None
    any_rs = next(iter(present.values()))
    e, n, b, k = majority
    seeds_union = sorted({r["seed"] for rs in present.values() for r in rs})
    lines = [
        "## Shuffle-mode ablation: seed variance",
        "",
        f"`scripts/seed_variance_report.py`: pooled over seeds "
        f"{seeds_union} at the identical budget "
        f"({e} epochs, {n} examples, batch {b}, K={k}, "
        f"`{any_rs[0]['dataset']}`, {any_rs[0]['num_devices']}-device CPU "
        "mesh). mean ± half-range (min–max shown); the question is "
        "whether the single-seed a2a-vs-gather_perm gap survives the "
        "noise band (`parallel/shuffle.py`'s equivalence claim).",
        "",
        "| Arm | kNN top-1 mean ± ½range | per-seed | contrast-acc tail mean |",
        "|---|---|---|---|",
    ]
    stats = {}
    for arm in ARMS:
        rs = present.get(arm)
        if not rs:
            continue
        knn = np.array([r["final_knn_top1"] for r in rs], float)
        tail = np.array([r["contrast_acc_tail_mean"] for r in rs], float)
        stats[arm] = knn
        per_seed = ", ".join(
            f"s{r['seed']}: {v:.1f}" for r, v in zip(rs, knn)
        )
        spread = (
            f"{knn.mean():.2f} ± {(knn.max() - knn.min()) / 2:.2f}"
            if len(knn) > 1
            else f"{knn.mean():.2f} (n=1 seed, no variance estimate)"
        )
        lines.append(
            f"| `{arm}` | {spread} | {per_seed} | {tail.mean():.2f}% |"
        )
    verdict_line = None
    if "gather_perm" in stats and "a2a" in stats and len(stats["a2a"]) >= 3:
        g, a = stats["gather_perm"], stats["a2a"]
        gap = g.mean() - a.mean()
        band = max(g.max() - g.min(), a.max() - a.min())
        if abs(gap) <= band:
            verdict_line = (
                f"The mean gap ({gap:+.2f} pts) sits inside the larger "
                f"per-arm seed range ({band:.2f} pts): the a2a mode's "
                "decorrelation is statistically indistinguishable from "
                "reference-exact gather_perm at this budget — the "
                "equivalence claim stands."
            )
        else:
            verdict_line = (
                f"The mean gap ({gap:+.2f} pts) EXCEEDS the per-arm seed "
                f"range ({band:.2f} pts): a2a is demoted from "
                "default-candidate to experimental until the gap is "
                "understood (parallel/shuffle.py's claim overstated)."
            )
    if verdict_line:
        lines += ["", verdict_line]
    if excluded:
        lines += [
            "",
            "Excluded from pooling (produced at a different budget than "
            f"the majority {majority}): {', '.join(excluded)}.",
        ]
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--base-dir", default="artifacts")
    ap.add_argument("--report", default="REPORT.md")
    args = ap.parse_args()
    section = render_section(collect(args.base_dir))
    if section is None:
        print("no arm results found")
        return
    from moco_tpu.utils.report import replace_marker_block

    replace_marker_block(args.report, "ablation-seeds", section)
    print(f"ablation-seeds section written into {args.report}")


if __name__ == "__main__":
    main()
