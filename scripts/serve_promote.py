#!/usr/bin/env python
"""Gate and promote training checkpoints into the serving fleet.

The auditable train→serve handoff (serve/promote.py holds the pieces):
watch a candidate checkpoint directory, and for each new step run the
promotion gate battery against the LIVE serving checkpoint —
embedding-space compatibility (`serve/compat_cosine`,
`serve/recall_overlap` vs the live index), the dimensional-collapse
floor, and the EMA-drift ceiling — writing every verdict as a schema'd
line in an append-only `promotions.jsonl` ledger. A candidate that
clears the gates rolls out through the fleet router ONE replica at a
time (`POST /admin/promote` → drain → restart onto the candidate →
wait for its digest to land), soaking on the fleet burn gauges between
replicas; a burn breach or a stuck swap auto-rolls every touched
replica back to the live checkpoint.

    python scripts/serve_promote.py --candidate-dir /run/new \
        --live-dir /run/current [--router http://127.0.0.1:9000] \
        [--ledger promotions.jsonl] [--watch-s 10] [--probes 32] [--k 5]

Without `--router` this is gates-only (verdict `accepted`/`rejected`
in the ledger, nothing touches traffic) — the CI shape. With a router
the final verdict is `promoted` or `rolled_back`. One-shot by default;
`--watch-s N` tails the candidate directory like serve_ingest tails
the queue. Exit code: 0 when the last verdict was accepted/promoted,
1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.parse
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# injectable for tests (a fleet is simulated by swapping this)
_urlopen = urllib.request.urlopen


def _get_json(url: str, timeout: float = 10.0) -> dict:
    with _urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post_json(url: str, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(url, data=b"")
    with _urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def load_engine_for_gates(workdir: str, n_probes: int, side: str = "k"):
    """(engine, params, queue, queue_ptr, config) for one checkpoint —
    a single AOT bucket sized to the probe set (the battery embeds
    exactly one batch, compiling the serving buckets would be waste)."""
    from moco_tpu.serve.engine import InferenceEngine, load_serving_encoder

    module, params, stats, queue, queue_ptr, config = load_serving_encoder(
        workdir, side=side
    )
    engine = InferenceEngine(
        module, params, stats,
        image_size=config.data.image_size, buckets=(int(n_probes),),
    )
    return engine, params, queue, queue_ptr, config


def gate_candidate(
    live_dir: str,
    candidate_dir: str,
    n_probes: int = 32,
    k: int = 5,
    floors: dict = None,
    live_recall: float = None,
) -> tuple:
    """Run the full battery for the newest candidate checkpoint.
    Returns (battery_result, candidate_digest, candidate_step)."""
    from moco_tpu.obs import quality
    from moco_tpu.serve.index import EmbeddingIndex
    from moco_tpu.serve.promote import run_gate_battery
    from moco_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(candidate_dir)
    step = mgr.latest_step()
    mgr.close()
    if step is None:
        raise FileNotFoundError(f"no checkpoint in {candidate_dir}")
    live_engine, _, queue, queue_ptr, config = load_engine_for_gates(
        live_dir, n_probes
    )
    index = EmbeddingIndex.from_train_queue(queue, queue_ptr)
    cand_engine, cand_params_k, _, _, _ = load_engine_for_gates(
        candidate_dir, n_probes
    )
    # the query-side twin, for the EMA-drift ceiling (a second restore
    # of the same checkpoint — cheap next to the gate embeds)
    _, cand_params_q, _, _, _ = load_engine_for_gates(
        candidate_dir, n_probes, side="q"
    )
    probes = quality.synthetic_probes(n_probes, config.data.image_size)
    result = run_gate_battery(
        live_engine, cand_engine, probes, index=index, k=k, floors=floors,
        cand_params_q=cand_params_q, cand_params_k=cand_params_k,
        live_recall=live_recall,
    )
    return result, quality.params_digest(cand_params_k), int(step)


def fleet_burn(router: str):
    """The rollout soak gauge: the worst reading across the router's
    latency AND freshness burn families (client-observed plus the
    per-replica aggregates) — any of them breaching pauses a rollout."""
    stats = _get_json(router.rstrip("/") + "/stats")
    vals = [
        v
        for key, v in stats.items()
        if key.startswith("fleet_serve/")
        and ("burn_rate_" in key)
        and isinstance(v, (int, float))
    ]
    return max(vals) if vals else None


def live_recall_estimate(router: str):
    """The fleet's current sampled online recall (the promotion
    baseline gate) — the max over replicas' serve/recall_estimate
    aggregate; None where no replica has sampled yet."""
    stats = _get_json(router.rstrip("/") + "/stats")
    v = stats.get("fleet_serve/recall_estimate_max")
    return v if isinstance(v, (int, float)) else None


def rollout(
    router: str,
    candidate_dir: str,
    live_dir: str,
    target_digest: str = None,
    soak_s: float = 2.0,
    swap_timeout_s: float = 60.0,
    burn_ceiling: float = None,
    poll_s: float = 0.25,
) -> dict:
    """Staged rollout over every replica behind `router`, auto-rollback
    to `live_dir` on breach (serve/promote.py StagedRollout does the
    sequencing; this wires its callables to the router HTTP surface)."""
    from moco_tpu.obs.slo import DEFAULT_FAST_BURN
    from moco_tpu.serve.promote import StagedRollout

    base = router.rstrip("/")
    replicas = _get_json(base + "/admin/replicas")["replicas"]

    def _swap_to(ckpt_dir):
        quoted = urllib.parse.quote(str(ckpt_dir), safe="")

        def _swap(i):
            _post_json(f"{base}/admin/promote?replica={i}&ckpt_dir={quoted}")

        return _swap

    def _status(i):
        for rep in _get_json(base + "/admin/replicas")["replicas"]:
            if rep["index"] == i:
                return rep
        return {}

    machine = StagedRollout(
        len(replicas),
        swap=_swap_to(candidate_dir),
        status=_status,
        burn=lambda: fleet_burn(base),
        swap_back=_swap_to(live_dir),
        target_digest=target_digest,
        soak_s=soak_s,
        swap_timeout_s=swap_timeout_s,
        burn_ceiling=DEFAULT_FAST_BURN if burn_ceiling is None else burn_ceiling,
        poll_s=poll_s,
    )
    return machine.run()


def promote_once(args, ledger) -> str:
    """One full pipeline pass: gates → ledger → (optionally) rollout →
    ledger. Returns the final verdict string."""
    from moco_tpu.serve.promote import ledger_record

    floors = {
        "compat_cosine": args.floor_cosine,
        "recall_overlap": args.floor_overlap,
        "feature_std": args.floor_feature_std,
        "ema_drift_max": args.max_ema_drift,
        "live_recall": args.floor_live_recall,
    }
    live_recall = None
    if args.router and args.floor_live_recall is not None:
        live_recall = live_recall_estimate(args.router)
    result, digest, step = gate_candidate(
        args.live_dir, args.candidate_dir,
        n_probes=args.probes, k=args.k, floors=floors, live_recall=live_recall,
    )
    verdict = "accepted" if result["ok"] else "rejected"
    ledger.append(ledger_record(
        step, verdict, "gates", digest=digest,
        failed_gate=result["failed_gate"], gates=result["gates"],
        compat=result["compat"],
    ))
    print(
        f"step {step} ({digest}): gates {verdict}"
        + (f" (failed: {result['failed_gate']})" if result["failed_gate"] else ""),
        flush=True,
    )
    if verdict == "rejected" or not args.router:
        return verdict
    out = rollout(
        args.router, args.candidate_dir, args.live_dir, target_digest=digest,
        soak_s=args.soak_s, swap_timeout_s=args.swap_timeout_s,
        burn_ceiling=args.burn_ceiling, poll_s=args.poll_s,
    )
    # a rollout failure's evidence is the breaching burn reading vs the
    # ceiling, in the same gate shape the battery uses
    gates = None
    if out["verdict"] == "rolled_back" and out["burn"] is not None:
        gates = {"burn": {
            "value": out["burn"],
            "floor": args.burn_ceiling,
            "ok": False,
        }}
    ledger.append(ledger_record(
        step, out["verdict"], "rollout", digest=digest,
        failed_gate=out["reason"], replica=out["replica"], gates=gates,
    ))
    print(
        f"step {step} ({digest}): rollout {out['verdict']}"
        + (f" (replica {out['replica']}: {out['reason']})"
           if out["reason"] else f" across {len(out['swapped'])} replicas"),
        flush=True,
    )
    return out["verdict"]


def main() -> int:
    from moco_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()
    ap = argparse.ArgumentParser(
        description="gate + promote checkpoints into the serving fleet"
    )
    ap.add_argument("--candidate-dir", required=True, help="checkpoint dir to watch")
    ap.add_argument("--live-dir", required=True, help="the fleet's current checkpoint dir")
    ap.add_argument("--router", default=None, help="fleet router base URL (omit for gates-only)")
    ap.add_argument("--ledger", default=None, help="promotions.jsonl path (default: <candidate-dir>/promotions.jsonl)")
    ap.add_argument("--probes", type=int, default=32, help="held-back probe batch size")
    ap.add_argument("--k", type=int, default=5, help="top-k for the recall-overlap gate")
    ap.add_argument("--floor-cosine", type=float, default=0.90)
    ap.add_argument("--floor-overlap", type=float, default=0.60)
    ap.add_argument("--floor-feature-std", type=float, default=0.25)
    ap.add_argument("--max-ema-drift", type=float, default=0.50)
    ap.add_argument("--floor-live-recall", type=float, default=None,
                    help="also require the fleet's live recall_estimate above this")
    ap.add_argument("--soak-s", type=float, default=2.0, help="burn-gauge soak between replica swaps")
    ap.add_argument("--swap-timeout-s", type=float, default=60.0)
    ap.add_argument("--burn-ceiling", type=float, default=14.4, help="rollback above this fleet burn reading")
    ap.add_argument("--poll-s", type=float, default=0.25)
    ap.add_argument("--watch-s", type=float, default=0.0,
                    help="poll the candidate dir every N seconds (0 = one shot)")
    args = ap.parse_args()

    from moco_tpu.serve.promote import PromotionLedger
    from moco_tpu.utils.checkpoint import CheckpointManager

    ledger_path = args.ledger or os.path.join(args.candidate_dir, "promotions.jsonl")
    ledger = PromotionLedger(ledger_path)

    if args.watch_s <= 0:
        verdict = promote_once(args, ledger)
        return 0 if verdict in ("accepted", "promoted") else 1

    last_step = None
    while True:
        mgr = CheckpointManager(args.candidate_dir)
        step = mgr.latest_step()
        mgr.close()
        if step is not None and step != last_step:
            promote_once(args, ledger)
            last_step = step
        time.sleep(args.watch_s)


if __name__ == "__main__":
    sys.exit(main())
