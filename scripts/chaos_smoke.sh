#!/usr/bin/env bash
# Chaos smoke (fault-tolerance layer): tiny synthetic training runs with
# injected checkpoint truncation, transient loader IOErrors, a NaN loss
# step, and a watchdog-caught stall — asserting resume-through-corruption
# reaches the fault-free final step count. See scripts/chaos_smoke.py for
# the leg-by-leg breakdown. CPU-only, a few minutes; run by CI.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python scripts/chaos_smoke.py "$@"
