"""BN-leak probe: does a trained MoCo model rely on batch-statistics
cheating? (mechanism-level companion to scripts/ablate_shuffle.py)

The Shuffle-BN design exists because, without it, per-device BatchNorm
lets the key encoder leak the positive's identity through co-batch
statistics (`moco/builder.py:~L79-126`). End-to-end metric gaps take
long training to develop; this probe tests the MECHANISM directly on a
finished checkpoint:

  compute the (K+1)-way contrast accuracy twice, holding params, queue
  and images fixed and changing ONLY the key batch's BN grouping:
    aligned  — key row i normalized in the same group position as query
               row i (the training-time co-batch composition of a
               shuffle='none' run), and
    shuffled — key rows permuted across groups before the forward and
               inverse-permuted after (what Shuffle-BN enforces).

A model that exploits the leak scores higher in `aligned` than in
`shuffled` — its accuracy rides on batch composition, not content; an
honest model scores the same in both. Per-device BN is emulated on one
device with `BatchNorm(virtual_groups=G)` (oracle-tested equivalent of
a G-device mesh), so the probe runs anywhere.

Run after (or during) the ablation:
    JAX_PLATFORMS=cpu python scripts/leak_probe.py --arms none gather_perm
Writes artifacts/leak_probe.json — deliberately OUTSIDE the per-arm
artifacts/ablation/ directory, whose `*.json` glob render_section in
scripts/ablate_shuffle.py treats as arm results — and a marker section
into REPORT.md.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.utils.platform import enable_persistent_compilation_cache, pin_platform_from_env

pin_platform_from_env()
enable_persistent_compilation_cache()

OUT_PATH = "artifacts/leak_probe.json"  # NOT in the per-arm dir: render_section globs *.json there


def probe_arm(arm: str, workdir: str, groups, batches: int, batch) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from moco_tpu.core import build_encoder, create_state
    from moco_tpu.data.augment import get_recipe, two_crop_augment
    from moco_tpu.data.datasets import build_dataset
    from moco_tpu.utils.checkpoint import CheckpointManager
    from moco_tpu.utils.config import config_from_dict
    from moco_tpu.utils.schedules import build_optimizer

    mgr = CheckpointManager(workdir)
    if mgr.latest_step() is None:
        raise FileNotFoundError(f"no checkpoint under {workdir}")
    extra = mgr.read_extra()
    config = config_from_dict(extra["config"])
    # default the grouping to the TRAINING topology recorded in the
    # checkpoint: the 'aligned' condition must reproduce the run's
    # per-device co-batch composition, not a guessed one
    if batch is None:
        batch = config.data.global_batch
    if groups is None:
        # training-time co-batch composition: num_data devices, each
        # split into bn_virtual_groups virtual groups (if trained so)
        groups = int(extra.get("num_data", 1)) * max(
            1, config.moco.bn_virtual_groups
        )
    if groups < 2:
        raise ValueError(
            f"{arm}: trained on {groups} device(s) with no virtual groups - "
            "per-device composition is the whole batch; pass --groups explicitly"
        )

    # restore with the ORIGINAL config's template...
    encoder = build_encoder(config.moco)
    tx = build_optimizer(config.optim, steps_per_epoch=1)
    sample = jnp.zeros((1, config.data.image_size, config.data.image_size, 3))
    template = create_state(jax.random.PRNGKey(0), config, encoder, tx, sample)
    state, _ = mgr.restore(template)
    mgr.close()

    # ...and forward with a virtual-groups backbone (identical tree
    # paths, so the restored params drop straight in). syncbn-trained
    # arms get plain per-group BN here too: the probe's question is
    # only "does THIS parameter set read co-batch statistics".
    probe_moco = dataclasses.replace(
        config.moco, shuffle="gather_perm", bn_virtual_groups=groups,
        # virtual_groups and stats_rows are mutually exclusive in
        # BatchNorm; a subset-stats-trained checkpoint is probed with
        # plain per-group statistics (same question: does THIS parameter
        # set read co-batch statistics)
        bn_stats_rows=0,
    )
    probe_encoder = build_encoder(probe_moco)

    recipe = get_recipe(
        config.data.aug_plus,
        config.data.image_size,
        crops_only=getattr(config.data, "crops_only", False),
    )

    @jax.jit
    def embed(params, stats, images):
        out = probe_encoder.apply(
            {"params": params, "batch_stats": stats},
            images,
            train=True,  # batch (group) statistics — the training condition
            mutable=["batch_stats"],
        )[0]
        return out / jnp.linalg.norm(out, axis=-1, keepdims=True)

    dataset = build_dataset(config.data.dataset, config.data.data_dir,
                            config.data.image_size, train=True)
    rng = jax.random.PRNGKey(1234)
    perm_rng = np.random.default_rng(99)
    queue = jnp.asarray(state.queue)  # (K, dim) normalized keys

    acc = {"aligned": [], "shuffled": []}
    sim = {"aligned": [], "shuffled": []}
    for b in range(batches):
        idx = np.arange(b * batch, (b + 1) * batch) % len(dataset)
        raw = np.stack([dataset.load(int(i))[0] for i in idx])
        rng, key = jax.random.split(rng)
        views = two_crop_augment(
            recipe, key, jnp.asarray(raw, jnp.float32) / 255.0,
            config.data.image_size,
        )
        im_q, im_k = views["im_q"], views["im_k"]
        q = embed(state.params_q, state.batch_stats_q, im_q)

        k_aligned = embed(state.params_k, state.batch_stats_k, im_k)
        perm = perm_rng.permutation(batch)
        inv = np.argsort(perm)
        k_shuffled = embed(state.params_k, state.batch_stats_k, im_k[perm])[inv]

        for name, k in (("aligned", k_aligned), ("shuffled", k_shuffled)):
            l_pos = jnp.sum(q * k, axis=1, keepdims=True)
            # evaluation-only probe: no grad is ever taken through these
            # logits, so the detach invariant is vacuous here
            l_neg = q @ queue.T  # mocolint: disable=JX005
            logits = jnp.concatenate([l_pos, l_neg], axis=1)
            acc[name].append(float((jnp.argmax(logits, axis=1) == 0).mean() * 100))
            sim[name].append(float(l_pos.mean()))

    return {
        "arm": arm,
        "dataset": config.data.dataset,
        "groups": groups,
        "batches": batches,
        "batch": batch,
        "contrast_acc_aligned": float(np.mean(acc["aligned"])),
        "contrast_acc_shuffled": float(np.mean(acc["shuffled"])),
        "acc_drop_when_decorrelated": float(
            np.mean(acc["aligned"]) - np.mean(acc["shuffled"])
        ),
        "pos_sim_aligned": float(np.mean(sim["aligned"])),
        "pos_sim_shuffled": float(np.mean(sim["shuffled"])),
    }


def render_section(results: list[dict]) -> str:
    ds = results[0].get("dataset")
    title = "## BN-leak probe (mechanism test on trained checkpoints"
    title += f", `{ds}`)" if ds else ")"
    lines = [
        title,
        "",
        "`scripts/leak_probe.py`: same params, queue, and images; only the",
        "key batch's BN grouping changes — `aligned` reproduces a",
        "shuffle-free run's co-batch composition, `shuffled` decorrelates",
        "it (per-device BN emulated via `BatchNorm(virtual_groups)`,",
        "oracle-tested). Accuracy that evaporates under decorrelation was",
        "never content — it was the BN statistics leak Shuffle-BN",
        "prevents (`moco/builder.py:~L79-126`).",
        "",
        "| Arm | contrast acc, aligned | contrast acc, shuffled | drop |",
        "|---|---|---|---|",
    ]
    for r in results:
        lines.append(
            f"| `{r['arm']}` | {r['contrast_acc_aligned']:.2f}% | "
            f"{r['contrast_acc_shuffled']:.2f}% | "
            f"{r['acc_drop_when_decorrelated']:+.2f}% |"
        )
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arms", nargs="*", default=["none", "gather_perm", "a2a", "syncbn", "m0"])
    ap.add_argument("--workdir", default="/tmp/moco_ablate")
    ap.add_argument("--groups", type=int, default=None,
                    help="BN groups (default: the checkpoint's num_data)")
    ap.add_argument("--batches", type=int, default=4)
    ap.add_argument("--batch", type=int, default=None,
                    help="probe batch (default: the checkpoint's global batch)")
    ap.add_argument("--out", default=OUT_PATH)
    ap.add_argument("--report", default="REPORT.md")
    ap.add_argument("--marker", default="leak-probe")
    args = ap.parse_args()

    results = []
    for arm in args.arms:
        workdir = os.path.join(args.workdir, arm)
        try:
            r = probe_arm(arm, workdir, args.groups, args.batches, args.batch)
        except FileNotFoundError as e:
            print(f"[{arm}] skipped: {e}")
            continue
        results.append(r)
        print(f"[{arm}] aligned {r['contrast_acc_aligned']:.2f}%  "
              f"shuffled {r['contrast_acc_shuffled']:.2f}%  "
              f"drop {r['acc_drop_when_decorrelated']:+.2f}%")
    if not results:
        sys.exit("no arm checkpoints found")
    os.makedirs(os.path.dirname(args.out) or '.', exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    from moco_tpu.utils.report import replace_marker_block

    replace_marker_block(args.report, args.marker, render_section(results))
    print(f"leak-probe section written into {args.report}")


if __name__ == "__main__":
    main()
