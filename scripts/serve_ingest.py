#!/usr/bin/env python
"""Stream a LIVE training run's dictionary into a serving replica.

The training queue and the serving index share their FIFO kernel
(serve/index.py:fifo_write) — this script closes the remaining gap in
the ROADMAP "streaming index updates from a live run" item: it tails a
training run's checkpoint directory and FIFO-ingests the freshly
enqueued queue rows into a RUNNING replica over the server's `/ingest`
endpoint, so a long-lived serving process tracks the dictionary the
trainer is still building without a restart or a bulk reload.

    python scripts/serve_ingest.py --ckpt-dir /run/workdir \
        --server http://127.0.0.1:8000 [--poll-s 10] [--once] [--fanout]

With `--fanout` the `--server` URL is a fleet ROUTER
(serve/router.py): each poll discovers the replica topology from
`GET /admin/replicas` and posts the fresh block to EVERY replica
directly (the router does not proxy /ingest — a dictionary update must
reach all of them, not one). Each replica gets its own retry site
(`ingest.post.r<i>` in the io_retries ledger), and one replica failing
its retries degrades to a logged warning, not a lost block for the
others — a restarting replica catches up through the supervisor's warm
replay anyway.

Per new checkpoint step: restore the queue + write head, diff against
the last seen head (the freshly enqueued region is `[old_ptr, new_ptr)`
circular; the FIRST sighting ingests the full queue oldest-first so the
replica starts aligned), POST the block as raw f32 rows. The replica's
IVF cell membership and int8 mirror follow each ingest incrementally
(serve/server.py `/ingest` → `EmbeddingIndex.add`), and
`serve/ingested_rows` / `serve/index_rows` advance in its metric flush
— which is exactly what the smoke asserts.

Assumes fewer than K rows are enqueued between polled checkpoints (a
full-queue turnover with an identical head is indistinguishable from
no-op; shorten --poll-s if the trainer outruns it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

DEFAULT_BLOCK = 512  # rows per POST: bounds request size and replica compiles

# injectable for tests (a flaky replica is simulated by swapping this)
_urlopen = urllib.request.urlopen


def fresh_rows(queue: np.ndarray, old_ptr, new_ptr: int) -> np.ndarray:
    """The block the trainer enqueued since the last sighting, in FIFO
    (oldest-first) order. `old_ptr=None` = first sighting: the whole
    valid queue, oldest-first from the write head."""
    if old_ptr is None:
        return np.concatenate([queue[new_ptr:], queue[:new_ptr]])
    old_ptr = int(old_ptr)
    if new_ptr == old_ptr:
        return queue[:0]
    if new_ptr > old_ptr:
        return queue[old_ptr:new_ptr]
    return np.concatenate([queue[old_ptr:], queue[:new_ptr]])


def post_rows(
    server: str, rows: np.ndarray, block: int = DEFAULT_BLOCK,
    site: str = "ingest.post", ckpt_step: int = None,
) -> int:
    """POST `rows` to the replica's /ingest in bounded blocks; returns
    the replica's reported index row count after the last block.
    `ckpt_step` (the checkpoint step the rows came from) travels as the
    `X-Ckpt-Step` header so the replica's `serve/ingest_ckpt_step`
    gauge tracks WHICH encoder's dictionary it is serving — the
    freshness SLO's `serve/row_age_max_s` is wall-clock, this is the
    training-step twin.

    Each POST runs through the `utils/retry.py` backoff layer (`site`,
    counted in the per-site io_retries ledger — fanout mode names one
    site per replica): a replica restart or transient connection reset
    mid-tail degrades to a logged retry instead of dropping the ingest
    block — `urllib`'s URLError is an OSError, so the default retry_on
    covers both network and HTTP transport failures."""
    from moco_tpu.utils import retry

    def _post(chunk: np.ndarray) -> int:
        headers = {"X-Rows-Shape": f"{chunk.shape[0]},{chunk.shape[1]}"}
        if ckpt_step is not None:
            headers["X-Ckpt-Step"] = str(int(ckpt_step))
        req = urllib.request.Request(
            server.rstrip("/") + "/ingest",
            data=chunk.tobytes(),
            headers=headers,
        )
        with _urlopen(req, timeout=60) as r:
            return json.loads(r.read())["index_rows"]

    index_rows = -1
    for lo in range(0, rows.shape[0], block):
        chunk = np.ascontiguousarray(rows[lo : lo + block], np.float32)
        index_rows = retry.retry_call(_post, chunk, site=site)
    return index_rows


def discover_replicas(router: str) -> dict:
    """{replica_index: base_url} from a fleet router's /admin/replicas
    (serve/router.py). Every known replica is returned, draining or
    not — an ingest a drained replica rejects is retried and then
    skipped with a warning; the supervisor's warm replay realigns it."""
    with _urlopen(router.rstrip("/") + "/admin/replicas", timeout=10) as r:
        body = json.loads(r.read())
    return {int(rep["index"]): rep["url"] for rep in body["replicas"]}


def fanout_rows(
    router: str, rows: np.ndarray, block: int = DEFAULT_BLOCK,
    ckpt_step: int = None,
) -> dict:
    """POST `rows` to every replica behind `router`, each under its own
    retry site (`ingest.post.r<i>`). Returns {index: index_rows | None}
    — None marks a replica whose retries were exhausted (logged; the
    other replicas still got the block)."""
    results: dict = {}
    for index, url in sorted(discover_replicas(router).items()):
        try:
            results[index] = post_rows(
                url, rows, block, site=f"ingest.post.r{index}",
                ckpt_step=ckpt_step,
            )
        except OSError as e:
            print(
                f"WARNING: replica {index} ({url}) dropped an ingest block "
                f"after retries: {e!r}",
                flush=True,
            )
            results[index] = None
    return results


def poll_once(
    ckpt_dir: str, server: str, seen: dict, block: int = DEFAULT_BLOCK,
    fanout: bool = False,
) -> int:
    """One tail step: ingest anything new; returns rows ingested.
    `seen` carries {'step', 'ptr'} across polls. With `fanout`,
    `server` is a router URL and the block goes to every replica."""
    from moco_tpu.lincls import restore_pretrain_state
    from moco_tpu.utils.checkpoint import CheckpointManager

    step = CheckpointManager(ckpt_dir).latest_step()
    if step is None or step == seen.get("step"):
        return 0
    state, _ = restore_pretrain_state(ckpt_dir)
    queue = np.asarray(state.queue, np.float32)
    new_ptr = int(state.queue_ptr)
    rows = fresh_rows(queue, seen.get("ptr"), new_ptr)
    if rows.shape[0]:
        if fanout:
            results = fanout_rows(server, rows, block, ckpt_step=step)
            summary = ", ".join(
                f"r{i}={'FAILED' if n is None else n}"
                for i, n in sorted(results.items())
            )
            print(
                f"step {step}: fanned {rows.shape[0]} fresh rows to "
                f"{len(results)} replicas (index_rows: {summary})",
                flush=True,
            )
        else:
            index_rows = post_rows(server, rows, block, ckpt_step=step)
            print(
                f"step {step}: ingested {rows.shape[0]} fresh rows "
                f"(replica index_rows={index_rows})",
                flush=True,
            )
    seen["step"], seen["ptr"] = step, new_ptr
    return int(rows.shape[0])


def main() -> int:
    from moco_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()
    ap = argparse.ArgumentParser(description="tail a training checkpoint dir into a serving replica")
    ap.add_argument("--ckpt-dir", required=True, help="the training run's workdir")
    ap.add_argument("--server", required=True, help="replica base URL, e.g. http://127.0.0.1:8000")
    ap.add_argument("--poll-s", type=float, default=10.0)
    ap.add_argument("--block", type=int, default=DEFAULT_BLOCK, help="rows per /ingest POST")
    ap.add_argument("--once", action="store_true", help="one poll, then exit (smoke/test mode)")
    ap.add_argument(
        "--fanout", action="store_true",
        help="--server is a fleet router: discover replicas via "
        "/admin/replicas and ingest into every one",
    )
    args = ap.parse_args()
    from moco_tpu.utils import retry

    seen: dict = {}
    while True:
        poll_once(args.ckpt_dir, args.server, seen, args.block, fanout=args.fanout)
        retries = retry.snapshot()
        if retries:
            # the per-site retry ledger (ingest.post + checkpoint-restore
            # sites), surfaced like the train driver's io_retries field
            print(f"io_retries: {json.dumps(retries)}", flush=True)
        if args.once:
            return 0
        time.sleep(args.poll_s)


if __name__ == "__main__":
    sys.exit(main())
