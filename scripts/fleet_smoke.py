#!/usr/bin/env python
"""Fleet-observability smoke: a tiny 8-virtual-device training run that
must produce the full fleet surface, asserted hard.

    python scripts/fleet_smoke.py [--workdir DIR]

(The script pins an 8-virtual-device CPU platform itself, so it runs
identically in CI and on a dev box.)

Since ISSUE 7 the smoke also proves the ZeRO-2/3 surface on the same
fake-8-device mesh (`run_zero_ab` + `assert_zero_surface`): a zero1 and
a zero23 driver run in subdirectories, asserting the at-rest
`hbm_state_bytes` drop, the per-bucket comms sites, identical loss
trajectories, and — under an injected `delay@site=zero.gather` slow
collective — `overlap/zero >= 0.5` with the `zero_gather` spans
visibly overlapping main-thread work in the trace.

Asserts (the ISSUE-4 acceptance bullet, executable):

1. every process-0 training line in `metrics.jsonl` carries the fleet
   reduction — `straggler_skew`, `fleet_hosts`, and the
   `fleet/<field>_{min,mean,max,argmax}` family;
2. the comms ledger surfaced NON-ZERO `comms/*` analytic byte counters
   for the shuffle, queue-enqueue, and gradient collectives (8-way data
   axis, a2a shuffle);
3. a deterministically injected fault (`nan@step=N`, utils/faults.py)
   fired an alert: `alerts.jsonl` has a `nonfinite_loss` entry and the
   metrics stream has the matching `event: "alert"` line;
4. `scripts/trace_merge.py` builds a single merged Perfetto trace with
   one track (pid) per process and the heartbeat clock anchor applied;
5. `scripts/obs_report.py --strict` validates every line, fleet fields
   included, and renders the fleet/comms/alerts sections.

CI runs this in the tier-1 job and uploads alerts.jsonl + the merged
trace as artifacts. Wall cost: one tiny compile + 4 steps.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# 8 virtual CPU devices, pinned BEFORE jax initializes (same trick as
# tests/conftest.py) — the fleet/comms surface needs a real multi-device
# data axis even though this is one process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

NAN_STEP = 3  # global step whose observed loss is corrupted to NaN


def run_smoke(workdir: str) -> dict:
    """Run the tiny driver run; returns {'workdir', 'result'}. Split
    from the assertions so tests can reuse the run."""
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train
    from moco_tpu.utils import faults
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        TrainConfig,
    )

    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18",
            dim=16,
            num_negatives=128,
            temperature=0.2,
            mlp=True,
            # balanced all_to_all shuffle: exercises the a2a comms site
            # AND the separate queue-enqueue all_gather (gather_perm
            # folds the queue gather into the unshuffle)
            shuffle="a2a",
            cifar_stem=True,
            compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=1, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=64, num_workers=2),
        workdir=workdir,
        log_every=1,
        obs_probe_every=2,
        sinks="jsonl",
        fleet_metrics=True,
        alert_rules="default",
    )
    # deterministic fault: the loss observed at NAN_STEP becomes NaN —
    # the non-finite guard skips the update and the alert engine's
    # `nonfinite_loss` event rule must fire
    faults.install(f"nan@step={NAN_STEP}")
    try:
        dataset = SyntheticDataset(num_examples=4 * 64, image_size=16)  # 4 steps of 64
        result = train(config, dataset=dataset)
    finally:
        faults.clear()
    return {"workdir": workdir, "result": result}


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


ZERO_DELAY_S = 0.05  # synthetic slow-collective: injected gather delay


def run_zero_ab(workdir: str) -> dict:
    """Two tiny ZeRO driver runs — stage 1 vs stage 2/3 — on the same
    fake-8-device mesh; the zero23 leg runs under a deterministic
    `delay@site=zero.gather` fault so the hoisted gather has something
    to hide. Returns {'zero1': subdir, 'zero23': subdir}."""
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train
    from moco_tpu.utils import faults
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        ParallelConfig,
        TrainConfig,
    )

    out = {}
    for name, stage, spec in (
        ("zero1", 1, None),
        ("zero23", 3, f"delay@site=zero.gather:seconds={ZERO_DELAY_S}"),
    ):
        wd = os.path.join(workdir, name)
        os.makedirs(wd, exist_ok=True)
        config = TrainConfig(
            moco=MocoConfig(
                arch="resnet18", dim=16, num_negatives=128, temperature=0.2,
                mlp=True, shuffle="none", cifar_stem=True, compute_dtype="float32",
            ),
            optim=OptimConfig(lr=0.03, epochs=1, cos=True),
            data=DataConfig(
                dataset="synthetic", image_size=16, global_batch=64, num_workers=2
            ),
            parallel=ParallelConfig(
                num_data=8, shard_weight_update=True, zero_stage=stage,
                # small buckets so the tiny model still packs >1 bucket
                # (the per-bucket ledger sites need plurality to prove
                # bucketing, not just one giant concat)
                zero_bucket_mb=0.002,
            ),
            workdir=wd, log_every=1, obs_probe_every=2, fleet_metrics=True,
        )
        if spec:
            faults.install(spec)
        try:
            train(config, dataset=SyntheticDataset(num_examples=4 * 64, image_size=16))
        finally:
            faults.clear()
        out[name] = wd
    return out


def assert_zero_surface(dirs: dict) -> None:
    """The ISSUE-7 acceptance bullet, executable: hbm drop, overlap,
    per-bucket sites, trace overlap, identical trajectories."""
    from moco_tpu.obs import schema

    lines = {}
    for name, wd in dirs.items():
        recs = schema.read_metrics(os.path.join(wd, "metrics.jsonl"))
        errors = schema.validate_file(os.path.join(wd, "metrics.jsonl"))
        assert not errors, f"{name} schema violations: {errors[:5]}"
        lines[name] = [r for r in recs if "loss" in r and "event" not in r]
        assert lines[name], f"{name} produced no training lines"

    # -- 1. persistently sharded params measurably shrink at-rest state
    s1 = lines["zero1"][-1]["hbm_state_bytes"]
    s23 = lines["zero23"][-1]["hbm_state_bytes"]
    assert s23 < 0.5 * s1, (
        f"zero23 at-rest state {s23} not measurably below zero1 {s1}"
    )

    # -- 2. the hoisted bucketed gather hides the injected slow
    # collective: overlap/zero >= 0.5 once past the compile steps
    overlaps = [r.get("overlap/zero") for r in lines["zero23"]]
    assert all(o is not None for o in overlaps), f"overlap/zero missing: {overlaps}"
    assert overlaps[-1] >= 0.5, (
        f"steady-state overlap/zero {overlaps[-1]} < 0.5 under the "
        f"{ZERO_DELAY_S}s gather delay fault: {overlaps}"
    )

    # -- 3. per-bucket collective sites in the comms ledger, non-zero
    last = lines["zero23"][-1]
    for site in ("comms/zero.gather_q.b0", "comms/zero.gather_k.b0", "comms/zero.scatter.b0"):
        assert last.get(site, 0) > 0, f"{site} missing or zero: {last.get(site)!r}"
    n_buckets = len([k for k in last if k.startswith("comms/zero.gather_q.b")])
    assert n_buckets > 1, f"expected >1 fusion bucket at the tiny bucket size, got {n_buckets}"

    # -- 4. zero23 trajectory identical to zero1 (same seeds, same math)
    l1 = [round(r["loss"], 6) for r in lines["zero1"]]
    l23 = [round(r["loss"], 6) for r in lines["zero23"]]
    assert l1 == l23, f"zero23 diverged from zero1: {l1} vs {l23}"

    # -- 5. the gather visibly overlaps main-thread work in the trace:
    # some zero_gather span (worker thread) intersects a step/data_wait
    # span (driver thread) in wall time
    spans = _read_jsonl(os.path.join(dirs["zero23"], "trace_events.jsonl"))
    gathers = [s for s in spans if s.get("name") == "zero_gather"]
    driver = [
        s for s in spans if s.get("name") in ("step", "data_wait", "device_wait")
    ]
    assert gathers, "no zero_gather spans in the trace"

    def _iv(s):
        return s["ts"], s["ts"] + s.get("dur", 0.0)

    overlapping = any(
        a0 < b1 and b0 < a1
        for g in gathers
        for d in driver
        if g.get("tid") != d.get("tid")
        for (a0, a1), (b0, b1) in [(_iv(g), _iv(d))]
    )
    assert overlapping, (
        "no zero_gather span overlaps driver-thread work — the gather "
        "is not hoisted under compute"
    )


def assert_surface(workdir: str) -> None:
    from moco_tpu.obs import schema

    # -- 1. fleet fields on every process-0 training line ---------------
    metrics_path = os.path.join(workdir, "metrics.jsonl")
    records = schema.read_metrics(metrics_path)
    train_lines = [r for r in records if "loss" in r and "event" not in r]
    assert len(train_lines) == 3, (
        f"expected 3 training lines (4 steps, one NaN-skipped), got {len(train_lines)}"
    )
    fleet_required = (
        "straggler_skew", "fleet_hosts",
        "fleet/t_step_min", "fleet/t_step_mean", "fleet/t_step_max",
        "fleet/t_step_argmax", "fleet/t_data_mean", "fleet/io_retries_max",
    )
    for rec in train_lines:
        missing = [k for k in fleet_required if k not in rec]
        assert not missing, f"training line {rec['step']} missing fleet fields {missing}"
        assert rec["fleet_hosts"] == 1  # single process, 8 devices
        assert rec["straggler_skew"] is not None and rec["straggler_skew"] >= 0
        # one host: min == mean == max for a reported field
        assert rec["fleet/t_step_min"] == rec["fleet/t_step_max"]

    # -- 2. non-zero comms counters for shuffle/queue/grad --------------
    last = train_lines[-1]
    for site in ("comms/shuffle.a2a", "comms/queue.enqueue_gather", "comms/grad.psum"):
        assert last.get(site, 0) > 0, f"{site} missing or zero: {last.get(site)!r}"
    assert last["comms/total"] >= sum(
        v for k, v in last.items()
        if k.startswith("comms/") and k != "comms/total"
    ) / 2  # sanity: total aggregates the sites

    # -- 3. injected NaN -> nonfinite event -> fired alert --------------
    events = {r["event"] for r in records if "event" in r}
    assert "nonfinite_loss" in events, f"no nonfinite_loss event (events: {events})"
    assert "alert" in events, f"no alert event line (events: {events})"
    alerts = _read_jsonl(os.path.join(workdir, "alerts.jsonl"))
    assert any(a["rule"] == "nonfinite_loss" for a in alerts), (
        f"alerts.jsonl has no nonfinite_loss alert: {alerts}"
    )

    # -- heartbeat: out-of-band liveness file ---------------------------
    hb_path = os.path.join(workdir, "heartbeat.p0.json")
    assert os.path.exists(hb_path), "process 0 wrote no heartbeat file"
    hb = json.load(open(hb_path))
    assert hb["process"] == 0 and hb["step"] >= NAN_STEP
    assert "trace_wall_t0" in hb, "heartbeat missing the trace clock anchor"


def assert_merged_trace(workdir: str) -> str:
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "trace_merge", os.path.join(os.path.dirname(os.path.abspath(__file__)), "trace_merge.py")
    )
    tm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tm)
    merged_path = os.path.join(workdir, "merged_trace.json")
    summary = tm.merge_traces(workdir, merged_path)
    assert summary["processes"], "trace_merge found no span streams"
    trace = json.load(open(merged_path))
    pids = {e["pid"] for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert pids == set(summary["processes"]), (
        f"merged trace tracks {pids} != processes {set(summary['processes'])}"
    )
    names = {e["name"] for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert {"epoch", "step"} <= names, f"merged trace missing driver spans: {names}"
    # clock anchor came from the heartbeat, not the zero fallback
    assert not summary["unanchored"], f"unanchored processes: {summary['unanchored']}"
    return merged_path


def assert_strict_report(workdir: str) -> None:
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(os.path.dirname(os.path.abspath(__file__)), "obs_report.py")
    )
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    from moco_tpu.obs import schema

    for p in rep.metrics_paths_for(workdir):
        errors = schema.validate_file(p)
        assert not errors, f"schema violations in {p}: {errors}"
    report = rep.render_report(
        rep.metrics_paths_for(workdir),
        os.path.join(workdir, "merged_trace.json"),
        workdir=workdir,
    )
    for section in ("## Fleet", "## Comms", "## Alerts", "straggler_skew"):
        assert section in report, f"report missing {section!r}"
    with open(os.path.join(workdir, "report.md"), "w") as f:
        f.write(report + "\n")


def main() -> int:
    ap = argparse.ArgumentParser(description="fleet observability smoke")
    ap.add_argument("--workdir", default=None, help="default: a fresh temp dir")
    args = ap.parse_args()
    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_smoke_")
    os.makedirs(workdir, exist_ok=True)
    out = run_smoke(workdir)
    assert_surface(workdir)
    merged = assert_merged_trace(workdir)
    assert_strict_report(workdir)
    zero_dirs = run_zero_ab(os.path.join(workdir, "zero_ab"))
    assert_zero_surface(zero_dirs)
    print(
        f"fleet smoke OK: {out['result']} — merged trace {merged}, "
        f"ZeRO A/B under {os.path.join(workdir, 'zero_ab')}, "
        f"artifacts in {workdir}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
