#!/usr/bin/env python
"""Serving smoke: boot the embedding service on a toy checkpoint and
prove the whole serving contract, asserted hard.

    JAX_PLATFORMS=cpu python scripts/serve_smoke.py [--workdir DIR]

The story (the ISSUE-8 acceptance bullet, executable):

1. a toy pretraining checkpoint (tiny ResNet, 64-key queue) is written
   the way the train driver writes them (config-carrying extras);
2. `load_serving_encoder` restores the KEY (EMA) encoder + the queue,
   the queue rows load into a sharded-capable `EmbeddingIndex`, and the
   engine AOT-compiles every padded bucket {1, 8, 32, 128};
3. the HTTP server boots (ephemeral port) with a JSONL metrics sink and
   `NUM_REQUESTS` mixed-size requests fire from concurrent clients —
   `/embed` and `/neighbors` interleaved;
4. asserts: every response well-formed (shapes, L2-normalized rows,
   neighbor indices inside the queue), ZERO recompiles after warmup
   across all request sizes, p99 latency ≤ the smoke SLO, batch
   occupancy in (0, 1], multiple buckets exercised, and the flushed
   `serve/*` metrics lines schema-strict;
5. the STREAMING-INGEST leg (ISSUE 9): a second checkpoint lands in the
   same workdir with fresh queue rows, `scripts/serve_ingest.py` tails
   it once into the still-running replica over `/ingest`, and the
   serving count (`serve/ingested_rows`, and retrievability of the new
   rows) advances without a restart;
6. the IVF leg (ISSUE 9): a second server boots with
   `neighbors_mode="ivf"` over a clustered dictionary (k-means cells,
   `nprobe` of `nlist` probed per query, recall sampled on EVERY
   neighbors flush) — asserts ZERO recompiles after warmup on the IVF
   path, the online `serve/recall_estimate` at or above the recall
   floor, p99 ≤ the smoke SLO, and the `serve/nprobe`/`serve/int8`
   gauges schema-strict;
7. the SLO-violation leg (ISSUE 10): a third server boots with request
   tracing, a tight SLO, short burn windows, and a tightened burn
   threshold; after a healthy baseline, `slow@site=serve.engine_execute`
   injects a deterministic tail — asserts the burn-rate alert FIRES
   (alerts.jsonl), the flight recorder DUMPED (`flight_*.json` under
   `slo_leg/`, a CI artifact), the dump contains the slowed requests'
   full stage waterfalls with `engine_execute` correctly dominating,
   `/debug/flight` answers on demand, and the flushed
   `serve/burn_rate_*` + `serve/trace_*` lines are schema-strict;
8. the W8A8 + FUSED-IVF leg (ISSUE 11): activation ranges are
   calibrated from a held-out sample at the checkpoint, the artifact
   round-trips through disk (`quant_calib.json` next to the
   checkpoint), a `engine_quant="w8a8"` engine boots serving
   `/neighbors` through the FUSED IVF gather-scan
   (`neighbors_mode="ivf_fused"`, recall sampled on every flush) —
   asserts ZERO recompiles after warmup across the new (mode, quant)
   bucket keys, embedding cosine ≥ 0.99 vs the f32 engine, the online
   recall estimate at the floor, p99 ≤ the smoke SLO, the donation
   audit clean on the quantized trees (no False — a consumed qtree
   buffer would be a use-after-free on the next request), and the
   `serve/quant_tier`/`serve/ivf_spill`/`serve/ivf_occupancy` gauges
   schema-strict.

CI runs this in the tier-1 job and uploads the workdir (metrics.jsonl +
serve_smoke.json summary + the SLO leg's flight dump) as an artifact.
Wall cost: one tiny-model AOT warmup + ~300 small requests, well under
a minute on a CPU host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

NUM_REQUESTS = 200
NUM_CLIENTS = 8
# Two latency knobs on purpose: the BATCHER runs at a tight production-
# shaped SLO (sets the slo/2 coalescing deadline; violations are counted,
# not asserted zero), while the smoke's pass/fail bar is the generous
# SMOKE_SLO_MS — shared CI runners jitter, and the smoke's job is "the
# SLO machinery works and latency is sane", not a perf bar (the bench
# serving leg owns the tracked queries/s series).
SERVER_SLO_MS = float(os.environ.get("SERVE_SMOKE_SERVER_SLO_MS", 1000.0))
SMOKE_SLO_MS = float(os.environ.get("SERVE_SMOKE_SLO_MS", 4000.0))
# capped at 16 rows: 8 closed-loop clients x 16 keeps the coalesced
# micro-batch ≤ one 128-bucket execution, so p99 stays bounded by ONE
# flush even on a 1-core host (32-row requests pushed it to two)
REQUEST_SIZES = (1, 2, 4, 8, 16)
# NB: 32px, not the obs-smoke's 16px — XLA:CPU hits a tiny-spatial-dim
# conv slow path at 16px (measured 10x fewer imgs/s than 32px for the
# SAME ResNet-18 on this host), which would turn the smoke into a
# 10-minute run for no extra coverage
IMAGE_SIZE = 32
# IVF leg: a clustered dictionary (nlist cells), nprobe of them probed
# per query, recall sampled on every neighbors flush and gated at the
# floor. The smoke proves the WIRING + freeze discipline; the bench
# ann_ab leg owns the speed claim at real dictionary sizes.
IVF_REQUESTS = 60
IVF_DICT_ROWS = 256
IVF_NLIST = 16
IVF_NPROBE = 12
RECALL_FLOOR = float(os.environ.get("SERVE_SMOKE_RECALL_FLOOR", 0.95))
# SLO leg (ISSUE 10). Sizing: sequential 1-request traffic flushes at
# the batcher's slo/2 coalescing deadline, so baseline latency is
# ~slo/2 + compute — the 800ms SLO leaves CI-jitter headroom for the
# baseline while the injected 3x-SLO sleep violates decisively. Short
# burn windows so the smoke's seconds of traffic fill them, and a burn
# threshold of 1.0 (= "budget exhausts before the period ends")
# instead of the production 14.4 pager so a short run can trip it:
# 4 slowed among ~16 window requests at objective 0.9 burns at ~2.5.
SLO_LEG_SLO_MS = float(os.environ.get("SERVE_SMOKE_SLO_LEG_SLO_MS", 800.0))
SLO_LEG_SLOW_MS = 3.0 * SLO_LEG_SLO_MS
SLO_LEG_REQUESTS = 12
SLO_LEG_SLOWED = 4
# W8A8 + fused-IVF leg (ISSUE 11): calibration sample size, request
# count, and the cosine floor the quantized embeddings must hold vs the
# f32 engine (the same floor perf_ledger gates on the bench record)
QUANT_CALIB_SAMPLES = 32
QUANT_REQUESTS = 40
QUANT_COSINE_FLOOR = float(os.environ.get("SERVE_SMOKE_QUANT_COSINE_FLOOR", 0.99))


def make_toy_checkpoint(workdir: str, seed: int = 0, step: int = 0):
    """A pretraining checkpoint exactly as the train driver saves them
    (config-carrying extras), from a freshly-initialized tiny model —
    serving correctness doesn't need trained weights. `seed`/`step` let
    the fleet smoke mint deliberately-incompatible candidates (a
    different init posing as a later step) for the promotion gates."""
    import jax
    import jax.numpy as jnp

    from moco_tpu.core import build_encoder, create_state
    from moco_tpu.utils.checkpoint import CheckpointManager
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        TrainConfig,
        config_to_dict,
    )
    from moco_tpu.utils.schedules import build_optimizer

    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18",
            dim=16,
            num_negatives=64,
            mlp=True,
            shuffle="none",
            cifar_stem=True,
            compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=1),
        data=DataConfig(dataset="synthetic", image_size=IMAGE_SIZE, global_batch=8),
        workdir=workdir,
    )
    encoder = build_encoder(config.moco)
    tx = build_optimizer(config.optim, steps_per_epoch=1)
    state = create_state(
        jax.random.PRNGKey(seed), config, encoder, tx,
        jnp.zeros((1, IMAGE_SIZE, IMAGE_SIZE, 3), jnp.float32),
    )
    mgr = CheckpointManager(workdir)
    mgr.save(
        step, state,
        extra={"epoch": 0, "config": config_to_dict(config), "num_data": 1},
        force=True,
    )
    mgr.close()
    return config


def run_smoke(
    workdir: str,
    sanitize_threads: bool = False,
    contract_coverage: bool = False,
) -> dict:
    """Boot → fire → tear down; returns the summary dict (also written
    to workdir/serve_smoke.json). Split from the assertions so tests
    can reuse the run.

    `sanitize_threads` (mocolint v3, analysis/tsan.py) wraps the whole
    run in a lock-order recorder — every tsan-factory lock's nesting is
    traced, and the pass is CLEAN only with zero order cycles and the
    sanctioned serve.index -> serve.metrics edge observed; then a chaos
    leg re-boots a replica under `deadlock@site=serve.metrics` and
    asserts the forced inversion IS caught, with the per-thread stack
    diff artifact (lock_order_diff.json) dumped. Recording only — the
    profile hook stays off here so the latency assertions stay honest.
    """
    import numpy as np

    from moco_tpu.analysis import contracts as contract_cov
    from moco_tpu.obs import schema
    from moco_tpu.obs.sinks import JsonlSink
    from moco_tpu.serve.engine import InferenceEngine, load_serving_encoder
    from moco_tpu.serve.index import EmbeddingIndex
    from moco_tpu.serve.server import ServeServer
    from moco_tpu.utils import contracts as decl

    tsan_sanitizer = None
    if sanitize_threads:
        from moco_tpu.analysis.tsan import ThreadSanitizer

        tsan_sanitizer = ThreadSanitizer(
            workdir=workdir, strict=False, profile=False
        )

    recorder = None
    if contract_coverage:
        recorder = contract_cov.install_recorder()

    ckpt_dir = os.path.join(workdir, "toy_ckpt")
    make_toy_checkpoint(ckpt_dir)
    module, params, stats, queue, queue_ptr, config = load_serving_encoder(ckpt_dir)
    engine = InferenceEngine(
        module, params, stats, image_size=config.data.image_size
    )
    index = EmbeddingIndex.from_train_queue(queue, queue_ptr)
    sink = JsonlSink(workdir)
    server = ServeServer(
        engine,
        index=index,
        port=0,
        slo_ms=SERVER_SLO_MS,
        neighbors_k=5,
        sink=sink,
        metrics_flush_s=0.5,
    )
    base = f"http://127.0.0.1:{server.port}"
    rng = np.random.default_rng(0)
    canned = {
        n: rng.integers(0, 255, (n, IMAGE_SIZE, IMAGE_SIZE, 3), np.uint8)
        for n in REQUEST_SIZES
    }
    failures: list[str] = []
    done = threading.Lock()

    def post(path: str, imgs) -> dict:
        req = urllib.request.Request(
            base + path,
            data=imgs.tobytes(),
            headers={"X-Image-Shape": ",".join(map(str, imgs.shape))},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def client(ci: int, num: int) -> None:
        crng = np.random.default_rng(1000 + ci)
        for j in range(num):
            n = int(crng.choice(REQUEST_SIZES))
            imgs = canned[n]
            want_neighbors = (ci + j) % 2 == 0
            try:
                out = post("/neighbors?k=3" if want_neighbors else "/embed", imgs)
                emb = np.asarray(out["embedding"], np.float32)
                ok = emb.shape[0] == n and np.allclose(
                    np.linalg.norm(emb, axis=1), 1.0, atol=1e-3
                )
                if want_neighbors:
                    idx = np.asarray(out["indices"])
                    ok = ok and idx.shape == (n, 3) and (idx >= 0).all() and (
                        idx < index.capacity
                    ).all()
                if not ok:
                    raise ValueError(f"malformed response for n={n}: {out.keys()}")
            except Exception as e:
                with done:
                    failures.append(f"client {ci} req {j} (n={n}): {e!r}")
                return

    per_client = NUM_REQUESTS // NUM_CLIENTS
    threads = [
        threading.Thread(target=client, args=(i, per_client)) for i in range(NUM_CLIENTS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)

    # -- leg 5: streaming ingest from a "live" training run -------------
    # A fresh checkpoint (same dir, fresh queue rows at the write head)
    # appears while the replica serves; serve_ingest tails it once over
    # /ingest and the serving count advances — no restart, no reload.
    ingest_summary = _ingest_leg(ckpt_dir, server, index)

    stats_out = server.stats()

    if contract_coverage:
        # one-shot probes: the health/stats/drain routes the load legs
        # never touch, so the coverage gate can demand every declared
        # replica route (drain last — the server is done serving here)
        for probe in ("/healthz", "/stats"):
            with urllib.request.urlopen(base + probe, timeout=30) as r:
                r.read()
        drain_req = urllib.request.Request(base + "/admin/drain", data=b"")
        with urllib.request.urlopen(drain_req, timeout=60) as r:
            r.read()

    server.close()

    # -- leg 6: the IVF retrieval tier ----------------------------------
    ivf_summary = _ivf_leg(engine, sink, canned)

    # -- leg 7: SLO burn-rate alert + flight recorder -------------------
    slo_summary = _slo_leg(engine, workdir, canned)

    # -- leg 8: w8a8 engine + fused IVF scan ----------------------------
    quant_summary = _quant_leg(ckpt_dir, engine, sink, canned)

    # -- leg 9: thread sanitizer (mocolint v3) --------------------------
    # clean report over everything above, then the deadlock@site chaos
    # arm proving the detector catches a forced inversion end-to-end
    tsan_summary = None
    if tsan_sanitizer is not None:
        clean = tsan_sanitizer.close()
        tsan_summary = {
            "acquisitions": clean["acquisitions"],
            "edges": clean["edges"],
            "cycles": len(clean["cycles"]),
            "blocking_ops": len(clean["blocking_ops_under_lock"]),
        }
        tsan_summary["chaos"] = _tsan_chaos_leg(engine, index, workdir)

    sink.close()

    contract_summary = None
    if recorder is not None:
        # re-validating the flushed stream with the recorder still wired
        # into obs/schema records validator coverage (assert_serve_surface
        # re-checks the same file later for correctness)
        problems = schema.validate_file(os.path.join(workdir, "metrics.jsonl"))
        assert not problems, f"metrics schema violations: {problems[:5]}"
        cov = recorder.snapshot()
        contract_cov.uninstall_recorder()
        missing = contract_cov.check_coverage(
            cov,
            routes=contract_cov.declared_route_gates("replica"),
            fault_sites=[f"slow@{s}" for s in decl.SERVE_STAGE_SITES],
            validators=decl.SERVE_GATED_VALIDATORS,
        )
        with open(os.path.join(workdir, "contract_coverage.json"), "w") as f:
            json.dump({
                "coverage": cov,
                "gates": {
                    "routes": contract_cov.declared_route_gates("replica"),
                    "fault_sites": [
                        f"slow@{s}" for s in decl.SERVE_STAGE_SITES
                    ],
                    "validators": list(decl.SERVE_GATED_VALIDATORS),
                },
                "missing": missing,
            }, f, indent=2, sort_keys=True)
        assert not missing, (
            f"newly-dead contracts (registered but never fired): {missing}"
        )
        contract_summary = {
            "routes": len(cov["routes"]),
            "fault_hooks": len(cov["fault_hooks"]),
            "validators": len(cov["validators"]),
            "missing": 0,
        }

    summary = {
        "tsan": tsan_summary,
        "contract_coverage": contract_summary,
        "requests_sent": per_client * NUM_CLIENTS,
        "failures": failures,
        "smoke_slo_ms": SMOKE_SLO_MS,
        "stats": stats_out,
        "donation_audit": {str(k): v for k, v in engine.donation_audit().items()},
        "buckets": list(engine.buckets),
        "ingest": ingest_summary,
        "ivf": ivf_summary,
        "slo": slo_summary,
        "quant": quant_summary,
    }
    with open(os.path.join(workdir, "serve_smoke.json"), "w") as f:
        json.dump(summary, f, indent=2)
    return summary


def _tsan_chaos_leg(engine, index, workdir: str) -> dict:
    """`deadlock@site=serve.metrics` chaos arm: re-boot a replica on the
    already-warm engine, hit /stats once — the handler nests serve.index
    -> serve.metrics (the sanctioned order), the fault records the
    inverted edge as if a second thread raced it backwards, and the
    recorder must catch the cycle and dump lock_order_diff.json with
    BOTH acquisition stacks. Non-strict: serving keeps answering; the
    artifact is the proof."""
    from moco_tpu.analysis.tsan import ThreadSanitizer
    from moco_tpu.serve.server import ServeServer
    from moco_tpu.utils import faults

    chaos_dir = os.path.join(workdir, "tsan_chaos")
    os.makedirs(chaos_dir, exist_ok=True)
    faults.install("deadlock@site=serve.metrics")
    san = ThreadSanitizer(workdir=chaos_dir, strict=False, profile=False)
    try:
        server = ServeServer(
            engine, index=index, port=0, warmup=False, metrics_flush_s=30.0,
            reqtrace=False, alert_spec="",
        )
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/stats", timeout=60
            ) as r:
                json.loads(r.read())
        finally:
            server.close()
    finally:
        report = san.close()
        faults.clear()
    diff_path = os.path.join(chaos_dir, "lock_order_diff.json")
    diff = None
    if os.path.isfile(diff_path):
        with open(diff_path) as f:
            diff = json.load(f)
    return {
        "cycles_caught": len(report["cycles"]),
        "diff_path": diff_path if diff is not None else None,
        "diff_cycle": (diff or {}).get("cycle"),
        "diff_has_both_stacks": bool(diff) and all(
            e.get("stack") for e in diff.get("edges", [])
        ) and bool((diff or {}).get("acquiring", {}).get("stack")),
        "injected_edges": sum(
            1 for e in (diff or {}).get("edges", []) if e.get("injected")
        ),
    }


def _ingest_leg(ckpt_dir: str, server, index) -> dict:
    """Write checkpoint step 1 with fresh queue rows, tail it once with
    scripts/serve_ingest.py machinery, return what advanced."""
    import numpy as np

    from moco_tpu.lincls import restore_pretrain_state
    from moco_tpu.utils.checkpoint import CheckpointManager
    from moco_tpu.utils.config import config_to_dict

    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_ingest", os.path.join(os.path.dirname(os.path.abspath(__file__)), "serve_ingest.py")
    )
    ingest = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ingest)

    state, config = restore_pretrain_state(ckpt_dir)
    fresh_n = 16
    rng = np.random.default_rng(42)
    fresh = rng.normal(size=(fresh_n, state.queue.shape[1])).astype(np.float32)
    fresh /= np.linalg.norm(fresh, axis=1, keepdims=True)
    queue = np.asarray(state.queue).copy()
    queue[:fresh_n] = fresh
    import jax.numpy as jnp

    state = state.replace(queue=jnp.asarray(queue), queue_ptr=jnp.int32(fresh_n))
    mgr = CheckpointManager(ckpt_dir)
    mgr.save(1, state, extra={"epoch": 0, "config": config_to_dict(config), "num_data": 1})
    mgr.close()

    before = server.ingested_rows
    # seen pre-seeded at (step 0, head 0): only the fresh region ingests
    seen = {"step": 0, "ptr": 0}
    ingested = ingest.poll_once(ckpt_dir, f"http://127.0.0.1:{server.port}", seen)
    # the freshly ingested rows must be retrievable at the write head
    # (k=5 / bucket 1 is a prepared shape on the frozen index)
    scores, idx = index.query(fresh[:1], 5)
    return {
        "ingested": int(ingested),
        "counter_before": int(before),
        "counter_after": int(server.ingested_rows),
        "head_hit": bool(idx[0, 0] == 0 and scores[0, 0] > 0.999),
    }


def _ivf_leg(engine, sink, canned) -> dict:
    """Second server, approximate tier: clustered dictionary, IVF cells,
    per-flush recall sampling against the exact oracle."""
    import numpy as np

    from moco_tpu.serve.index import EmbeddingIndex
    from moco_tpu.serve.server import ServeServer

    rng = np.random.default_rng(5)
    dim = engine.num_features or 16
    per = IVF_DICT_ROWS // IVF_NLIST
    centers = rng.normal(size=(IVF_NLIST, dim)).astype(np.float32)
    rows = np.repeat(centers, per, axis=0) + 0.2 * rng.normal(
        size=(IVF_DICT_ROWS, dim)
    ).astype(np.float32)
    rows /= np.linalg.norm(rows, axis=1, keepdims=True)
    index = EmbeddingIndex(IVF_DICT_ROWS, dim)
    index.snapshot(rows)
    index.train_ivf(nlist=IVF_NLIST, nprobe=IVF_NPROBE)
    server = ServeServer(
        engine,
        index=index,
        port=0,
        slo_ms=SERVER_SLO_MS,
        neighbors_k=5,
        neighbors_mode="ivf",
        nprobe=IVF_NPROBE,
        recall_sample_every=1,  # sample the oracle on EVERY neighbors flush
        sink=sink,
        metrics_flush_s=0.5,
    )
    base = f"http://127.0.0.1:{server.port}"
    failures: list[str] = []
    try:
        for j in range(IVF_REQUESTS):
            n = int(rng.choice(REQUEST_SIZES))
            imgs = canned[n]
            # 2/3 of requests name the tier explicitly, the rest ride
            # the server default — both must resolve to ivf
            path = "/neighbors?k=5&mode=ivf" if j % 3 else "/neighbors?k=5"
            req = urllib.request.Request(
                base + path,
                data=imgs.tobytes(),
                headers={"X-Image-Shape": ",".join(map(str, imgs.shape))},
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    out = json.loads(r.read())
                idx = np.asarray(out["indices"])
                if out.get("mode") != "ivf" or idx.shape != (n, 5) or (
                    idx >= IVF_DICT_ROWS
                ).any():
                    failures.append(f"ivf req {j}: malformed {out.get('mode')}")
            except Exception as e:
                failures.append(f"ivf req {j}: {e!r}")
        stats = server.stats()
    finally:
        server.close()
    return {
        "failures": failures,
        "stats": stats,
        "recall_floor": RECALL_FLOOR,
        "ivf_stats": index.ivf_stats(),
    }


def _slo_leg(engine, workdir: str, canned) -> dict:
    """Third server: request tracing on, tight SLO, short burn windows,
    tightened burn threshold; a deterministic `slow@` fault injects the
    tail. The acceptance bullet, executable: the slowed requests trip
    the burn-rate alert and the flight dump attributes their latency to
    exactly the slowed stage."""
    import glob as globmod
    import urllib.request

    import numpy as np

    from moco_tpu.obs.sinks import JsonlSink
    from moco_tpu.serve.server import ServeServer
    from moco_tpu.utils import faults

    slo_dir = os.path.join(workdir, "slo_leg")
    os.makedirs(slo_dir, exist_ok=True)
    sink = JsonlSink(slo_dir)
    server = ServeServer(
        engine,
        index=None,
        port=0,
        slo_ms=SLO_LEG_SLO_MS,
        sink=sink,
        metrics_flush_s=0.25,
        warmup=False,  # the shared engine is already warm
        workdir=slo_dir,
        reqtrace=True,
        slo_objective=0.9,
        burn_windows=(30, 120),
        alert_spec=(
            "threshold@name=slo_burn_fast:field=serve/burn_rate_30s:value=1.0"
        ),
    )
    base = f"http://127.0.0.1:{server.port}"

    def post(imgs) -> dict:
        req = urllib.request.Request(
            base + "/embed",
            data=imgs.tobytes(),
            headers={"X-Image-Shape": ",".join(map(str, imgs.shape))},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    imgs = canned[2]
    slowed_ids: list[str] = []
    try:
        for _ in range(SLO_LEG_REQUESTS):  # healthy baseline
            post(imgs)
        # deterministic tail: the NEXT engine executions sleep; a fresh
        # plan install resets the site counters so at=1 means "from the
        # next call" regardless of warmup/baseline execution counts
        faults.install(
            f"slow@site=serve.engine_execute:ms={SLO_LEG_SLOW_MS:g}"
            f":at=1:times={SLO_LEG_SLOWED}"
        )
        try:
            for _ in range(SLO_LEG_SLOWED):
                slowed_ids.append(post(imgs)["request_id"])
        finally:
            faults.clear()
        for _ in range(6):  # post-incident traffic keeps the window live
            post(imgs)
        # give the flusher a turn: burn-rate computed, alert fired,
        # flight dumped via the on_fire hook
        deadline = time.time() + 10.0
        while time.time() < deadline and not globmod.glob(
            os.path.join(slo_dir, "flight_*.json")
        ):
            time.sleep(0.1)
        with urllib.request.urlopen(base + "/debug/flight", timeout=30) as r:
            debug_flight = json.loads(r.read())
        stats = server.stats()
        server._write_metrics()  # land the incident's gauges before close
    finally:
        server.close()
        sink.close()
    from moco_tpu.obs.alerts import read_alerts

    alerts = read_alerts(os.path.join(slo_dir, "alerts.jsonl"))
    dumps = sorted(globmod.glob(os.path.join(slo_dir, "flight_*.json")))
    alert_dump = None
    for path in dumps:
        with open(path) as f:
            rec = json.load(f)
        if str(rec.get("reason", "")).startswith("alert:"):
            alert_dump = rec
    return {
        "slo_ms": SLO_LEG_SLO_MS,
        "slow_ms": SLO_LEG_SLOW_MS,
        "slowed_ids": slowed_ids,
        "alerts": alerts,
        "dumps": [os.path.basename(p) for p in dumps],
        "alert_dump": alert_dump,
        "debug_flight": debug_flight,
        "stats": stats,
    }


def _quant_leg(ckpt_dir: str, engine_f32, sink, canned) -> dict:
    """Fourth server: the w8a8 engine behind the fused IVF scan
    (module docstring leg 8). Calibration is captured from a held-out
    sample at the checkpoint, saved as `quant_calib.json` NEXT TO the
    checkpoint, and loaded back through disk — the exact boot path a
    production replica takes — before the quantized engine compiles its
    buckets. Traffic mixes explicit `?mode=ivf_fused` riders with the
    server default; recall samples on every flush."""
    import numpy as np

    from moco_tpu.serve import quant
    from moco_tpu.serve.engine import InferenceEngine, load_serving_encoder
    from moco_tpu.serve.index import EmbeddingIndex
    from moco_tpu.serve.server import ServeServer

    module, params, stats, _queue, _ptr, _config = load_serving_encoder(ckpt_dir)
    rng = np.random.default_rng(11)
    sample = rng.integers(
        0, 255, (QUANT_CALIB_SAMPLES, IMAGE_SIZE, IMAGE_SIZE, 3), np.uint8
    )
    calib = quant.calibrate_encoder(module, params, stats, sample, IMAGE_SIZE)
    calib_path = quant.save_calibration(ckpt_dir, calib)
    loaded = quant.load_calibration(ckpt_dir)
    engine = InferenceEngine(
        module, params, stats,
        image_size=IMAGE_SIZE, buckets=(1, 8, 32),
        engine_quant="w8a8", calibration=loaded,
    )
    # quantized embeddings must stay in the f32 engine's space
    probe = canned[16]
    emb_q, _ = engine.embed(probe)
    emb_f, _ = engine_f32.embed(probe)
    cosine = float(np.mean(np.sum(
        emb_q.astype(np.float64) * emb_f.astype(np.float64), axis=-1
    )))
    # clustered dictionary, served through the fused scan
    dim = engine.num_features or 16
    per = IVF_DICT_ROWS // IVF_NLIST
    centers = rng.normal(size=(IVF_NLIST, dim)).astype(np.float32)
    rows = np.repeat(centers, per, axis=0) + 0.2 * rng.normal(
        size=(IVF_DICT_ROWS, dim)
    ).astype(np.float32)
    rows /= np.linalg.norm(rows, axis=1, keepdims=True)
    index = EmbeddingIndex(IVF_DICT_ROWS, dim)
    index.snapshot(rows)
    index.train_ivf(nlist=IVF_NLIST, nprobe=IVF_NPROBE)
    server = ServeServer(
        engine,
        index=index,
        port=0,
        slo_ms=SERVER_SLO_MS,
        neighbors_k=5,
        neighbors_mode="ivf_fused",
        nprobe=IVF_NPROBE,
        recall_sample_every=1,
        sink=sink,
        metrics_flush_s=0.5,
    )
    base = f"http://127.0.0.1:{server.port}"
    failures: list[str] = []
    try:
        for j in range(QUANT_REQUESTS):
            n = int(rng.choice(REQUEST_SIZES))
            imgs = canned[n]
            path = "/neighbors?k=5&mode=ivf_fused" if j % 3 else "/neighbors?k=5"
            req = urllib.request.Request(
                base + path,
                data=imgs.tobytes(),
                headers={"X-Image-Shape": ",".join(map(str, imgs.shape))},
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    out = json.loads(r.read())
                idx = np.asarray(out["indices"])
                if out.get("mode") != "ivf_fused" or idx.shape != (n, 5) or (
                    idx >= IVF_DICT_ROWS
                ).any():
                    failures.append(f"quant req {j}: malformed {out.get('mode')}")
            except Exception as e:
                failures.append(f"quant req {j}: {e!r}")
        stats_out = server.stats()
    finally:
        server.close()
    return {
        "failures": failures,
        "stats": stats_out,
        "cosine_vs_f32": cosine,
        "cosine_floor": QUANT_COSINE_FLOOR,
        "calib_path": os.path.basename(calib_path),
        "calib_layers": calib["num_layers"],
        "calib_roundtrip": loaded == calib,
        "recall_floor": RECALL_FLOOR,
        "donation_audit": {str(k): v for k, v in engine.donation_audit().items()},
        "ivf_stats": index.ivf_stats(),
    }


def assert_serve_surface(workdir: str, summary: dict) -> None:
    from moco_tpu.obs import schema

    # leg 9 (--sanitize-threads): the clean pass saw real lock traffic
    # including the sanctioned serve.index -> serve.metrics nesting and
    # recorded ZERO order cycles; the chaos arm's forced inversion was
    # caught with a both-stacks diff artifact
    tsan = summary.get("tsan")
    if tsan is not None:
        assert tsan["cycles"] == 0, f"lock-order cycles on the clean pass: {tsan}"
        assert tsan["acquisitions"] > 0, "sanitizer saw no lock traffic"
        edges = {(e["held"], e["acquired"]) for e in tsan["edges"]}
        assert ("serve.index", "serve.metrics") in edges, (
            f"sanctioned stats() nesting not observed: {sorted(edges)}"
        )
        chaos = tsan["chaos"]
        assert chaos["cycles_caught"] >= 1, f"injected inversion not caught: {chaos}"
        assert chaos["diff_path"] and chaos["diff_has_both_stacks"], chaos
        assert chaos["injected_edges"] >= 1, chaos

    stats = summary["stats"]
    assert not summary["failures"], f"request failures: {summary['failures'][:5]}"
    assert stats["serve/requests"] >= summary["requests_sent"], stats
    # the headline contract: mixed request sizes, ZERO recompiles after
    # the AOT warmup (every shape served by a precompiled bucket)
    assert stats["serve/recompiles_after_warmup"] == 0, stats
    assert stats["serve/p99_ms"] is not None and stats["serve/p99_ms"] <= SMOKE_SLO_MS, (
        f"p99 {stats['serve/p99_ms']}ms over the smoke SLO {SMOKE_SLO_MS}ms"
    )
    assert stats["serve/occupancy"] is not None and 0 < stats["serve/occupancy"] <= 1
    buckets_hit = [k for k in stats if k.startswith("serve/bucket_")]
    assert len(buckets_hit) >= 2, f"mixed sizes should exercise >1 bucket: {stats}"
    assert stats["serve/index_rows"] == 64, stats
    # leg 5: streaming ingest advanced the serving count, no restart
    ingest = summary["ingest"]
    assert ingest["ingested"] > 0, ingest
    assert ingest["counter_after"] == ingest["counter_before"] + ingest["ingested"]
    assert stats["serve/ingested_rows"] == ingest["counter_after"], stats
    assert ingest["head_hit"], "freshly ingested rows not retrievable at the head"
    # leg 6: the IVF path — zero recompiles after warmup, the online
    # recall estimate at/above the floor, p99 under the smoke SLO
    ivf = summary["ivf"]
    assert not ivf["failures"], f"ivf request failures: {ivf['failures'][:5]}"
    istats = ivf["stats"]
    assert istats["serve/recompiles_after_warmup"] == 0, istats
    assert istats["serve/recall_estimate"] is not None, istats
    assert istats["serve/recall_estimate"] >= RECALL_FLOOR, (
        f"online recall {istats['serve/recall_estimate']} below the "
        f"{RECALL_FLOOR} floor (nprobe={istats.get('serve/nprobe')})"
    )
    assert istats["serve/p99_ms"] is not None and istats["serve/p99_ms"] <= SMOKE_SLO_MS
    assert istats["serve/nprobe"] == IVF_NPROBE and istats["serve/int8"] == 0, istats
    # leg 7: the SLO-violation story end-to-end (ISSUE 10 acceptance):
    # injected slow@serve.engine_execute -> burn-rate alert fired ->
    # flight dump contains the slowed requests' waterfalls with the
    # slowed stage correctly attributed
    slo = summary["slo"]
    assert any(a["rule"] == "slo_burn_fast" for a in slo["alerts"]), (
        f"burn-rate alert never fired: {slo['alerts']}"
    )
    assert slo["slowed_ids"], "slowed requests carried no request ids"

    def _assert_attributed(wf, rid):
        stage_ms = {s["stage"]: s["dur_ms"] for s in wf["stages"]}
        for stage in ("ingress", "queue_wait", "batch_assemble", "engine_execute",
                      "scatter", "respond"):
            assert stage in stage_ms, f"{rid}: stage {stage} missing: {stage_ms}"
        worst = max(stage_ms, key=stage_ms.get)
        assert worst == "engine_execute" and stage_ms[worst] >= slo["slow_ms"], (
            f"{rid}: injected tail misattributed — {stage_ms}"
        )

    # the alert-edge dump already holds (at least) the first offender
    # with the slowed stage attributed — the alert fires mid-incident
    assert slo["alert_dump"] is not None, f"no alert-triggered flight dump: {slo['dumps']}"
    alert_dumped = {r["request_id"]: r for r in slo["alert_dump"]["requests"]}
    caught = [rid for rid in slo["slowed_ids"] if rid in alert_dumped]
    assert caught, (
        f"no slowed request in the alert dump: {sorted(alert_dumped)[-8:]}"
    )
    for rid in caught:
        _assert_attributed(alert_dumped[rid], rid)
    # the on-demand dump at the end holds the FULL incident
    debug = slo["debug_flight"]
    assert debug.get("dump_path"), "/debug/flight did not dump on demand"
    debug_dumped = {r["request_id"]: r for r in debug["requests"]}
    for rid in slo["slowed_ids"]:
        assert rid in debug_dumped, f"slowed request {rid} missing from /debug/flight"
        _assert_attributed(debug_dumped[rid], rid)
    # the p99 exemplar names one of the offenders
    sstats = slo["stats"]
    assert sstats["serve/slo_violations"] >= len(slo["slowed_ids"]), sstats
    assert any(
        k.startswith("serve/burn_rate_") and sstats[k] is not None for k in sstats
    ), f"no burn-rate gauge in stats: {sorted(sstats)}"
    slowest = debug["slowest"][0]
    assert slowest["request_id"] in slo["slowed_ids"], slowest
    slo_metrics = os.path.join(workdir, "slo_leg", "metrics.jsonl")
    errors = schema.validate_file(slo_metrics)
    assert not errors, f"slo leg schema violations: {errors[:5]}"
    slo_lines = schema.read_metrics(slo_metrics)
    assert any(
        r.get("serve/trace_engine_execute_ms") is not None for r in slo_lines
    ), "no stage-trace means reached the sink"
    assert any(r.get("event") == "alert" for r in slo_lines), (
        "no in-band alert event line"
    )
    # the p99 exemplar on the incident's metrics lines blames an
    # injected-slow request id — the gauge-to-request link, on the wire
    assert any(
        r.get("serve/p99_exemplar") in slo["slowed_ids"] for r in slo_lines
    ), "no metrics line exemplar blames a slowed request"
    # request spans reached the replica's Perfetto stream
    assert os.path.exists(os.path.join(workdir, "slo_leg", "trace_events.s0.jsonl"))
    assert os.path.exists(os.path.join(workdir, "slo_leg", "heartbeat.s0.json"))

    # leg 8: the w8a8 engine behind the fused IVF scan (ISSUE 11) —
    # zero recompiles across the new (mode, quant) bucket keys, the
    # quantized embeddings pinned to the f32 space, the recall floor
    # held through the fused tier, and the donation audit clean on the
    # quantized trees (fail LOUDLY on any False: a consumed qtree
    # buffer is a use-after-free on the next request)
    qleg = summary["quant"]
    assert not qleg["failures"], f"quant request failures: {qleg['failures'][:5]}"
    assert qleg["calib_roundtrip"], "calibration artifact did not roundtrip"
    assert qleg["cosine_vs_f32"] >= qleg["cosine_floor"], (
        f"w8a8 cosine {qleg['cosine_vs_f32']:.5f} below the "
        f"{qleg['cosine_floor']} floor"
    )
    qstats = qleg["stats"]
    assert qstats["serve/recompiles_after_warmup"] == 0, qstats
    assert qstats["serve/quant_tier"] == 2, qstats
    assert qstats["serve/recall_estimate"] is not None, qstats
    assert qstats["serve/recall_estimate"] >= qleg["recall_floor"], (
        f"fused-tier online recall {qstats['serve/recall_estimate']} below "
        f"the {qleg['recall_floor']} floor under the w8a8 engine"
    )
    assert qstats["serve/p99_ms"] is not None and qstats["serve/p99_ms"] <= SMOKE_SLO_MS
    # ivf_stats exported: spill + occupancy gauges (the re-fit trigger)
    assert qstats["serve/ivf_spill"] is not None and qstats["serve/ivf_spill"] >= 0
    assert qstats["serve/ivf_occupancy"] is not None and 0 < qstats["serve/ivf_occupancy"] <= 1
    bad_audit = {k: v for k, v in qleg["donation_audit"].items() if v is False}
    assert not bad_audit, (
        f"donation audit failed on the quantized engine: {bad_audit} — "
        "a donated-but-surviving input leaks memory per request; a "
        "consumed quantized tree is a use-after-free on the next one"
    )

    # metrics flushed through the sink are schema-strict
    metrics_path = os.path.join(workdir, "metrics.jsonl")
    assert os.path.exists(metrics_path), "server flushed no metrics.jsonl"
    errors = schema.validate_file(metrics_path)
    assert not errors, f"schema violations: {errors[:5]}"
    lines = schema.read_metrics(metrics_path)
    assert any("serve/qps" in r for r in lines), "no serve/* line reached the sink"
    assert any(
        r.get("serve/recall_estimate") is not None for r in lines
    ), "no recall estimate reached the sink"


def main() -> int:
    from moco_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()  # honor JAX_PLATFORMS at the config level
    ap = argparse.ArgumentParser(description="embedding-service smoke")
    ap.add_argument("--workdir", default=None, help="default: a fresh temp dir")
    ap.add_argument(
        "--sanitize-threads", action="store_true",
        help="mocolint v3 runtime arm: trace lock acquisition order over "
        "the whole run (clean = zero cycles), then prove the detector on "
        "a deadlock@site=serve.metrics chaos leg (lock_order_diff.json "
        "with both stacks uploads as a CI artifact)",
    )
    ap.add_argument(
        "--contract-coverage", action="store_true",
        help="mocolint v4 runtime arm: record which declared routes, "
        "fault sites, and schema validators actually fire during the "
        "run, write contract_coverage.json, and FAIL on any registered "
        "contract that never fired",
    )
    args = ap.parse_args()
    workdir = args.workdir or tempfile.mkdtemp(prefix="serve_smoke_")
    os.makedirs(workdir, exist_ok=True)
    summary = run_smoke(
        workdir,
        sanitize_threads=args.sanitize_threads,
        contract_coverage=args.contract_coverage,
    )
    assert_serve_surface(workdir, summary)
    s = summary["stats"]
    iv = summary["ivf"]["stats"]
    slo = summary["slo"]
    print(
        f"serve smoke OK: {s['serve/requests']} requests, "
        f"p50={s['serve/p50_ms']:.1f}ms p99={s['serve/p99_ms']:.1f}ms "
        f"qps={s['serve/qps']:.1f} occupancy={s['serve/occupancy']:.3f} "
        f"recompiles_after_warmup={s['serve/recompiles_after_warmup']} | "
        f"ingested={summary['ingest']['ingested']} | "
        f"ivf: {iv['serve/requests']} requests "
        f"recall={iv['serve/recall_estimate']:.3f} "
        f"nprobe={iv['serve/nprobe']}/{IVF_NLIST} "
        f"p99={iv['serve/p99_ms']:.1f}ms "
        f"recompiles={iv['serve/recompiles_after_warmup']} | "
        f"slo leg: {len(slo['slowed_ids'])} slowed requests -> "
        f"{len(slo['alerts'])} alert(s), {len(slo['dumps'])} flight dump(s), "
        f"p99 exemplar {slo['stats'].get('serve/p99_exemplar')} | "
        f"quant leg: w8a8 cos={summary['quant']['cosine_vs_f32']:.5f} "
        f"fused recall={summary['quant']['stats']['serve/recall_estimate']:.3f} "
        f"recompiles={summary['quant']['stats']['serve/recompiles_after_warmup']} "
        f"spill={summary['quant']['stats']['serve/ivf_spill']}"
        + (
            " | tsan: {a} acquisitions, 0 cycles clean, chaos caught "
            "{c} cycle(s)".format(
                a=summary["tsan"]["acquisitions"],
                c=summary["tsan"]["chaos"]["cycles_caught"],
            )
            if summary.get("tsan")
            else ""
        )
        + f" — artifacts in {workdir}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
