"""Per-stage breakdown of the exact-host-RRC input path (VERDICT r2 #5).

PROFILE.md's with-data ladder showed the host pipeline ~10x below the
device rate but attributed the ceiling by extrapolation. This script
measures where each millisecond goes, per batch, for every input mode:

  stages: dims lookup -> RRC box sampling -> source read (JPEG decode
  or cache mmap) -> crop+resize (PIL or C++ resize_region) -> assemble
  [-> host-to-device transfer, when an accelerator is attached]

Modes (the same ladder bench.py / PROFILE.md use):
  jpeg_pil     — ImageFolderDataset: PIL decode + PIL crop/resize
  jpeg_native  — native/loader.cc decode pool + C++ crops
  cache_pil    — PackedRGBCacheDataset(use_native=False): mmap + PIL
  cache_native — PackedRGBCacheDataset: mmap + C++ resize_region
  cache_canvas — canvas mode: pure mmap row read (host_rrc=False)

The crop stage is additionally swept over thread counts; on a 1-core
host that curve is expected flat (it measures GIL/pool overhead, not
parallel speedup) — the per-thread number is what transfers to
multi-core hosts since both crop backends release the GIL (C++) or run
in PIL's C core.

`--overlap` additionally A/Bs the end-to-end input path — the
synchronous epoch iterator (decode → transfer → augment dispatch taking
turns on one producer thread) vs the device prefetch ring
(`data/device_prefetch.py`: decode thread + dedicated transfer thread +
staged device batches) — and reports the ring's measured wire rate and
`overlap_efficiency` = achieved / min(host-rate, wire-rate).

Writes artifacts/input_profile.json and a marker-delimited section into
PROFILE.md. Run:
    python scripts/profile_input.py            # TPU if healthy, else CPU
    JAX_PLATFORMS=cpu python scripts/profile_input.py --batches 4
    python scripts/profile_input.py --overlap  # + sync-vs-ring A/B
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

import numpy as np

ART_PATH = "artifacts/input_profile.json"


def _sample_boxes(dims: np.ndarray, n_crops: int, seed: int, epoch: int, step: int,
                  idx: np.ndarray, scale=(0.2, 1.0)) -> np.ndarray:
    """The pipeline's exact box sampling (pipeline.py:_put_crop_batch):
    one (seed, epoch, step)-keyed vectorized uniform draw for the whole
    batch × crops, sliced by global position. (The prior per-(row, crop)
    seeded-Generator scheme measured ~0.24 ms per crop of pure seeding
    overhead here — the reason the pipeline was rewritten; 107x faster.)"""
    from moco_tpu.data.datasets import draw_rrc_uniforms, rrc_boxes_from_uniforms

    rng = np.random.default_rng((seed, epoch, step))
    u = draw_rrc_uniforms(rng, len(idx) * n_crops)
    return rrc_boxes_from_uniforms(
        u, np.repeat(dims, n_crops, axis=0), scale=scale
    ).reshape(len(idx), n_crops, 4)


def _time(fn, reps: int) -> float:
    """Best-of-reps milliseconds (min filters scheduler noise on the
    shared single core)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def profile_mode(name: str, dataset, batch: int, out_size: int, reps: int,
                 pool) -> dict:
    idx = np.arange(batch) % len(dataset)
    res = {"mode": name, "batch": batch, "out_size": out_size}

    res["dims_ms"] = _time(lambda: dataset.dims(idx), reps)
    dims = dataset.dims(idx)
    res["boxes_ms"] = _time(lambda: _sample_boxes(dims, 2, 0, 0, 0, idx), reps)
    boxes = _sample_boxes(dims, 2, 0, 0, 0, idx)

    if name == "cache_canvas":
        # canvas mode has no crop stage: one mmap row read per image
        res["read_ms"] = _time(
            lambda: np.stack([dataset.load(int(i))[0] for i in idx]), reps
        )
        res["crop_ms"] = 0.0
        # boxes_ms included for cross-mode comparability even though
        # canvas mode consumes no boxes host-side (the RRC crop runs on
        # device from the fixed canvas) — every mode's total now sums
        # the same stages
        res["total_ms"] = res["dims_ms"] + res["boxes_ms"] + res["read_ms"]
        return res

    # full crop-batch stage (read + crop + resize + assembly into the
    # output array, exactly what the pipeline calls)
    res["crop_batch_ms"] = _time(
        lambda: dataset.load_crop_batch(idx, boxes, out_size, pool=pool), reps
    )

    # source-read sub-stage: decode (JPEG) or mmap slice (cache)
    if hasattr(dataset, "_image"):  # cache: mmap read + materialize
        res["read_ms"] = _time(
            lambda: [np.ascontiguousarray(dataset._image(int(i))) for i in idx], reps
        )
    elif hasattr(dataset, "samples"):  # JPEG folder: PIL decode only
        from PIL import Image

        def decode_all():
            for i in idx:
                with Image.open(dataset.samples[int(i)][0]) as im:
                    np.asarray(im.convert("RGB"))

        res["read_ms"] = _time(decode_all, reps)
    else:
        res["read_ms"] = None
    if res["read_ms"] is not None:
        # APPROXIMATE: crop_batch_ms and read_ms are independent
        # best-of-reps measurements, so their difference can misattribute
        # assembly cost or go negative under scheduler noise — clamp at 0
        # and treat as indicative only (the render marks it "~")
        res["crop_resize_ms"] = max(0.0, res["crop_batch_ms"] - res["read_ms"])
    res["total_ms"] = res["dims_ms"] + res["boxes_ms"] + res["crop_batch_ms"]
    return res


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--out-size", type=int, default=224)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--src-size", type=int, default=256, help="synthetic JPEG geometry")
    ap.add_argument("--n-images", type=int, default=512)
    ap.add_argument("--threads", type=int, nargs="*", default=[1, 2, 4, 8])
    ap.add_argument("--profile-md", default="PROFILE.md")
    ap.add_argument("--artifact", default=ART_PATH)
    ap.add_argument(
        "--overlap", action="store_true",
        help="A/B the sync epoch iterator vs the device prefetch ring "
        "(cache canvas mode, the fastest host path) and report "
        "overlap_efficiency",
    )
    args = ap.parse_args()

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import _ensure_jpeg_folder

    from moco_tpu.data.cache import PackedRGBCacheDataset, build_rgb_cache
    from moco_tpu.data.datasets import ImageFolderDataset
    from moco_tpu.data.native_loader import native_available

    folder = _ensure_jpeg_folder("/tmp/moco_bench_imgfolder", args.n_images, args.src_size)
    cache_dir = "/tmp/moco_input_profile_cache"
    build_rgb_cache(
        lambda: ImageFolderDataset(folder, decode_size=args.src_size),
        cache_dir, num_workers=1, canvas_size=args.src_size, root=folder,
    )

    from concurrent.futures import ThreadPoolExecutor

    results = []
    native = native_available()
    for threads in args.threads:
        pool = ThreadPoolExecutor(max_workers=threads)
        modes = {
            "jpeg_pil": ImageFolderDataset(folder, decode_size=args.src_size),
            "cache_pil": PackedRGBCacheDataset(
                cache_dir, decode_size=args.src_size, use_native=False,
                num_workers=threads,
            ),
        }
        if native:
            from moco_tpu.data.native_loader import NativeImageFolderDataset

            modes["jpeg_native"] = NativeImageFolderDataset(
                folder, decode_size=args.src_size, threads=threads
            )
            modes["cache_native"] = PackedRGBCacheDataset(
                cache_dir, decode_size=args.src_size, use_native=True,
                num_workers=threads,
            )
        modes["cache_canvas"] = PackedRGBCacheDataset(
            cache_dir, decode_size=args.src_size, use_native=False,
            num_workers=threads,
        )
        for name, ds in modes.items():
            r = profile_mode(name, ds, args.batch, args.out_size, args.reps, pool)
            r["threads"] = threads
            r["imgs_per_sec"] = 1e3 * args.batch / r["total_ms"]
            results.append(r)
            print(
                f"[threads={threads}] {name:13s} total {r['total_ms']:8.1f} ms/batch "
                f"({r['imgs_per_sec']:7.1f} imgs/s) "
                + " ".join(
                    f"{k.replace('_ms','')}={v:.1f}"
                    for k, v in r.items()
                    if k.endswith("_ms") and k != "total_ms" and v is not None
                ),
                flush=True,
            )
        pool.shutdown()

    # host->device transfer of one batch's fresh uint8 buffers (2 crops)
    transfer = None
    import jax

    try:
        dev = jax.devices()[0]
        buf = np.random.default_rng(0).integers(
            0, 255, (args.batch, args.out_size, args.out_size, 3), np.uint8
        )
        def put():
            a = jax.device_put(buf.copy(), dev)  # fresh buffer: no cache
            b = jax.device_put(buf.copy(), dev)
            np.asarray(a[0, 0, 0]); np.asarray(b[0, 0, 0])  # sync via fetch
        transfer = {
            "platform": dev.platform,
            "two_crop_put_ms": _time(put, args.reps),
            "bytes": 2 * buf.nbytes,
        }
        transfer["mb_per_sec"] = (
            transfer["bytes"] / 1e6 / (transfer["two_crop_put_ms"] / 1e3)
        )
        print(f"transfer: {transfer}")
    except Exception as e:
        print(f"transfer timing skipped: {e}", file=sys.stderr)

    # sync-vs-ring overlap A/B over the full epoch path (--overlap)
    overlap = None
    if args.overlap:
        try:
            overlap = profile_overlap(folder, cache_dir, args.batch, args.out_size,
                                      src_size=args.src_size)
            print(f"overlap: {overlap}")
        except Exception as e:
            print(f"overlap profiling skipped: {e}", file=sys.stderr)

    os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
    payload = {
        "batch": args.batch, "out_size": args.out_size,
        "src_size": args.src_size, "native_available": native,
        "results": results, "transfer": transfer, "overlap": overlap,
    }
    with open(args.artifact, "w") as f:
        json.dump(payload, f, indent=2)
    write_section(args.profile_md, payload)


def profile_overlap(folder: str, cache_dir: str, batch: int, out_size: int,
                    src_size: int, n_batches: int = 6) -> dict:
    """End-to-end epoch-path A/B: sync iterator vs the device prefetch
    ring, canvas mode (the fastest host path, so the WIRE + consumer
    side is what the A/B isolates). Consumes each batch to readiness —
    the closest harness to the train loop without paying a train step.

    The geometric-only recipe (crops_only) stands in for the augment:
    on a 1-core CPU host the full jitter/blur recipe costs ~80 s/batch
    of pure compute, which would bury the input path this script
    profiles (on a TPU the augment is microseconds — bench.py's
    overlapped with-data leg is the on-hardware measurement)."""
    import jax

    from moco_tpu.data.pipeline import TwoCropPipeline
    from moco_tpu.parallel import create_mesh
    from moco_tpu.utils.config import DataConfig

    mesh = create_mesh(num_data=1, num_model=1, devices=jax.devices()[:1])
    cfg = DataConfig(
        dataset="imagefolder", data_dir=folder, image_size=out_size,
        global_batch=batch, crops_only=True, num_workers=8,
        cache_dir=cache_dir, host_rrc=False,  # canvas: pure mmap row read
    )
    pipe = TwoCropPipeline(cfg, mesh, seed=0)

    def leg(device: bool) -> tuple[float, object]:
        state = {"it": pipe.epoch(0, device=device), "epoch": 0}

        def nxt():
            while True:
                b = next(state["it"], None)
                if b is not None:
                    return b
                getattr(state["it"], "close", lambda: None)()
                state["epoch"] += 1
                state["it"] = pipe.epoch(state["epoch"], device=device)

        jax.block_until_ready(nxt()["im_q"])  # spin-up + compile
        t0 = time.perf_counter()
        for _ in range(n_batches):
            jax.block_until_ready(nxt()["im_q"])
        dt = time.perf_counter() - t0
        stats = getattr(state["it"], "stats", None)
        getattr(state["it"], "close", lambda: None)()
        return batch * n_batches / dt, stats

    sync_rate, _ = leg(device=False)
    ring_rate, stats = leg(device=True)
    out = {
        "mode": "cache_canvas+crops_only",
        "sync_imgs_per_sec": round(sync_rate, 1),
        "ring_imgs_per_sec": round(ring_rate, 1),
        "speedup": round(ring_rate / sync_rate, 3) if sync_rate else None,
    }
    # stage bounds for the efficiency denominator: host decode alone,
    # the measured wire rate, and the CONSUMER (transfer + augment
    # compute on the same staged batch — on a CPU host this is the
    # binding stage and must be in the denominator, else the ratio
    # reads as overlap failure when compute is simply the bottleneck)
    bounds = {}
    t0 = time.perf_counter()
    n = 0
    for _ in pipe._host_gen(97):
        n += 1
        if n >= n_batches:
            break
    bounds["host"] = batch * n / (time.perf_counter() - t0)
    hb = next(pipe._host_gen(98))
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out_b, _ = pipe._stage(hb, False)
        jax.block_until_ready(out_b["im_q"])
    bounds["consume"] = batch * reps / (time.perf_counter() - t0)
    if stats is not None and stats.batches:
        wire_bps = stats.wire_rate_bytes_per_sec()
        bytes_per_img = stats.total_bytes / stats.batches / batch
        if wire_bps and bytes_per_img:
            bounds["wire"] = wire_bps / bytes_per_img
            out["wire_mb_per_sec"] = round(wire_bps / 1e6, 1)
    for name, rate in bounds.items():
        out[f"{name}_imgs_per_sec"] = round(rate, 1)
    out["overlap_efficiency"] = round(ring_rate / min(bounds.values()), 3)
    return out


def write_section(profile_md: str, payload: dict) -> None:
    rows = [r for r in payload["results"] if r["threads"] == 1]
    by_threads: dict = {}
    for r in payload["results"]:
        by_threads.setdefault(r["mode"], {})[r["threads"]] = r["imgs_per_sec"]
    lines = [
        "## Input-path per-stage breakdown",
        "",
        f"`scripts/profile_input.py`: batch {payload['batch']}, two "
        f"{payload['out_size']}px crops/image, {payload['src_size']}px synthetic "
        "JPEGs, best-of-reps ms per batch, single thread (per-stage):",
        "",
        "| mode | dims | box sample | source read | ~crop+resize | total ms | imgs/s |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        cr = r.get("crop_resize_ms")
        lines.append(
            f"| {r['mode']} | {r['dims_ms']:.1f} | {r.get('boxes_ms', 0):.1f} | "
            f"{r['read_ms'] if r['read_ms'] is not None else float('nan'):.1f} | "
            f"{cr if cr is not None else 0:.1f} | "
            f"{r['total_ms']:.1f} | {r['imgs_per_sec']:.0f} |"
        )
    lines += [
        "",
        "(~crop+resize is approximate — derived by subtracting two",
        "independently-timed best-of-reps stages, clamped at 0; canvas",
        "mode's box-sample column is host cost only, its RRC crop runs",
        "on device from the fixed canvas.)",
    ]
    lines += [
        "",
        "Thread scaling (imgs/s; flat on this 1-core host — the pools add",
        "no overhead but there is no parallelism to harvest; both crop",
        "backends run outside the GIL, so the 1-thread rate scales with",
        "cores on real TPU-VM hosts):",
        "",
        "| mode | " + " | ".join(f"{t} thr" for t in sorted({r['threads'] for r in payload['results']})) + " |",
        "|---|" + "---|" * len({r['threads'] for r in payload['results']}),
    ]
    for mode, per in by_threads.items():
        lines.append(
            f"| {mode} | " + " | ".join(f"{per[t]:.0f}" for t in sorted(per)) + " |"
        )
    t = payload.get("transfer")
    if t:
        lines += [
            "",
            f"Host→device transfer ({t['platform']}): {t['two_crop_put_ms']:.1f} ms "
            f"for both crop buffers ({t['bytes'] / 1e6:.0f} MB) = "
            f"{t['mb_per_sec']:.0f} MB/s.",
        ]
    ov = payload.get("overlap")
    if ov:
        lines += [
            "",
            "### Input-wire overlap (device prefetch ring)",
            "",
            f"End-to-end epoch path, {ov['mode']} mode, sync iterator vs "
            "`epoch(device=True)` (`data/device_prefetch.py`):",
            "",
            f"- sync: {ov['sync_imgs_per_sec']:.0f} imgs/s; overlapped: "
            f"{ov['ring_imgs_per_sec']:.0f} imgs/s "
            f"(×{ov['speedup']:.2f})",
            "- stage bounds (imgs/s): "
            + ", ".join(
                f"{k.removesuffix('_imgs_per_sec')} {ov[k]:.0f}"
                for k in ("host_imgs_per_sec", "wire_imgs_per_sec",
                          "consume_imgs_per_sec")
                if k in ov
            )
            + (f" (wire {ov['wire_mb_per_sec']:.0f} MB/s)"
               if "wire_mb_per_sec" in ov else ""),
            f"- overlap_efficiency (achieved / min(stage bounds)): "
            f"{ov['overlap_efficiency']:.3f} — on this 1-core host the "
            "consumer (augment compute shares the single core) is the "
            "binding stage, and >1 means the serially-measured consume "
            "bound (transfer then augment, no overlap) understates the "
            "pipelined bound; bench.py's overlapped with-data leg is "
            "the on-hardware measurement",
        ]
    from moco_tpu.utils.report import replace_marker_block

    replace_marker_block(profile_md, "input-profile", "\n".join(lines))
    print(f"input-profile section written into {profile_md}")


if __name__ == "__main__":
    main()
