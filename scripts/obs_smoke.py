#!/usr/bin/env python
"""Telemetry driver smoke: a ≤5-step CPU training run that must produce
the full observability surface, asserted hard.

    JAX_PLATFORMS=cpu python scripts/obs_smoke.py [--workdir DIR]

Asserts (the ISSUE-3 acceptance bullet, executable):

1. `trace.json` is a valid Chrome trace-event file with nested
   epoch > step / data_wait spans (timestamp containment per thread);
2. every training line in `metrics.jsonl` carries the step-time
   breakdown (`t_data`/`t_step`), device-memory gauges
   (`hbm_live_bytes`, number or null), and the MoCo health gauges
   (`queue_age_mean`, `ema_drift`, `logit_pos_mean`/`logit_neg_mean`) —
   computed INSIDE the jitted step;
3. every line validates against the schema (obs/schema.py);
4. the CSV sink and span JSONL stream exist and parse.

CI runs this in the tier-1 job, uploads the workdir as an artifact, and
then renders `scripts/obs_report.py --strict` against it — so neither
the telemetry surface nor the report renderer can rot. Wall cost: one
tiny compile + 3 steps, a couple of minutes on a CPU host.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def run_smoke(workdir: str, metrics_port: int = 0) -> dict:
    """Run the tiny driver run; returns {'workdir', 'result'}. Split
    from the assertions so tests can reuse the run."""
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        TrainConfig,
    )

    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18",
            dim=16,
            num_negatives=32,
            temperature=0.2,
            mlp=True,
            shuffle="none",
            cifar_stem=True,
            compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=1, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=8, num_workers=2),
        workdir=workdir,
        log_every=1,
        obs_probe_every=2,  # sample steps 0 and 2 of the 3-step run
        metrics_port=metrics_port,
        sinks="jsonl,csv",
    )
    dataset = SyntheticDataset(num_examples=24, image_size=16)  # 3 steps of 8
    result = train(config, dataset=dataset)
    return {"workdir": workdir, "result": result}


def assert_obs_surface(workdir: str) -> None:
    from moco_tpu.obs import schema

    # -- 1. chrome trace: valid JSON, nested epoch/step/data_wait -------
    trace_path = os.path.join(workdir, "trace.json")
    assert os.path.exists(trace_path), "driver did not export trace.json"
    with open(trace_path) as f:
        trace = json.load(f)
    events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    by_name: dict[str, list[dict]] = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    for required in ("epoch", "step", "data_wait", "checkpoint_save"):
        assert by_name.get(required), f"trace has no {required!r} spans"
    epoch_span = by_name["epoch"][0]
    e0, e1 = epoch_span["ts"], epoch_span["ts"] + epoch_span["dur"]
    for child_name in ("step", "data_wait"):
        for child in by_name[child_name]:
            if child["tid"] != epoch_span["tid"]:
                continue  # producer-thread spans nest on their own track
            assert e0 <= child["ts"] and child["ts"] + child["dur"] <= e1 + 1, (
                f"{child_name} span not nested inside the epoch span"
            )
    assert len(by_name["step"]) == 3, "expected exactly 3 step spans"

    # -- 2+3. metrics lines: breakdown + health + schema-valid ----------
    metrics_path = os.path.join(workdir, "metrics.jsonl")
    errors = schema.validate_file(metrics_path)
    assert not errors, f"schema violations: {errors}"
    records = schema.read_metrics(metrics_path)
    train_lines = [r for r in records if "loss" in r and "event" not in r]
    assert len(train_lines) == 3, f"expected 3 training lines, got {len(train_lines)}"
    required = (
        "t_data", "t_step", "hbm_live_bytes", "queue_age_mean", "queue_age_max",
        "queue_age_hist", "ema_drift", "logit_pos_mean", "logit_neg_mean",
        "logit_pos_std", "logit_neg_std", "feature_std",
    )
    for rec in train_lines:
        missing = [k for k in required if k not in rec]
        assert not missing, f"training line {rec['step']} missing {missing}"
        # hbm gauges: number or null, never absent (schema lock)
        assert rec["hbm_live_bytes"] is None or rec["hbm_live_bytes"] >= 0
    # probe sampled at least one step -> dispatch/device split appears
    assert any("t_device" in r for r in train_lines), "probe never sampled"
    # health gauges came from the jitted step: finite and sane
    last = train_lines[-1]
    assert last["queue_age_mean"] > 0, "queue age should advance after step 1"
    assert last["ema_drift"] > 0, "EMA drift should be nonzero after an update"
    assert last["logit_pos_std"] >= 0 and last["logit_neg_std"] >= 0

    # -- 4. secondary sinks + span stream -------------------------------
    csv_path = os.path.join(workdir, "metrics.csv")
    with open(csv_path, newline="") as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == len(records), "csv sink row count != jsonl line count"
    assert "loss" in rows[-1], "csv sink missing the loss column"
    span_stream = os.path.join(workdir, "trace_events.jsonl")
    with open(span_stream) as f:
        spans = [json.loads(l) for l in f if l.strip()]
    assert any(s["name"] == "host_decode" for s in spans), (
        "pipeline decode spans missing from the stream"
    )


def main() -> int:
    ap = argparse.ArgumentParser(description="telemetry driver smoke")
    ap.add_argument("--workdir", default=None, help="default: a fresh temp dir")
    args = ap.parse_args()
    workdir = args.workdir or tempfile.mkdtemp(prefix="obs_smoke_")
    os.makedirs(workdir, exist_ok=True)
    out = run_smoke(workdir)
    assert_obs_surface(workdir)
    print(f"obs smoke OK: {out['result']} — artifacts in {workdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
