"""Summarize a jax.profiler trace: per-op device time + roofline check.

    BENCH_TRACE_DIR=/tmp/trace python bench.py          # capture
    python scripts/analyze_trace.py /tmp/trace [--steps 20] \
        [--flops 8.18e12 --bytes 100e9 --peak-tflops 197 --hbm-gbs 819]

Reads the newest `*.trace.json.gz` under the directory (the Perfetto
JSON the profiler writes next to the xplane proto), aggregates X events
on the device track by fusion-name bucket, and — when the XLA
cost-analysis numbers are passed — prints the compute/HBM rooflines the
way PROFILE.md reports them. This is the exact analysis behind
PROFILE.md, packaged so the next profiling pass is one command.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys


def load_trace(path: str) -> dict:
    if os.path.isdir(path):
        hits = sorted(
            glob.glob(os.path.join(path, "**", "*.trace.json.gz"), recursive=True),
            key=os.path.getmtime,
        )
        if not hits:
            sys.exit(f"no *.trace.json.gz under {path}")
        path = hits[-1]
    print(f"# {path}")
    with gzip.open(path) as f:
        return json.load(f)


def device_pids(trace: dict) -> dict:
    names = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names[e["pid"]] = e["args"].get("name", "")
    return {pid: n for pid, n in names.items() if "TPU" in n or "GPU" in n}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", help="trace dir (or a .trace.json.gz file)")
    ap.add_argument("--steps", type=int, default=None,
                    help="steps captured, for ms/step (default: inferred from "
                    "the jit_* umbrella event count)")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--flops", type=float, default=None, help="per-step FLOPs (cost analysis)")
    ap.add_argument("--bytes", type=float, default=None, help="per-step bytes accessed")
    ap.add_argument("--peak-tflops", type=float, default=197.0, help="chip peak (v5e bf16 default)")
    ap.add_argument("--hbm-gbs", type=float, default=819.0, help="chip HBM GB/s (v5e default)")
    args = ap.parse_args()

    trace = load_trace(args.trace)
    devs = device_pids(trace)
    if not devs:
        sys.exit("no device track in trace (CPU-only capture?)")
    # aggregate ONE device track: SPMD devices run the same program, and
    # summing across pids would silently inflate every ms/step figure by
    # the device count
    pid = sorted(devs)[0]
    if len(devs) > 1:
        print(f"({len(devs)} device tracks; analyzing {devs[pid]})")

    umbrella = re.compile(r"^jit_\w+")
    buckets: collections.Counter = collections.Counter()
    counts: collections.Counter = collections.Counter()
    umbrella_total = 0.0
    umbrella_n = 0
    for e in trace["traceEvents"]:
        if e.get("ph") != "X" or e.get("pid") != pid or "dur" not in e:
            continue
        name = e.get("name", "?")
        if umbrella.match(name):
            umbrella_total += e["dur"]
            umbrella_n += 1
            continue
        if re.fullmatch(r"\d+", name):  # per-step marker rows
            continue
        b = re.sub(r"\.\d+$", "", name)
        buckets[b] += e["dur"]
        counts[b] += 1

    steps = args.steps or max(umbrella_n, 1)
    total = sum(buckets.values())
    print(f"device: {devs[pid]}")
    print(f"steps: {steps}   umbrella (jit_*) total: {umbrella_total / 1e3:.1f} ms "
          f"-> {umbrella_total / steps / 1e3:.2f} ms/step")
    print(f"attributed op time: {total / steps / 1e3:.2f} ms/step\n")
    print(f"{'ms/step':>9}  {'%':>5}  {'ops/step':>8}  bucket")
    for b, d in buckets.most_common(args.top):
        print(f"{d / steps / 1e3:9.3f}  {100 * d / total:5.1f}  {counts[b] / steps:8.1f}  {b[:70]}")

    if args.flops or args.bytes:
        print()
        step_ms = umbrella_total / steps / 1e3
        # no jit_* umbrella in this capture: print absolute rooflines only
        pct = (lambda ms: f" ({100 * ms / step_ms:.0f}% of step)") if step_ms else (lambda ms: "")
        if args.flops:
            c_ms = args.flops / (args.peak_tflops * 1e12) * 1e3
            print(f"compute roofline @{args.peak_tflops:.0f} TFLOPS: {c_ms:.1f} ms{pct(c_ms)}")
        if args.bytes:
            m_ms = args.bytes / (args.hbm_gbs * 1e9) * 1e3
            print(f"HBM roofline @{args.hbm_gbs:.0f} GB/s: {m_ms:.1f} ms{pct(m_ms)}")


if __name__ == "__main__":
    main()
