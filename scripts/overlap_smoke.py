#!/usr/bin/env python
"""Input-wire overlap smoke: the ISSUE-5 acceptance bullet, executable.

    python scripts/overlap_smoke.py [--workdir DIR]

Two parts, both asserted hard:

1. *Driver surface* — a 3-step fake-device training run with the device
   prefetch ring on (the default) must put `t_transfer`,
   `transfer_bytes`, and `prefetch_depth_live` on every training line,
   an `input.h2d` entry in the comms byte ledger (`comms/input.h2d`),
   `transfer` spans on the ring thread's trace track, and the whole
   metrics file must validate against the schema (`--strict`
   equivalent: any violation is fatal here).

2. *Overlap efficiency* — with a synthetic slow wire
   (`delay@site=input.h2d`) and slow decode (`delay@site=data.read`)
   injected through the deterministic fault hooks, the overlapped
   pipeline's wall-clock for N batches must be ≈ N·max(stage), not
   N·sum(stages): `overlap_efficiency = N·max(stage) / wall ≥ 0.9`.
   The serial path would score ~max/sum ≈ 0.6 on the same delays, so
   the bar discriminates overlap from turn-taking.

CI runs this in the tier-1 job (after the obs/fleet smokes) and uploads
the workdir. Wall cost: one tiny compile + 3 steps + ~2s of injected
delays.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# 8 virtual CPU devices, pinned BEFORE jax initializes (same trick as
# tests/conftest.py) — the ring must stage SHARDED batches over a real
# multi-device data axis, not a single-device degenerate.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# injected per-batch stage times for the efficiency leg: the wire is the
# deliberate bottleneck (overlapped wall/batch should approach WIRE_S)
DECODE_S = 0.06
WIRE_S = 0.10
EFFICIENCY_BAR = 0.9


def run_driver_smoke(workdir: str) -> dict:
    """3-step training run, ring on (default config)."""
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        TrainConfig,
    )

    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18",
            dim=16,
            num_negatives=32,
            temperature=0.2,
            mlp=True,
            shuffle="none",
            cifar_stem=True,
            compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=1, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=8, num_workers=2),
        workdir=workdir,
        log_every=1,
        obs_probe_every=0,  # no block_until_ready sampling: pure overlap
    )
    dataset = SyntheticDataset(num_examples=24, image_size=16)  # 3 steps of 8
    result = train(config, dataset=dataset)
    return {"workdir": workdir, "result": result}


def assert_wire_surface(workdir: str) -> None:
    from moco_tpu.obs import schema

    metrics_path = os.path.join(workdir, "metrics.jsonl")
    errors = schema.validate_file(metrics_path)
    assert not errors, f"schema violations: {errors}"
    records = schema.read_metrics(metrics_path)
    train_lines = [r for r in records if "loss" in r and "event" not in r]
    assert len(train_lines) == 3, f"expected 3 training lines, got {len(train_lines)}"
    for rec in train_lines:
        for key in ("t_transfer", "transfer_bytes", "prefetch_depth_live"):
            assert key in rec, f"training line {rec['step']} missing {key!r}"
        assert rec["t_transfer"] >= 0
        assert rec["transfer_bytes"] > 0
        assert 0 <= rec["prefetch_depth_live"]
        # the comms ledger carries the H2D wire next to the collectives
        assert rec.get("comms/input.h2d", 0) > 0, "no input.h2d comms entry"
    # transfer spans landed on the ring thread's own trace track
    span_stream = os.path.join(workdir, "trace_events.jsonl")
    with open(span_stream) as f:
        spans = [json.loads(l) for l in f if l.strip()]
    transfer = [s for s in spans if s.get("name") == "transfer"]
    assert transfer, "no transfer spans in the trace stream"
    step_tids = {s["tid"] for s in spans if s.get("name") == "step"}
    assert all(s["tid"] not in step_tids for s in transfer), (
        "transfer spans on the driver thread — the wire is not overlapped"
    )
    # live-depth counter series for Perfetto
    assert any("counter" in s for s in spans), "no prefetch depth counter events"


def measure_overlap_efficiency() -> float:
    """N batches through the ring with injected slow decode + slower
    wire; returns N*max(stage)/wall (1.0 = perfect overlap)."""
    import jax

    from moco_tpu.data.device_prefetch import H2D_SITE
    from moco_tpu.data.pipeline import TwoCropPipeline
    from moco_tpu.parallel import create_mesh
    from moco_tpu.utils import faults
    from moco_tpu.utils.config import DataConfig

    mesh = create_mesh()
    cfg = DataConfig(dataset="synthetic", image_size=8, global_batch=8, num_workers=2)
    pipe = TwoCropPipeline(cfg, mesh, seed=0)
    n = 10
    faults.install(
        f"delay@site=data.read:seconds={DECODE_S},"
        f"delay@site={H2D_SITE}:seconds={WIRE_S}"
    )
    try:
        it = pipe.epoch(0, device=True, depth=2)
        # first batch out excludes thread spin-up + augment compile
        jax.block_until_ready(next(it)["im_q"])
        t0 = time.perf_counter()
        for _ in range(n):
            jax.block_until_ready(next(it)["im_q"])
        wall = time.perf_counter() - t0
        it.close()
    finally:
        faults.clear()
    return n * max(DECODE_S, WIRE_S) / wall


def main() -> int:
    ap = argparse.ArgumentParser(description="input-wire overlap smoke")
    ap.add_argument("--workdir", default=None, help="default: a fresh temp dir")
    args = ap.parse_args()
    workdir = args.workdir or tempfile.mkdtemp(prefix="overlap_smoke_")
    os.makedirs(workdir, exist_ok=True)
    out = run_driver_smoke(workdir)
    assert_wire_surface(workdir)
    eff = measure_overlap_efficiency()
    print(f"overlap_efficiency={eff:.3f} (bar {EFFICIENCY_BAR})")
    assert eff >= EFFICIENCY_BAR, (
        f"overlap_efficiency {eff:.3f} < {EFFICIENCY_BAR}: the ring is "
        "serializing stages (wall ≈ sum, expected ≈ max)"
    )
    with open(os.path.join(workdir, "overlap_smoke.json"), "w") as f:
        json.dump(
            {"overlap_efficiency": round(eff, 3),
             "decode_s": DECODE_S, "wire_s": WIRE_S}, f,
        )
    print(f"overlap smoke OK: {out['result']} — artifacts in {workdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
