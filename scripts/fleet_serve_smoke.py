#!/usr/bin/env python
"""Serving-fleet chaos smoke: the router + supervisor story (ISSUE 16),
asserted hard.

    JAX_PLATFORMS=cpu python scripts/fleet_serve_smoke.py [--workdir DIR]

The story, executable:

1. a toy pretraining checkpoint is written (serve_smoke's maker) and a
   `ReplicaSupervisor` boots THREE `replica_main` processes from it,
   each binding its pre-claimed port only after AOT warmup;
2. per-replica chaos is planted through the supervisor's `extra_env`:
   replica 1 carries `kill@replica=1:at=5` (sudden `os._exit` mid-
   request on its 5th data POST) and replica 2 carries a PERMANENT
   `slow@site=serve.engine_execute:ms=2500` (every request there
   outlives the router's hedge delay — the deterministic tail);
3. a `FleetRouter` fronts the fleet and a mixed `/embed` +
   `/neighbors` burst fires from concurrent closed-loop clients —
   asserts ZERO failed client requests: the kill is absorbed by
   breaker + bounded retry (counted: `fleet_serve/retries`,
   `fleet_serve/breaker_trips`), the injected tail by hedging
   (counted: `fleet_serve/hedges`, `fleet_serve/hedge_wins` — first
   success wins), and every response's `replica` attribution matches
   its replica-minted `r<i>-` request id;
4. the supervisor's monitor respawns the corpse (exactly one `exit`
   event with rc=KILL_EXIT_CODE, reason "crash"), scrubs the kill rule
   from the reborn env, waits out the AOT re-warmup, re-plays the warm
   rows through `/ingest` (the reborn replica reports them in
   `serve/ingested_rows` — a WARM rejoin, not an empty index), and the
   router re-admits it into live rotation;
5. the drain leg: `POST /admin/drain?replica=0` under live traffic —
   dispatch stops, in-flight waits out, the supervisor restarts the
   replica gracefully (SIGTERM → batcher drain → respawn → re-warm),
   the router re-admits on healthy, and NOT ONE background request
   failed across the whole cycle;
6. the fanout-ingest leg: `scripts/serve_ingest.py`'s `--fanout` path
   discovers the topology from `/admin/replicas` and lands a fresh
   block on EVERY replica (per-replica `ingest.post.r<i>` retry
   sites);
7. the tracing leg (ISSUE 18): every burst response carries the
   router-minted `trace_id`, and the router's `/debug/flight` ring must
   hold a stitched multi-hop waterfall for 100% of them — with the
   critical-path hop sum (obs/critpath.py) within eps of the CLIENT-
   measured wall, and every 200's winning attempt joined to a real
   replica waterfall. After teardown `scripts/trace_merge.py` merges
   the router stream (pid 200) with every replica stream into
   `merged_fleet_trace.json` — flow arrows (`ph:"s"`/`ph:"f"`) must
   link router attempts to replica requests — and the offline
   `stitch_traces()` twin must reproduce stitched records from the
   on-disk artifacts alone;
8. the promotion leg (ISSUE 19): every replica declares a freshness
   objective and serves its model identity (step + params digest), so
   the router's `fleet_serve/model_skew` gauge is live. A SKEWED
   candidate checkpoint (a re-initialized encoder posing as step 1)
   must be REJECTED by the gate battery — the append-only
   `promotions.jsonl` ledger names the failing gate with its measured
   value vs floor — and a compatible candidate must clear the gates
   and roll out through `POST /admin/promote` one replica at a time
   under live traffic with ZERO dropped requests, the skew gauge
   visibly passing through >= 1 mid-rollout and landing back at 0 with
   every replica reporting the candidate's step and digest;
9. the freshness leg: an in-process replica with a 1s freshness
   objective ingests a block, then a `delay@site=ingest` fault stalls
   the next block inside the handler while the resident rows age past
   the objective — `serve/row_age_max_s` breaches,
   `serve/fresh_burn_rate_5s` climbs over the fast-burn threshold, and
   the `fresh_burn_fast` alert fires (flight dump attached), all on a
   schema-strict metrics stream;
10. final gates: `fleet_serve/burn_rate_60s` < 1.0 (the chaos never
    exhausted the client-observed error budget), the flushed
    `fleet_serve/*` metrics lines schema-strict (including the
    `fleet_serve/critpath_<hop>_ms` family), and mocolint clean on the
    fleet + promotion modules (JX011/JX012/JX013 — the threaded router
    must lint clean, not just run clean).

CI runs this in the tier-1 job; the router metrics stream, the merged
fleet trace, the router flight dump, the promotion ledger, the summary
JSON, and the supervisor event log upload as artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

NUM_REPLICAS = 3
KILLED_REPLICA = 1
SLOWED_REPLICA = 2
DRAINED_REPLICA = 0
BUCKETS = (1, 8)
REQUEST_SIZES = (1, 2, 4)
BURST_REQUESTS = 72
BURST_CLIENTS = 6
WARM_ROWS = 32
# per-replica batcher SLO (coalescing deadline) vs the router's client-
# observed SLO: same two-knob split as serve_smoke — the router bar is
# generous because its latency includes a replica flush AND (for the
# slowed replica) a full hedge delay before the fast twin answers.
SERVER_SLO_MS = float(os.environ.get("FLEET_SMOKE_SERVER_SLO_MS", 1000.0))
ROUTER_SLO_MS = float(os.environ.get("FLEET_SMOKE_ROUTER_SLO_MS", 4000.0))
# hedge floor: above the healthy replicas' worst latency (~one flush),
# well under the slowed replica's injected 2.5s stage — healthy traffic
# never hedges, slowed traffic always does
HEDGE_MIN_MS = float(os.environ.get("FLEET_SMOKE_HEDGE_MIN_MS", 1500.0))
SLOW_MS = 2500.0
KILL_AT = 5  # replica 1 dies handling its 5th data POST — mid-burst
RESPAWN_DEADLINE_S = 420.0
DRAIN_DEADLINE_S = 420.0
# freshness SLO declared fleet-wide: generous vs the smoke's own wall
# time so the MAIN fleet never burns it — the tight-objective burn
# story runs in the dedicated freshness leg instead
FRESH_MAX_AGE_S = 600.0
# promotion leg: probe batch for the gate battery, plus the collapse
# floor the UNTRAINED toy encoder actually clears (~0.08 measured —
# the 0.25 default calibrates to trained encoders; the floor is a
# deployment knob and the smoke's deployment is a random init)
PROMOTE_PROBES = 16
PROMOTE_FEATURE_STD_FLOOR = 0.05
# freshness leg: a 1s objective, and an ingest stall long enough that
# the resident rows age past it while the handler is stuck
STALL_FRESH_MAX_AGE_S = 1.0
STALL_DELAY_S = 2.5
STALL_DEADLINE_S = 60.0
# stitched hop-sum vs client wall: relative eps dominates at the smoke's
# realistic latencies; the absolute floor covers the fast path
TRACE_EPS_FRAC = 0.15
TRACE_EPS_FLOOR_MS = 25.0
STITCH_DEADLINE_S = 120.0  # hedge losers (the 2.5s lane) must land first


def _get(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _make_compatible_candidate(live_dir: str, out_dir: str, step: int = 1) -> None:
    """The live encoder nudged by a uniform 1e-3 weight scale, saved as
    a step-`step` checkpoint: the params digest changes (the rollout's
    landing signal needs a NEW digest to wait on) but the normalized
    embeddings barely move — the 'one more epoch' stand-in the gate
    battery must wave through."""
    import jax

    from moco_tpu.lincls import restore_pretrain_state
    from moco_tpu.utils.checkpoint import CheckpointManager
    from moco_tpu.utils.config import config_to_dict

    state, config = restore_pretrain_state(live_dir)
    nudge = lambda t: jax.tree_util.tree_map(lambda x: x * (1.0 + 1e-3), t)
    state = state.replace(
        params_q=nudge(state.params_q), params_k=nudge(state.params_k)
    )
    mgr = CheckpointManager(out_dir)
    mgr.save(
        step, state,
        extra={"epoch": 0, "config": config_to_dict(config), "num_data": 1},
        force=True,
    )
    mgr.close()


def _freshness_stall_leg(workdir: str) -> dict:
    """The freshness-SLO story at smoke scale, on a dedicated in-process
    replica (its own `workdir/freshness` stream — a tight 1s objective
    on the MAIN fleet would burn on wall time alone): ingest a block,
    watch it stay fresh, then stall the next `/ingest` inside the
    handler with `delay@site=ingest` while the resident rows age out —
    the fresh-burn gauge must breach and the `fresh_burn_fast` alert
    must fire."""
    import numpy as np

    from moco_tpu.obs import schema
    from moco_tpu.obs.alerts import read_alerts
    from moco_tpu.obs.sinks import JsonlSink
    from moco_tpu.obs.slo import DEFAULT_FAST_BURN
    from moco_tpu.serve.index import EmbeddingIndex
    from moco_tpu.serve.server import ServeServer
    from moco_tpu.utils import faults

    class _IngestOnlyEngine:
        """Engine-shaped stub: this leg exercises the ingest/freshness
        plane, never the embed path."""

        buckets = (1,)
        recompiles_after_warmup = 0
        num_features = 4
        image_size = 4

        def warmup(self):
            pass

        def embed(self, images, stages=None):
            emb = np.zeros((images.shape[0], 4), np.float32)
            return emb, [(images.shape[0], images.shape[0])]

    wd = os.path.join(workdir, "freshness")
    os.makedirs(wd, exist_ok=True)
    sink = JsonlSink(wd)
    server = ServeServer(
        _IngestOnlyEngine(),
        index=EmbeddingIndex(64, 4),
        port=0,
        sink=sink,
        metrics_flush_s=0.1,
        workdir=wd,
        fresh_max_age_s=STALL_FRESH_MAX_AGE_S,
        burn_windows=(5, 60),
    )
    stall_base = f"http://127.0.0.1:{server.port}"

    def _ingest(block, step: int) -> None:
        req = urllib.request.Request(
            stall_base + "/ingest",
            data=block.astype(np.float32).tobytes(),
            headers={
                "X-Rows-Shape": ",".join(map(str, block.shape)),
                "X-Ckpt-Step": str(step),
            },
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            r.read()

    try:
        rng = np.random.default_rng(7)
        _ingest(rng.standard_normal((8, 4)), 0)
        time.sleep(0.4)  # a few fresh observations land
        st = _get(stall_base + "/stats")
        assert st["serve/fresh_max_age_s"] == STALL_FRESH_MAX_AGE_S, st
        assert (st.get("serve/fresh_burn_rate_5s") or 0.0) == 0.0, (
            f"freshness burned before the stall: {st}"
        )
        # the stall: the NEXT block sticks in the handler while the
        # resident rows age past the declared objective
        faults.install(f"delay@site=ingest:seconds={STALL_DELAY_S}")
        try:
            _ingest(rng.standard_normal((8, 4)), 1)
        finally:
            faults.clear()
        deadline = time.monotonic() + STALL_DEADLINE_S
        burn, fired = None, []
        while time.monotonic() < deadline:
            st = _get(stall_base + "/stats")
            burn = st.get("serve/fresh_burn_rate_5s")
            fired = [
                a for a in read_alerts(os.path.join(wd, "alerts.jsonl"))
                if a["rule"] == "fresh_burn_fast"
            ]
            if burn is not None and burn > DEFAULT_FAST_BURN and fired:
                break
            time.sleep(0.1)
        assert burn is not None and burn > DEFAULT_FAST_BURN, (
            f"the ingest stall never breached the fresh burn gauge: {burn}"
        )
        assert fired, "fresh_burn_fast never fired despite the breach"
        st = _get(stall_base + "/stats")
        assert st["serve/row_age_max_s"] > STALL_FRESH_MAX_AGE_S, st
        assert st["serve/ingest_ckpt_step"] == 1, st
    finally:
        server.close()
        sink.close()
    problems = schema.validate_file(os.path.join(wd, "metrics.jsonl"))
    assert not problems, f"freshness leg schema violations: {problems[:5]}"
    return {
        "fresh_burn_rate_5s": burn,
        "fresh_alerts": len(fired),
        "row_age_max_s": st["serve/row_age_max_s"],
    }


def run_smoke(workdir: str, contract_coverage: bool = False) -> dict:
    import numpy as np

    import serve_smoke
    from moco_tpu.analysis import contracts as contract_cov
    from moco_tpu.obs import critpath, schema
    from moco_tpu.obs.sinks import JsonlSink
    from moco_tpu.serve.fleet import ReplicaSupervisor
    from moco_tpu.serve.router import FleetRouter
    from moco_tpu.utils import contracts as decl
    from moco_tpu.utils.faults import KILL_EXIT_CODE

    ckpt_dir = os.path.join(workdir, "toy_ckpt")
    serve_smoke.make_toy_checkpoint(ckpt_dir)
    rng = np.random.default_rng(0)
    warm_rows = rng.standard_normal((WARM_ROWS, 16)).astype(np.float32)

    recorder = None
    if contract_coverage:
        # plant the env var BEFORE the supervisor spawns: replicas
        # inherit it, install their own recorder, and dump
        # replica<i>/contract_coverage.json on graceful exit; this
        # (router) process records its own routes/validators directly
        os.environ["MOCO_CONTRACT_COVERAGE"] = "1"
        recorder = contract_cov.install_recorder()

    sup = ReplicaSupervisor(
        NUM_REPLICAS,
        ckpt_dir=ckpt_dir,
        workdir=workdir,
        buckets=BUCKETS,
        slo_ms=SERVER_SLO_MS,
        extra_env={
            KILLED_REPLICA: {"MOCO_FAULTS": f"kill@replica={KILLED_REPLICA}:at={KILL_AT}"},
            SLOWED_REPLICA: {
                "MOCO_FAULTS": f"slow@site=serve.engine_execute:ms={SLOW_MS:.0f}"
            },
        },
        warm_rows_fn=lambda: warm_rows,
        boot_timeout_s=RESPAWN_DEADLINE_S,
        monitor_interval_s=0.25,
        restart_backoff_s=0.5,
        # every replica declares the freshness objective: the fresh-burn
        # gauge family + row-age gauges go live on every /stats
        fresh_max_age_s=FRESH_MAX_AGE_S,
    )
    print(f"booting {NUM_REPLICAS} replicas (AOT warmup each)...", flush=True)
    t_boot = time.monotonic()
    sup.start()
    print(f"fleet healthy in {time.monotonic() - t_boot:.1f}s: {sup.urls()}", flush=True)

    sink = JsonlSink(workdir)
    router = FleetRouter(
        supervisor=sup,
        slo_ms=ROUTER_SLO_MS,
        slo_objective=0.9,
        sink=sink,
        metrics_flush_s=0.5,
        health_interval_s=0.25,
        retry_attempts=4,
        retry_base_delay_s=0.25,
        hedge_min_ms=HEDGE_MIN_MS,
        max_inflight=32,
        # one connection-reset is a trip: the smoke wants the breaker
        # OBSERVABLY in the story (fleet_serve/breaker_trips >= 1), and
        # a killed replica fails hard anyway
        breaker_fail_threshold=1,
        breaker_cooldown_s=1.0,
        drain_timeout_s=60.0,
        readmit_timeout_s=DRAIN_DEADLINE_S,
        # distributed tracing: per-router Perfetto stream + clock anchor
        # land next to the replicas' streams for the offline merge
        workdir=workdir,
    )
    base = f"http://127.0.0.1:{router.port}"
    canned = {
        n: rng.integers(0, 255, (n, serve_smoke.IMAGE_SIZE, serve_smoke.IMAGE_SIZE, 3),
                        np.uint8)
        for n in REQUEST_SIZES
    }
    failures: list[str] = []
    replicas_seen: set = set()
    traced: dict = {}  # trace_id -> client-measured wall ms (burst only)
    lock = threading.Lock()

    def post(path: str, imgs, record_trace: bool = False) -> dict:
        req = urllib.request.Request(
            base + path,
            data=imgs.tobytes(),
            headers={"X-Image-Shape": ",".join(map(str, imgs.shape))},
        )
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        if record_trace and isinstance(out, dict) and out.get("trace_id"):
            with lock:
                traced[out["trace_id"]] = (time.perf_counter() - t0) * 1e3
        return out

    def check_response(out: dict, n: int) -> None:
        emb = np.asarray(out["embedding"], np.float32)
        if emb.shape[0] != n:
            raise ValueError(f"expected {n} rows, got {emb.shape}")
        # replica attribution: the router's blame matches the replica-
        # scoped request id the replica itself minted
        rid, rep = out["request_id"], out["replica"]
        if not rid.startswith(f"r{rep}-"):
            raise ValueError(f"attribution mismatch: id {rid} vs replica {rep}")
        with lock:
            replicas_seen.add(rep)

    def client(ci: int, num: int) -> None:
        crng = np.random.default_rng(1000 + ci)
        for j in range(num):
            n = int(crng.choice(REQUEST_SIZES))
            path = "/neighbors?k=3" if (ci + j) % 2 == 0 else "/embed"
            try:
                check_response(post(path, canned[n], record_trace=True), n)
            except Exception as e:
                with lock:
                    failures.append(f"client {ci} req {j}: {e!r}")
                return

    summary: dict = {"workdir": workdir}
    try:
        # -- the chaos burst: kill@replica fires mid-burst -----------------
        print(f"burst: {BURST_REQUESTS} requests from {BURST_CLIENTS} clients "
              f"(kill@replica={KILLED_REPLICA}:at={KILL_AT} armed, replica "
              f"{SLOWED_REPLICA} permanently slowed {SLOW_MS:.0f}ms)", flush=True)
        t0 = time.monotonic()
        threads = [
            threading.Thread(target=client, args=(ci, BURST_REQUESTS // BURST_CLIENTS))
            for ci in range(BURST_CLIENTS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        burst_s = time.monotonic() - t0
        assert not failures, f"{len(failures)} requests failed: {failures[:5]}"
        print(f"burst clean in {burst_s:.1f}s; replicas seen: {sorted(replicas_seen)}",
              flush=True)

        # -- 100% stitched traces, hop sums within eps of client walls -----
        assert len(traced) == BURST_REQUESTS, (
            f"only {len(traced)}/{BURST_REQUESTS} responses carried a trace_id"
        )
        deadline = time.monotonic() + STITCH_DEADLINE_S
        flight_body: dict = {}
        flight_recs: dict = {}
        while time.monotonic() < deadline:
            # /debug/flight drains pending traces; held-back hedge
            # losers (the 2.5s slowed lane) land within their grace
            flight_body = _get(base + "/debug/flight", timeout=60)
            flight_recs = {
                r["trace_id"]: r
                for r in flight_body.get("requests", ())
                if r.get("trace_id")
            }
            if set(traced) <= set(flight_recs):
                break
            time.sleep(1.0)
        missing_traces = sorted(set(traced) - set(flight_recs))
        assert not missing_traces, (
            f"{len(missing_traces)}/{len(traced)} burst traces never "
            f"stitched into the flight ring: {missing_traces[:3]}"
        )
        hop_errs = []
        hedged_traces = retried_traces = 0
        for tid, wall_ms in traced.items():
            rec = flight_recs[tid]
            attr = critpath.attribute(rec)
            ssum = sum(attr["hops"].values())
            eps = max(TRACE_EPS_FRAC * wall_ms, TRACE_EPS_FLOOR_MS)
            if abs(ssum - wall_ms) > eps:
                hop_errs.append(
                    f"{tid}: hop sum {ssum:.1f}ms vs client wall "
                    f"{wall_ms:.1f}ms (eps {eps:.1f}ms)"
                )
            winner = next(
                (a for a in rec["attempts"] if a.get("winner")), None
            )
            if rec.get("status") == 200 and (
                winner is None or not winner.get("remote")
            ):
                hop_errs.append(
                    f"{tid}: 200 with no replica waterfall stitched in"
                )
            hedged_traces += 1 if attr["hedged"] else 0
            retried_traces += 1 if attr["retry_failed_ms"] else 0
        assert not hop_errs, (
            f"{len(hop_errs)} stitched traces failed the hop-sum/"
            f"stitching gate: {hop_errs[:5]}"
        )
        print(f"tracing: {len(traced)} burst traces 100% stitched "
              f"({hedged_traces} hedged, {retried_traces} with a failed "
              f"attempt on the critical path); hop sums within eps of "
              f"client walls", flush=True)
        summary["router_flight_dump"] = flight_body.get("dump_path")

        # -- the corpse respawns, scrubbed and WARM ------------------------
        deadline = time.monotonic() + RESPAWN_DEADLINE_S
        while time.monotonic() < deadline:
            kinds = [(e["kind"], e["replica"]) for e in sup.events()]
            if ("restart", KILLED_REPLICA) in kinds:
                break
            time.sleep(0.25)
        events = sup.events()
        crashes = [
            e for e in events
            if e["kind"] == "exit" and e["replica"] == KILLED_REPLICA
            and e.get("reason") == "crash"
        ]
        assert crashes, f"no crash event for replica {KILLED_REPLICA}: {events}"
        assert crashes[0]["rc"] == KILL_EXIT_CODE, crashes
        warms = [
            e for e in events if e["kind"] == "warm" and e["replica"] == KILLED_REPLICA
        ]
        assert warms and warms[0]["rows"] == WARM_ROWS, warms
        reborn = _get(sup.url(KILLED_REPLICA) + "/healthz")
        assert reborn["ok"] and reborn["warm"], reborn
        reborn_stats = _get(sup.url(KILLED_REPLICA) + "/stats")
        assert reborn_stats["serve/ingested_rows"] == WARM_ROWS, (
            f"reborn replica not warm: {reborn_stats.get('serve/ingested_rows')}"
        )
        print(f"replica {KILLED_REPLICA} respawned warm "
              f"(rc={crashes[0]['rc']}, {WARM_ROWS} rows replayed)", flush=True)
        # ...and the ROUTER re-admits it into live rotation
        deadline = time.monotonic() + 60.0
        readmitted = False
        while time.monotonic() < deadline and not readmitted:
            out = post("/embed", canned[1])
            readmitted = out["replica"] == KILLED_REPLICA
        assert readmitted, "reborn replica never took router traffic again"

        # -- drain/restart under live traffic: zero dropped ---------------
        stop = threading.Event()
        drain_failures: list[str] = []

        def background() -> None:
            while not stop.is_set():
                try:
                    check_response(post("/embed", canned[1]), 1)
                except Exception as e:
                    with lock:
                        drain_failures.append(repr(e))
                time.sleep(0.05)

        bg = [threading.Thread(target=background) for _ in range(2)]
        for t in bg:
            t.start()
        try:
            time.sleep(1.0)
            req = urllib.request.Request(
                base + f"/admin/drain?replica={DRAINED_REPLICA}", data=b""
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 202 and json.loads(r.read())["accepted"]
            deadline = time.monotonic() + DRAIN_DEADLINE_S
            snap = None
            while time.monotonic() < deadline:
                snap = next(
                    s for s in _get(base + "/admin/replicas")["replicas"]
                    if s["index"] == DRAINED_REPLICA
                )
                if not snap["draining"] and snap["healthy"]:
                    break
                time.sleep(0.5)
            assert snap and snap["healthy"] and not snap["draining"], (
                f"replica {DRAINED_REPLICA} never rejoined after drain: {snap}"
            )
        finally:
            stop.set()
            for t in bg:
                t.join(timeout=60)
        assert not drain_failures, (
            f"{len(drain_failures)} requests failed during the drain/restart "
            f"cycle: {drain_failures[:5]}"
        )
        graceful = [
            e for e in sup.events()
            if e["kind"] == "exit" and e["replica"] == DRAINED_REPLICA
            and e.get("reason") == "restart"
        ]
        assert graceful, "drain leg produced no graceful restart event"
        print(f"drain/restart of replica {DRAINED_REPLICA} clean under live traffic",
              flush=True)

        # -- fanout ingest: the block reaches EVERY replica ----------------
        import serve_ingest

        fresh = rng.standard_normal((10, 16)).astype(np.float32)
        # the rows' source checkpoint step rides the X-Ckpt-Step header:
        # every replica's serve/ingest_ckpt_step gauge picks it up
        results = serve_ingest.fanout_rows(base, fresh, ckpt_step=0)
        assert set(results) == set(range(NUM_REPLICAS)) and all(
            v is not None for v in results.values()
        ), f"fanout dropped a replica: {results}"
        provenance = _get(sup.url(0) + "/admin/model")
        assert provenance["ingest_ckpt_step"] == 0, (
            f"X-Ckpt-Step never reached the ingest gauge: {provenance}"
        )
        print(f"fanout ingest landed on all {NUM_REPLICAS} replicas: {results}",
              flush=True)

        # -- promotion: gate battery + audit ledger + staged rollout -------
        import serve_promote

        # heal the fleet first: the burst leg's slowed replica carries
        # its slow@ fault in the spawn env, so every request it serves
        # blows the replica SLO and pins its latency burn at the cap —
        # and the rollout soak (correctly) refuses to promote into a
        # burning fleet. Clear the fault env, cycle the replica clean,
        # and wait for every fleet burn gauge to settle under the
        # rollout ceiling before any candidate goes near traffic.
        sup.clear_extra_env(SLOWED_REPLICA)
        assert router.drain_replica(SLOWED_REPLICA), (
            "slowed replica was already draining at heal time"
        )
        deadline = time.monotonic() + RESPAWN_DEADLINE_S
        while time.monotonic() < deadline:
            snap = next(
                s for s in _get(base + "/admin/replicas")["replicas"]
                if s["index"] == SLOWED_REPLICA
            )
            if snap["healthy"] and not snap["draining"]:
                break
            time.sleep(0.25)
        assert snap["healthy"] and not snap["draining"], (
            f"slowed replica never re-admitted after heal: {snap}"
        )
        deadline = time.monotonic() + 75.0  # the slow burn window is 60s
        while time.monotonic() < deadline:
            burn = serve_promote.fleet_burn(base)
            if burn is None or burn <= 14.4:
                break
            time.sleep(1.0)
        assert burn is None or burn <= 14.4, (
            f"fleet burn never settled after healing the slowed replica: {burn}"
        )
        print(f"healed replica {SLOWED_REPLICA} (slow fault cleared, "
              f"fleet burn settled at {0.0 if burn is None else burn:.2f})",
              flush=True)

        from moco_tpu.serve.promote import PromotionLedger

        cand_bad = os.path.join(workdir, "cand_skewed")
        cand_good = os.path.join(workdir, "cand_good")
        print("promotion: building candidates (skewed re-init + compatible "
              "nudge, both posing as step 1)...", flush=True)
        # skewed: a different random init saved as "step 1" — embeds the
        # probes into an unrelated space, the compat gates must catch it
        serve_smoke.make_toy_checkpoint(cand_bad, seed=1, step=1)
        _make_compatible_candidate(ckpt_dir, cand_good, step=1)

        ledger_path = os.path.join(workdir, "promotions.jsonl")
        ledger = PromotionLedger(ledger_path)
        pargs = argparse.Namespace(
            candidate_dir=cand_bad, live_dir=ckpt_dir, router=base,
            probes=PROMOTE_PROBES, k=5,
            floor_cosine=0.90, floor_overlap=0.60,
            floor_feature_std=PROMOTE_FEATURE_STD_FLOOR,
            max_ema_drift=0.50, floor_live_recall=None,
            soak_s=1.0, swap_timeout_s=RESPAWN_DEADLINE_S,
            burn_ceiling=14.4, poll_s=0.5,
        )
        verdict = serve_promote.promote_once(pargs, ledger)
        assert verdict == "rejected", (
            f"the skewed candidate cleared the gate battery: {verdict}"
        )
        rejected = [
            r for r in ledger.read() if r["promotion/verdict"] == "rejected"
        ]
        assert rejected and rejected[-1]["promotion/failed_gate"] == "compat_cosine", (
            f"rejection did not name the compat gate: {rejected}"
        )
        # the evidence is IN the ledger line: measured value vs floor
        assert rejected[-1]["promotion/gate/compat_cosine"] < rejected[-1][
            "promotion/floor/compat_cosine"
        ], rejected[-1]
        # ...and a rejected candidate never touched traffic
        assert not _get(base + "/stats").get("fleet_serve/promotions"), (
            "a rejected candidate reached the fleet"
        )
        print("promotion: skewed candidate rejected at the "
              f"compat_cosine gate ({rejected[-1]['promotion/gate/compat_cosine']:.3f} "
              f"vs floor {rejected[-1]['promotion/floor/compat_cosine']})", flush=True)

        # the compatible candidate rolls out replica-by-replica under
        # live traffic: zero dropped requests, and the version-skew
        # gauge must pass through a mixed-fleet reading before settling
        stop = threading.Event()
        promo_failures: list[str] = []
        skew_seen: list = []

        def promo_background(ci: int) -> None:
            j = 0
            while not stop.is_set():
                path = "/neighbors?k=3" if (ci + j) % 2 == 0 else "/embed"
                j += 1
                try:
                    check_response(post(path, canned[1]), 1)
                except Exception as e:
                    with lock:
                        promo_failures.append(repr(e))
                time.sleep(0.05)

        def skew_watcher() -> None:
            while not stop.is_set():
                try:
                    s = _get(base + "/stats").get("fleet_serve/model_skew")
                except Exception:
                    s = None
                if s is not None:
                    with lock:
                        skew_seen.append(int(s))
                time.sleep(0.25)

        pargs.candidate_dir = cand_good
        promo_threads = [
            threading.Thread(target=promo_background, args=(ci,)) for ci in range(2)
        ] + [threading.Thread(target=skew_watcher)]
        for t in promo_threads:
            t.start()
        try:
            verdict = serve_promote.promote_once(pargs, ledger)
        finally:
            stop.set()
            for t in promo_threads:
                t.join(timeout=60)
        assert verdict == "promoted", (
            f"the compatible candidate did not promote: {verdict}"
        )
        assert not promo_failures, (
            f"{len(promo_failures)} requests dropped during the staged "
            f"rollout: {promo_failures[:5]}"
        )
        assert max(skew_seen, default=0) >= 1, (
            "the rollout never showed a mixed-version fleet on "
            "fleet_serve/model_skew"
        )
        promoted = [
            r for r in ledger.read() if r["promotion/verdict"] == "promoted"
        ]
        assert promoted and promoted[-1]["promotion/step"] == 1, promoted
        target_digest = promoted[-1]["promotion/digest"]
        # every replica now serves the candidate (step + digest), and
        # the router's skew gauge settles back to 0
        for i in range(NUM_REPLICAS):
            m = _get(sup.url(i) + "/admin/model")
            assert m["model_step"] == 1 and m["model_digest"] == target_digest, (
                f"replica {i} is not on the promoted encoder: {m}"
            )
        deadline = time.monotonic() + 60.0
        skew = None
        while time.monotonic() < deadline:
            skew = _get(base + "/stats").get("fleet_serve/model_skew")
            if skew == 0:
                break
            time.sleep(0.5)
        assert skew == 0, f"fleet_serve/model_skew stuck at {skew} post-rollout"
        stats = _get(base + "/stats")
        assert stats.get("fleet_serve/promotions") == NUM_REPLICAS, stats
        print(f"promotion: candidate {target_digest} promoted across "
              f"{NUM_REPLICAS} replicas (skew peaked at "
              f"{max(skew_seen)}, settled at 0, zero dropped requests)",
              flush=True)
        summary["promotion"] = {
            "ledger": ledger_path,
            "rejected_gate": rejected[-1]["promotion/failed_gate"],
            "promoted_digest": target_digest,
            "promoted_step": 1,
            "skew_peak": max(skew_seen),
        }

        # -- final gates ---------------------------------------------------
        stats = _get(base + "/stats")
        assert stats["fleet_serve/replicas_healthy"] == NUM_REPLICAS, stats
        assert stats["fleet_serve/failed"] == 0, stats
        assert stats["fleet_serve/shed"] == 0, stats
        assert stats["fleet_serve/breaker_trips"] >= 1, (
            "the kill never tripped a breaker"
        )
        assert stats["fleet_serve/retries"] >= 1, (
            "the kill never exercised the retry path"
        )
        assert stats["fleet_serve/hedges"] >= 1, (
            "the slowed replica never triggered a hedge"
        )
        assert stats["fleet_serve/hedge_wins"] >= 1, (
            "no hedge ever beat the slow primary"
        )
        burn = stats.get("fleet_serve/burn_rate_60s")
        assert burn is not None and burn < 1.0, (
            f"fleet_serve/burn_rate_60s={burn}: the chaos burned the whole "
            f"client-observed error budget"
        )
        summary.update({
            "burst_requests": BURST_REQUESTS,
            "burst_seconds": round(burst_s, 2),
            "failed_requests": 0,
            "replicas_seen": sorted(replicas_seen),
            "kill_exit_code": crashes[0]["rc"],
            "warm_rows_replayed": WARM_ROWS,
            "burn_rate_60s": burn,
            "breaker_trips": stats["fleet_serve/breaker_trips"],
            "retries": stats["fleet_serve/retries"],
            "hedges": stats["fleet_serve/hedges"],
            "hedge_wins": stats["fleet_serve/hedge_wins"],
            "drains": stats["fleet_serve/drains"],
            "requests_total": stats["fleet_serve/requests"],
        })

        if contract_coverage:
            # one-shot probes for the admin/debug routes the chaos story
            # itself never needs — the coverage gate below demands EVERY
            # declared route, not just the busy ones
            _get(base + "/healthz")
            _get(sup.url(0) + "/debug/flight")
            req = urllib.request.Request(
                base + f"/admin/undrain?replica={DRAINED_REPLICA}", data=b""
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                r.read()
            # HTTP drain of a replica directly (the supervisor's own
            # graceful path is SIGTERM): last thing before teardown
            req = urllib.request.Request(
                sup.url(SLOWED_REPLICA) + "/admin/drain", data=b""
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                r.read()
    finally:
        router.close()
        sup.close()
        sink.close()
        if contract_coverage:
            os.environ.pop("MOCO_CONTRACT_COVERAGE", None)
        with open(os.path.join(workdir, "supervisor_events.json"), "w") as f:
            json.dump(sup.events(), f, indent=2)

    # flushed fleet_serve/* lines must be schema-strict
    problems = schema.validate_file(os.path.join(workdir, "metrics.jsonl"))
    assert not problems, f"router metrics schema violations: {problems[:5]}"

    # -- offline merge: router + replica streams on one clock --------------
    # trace_merge must find the router track and link at least one
    # router/attempt -> replica request flow arrow; its offline stitcher
    # (heartbeat-anchored, no in-band echo) must reproduce waterfalls.
    # The killed replica's stream dies with it, so the offline gate is
    # "non-empty and consistent", while the in-band gate above is 100%.
    import trace_merge

    merged_path = os.path.join(workdir, "merged_fleet_trace.json")
    tm_summary = trace_merge.merge_traces(workdir, merged_path)
    assert 0 in tm_summary["routers"], (
        f"trace_merge never found the router stream: {tm_summary}"
    )
    assert tm_summary["flow_events"] >= 1, (
        "trace_merge linked no router attempt -> replica request flows"
    )
    offline = trace_merge.stitch_traces(workdir)
    assert offline, "offline stitcher reconstructed no traces"
    summary["merged_trace"] = merged_path
    summary["flow_pairs"] = tm_summary["flow_events"]
    summary["offline_stitched"] = len(offline)
    print(f"offline merge: {len(tm_summary['routers'])} router + "
          f"{len(tm_summary['serve_replicas'])} replica streams on one clock, "
          f"{tm_summary['flow_events']} flow arrows, "
          f"{len(offline)} traces re-stitched offline", flush=True)

    # -- freshness: an ingest stall must trip the fresh-burn alert ---------
    # (after the trace merge: this leg's own trace stream lives in a
    # subdir and must not enter the fleet's merged timeline)
    summary["freshness"] = _freshness_stall_leg(workdir)
    print(f"freshness: ingest stall tripped fresh_burn_fast "
          f"(burn {summary['freshness']['fresh_burn_rate_5s']:.1f}, "
          f"row age {summary['freshness']['row_age_max_s']:.1f}s)", flush=True)

    if recorder is not None:
        # validate each replica's serve/* stream too — with the recorder
        # still wired into obs/schema this doubles as validator coverage
        for i in range(NUM_REPLICAS):
            rp = os.path.join(workdir, f"replica{i}", "metrics.jsonl")
            if os.path.exists(rp):
                rproblems = schema.validate_file(rp)
                assert not rproblems, (
                    f"replica {i} metrics schema violations: {rproblems[:5]}"
                )
        snaps = [recorder.snapshot()]
        for i in range(NUM_REPLICAS):
            p = os.path.join(workdir, f"replica{i}", "contract_coverage.json")
            if os.path.exists(p):
                with open(p) as fh:
                    snaps.append(json.load(fh))
        contract_cov.uninstall_recorder()
        cov = contract_cov.merge_coverage(snaps)
        gate_routes = list(dict.fromkeys(
            contract_cov.declared_route_gates("replica")
            + contract_cov.declared_route_gates("router")
        ))
        gate_faults = [f"slow@{s}" for s in decl.SERVE_STAGE_SITES] + [
            "kill@replica",
            # the freshness leg's chaos lever: the /ingest stall hook
            "delay@ingest",
        ]
        gate_validators = (
            tuple(decl.SERVE_GATED_VALIDATORS)
            + tuple(decl.FLEET_GATED_VALIDATORS)
            # model identity / freshness gauges (every replica declares
            # the objective) + the promotion ledger's verdict fields
            + tuple(decl.QUALITY_GATED_VALIDATORS)
            + tuple(decl.PROMOTION_GATED_VALIDATORS)
        )
        missing = contract_cov.check_coverage(
            cov,
            routes=gate_routes,
            fault_sites=gate_faults,
            validators=gate_validators,
            headers=decl.TRACE_HEADERS,
        )
        with open(os.path.join(workdir, "contract_coverage.json"), "w") as f:
            json.dump({
                "coverage": cov,
                "gates": {
                    "routes": gate_routes,
                    "fault_sites": gate_faults,
                    "validators": list(gate_validators),
                    "headers": list(decl.TRACE_HEADERS),
                },
                "missing": missing,
            }, f, indent=2, sort_keys=True)
        assert not missing, (
            f"newly-dead contracts (registered but never fired): {missing}"
        )
        summary["contract_coverage"] = {
            "routes": len(cov["routes"]),
            "fault_hooks": len(cov["fault_hooks"]),
            "validators": len(cov["validators"]),
            "headers": len(cov.get("headers", {})),
            "missing": 0,
        }

    # the threaded fleet modules must LINT clean, not just run clean
    # (JX011 join discipline, JX012 shared-state, JX013 lock ordering)
    repo = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    lint = subprocess.run(
        [
            sys.executable, "-m", "moco_tpu.analysis",
            "moco_tpu/serve/router.py", "moco_tpu/serve/fleet.py",
            "moco_tpu/serve/replica_main.py", "moco_tpu/serve/batcher.py",
            "moco_tpu/serve/promote.py",
            "scripts/fleet_serve_smoke.py", "scripts/serve_promote.py",
            "--no-baseline",
        ],
        cwd=repo, capture_output=True, text=True,
    )
    assert lint.returncode == 0, (
        f"mocolint findings in the fleet modules:\n{lint.stdout}\n{lint.stderr}"
    )
    summary["mocolint_clean"] = True

    with open(os.path.join(workdir, "fleet_serve_smoke.json"), "w") as f:
        json.dump(summary, f, indent=2)
    return summary


def main() -> int:
    from moco_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()
    ap = argparse.ArgumentParser(description="serving-fleet router chaos smoke")
    ap.add_argument("--workdir", default=None)
    ap.add_argument(
        "--contract-coverage", action="store_true",
        help="mocolint v4 runtime arm: record which declared routes, "
        "fault sites, and schema validators actually fire (router + "
        "every replica process), merge into contract_coverage.json, and "
        "FAIL on any registered contract that never fired",
    )
    args = ap.parse_args()
    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_serve_smoke_")
    os.makedirs(workdir, exist_ok=True)
    summary = run_smoke(workdir, contract_coverage=args.contract_coverage)
    print("\n== fleet serve smoke PASS ==")
    for k, v in summary.items():
        print(f"  {k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
