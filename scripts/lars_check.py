"""Large-batch LARS path check (VERDICT r3 #7).

The `imagenet_v2_large_batch` preset (LARS, lr=4.8@4096, SURVEY §7 hard
part 5) had no run anywhere — a broken LARS integration would ship
silently. This gives the optimizer path one measured data point: the
synthetic learning-signal chain at 8× the ablation batch (512 over the
8-virtual-device mesh), LARS vs SGD at the same budget, same data,
same schedule shape. LARS lr follows the preset's square-root-free
linear scale (0.3 · batch/256, the LARS-for-contrastive convention its
lr=4.8@4096 encodes); SGD follows the reference's linear scaling rule
(0.03 · batch/64 from the ablation anchor, `main_moco.py:~L140`).

Pass criteria (written into REPORT.md):
  - LARS loss decreases and final kNN is within a few points of SGD's
    at the same budget (the path TRAINS — not an accuracy contest at
    toy scale), and
  - per-step time is reported for both (the trust-ratio per-layer
    norms are the only extra cost; on TPU they are tiny vector work).

Run:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/lars_check.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.utils.platform import enable_persistent_compilation_cache, pin_platform_from_env

pin_platform_from_env()
enable_persistent_compilation_cache()

OUT_DIR = "artifacts/lars"


def run_arm(optimizer: str, args) -> dict:
    import jax
    import numpy as np

    from moco_tpu.data.datasets import build_dataset
    from moco_tpu.train import train
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        ParallelConfig,
        TrainConfig,
    )

    n_dev = len(jax.devices())
    if optimizer == "lars":
        lr = 0.3 * args.batch / 256
        optim = OptimConfig(
            optimizer="lars", lr=lr, weight_decay=1e-6,
            epochs=args.epochs, cos=True, warmup_epochs=1,
        )
    else:
        lr = 0.03 * args.batch / 64
        optim = OptimConfig(
            lr=lr, epochs=args.epochs, cos=True, warmup_epochs=1
        )
    workdir = os.path.join(args.workdir, optimizer)
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=128, num_negatives=args.queue,
            momentum=0.99, temperature=0.2, mlp=True,
            shuffle="gather_perm", cifar_stem=True,
            compute_dtype="bfloat16" if jax.default_backend() == "tpu" else "float32",
        ),
        optim=optim,
        data=DataConfig(
            dataset="synthetic_learnable", image_size=32,
            global_batch=args.batch, aug_plus=True,
        ),
        parallel=ParallelConfig(num_data=n_dev),
        workdir=workdir,
        knn_every_epochs=args.knn_every,
        knn_k=20,
        log_every=1,
        seed=args.seed,
    )
    bank = build_dataset("synthetic_learnable", None, 32, train=True)
    bank.num_examples = args.examples
    test = build_dataset("synthetic_learnable", None, 32, train=False)
    test.num_examples = 512
    dataset = build_dataset("synthetic_learnable", None, 32, train=True)
    dataset.num_examples = args.examples

    final = train(config, dataset=dataset, knn_datasets=(bank, test))

    rows = []
    with open(os.path.join(workdir, "metrics.jsonl")) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    losses = [(r["step"], r["loss"]) for r in rows if "loss" in r]
    knns = [(r["epoch"], r["knn_top1"]) for r in rows if "knn_top1" in r]
    # wall-clock per step: the JSONL 'time' column is an absolute
    # timestamp per logged step (log_every=1 here), so per-step wall
    # time is the DIFF of consecutive stamps; drop the first epoch
    # (compile + warmup) before taking the median. kNN-eval rows share
    # the stream — only diff stamps of ADJACENT-in-stream step rows
    # (ones carrying 'loss'), so no diff absorbs an eval's wall time.
    stamped = [
        (i, r["time"]) for i, r in enumerate(rows)
        if "time" in r and "loss" in r
        and r.get("step", 0) > args.examples // args.batch
    ]
    times = [
        tb - ta for (ia, ta), (ib, tb) in zip(stamped, stamped[1:])
        if ib == ia + 1
    ]
    return {
        "optimizer": optimizer,
        "lr": lr,
        "global_batch": args.batch,
        "num_devices": n_dev,
        "epochs": args.epochs,
        "examples": args.examples,
        "queue": args.queue,
        "seed": args.seed,
        "backend": jax.default_backend(),
        "final_loss": final.get("loss"),
        "first_loss": losses[0][1] if losses else None,
        "median_step_s": float(np.median(times)) if times else None,
        "loss_trajectory": losses,
        "knn_trajectory": knns,
        "final_knn_top1": knns[-1][1] if knns else None,
    }


def render_section(results: list[dict]) -> str:
    r0 = results[0]
    lines = [
        "## Large-batch LARS path (one measured data point)",
        "",
        f"`scripts/lars_check.py`: {r0['backend']}, {r0['num_devices']} devices, "
        f"global batch {r0['global_batch']} (8× the ablation anchor), "
        f"`synthetic_learnable`, {r0['epochs']} epochs, seed {r0['seed']}; "
        "identical data/budget — only the optimizer differs.",
        "",
        "| optimizer | lr | first loss | final loss | kNN top-1 (final) | median step s |",
        "|---|---|---|---|---|---|",
    ]
    for r in results:
        knn = f"{r['final_knn_top1']:.2f}%" if r["final_knn_top1"] is not None else "n/a"
        st = f"{r['median_step_s']:.2f}" if r["median_step_s"] is not None else "n/a"
        lines.append(
            f"| `{r['optimizer']}` | {r['lr']:.3g} | {r['first_loss']:.3f} | "
            f"{r['final_loss']:.3f} | {knn} | {st} |"
        )
    lines += [
        "",
        "Pass criterion: the LARS arm's loss decreases and its kNN lands",
        "within a few points of SGD's at the same toy budget — evidence the",
        "`imagenet_v2_large_batch` preset's optimizer path trains, not an",
        "accuracy contest at this scale (kNN chance 12.5%).",
    ]
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arms", nargs="*", default=["sgd", "lars"], choices=("sgd", "lars"))
    ap.add_argument("--workdir", default="/tmp/moco_lars")
    ap.add_argument("--out", default=OUT_DIR)
    ap.add_argument("--examples", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--queue", type=int, default=2048)
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--knn-every", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--report", default="REPORT.md")
    args = ap.parse_args()

    os.makedirs(args.out, exist_ok=True)
    results = []
    for arm in args.arms:
        out_path = os.path.join(args.out, f"{arm}.json")
        if os.path.exists(out_path):
            print(f"[{arm}] done already ({out_path}); skipping")
            with open(out_path) as f:
                results.append(json.load(f))
            continue
        print(f"[{arm}] running...", flush=True)
        result = run_arm(arm, args)
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        results.append(result)
        print(f"[{arm}] final loss {result['final_loss']:.3f} "
              f"kNN {result['final_knn_top1']}")
    from moco_tpu.utils.report import replace_marker_block

    replace_marker_block(args.report, "lars-check", render_section(results))
    print(f"lars-check section written into {args.report}")


if __name__ == "__main__":
    main()
