"""End-to-end learning-signal run: pretrain → kNN → linear probe.

The reference's only QA mechanism is metric reproduction on ImageNet
(SURVEY.md §4); no dataset ships with this environment, so this script
produces the equivalent evidence on `LearnableSyntheticDataset` (class-
structured synthetic images, `moco_tpu/data/datasets.py`): contrastive
pretraining must drive the InfoNCE loss down, the (K+1)-way contrast
accuracy up, and frozen-feature kNN + linear-probe top-1 far above
chance — while a raw-pixel kNN stays weak. Writes `REPORT.md` at the
repo root plus the raw metrics JSONL files under --workdir.

Run (TPU or CPU):
    python scripts/learning_signal.py --epochs 30 --workdir /tmp/moco_signal
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.utils.platform import enable_persistent_compilation_cache, pin_platform_from_env

pin_platform_from_env()
enable_persistent_compilation_cache()

import jax
import numpy as np


DEFAULT_WORKDIR = "/tmp/moco_signal"
DEFAULT_REPORT = "REPORT.md"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default=DEFAULT_WORKDIR)
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--probe-epochs", type=int, default=15)
    ap.add_argument("--probe-lr", type=float, default=0.5)
    ap.add_argument("--examples", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--queue", type=int, default=4096)
    ap.add_argument("--report", default=DEFAULT_REPORT)
    # v3 mode (VERDICT r2 #3): the same chain on the queue-free
    # symmetric MoCo v3 recipe with a ViT backbone — the reference's
    # named successor (BASELINE.json vit_b16_v3; arXiv:2104.02057).
    # Writes a marker-delimited v3 section into REPORT.md instead of
    # replacing the main report.
    ap.add_argument("--v3", action="store_true")
    ap.add_argument("--arch", default=None, help="v3 backbone (default vit_tiny)")
    ap.add_argument("--lr", type=float, default=None)
    ap.add_argument("--dataset", default="synthetic_learnable",
                    choices=("synthetic_learnable", "synthetic_hard",
                             "synthetic_learnable32"))
    ap.add_argument("--bn-stats-rows", type=int, default=0,
                    help="subset-row BN statistics (accuracy arm of the "
                    "BN-bytes lever; 0 = full-batch stats)")
    ap.add_argument("--key-bn-eval", action="store_true",
                    help="EMAN-style key forward: eval-mode BN from EMA'd "
                    "running stats (accuracy arm of the key-stats-pass "
                    "lever; forces shuffle='none')")
    args = ap.parse_args()
    if args.v3 and args.key_bn_eval:
        ap.error("--key-bn-eval is a v2-step lever; not valid with --v3")
    if args.v3 and args.bn_stats_rows:
        # the v3 config never receives bn_stats_rows (ViT has no BN);
        # silently recording the lever as active would fake the arm
        ap.error("--bn-stats-rows is a ResNet BatchNorm lever; not valid with --v3")
    if args.v3 and args.workdir == DEFAULT_WORKDIR:
        # never share the baseline run's workdir: train() would auto-resume
        # the ResNet checkpoint into the ViT template and metrics.jsonl
        # (append-mode) would interleave both runs
        args.workdir = DEFAULT_WORKDIR + "_v3"

    from moco_tpu.data.datasets import LearnableSyntheticDataset
    from moco_tpu.knn import extract_features, knn_classify, knn_eval
    from moco_tpu.lincls import train_lincls
    from moco_tpu.train import train
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        ParallelConfig,
        ProbeConfig,
        TrainConfig,
    )

    on_tpu = jax.default_backend() == "tpu"
    n_dev = len(jax.devices())
    dtype = "bfloat16" if on_tpu else "float32"
    if args.v3:
        # queue-free symmetric v3: ViT + AdamW + EMA cosine ramp
        # (arXiv:2104.02057 recipe scaled to the synthetic task)
        moco = MocoConfig(
            arch=args.arch or "vit_tiny",
            dim=64,
            num_negatives=0,
            momentum=0.99,
            momentum_cos=True,
            temperature=0.2,
            v3=True,
            shuffle="none",
            vit_patch_size=4,  # 32px inputs -> 8x8 tokens
            compute_dtype=dtype,
        )
        optim = OptimConfig(
            optimizer="adamw",
            lr=args.lr if args.lr is not None else 1e-3,
            weight_decay=0.1,
            epochs=args.epochs,
            cos=True,
            warmup_epochs=2,
        )
    else:
        moco = MocoConfig(
            arch=args.arch or "resnet18",
            dim=128,
            num_negatives=args.queue,
            momentum=0.99,  # small dataset: faster EMA than ImageNet's 0.999
            temperature=0.2,
            mlp=True,
            shuffle="none" if args.key_bn_eval
            else "gather_perm" if n_dev > 1 else "none",
            cifar_stem=True,
            compute_dtype=dtype,
            key_bn_running_stats=args.key_bn_eval,
            # VERDICT r3 #2's accuracy arm: the BN-bytes perf lever
            # changes training semantics (stats + their gradients from
            # the first N rows only, models/resnet.py) — a win on step
            # time must show the RECIPE survives subset statistics
            bn_stats_rows=args.bn_stats_rows,
        )
        optim = OptimConfig(
            lr=args.lr if args.lr is not None else 0.06,
            epochs=args.epochs, cos=True, warmup_epochs=2,
        )
    config = TrainConfig(
        moco=moco,
        optim=optim,
        data=DataConfig(
            dataset=args.dataset,
            image_size=32,
            global_batch=args.batch,
            aug_plus=True,
        ),
        parallel=ParallelConfig(num_data=n_dev),
        workdir=args.workdir,
        knn_every_epochs=5,
        knn_k=64,
        seed=0,
    )

    from moco_tpu.data.datasets import HardSyntheticDataset

    if args.dataset == "synthetic_hard":
        num_classes = 32
        bank = HardSyntheticDataset(args.examples, 32, num_classes, train=True)
        test = HardSyntheticDataset(max(args.examples // 8, 512), 32, num_classes, train=False)
    elif args.dataset == "synthetic_learnable32":
        # round-3 redesign survivor: proven template structure, 32
        # classes, heavy per-instance noise (REPORT.md hard-signal
        # lesson v2) — the budget-binding claim's test article
        num_classes = 32
        mk = lambda n, train: LearnableSyntheticDataset(  # noqa: E731
            n, 32, num_classes, train=train, noise=0.5
        )
        bank = mk(args.examples, True)
        test = mk(max(args.examples // 8, 512), False)
    else:
        num_classes = 8
        bank = LearnableSyntheticDataset(args.examples, 32, num_classes, train=True)
        test = LearnableSyntheticDataset(max(args.examples // 8, 256), 32, num_classes, train=False)

    # ---- raw-pixel kNN baseline (what a trivial encoder would score) --
    def pixels(ds):
        X = np.stack([ds.load(i)[0] for i in range(len(ds))]).reshape(len(ds), -1)
        X = X.astype(np.float32) / 255.0
        X /= np.linalg.norm(X, axis=1, keepdims=True) + 1e-8
        y = np.asarray([ds.load(i)[1] for i in range(len(ds))], np.int32)
        return X, y

    bx, by = pixels(bank)
    tx_, ty = pixels(test)
    pixel_preds = knn_classify(bx, by, tx_, num_classes=num_classes, k=64)
    pixel_top1 = 100.0 * float((pixel_preds == ty).mean())
    print(f"raw-pixel kNN top-1: {pixel_top1:.2f}%")

    # ---- pretrain (with the periodic kNN monitor) ---------------------
    if args.dataset == "synthetic_learnable32":
        dataset = mk(args.examples, True)  # keep the noise=0.5 variant
    else:
        dataset = type(bank)(args.examples, 32, num_classes, train=True)
    final = train(config, dataset=dataset, knn_datasets=(bank, test))
    print("pretrain final:", final)

    # ---- linear probe -------------------------------------------------
    # probe lr scaled to this dataset size (the reference's lr=30 is an
    # ImageNet/1000-way setting); step-decay at 2/3 and 5/6 of the run
    probe = ProbeConfig(
        num_classes=num_classes,
        lr=args.probe_lr,
        epochs=args.probe_epochs,
        # distinct milestones even for tiny --probe-epochs: colliding
        # milestones would apply both 10x drops in one epoch
        schedule=(
            max(args.probe_epochs * 2 // 3, 1),
            max(args.probe_epochs * 5 // 6, args.probe_epochs * 2 // 3 + 1, 2),
        ),
    )
    probe_metrics = train_lincls(
        args.workdir,
        probe,
        data=config.data,
        train_dataset=bank,
        val_dataset=test,
    )
    print("probe:", probe_metrics)

    # ---- report -------------------------------------------------------
    summary = {
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "epochs": args.epochs,
        "examples": args.examples,
        "batch": args.batch,
        "queue": 0 if args.v3 else args.queue,
        "num_classes": num_classes,
        "dataset": args.dataset,
        "arch": config.moco.arch,
        "v3": args.v3,
        "bn_stats_rows": args.bn_stats_rows,
        "key_bn_running_stats": args.key_bn_eval,
        "pixel_top1": pixel_top1,
        "probe_metrics": probe_metrics,
        "final_knn": final.get("knn_top1"),
    }
    name = "signal_summary_v3.json" if args.v3 else "signal_summary.json"
    with open(os.path.join(args.workdir, name), "w") as f:
        json.dump(summary, f, indent=2)
    if args.v3:
        write_v3_section(args.workdir, args.report, summary)
    else:
        write_report(args.workdir, args.report, summary)


def _knn_rows(workdir: str) -> tuple[list, list, list]:
    metrics_path = os.path.join(workdir, "metrics.jsonl")
    rows = []
    if os.path.exists(metrics_path):
        with open(metrics_path) as f:
            rows = [json.loads(line) for line in f if line.strip()]
    losses = [(r["step"], r["loss"]) for r in rows if "loss" in r]
    accs = [(r["step"], r["acc1"]) for r in rows if "acc1" in r]
    knns = [(r.get("epoch"), r["knn_top1"]) for r in rows if "knn_top1" in r]
    return losses, accs, knns


def _final_knn(knns: list, summary: dict) -> float:
    """Last kNN-monitor value, falling back to the summary then nan
    (shared by both report writers so they can't disagree)."""
    if knns:
        return knns[-1][1]
    s = summary.get("final_knn")
    return s if s is not None else float("nan")


def write_v3_section(workdir: str, report_path: str, summary: dict) -> None:
    """v3 learning-signal section (marker-delimited) appended to
    REPORT.md — evidence for the queue-free symmetric recipe."""
    losses, accs, knns = _knn_rows(workdir)
    chance = 100.0 / summary["num_classes"]
    probe = summary["probe_metrics"]
    final_knn = _final_knn(knns, summary)
    lines = [
        "## MoCo v3 (queue-free symmetric, ViT) learning signal",
        "",
        f"`scripts/learning_signal.py --v3` on `{summary['device_kind']}`"
        f" ({summary['backend']}): `{summary['arch']}` (patch 4, 8x8 tokens),"
        f" AdamW + EMA cosine ramp, {summary['epochs']} epochs,"
        f" {summary['examples']} examples of `{summary['dataset']}`,"
        f" batch {summary['batch']} — the reference's successor recipe"
        " (BASELINE.json `vit_b16_v3`; arXiv:2104.02057) at CI scale.",
        "",
        "| Metric | Value | Reference point |",
        "|---|---|---|",
        f"| symmetric InfoNCE loss, last | {losses[-1][1]:.3f} | down from "
        f"{losses[0][1]:.3f} at step {losses[0][0]} |" if losses else None,
        f"| contrast acc@1, last | {accs[-1][1]:.2f}% | positives vs "
        "in-batch negatives |" if accs else None,
        f"| **kNN top-1 (frozen features)** | **{final_knn:.2f}%** | {chance:.1f}% chance |",
        f"| **linear-probe top-1** | **{probe['acc1']:.2f}%** | {chance:.1f}% chance |",
        f"| raw-pixel kNN top-1 (baseline) | {summary['pixel_top1']:.2f}% | {chance:.1f}% chance |",
        "",
        "kNN monitor trajectory:",
        "",
        "```",
        *[f"epoch {e:>3}: {v:6.2f}%" for e, v in knns],
        "```",
    ]
    from moco_tpu.utils.report import replace_marker_block

    replace_marker_block(report_path, "v3-signal", "\n".join(l for l in lines if l is not None))
    print(f"v3 section written into {report_path}")


def write_report(workdir: str, report_path: str, summary: dict) -> None:
    """Render REPORT.md from the run's metrics.jsonl + summary dict,
    preserving any marker-delimited sections other tools appended
    (ablation table, v3 signal)."""
    import math

    losses, accs, knns = _knn_rows(workdir)

    k = summary["queue"]
    chance = 100.0 / summary["num_classes"]
    contrast_chance = 100.0 / (1 + k)
    random_loss = math.log(1 + k)  # CE of uniform guessing over (K+1) ways
    probe_metrics = summary["probe_metrics"]
    final_knn = _final_knn(knns, summary)
    ds_name = summary.get("dataset", "synthetic_learnable")
    if ds_name == "synthetic_hard":
        ds_lines = [
            "Dataset: `HardSyntheticDataset` — 32 classes whose identity is",
            "a power-spectrum signature (mask-filtered white noise per",
            "instance, `moco_tpu/data/datasets.py`): raw-pixel kNN sits at",
            "chance by construction, so the full margin below is learned",
            "crop-invariant structure. The reference's de-facto test is",
            "metric reproduction on ImageNet (SURVEY.md §4); this is the same",
            "end-to-end chain at CI scale: MoCo v2 recipe (two-crop aug, EMA",
            "key encoder, queue, InfoNCE), then frozen-feature evals.",
        ]
    else:
        ds_lines = [
            "Dataset: `LearnableSyntheticDataset` — 8 classes of structured",
            "low-frequency color fields with per-instance warp/texture/noise",
            "(`moco_tpu/data/datasets.py`). The reference's de-facto test is",
            "metric reproduction on ImageNet (SURVEY.md §4); this is the same",
            "end-to-end chain at CI scale: MoCo v2 recipe (two-crop aug, EMA",
            "key encoder, queue, InfoNCE), then frozen-feature evals.",
        ]
    lines = [
        "# Learning-signal report (pretrain → kNN → linear probe)",
        "",
        f"Generated by `scripts/learning_signal.py` on `{summary['device_kind']}`"
        f" ({summary['backend']}), {summary['epochs']} pretrain epochs, "
        f"{summary['examples']} examples, batch {summary['batch']}, K={k}.",
        "",
        *ds_lines,
        "",
        "| Metric | Value | Reference point |",
        "|---|---|---|",
        f"| InfoNCE loss, last logged step | {losses[-1][1]:.3f} | "
        f"{random_loss:.3f} = ln(1+K), random guessing |"
        if losses
        else "| loss | n/a | |",
        f"| contrast acc@1, last | {accs[-1][1]:.2f}% | ~{contrast_chance:.3f}% chance "
        f"({accs[-1][1] / contrast_chance:.0f}x) |"
        if accs
        else "",
        f"| **kNN top-1 (frozen features)** | **{final_knn:.2f}%** | {chance:.1f}% chance |",
        f"| **linear-probe top-1** | **{probe_metrics['acc1']:.2f}%** | {chance:.1f}% chance |",
        f"| probe best top-1 | {probe_metrics['best_acc1']:.2f}% | {chance:.1f}% chance |",
        f"| raw-pixel kNN top-1 (baseline) | {summary['pixel_top1']:.2f}% | {chance:.1f}% chance |",
        "",
        "The InfoNCE loss/contrast-acc trajectory is NOT monotone by design:",
        "the queue starts full of random keys (trivial negatives, so early",
        "steps score near-perfect contrast acc), then fills with real",
        "encoded keys and the task hardens while the EMA encoder trails the",
        "online one. The monotone signal is the frozen-feature kNN monitor:",
        "",
        "```",
        *[f"epoch {e:>3}: {v:6.2f}%" for e, v in knns],
        "```",
        "",
        "Raw metrics: `metrics.jsonl` in the pretrain/probe workdirs;",
        "render inputs: `signal_summary.json`.",
    ]
    body = "\n".join(line for line in lines if line is not None) + "\n"
    # preserve marker-delimited sections other tools appended (the
    # ablation table, the v3 section) across regeneration
    from moco_tpu.utils.report import extract_marker_blocks

    kept = []
    if os.path.exists(report_path):
        with open(report_path) as f:
            kept = extract_marker_blocks(f.read())
    if kept:
        body = body.rstrip("\n") + "\n\n" + "\n\n".join(kept) + "\n"
    with open(report_path, "w") as f:
        f.write(body)
    print(f"wrote {report_path}")


if __name__ == "__main__":
    if "--report-only" in sys.argv:
        # re-render REPORT.md from a finished run's artifacts (no TPU use);
        # --v3 (or a workdir holding only a v3 summary) re-renders the
        # marker-delimited v3 section instead of the main body
        argv = [a for a in sys.argv[1:] if a != "--report-only"]
        ap = argparse.ArgumentParser()
        ap.add_argument("--workdir", default=None)
        ap.add_argument("--report", default=DEFAULT_REPORT)
        ap.add_argument("--v3", action="store_true")
        a, _ = ap.parse_known_args(argv)
        if a.workdir is None:
            a.workdir = DEFAULT_WORKDIR + "_v3" if a.v3 else DEFAULT_WORKDIR
        v3_path = os.path.join(a.workdir, "signal_summary_v3.json")
        if a.v3 or (not os.path.exists(os.path.join(a.workdir, "signal_summary.json"))
                    and os.path.exists(v3_path)):
            with open(v3_path) as f:
                write_v3_section(a.workdir, a.report, json.load(f))
        else:
            with open(os.path.join(a.workdir, "signal_summary.json")) as f:
                write_report(a.workdir, a.report, json.load(f))
    else:
        main()
