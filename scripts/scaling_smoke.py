#!/usr/bin/env python
"""Huge-batch scaling-law battery + layer-granular ZeRO-3 A/B smoke.

    python scripts/scaling_smoke.py [--workdir DIR]

(The script pins an 8-virtual-device CPU platform itself.)

Part A — the scaling-law battery (ISSUE 20 tentpole): short fake-8
trainings at kappa in {1, 2, 4} with `--auto-scale` deriving lr*kappa
and momentum^kappa from the kappa=1 reference recipe ("How to Scale
Your EMA", arXiv:2307.13813), plus a kappa=4 CONTROL that scales lr
linearly but leaves the EMA momentum at the reference value — the
naive recipe the battery exists to catch. The PR 3 health gauges
become pass/fail:

  ema_drift      the query->key EMA gap must stay scale-invariant:
                 each auto leg's final drift within DRIFT_RATIO_MAX of
                 the kappa=1 reference. Constant momentum at kappa=4
                 leaves the EMA averaging horizon unscaled while the
                 per-step parameter velocity quadruples, so the control
                 leg's drift gap roughly doubles (~1.9x measured on
                 this recipe vs <=1.0x for every auto leg) — measurably
                 over the band.
  logit gap      pos - neg logit margin positive (training trains)
  feature_std    collapse floor, normalized by sqrt(dim) (the
                 serve/promote.py gate convention)

The reference recipe pins a SHORT EMA horizon (momentum 0.5, ~2 steps)
so the drift gauge reaches its momentum-determined plateau inside the
8-step legs; with a production-style 0.99 the 8-step transient would
be momentum-blind and the discriminator toothless.

Part B — layer-granular ZeRO-3 A/B: zero23 whole-tree vs the
per-layer-group schedule on the same seed, with a
`delay@site=zero.gather` slow collective injected into the layer leg:

  * loss trajectory BITWISE identical across the two schedules (the
    injected delay only sleeps — values must not move);
  * analytic peak model bytes (shards + one live group pair) at least
    PEAK_DROP_MIN x below the whole-tree gather's;
  * `overlap/zero` >= OVERLAP_MIN: the one-group-ahead prefetch hides
    the slowed gather under step compute.

Every leg verdict is emitted through `obs.schema.validate_line` as a
`scaling/*` ledger line (scaling_battery.jsonl) — the
SCALING_GATED_VALIDATORS coverage gate in utils/contracts.py — and CI
uploads the ledger, per-leg metrics, and the summary as artifacts.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

EPOCHS = 1  # single epoch: one compile per leg — the smoke's budget lever
SPE = 8  # steps per epoch (pinned: every leg trains EPOCHS*SPE steps)
REF_BATCH = 16
KAPPAS = (1, 2, 4)
REF_LR = 0.02
REF_MOMENTUM = 0.5  # short EMA horizon — see the module docstring
DIM = 16
NUM_NEGATIVES = 256  # divisible by every leg's global batch

# Band calibrated on the deterministic fake-8 recipe below: the auto
# legs' final drift lands within [0.87, 1.0] of the kappa=1 reference
# while the constant-momentum control lands at ~1.9x — the band splits
# the gap with >=1.4x margin on both sides.
DRIFT_RATIO_MAX = 1.4  # auto legs stay under; the control must exceed
# Collapse sanity floor (x sqrt(dim)). On 8 steps of synthetic noise the
# features PARTIALLY collapse by construction (the smoke's healthy legs
# settle near 0.03-0.04, kappa=4 near 0.013), so this floor is
# calibrated to catch total collapse only; the production gate on real
# features is serve/promote.py's 0.25.
FEATURE_STD_FLOOR = 0.01
PEAK_DROP_MIN = 2.0
OVERLAP_MIN = 0.5
GATHER_DELAY_S = 0.05
AB_BATCH = 64
AB_MOMENTUM = 0.99  # the A/B legs are bitwise, not scale-law, science


def _config(
    workdir: str,
    batch: int,
    lr: float,
    momentum: float,
    auto_scale: str = "",
    zero: bool = False,
    layer: bool = False,
):
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        ParallelConfig,
        TrainConfig,
    )

    return TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=DIM, num_negatives=NUM_NEGATIVES,
            momentum=momentum, temperature=0.2, mlp=True, shuffle="none",
            cifar_stem=True, compute_dtype="float32",
        ),
        optim=OptimConfig(lr=lr, epochs=EPOCHS, cos=True),
        data=DataConfig(
            dataset="synthetic", image_size=16, global_batch=batch, num_workers=2
        ),
        parallel=ParallelConfig(
            num_data=8,
            shard_weight_update=zero,
            zero_stage=3 if zero else 1,
            zero_layer_granular=layer,
        ),
        workdir=workdir,
        log_every=1,
        steps_per_epoch=SPE,
        obs_probe_every=1,  # health gauges on every line — the battery's input
        auto_scale=auto_scale,
    )


def _run(config) -> dict:
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train

    return train(
        config,
        dataset=SyntheticDataset(
            num_examples=SPE * config.data.global_batch, image_size=16
        ),
    )


def _train_lines(workdir: str) -> list[dict]:
    from moco_tpu.obs import schema

    path = os.path.join(workdir, "metrics.jsonl")
    errors = schema.validate_file(path)
    assert not errors, f"schema violations in {path}: {errors[:5]}"
    records = schema.read_metrics(path)
    lines = [r for r in records if "loss" in r and "event" not in r]
    assert lines, f"no training lines in {path}"
    return lines


class Ledger:
    """scaling/* verdict lines, schema-validated at write time (the
    SCALING_GATED_VALIDATORS runtime-coverage contract)."""

    def __init__(self, path: str):
        self.path = path
        self.records: list[dict] = []

    def emit(self, leg: str, verdict: str, step: int, fields: dict) -> None:
        from moco_tpu.obs import schema

        rec = {
            "step": step,
            "time": time.time(),
            "scaling/leg": leg,
            "scaling/verdict": verdict,
        }
        rec.update({f"scaling/{k}": v for k, v in fields.items()})
        errors = schema.validate_line(rec)
        assert not errors, f"scaling ledger line fails schema: {errors}"
        self.records.append(rec)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, allow_nan=False) + "\n")


def evaluate_leg(gauges: dict, ref_drift: float) -> dict:
    """Pure battery verdict for one leg's final health gauges: the
    drift-ratio band vs the kappa=1 reference, the positive logit gap,
    and the collapse floor (tests/test_scaling.py exercises this
    directly)."""
    out = dict(gauges)
    out["drift_ratio"] = out["ema_drift"] / ref_drift
    checks = {
        "drift_ratio": out["drift_ratio"] < DRIFT_RATIO_MAX,
        "logit_gap": out["logit_gap"] > 0.0,
        "feature_std": out["feature_std_norm"] >= FEATURE_STD_FLOOR,
    }
    out["failed_checks"] = sorted(k for k, ok in checks.items() if not ok)
    out["verdict"] = "PASS" if not out["failed_checks"] else "FAIL"
    return out


def run_battery(base: str, ledger: Ledger) -> dict:
    """Part A: kappa sweep + constant-momentum control."""
    legs = {}
    # (name, batch, lr, momentum, auto_scale)
    specs = [
        (
            f"kappa{k}", REF_BATCH * k, REF_LR, REF_MOMENTUM,
            f"ref_batch={REF_BATCH}",
        )
        for k in KAPPAS
    ]
    # the naive recipe: linear lr scaling, momentum left at the
    # reference — what the battery must measurably reject
    specs.append(("kappa4_const", REF_BATCH * 4, REF_LR * 4, REF_MOMENTUM, ""))

    gauges = {}
    for name, batch, lr, momentum, auto in specs:
        wd = os.path.join(base, name)
        os.makedirs(wd, exist_ok=True)
        cfg = _config(wd, batch=batch, lr=lr, momentum=momentum, auto_scale=auto)
        result = _run(cfg)
        last = _train_lines(wd)[-1]
        for key in ("ema_drift", "logit_pos_mean", "logit_neg_mean", "feature_std"):
            assert last.get(key) is not None, f"{name}: no {key} on the last line"
        gauges[name] = {
            "batch": batch,
            "kappa": batch / REF_BATCH,
            "final_loss": result["loss"],
            "ema_drift": last["ema_drift"],
            "logit_gap": last["logit_pos_mean"] - last["logit_neg_mean"],
            "feature_std_norm": last["feature_std"] * math.sqrt(DIM),
            "step": last["step"],
        }

    ref_drift = gauges["kappa1"]["ema_drift"]
    assert ref_drift > 0, "kappa=1 reference logged zero EMA drift"
    for name, raw in gauges.items():
        g = evaluate_leg(raw, ref_drift)
        ledger.emit(
            name, g["verdict"], g["step"],
            {
                "kappa": g["kappa"],
                "drift": g["ema_drift"],
                "drift_ratio": g["drift_ratio"],
                "logit_gap": g["logit_gap"],
                "feature_std_norm": g["feature_std_norm"],
            },
        )
        legs[name] = g

    for k in KAPPAS:
        g = legs[f"kappa{k}"]
        assert g["verdict"] == "PASS", (
            f"auto-scale kappa={k} leg failed the battery on "
            f"{g['failed_checks']} (drift ratio {g['drift_ratio']:.2f})"
        )
    ctrl = legs["kappa4_const"]
    assert ctrl["verdict"] == "FAIL" and "drift_ratio" in ctrl["failed_checks"], (
        f"constant-momentum control PASSED the battery (drift ratio "
        f"{ctrl['drift_ratio']:.2f} < {DRIFT_RATIO_MAX}) — the "
        "discriminator has no teeth"
    )
    assert ctrl["drift_ratio"] >= DRIFT_RATIO_MAX, ctrl
    return legs


def run_zero_layer_ab(base: str, ledger: Ledger) -> dict:
    """Part B: zero23 whole-tree vs layer-granular, slow gather injected
    into the layer leg."""
    from moco_tpu.parallel.zero import AsyncParamGather
    from moco_tpu.utils import faults

    wd23 = os.path.join(base, "zero23")
    wdl = os.path.join(base, "zero_layer")
    os.makedirs(wd23, exist_ok=True)
    os.makedirs(wdl, exist_ok=True)
    _run(_config(wd23, batch=AB_BATCH, lr=REF_LR, momentum=AB_MOMENTUM, zero=True))
    faults.install(
        f"delay@site={AsyncParamGather.FAULT_SITE}:seconds={GATHER_DELAY_S}"
    )
    try:
        _run(
            _config(
                wdl, batch=AB_BATCH, lr=REF_LR, momentum=AB_MOMENTUM,
                zero=True, layer=True,
            )
        )
    finally:
        faults.clear()

    lines23 = _train_lines(wd23)
    linesl = _train_lines(wdl)
    losses23 = [r["loss"] for r in lines23]
    lossesl = [r["loss"] for r in linesl]
    assert losses23 == lossesl, (
        f"layer-granular loss trajectory diverged from zero23 under the "
        f"slow gather: {losses23} vs {lossesl}"
    )
    peak23 = lines23[-1]["hbm_model_peak_bytes"]
    peakl = linesl[-1]["hbm_model_peak_bytes"]
    assert peak23 and peakl, "analytic hbm_model_peak_bytes gauge missing"
    peak_ratio = peak23 / peakl
    assert peak_ratio >= PEAK_DROP_MIN, (
        f"layer-granular peak model bytes {peakl} only {peak_ratio:.2f}x "
        f"below whole-tree {peak23} (< {PEAK_DROP_MIN}x)"
    )
    # the layer leg mirrors the gauge under its own key too
    assert "overlap/zero_layer" in linesl[-1], "overlap/zero_layer not logged"
    overlaps = [
        r["overlap/zero"] for r in linesl if r.get("overlap/zero") is not None
    ]
    assert overlaps, "no overlap/zero samples on the layer leg"
    # steady-state hiding: the best sample, not the first (the initial
    # submit's gather runs before any step compute exists to hide it)
    overlap = max(overlaps)
    assert overlap >= OVERLAP_MIN, (
        f"one-group-ahead prefetch hid only {overlap:.2f} of the slowed "
        f"gather (< {OVERLAP_MIN})"
    )
    summary = {
        "losses": losses23,
        "peak_bytes_zero23": peak23,
        "peak_bytes_layer": peakl,
        "peak_ratio": peak_ratio,
        "overlap_zero": overlap,
        "verdict": "PASS",
    }
    ledger.emit(
        "zero_layer_ab", "PASS", linesl[-1]["step"],
        {
            "peak_ratio": peak_ratio,
            "overlap_zero": overlap,
            "loss_bitwise": 1,
        },
    )
    return summary


def main() -> int:
    ap = argparse.ArgumentParser(
        description="scaling-law battery + layer-granular ZeRO-3 smoke"
    )
    ap.add_argument("--workdir", default=None, help="default: a fresh temp dir")
    ap.add_argument(
        "--part", choices=("all", "battery", "zero-ab"), default="all",
        help="run one half only (CI can parallelize the two legs; the "
        "summary then carries just that part)",
    )
    ap.add_argument(
        "--contract-coverage", action="store_true",
        help="mocolint v4 runtime arm: record which schema validators and "
        "fault hooks actually fire, write contract_coverage.json, and "
        "FAIL if the scaling/* validators or the zero.gather delay hook "
        "never ran",
    )
    args = ap.parse_args()
    base = args.workdir or tempfile.mkdtemp(prefix="scaling_smoke_")
    os.makedirs(base, exist_ok=True)

    recorder = None
    if args.contract_coverage:
        from moco_tpu.analysis import contracts as contract_cov

        recorder = contract_cov.install_recorder()

    ledger = Ledger(os.path.join(base, "scaling_battery.jsonl"))
    battery = run_battery(base, ledger) if args.part in ("all", "battery") else None
    zero_ab = (
        run_zero_layer_ab(base, ledger) if args.part in ("all", "zero-ab") else None
    )

    summary = {
        "battery": battery,
        "zero_layer_ab": zero_ab,
        "bands": {
            "drift_ratio_max": DRIFT_RATIO_MAX,
            "feature_std_floor": FEATURE_STD_FLOOR,
            "peak_drop_min": PEAK_DROP_MIN,
            "overlap_min": OVERLAP_MIN,
        },
    }
    if recorder is not None:
        from moco_tpu.analysis import contracts as contract_cov
        from moco_tpu.parallel.zero import AsyncParamGather
        from moco_tpu.utils.contracts import SCALING_GATED_VALIDATORS

        cov = recorder.snapshot()
        contract_cov.uninstall_recorder()
        # the slow-gather hook only fires on the zero-ab leg
        gate_faults = (
            [f"delay@{AsyncParamGather.FAULT_SITE}"] if zero_ab is not None else []
        )
        missing = contract_cov.check_coverage(
            cov, fault_sites=gate_faults,
            validators=SCALING_GATED_VALIDATORS,
        )
        with open(os.path.join(base, "contract_coverage.json"), "w") as f:
            json.dump({
                "coverage": cov,
                "gates": {
                    "fault_sites": gate_faults,
                    "validators": list(SCALING_GATED_VALIDATORS),
                },
                "missing": missing,
            }, f, indent=2, sort_keys=True)
        assert not missing, (
            f"newly-dead contracts (registered but never fired): {missing}"
        )
        summary["contract_coverage"] = {
            "fault_hooks": len(cov["fault_hooks"]),
            "validators": len(cov["validators"]),
            "missing": 0,
        }
    with open(os.path.join(base, "scaling_smoke.json"), "w") as f:
        json.dump(summary, f, indent=2)
    parts = []
    if battery is not None:
        ctrl = battery["kappa4_const"]
        parts.append(
            "auto kappa legs "
            + ", ".join(
                f"{k}:{battery[f'kappa{k}']['drift_ratio']:.2f}x" for k in KAPPAS
            )
            + f" PASS; constant-momentum control {ctrl['drift_ratio']:.2f}x FAIL"
        )
    if zero_ab is not None:
        parts.append(
            f"layer-granular peak {zero_ab['peak_ratio']:.2f}x below zero23, "
            f"overlap {zero_ab['overlap_zero']:.2f}, losses bitwise"
        )
    print(f"scaling smoke OK: {'; '.join(parts)} — artifacts in {base}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
