#!/bin/bash
# VERDICT r3 #4: seed-variance for the shuffle-mode ablation. Seeds 1,2
# for gather_perm/a2a/syncbn at the EXACT r3 seed-0 budget (epochs 10,
# examples 1024, batch 64, K=2048) so the three seeds pool into one
# mean±range table. Sequential: host has one core. Report write goes to
# a throwaway file; the aggregate section is rendered by
# scripts/seed_variance_report.py afterwards.
set -u
cd "$(dirname "$0")/.."
for seed in 1 2; do
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python scripts/ablate_shuffle.py \
    --arms gather_perm a2a syncbn \
    --epochs 10 --examples 1024 --batch 64 --queue 2048 \
    --seed "$seed" \
    --workdir "/tmp/moco_ablate_seed$seed" \
    --out "artifacts/ablation_seeds/seed$seed" \
    --report "/tmp/seed_report_scratch.md" --marker "ablation-seeds-scratch" \
    >> artifacts/ablation_seeds/run.log 2>&1
done
echo done > artifacts/ablation_seeds/finished
