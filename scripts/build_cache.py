"""Prebuild the decode-once packed RGB cache (moco_tpu/data/cache.py).

    python scripts/build_cache.py --data-dir /data/imagenet \
        --cache-dir /ssd/moco_cache [--image-size 224] [--workers 16]

Training with `--cache-dir` builds the cache lazily on first use; on a
pod you usually want it built ONCE up front (per host, or on a shared
filesystem) instead of inside the first training step of every job.
Builds the train and val splits (one shared cache for a flat layout).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--cache-dir", required=True)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--workers", type=int, default=8)
    args = ap.parse_args()

    from moco_tpu.data.datasets import build_dataset

    for train in (True, False):
        ds = build_dataset(
            "imagefolder",
            args.data_dir,
            args.image_size,
            train=train,
            num_workers=args.workers,
            cache_dir=args.cache_dir,
        )
        split = "train" if train else "val"
        print(f"{split}: {len(ds)} images cached ({ds.num_classes} classes)")


if __name__ == "__main__":
    main()
