"""Prebuild the decode-once packed RGB cache (moco_tpu/data/cache.py).

    python scripts/build_cache.py --data-dir /data/imagenet \
        --cache-dir /ssd/moco_cache [--image-size 224] [--workers 16]

Training with `--cache-dir` builds the cache lazily on first use; on a
pod you usually want it built ONCE up front (per host, or on a shared
filesystem) instead of inside the first training step of every job.
Builds the train and val splits (one shared cache for a flat layout).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--cache-dir", required=True)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--workers", type=int, default=8)
    args = ap.parse_args()

    from moco_tpu.data.datasets import build_dataset

    # build only the splits that exist: a pretrain-only dataset (train/
    # without val/) must not die after the expensive train decode, and a
    # flat layout builds one shared cache via the train pass
    has_train = os.path.isdir(os.path.join(args.data_dir, "train"))
    has_val = os.path.isdir(os.path.join(args.data_dir, "val"))
    if has_train or has_val:
        passes = ([True] if has_train else []) + ([False] if has_val else [])
    else:
        passes = [True]  # flat: both splits share the "all" cache
    for train in passes:
        ds = build_dataset(
            "imagefolder",
            args.data_dir,
            args.image_size,
            train=train,
            num_workers=args.workers,
            cache_dir=args.cache_dir,
        )
        split = ("train" if train else "val") if (has_train or has_val) else "all"
        print(f"{split}: {len(ds)} images cached ({ds.num_classes} classes)")


if __name__ == "__main__":
    main()
