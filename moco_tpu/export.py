"""Checkpoint export for transfer evaluation.

The reference bridges pretraining → Detectron2 with
`detection/convert-pretrain-to-detectron2.py` (~35 LoC, SURVEY.md §2.2
row 11): load the `.pth.tar`, keep `module.encoder_q.` backbone keys
(drop fc/head), rename to Detectron2's ResNet naming, dump a pickle
`{"model": …, "__author__": "MOCO", "matching_heuristics": True}`.

Here the chain is: Orbax checkpoint → (1) a *torchvision-named* numpy
state dict — the universal interop format the rest of the GPU ecosystem
(timm, detectron2, mmdet converters) consumes — → (2) the same Detectron2
pickle the reference emits. Detection fine-tuning itself stays on
Detectron2/GPU, exactly as the reference's does (SURVEY.md §2.2's
native-dependency table scopes ROIAlign/NMS out of the TPU core).

Flax→torch weight-layout rules:
- conv kernels (H, W, Cin, Cout) → (Cout, Cin, H, W)
- dense kernels (Cin, Cout) → (Cout, Cin)
- BatchNorm: scale→weight, bias→bias, mean→running_mean, var→running_var
"""

from __future__ import annotations

import pickle
from typing import Any, Dict

import numpy as np


def _np(x) -> np.ndarray:
    return np.asarray(x, dtype=np.float32)


def _conv(kernel) -> np.ndarray:
    return _np(kernel).transpose(3, 2, 0, 1)


def _convbn(out: Dict[str, np.ndarray], params, stats, conv_name: str, bn_name: str) -> None:
    out[f"{conv_name}.weight"] = _conv(params["Conv_0"]["kernel"])
    bn_p, bn_s = params["BatchNorm_0"], stats["BatchNorm_0"]
    out[f"{bn_name}.weight"] = _np(bn_p["scale"])
    out[f"{bn_name}.bias"] = _np(bn_p["bias"])
    out[f"{bn_name}.running_mean"] = _np(bn_s["mean"])
    out[f"{bn_name}.running_var"] = _np(bn_s["var"])


def resnet_to_torchvision(
    backbone_params: Any, backbone_stats: Any, stage_sizes=(3, 4, 6, 3)
) -> Dict[str, np.ndarray]:
    """Flax ResNet (moco_tpu.models.resnet) → torchvision ResNet names.

    Works for both BasicBlock (2 ConvBNs + optional downsample) and
    Bottleneck (3 + optional downsample); block class is inferred from
    the parameter tree.
    """
    out: Dict[str, np.ndarray] = {}
    p, s = backbone_params, backbone_stats
    # stem (ImageNet stem: top-level Conv_0 + BatchNorm_0; CIFAR stem:
    # a ConvBN_0 submodule)
    if "Conv_0" in p:
        out["conv1.weight"] = _conv(p["Conv_0"]["kernel"])
        bn_p, bn_s = p["BatchNorm_0"], s["BatchNorm_0"]
        out["bn1.weight"] = _np(bn_p["scale"])
        out["bn1.bias"] = _np(bn_p["bias"])
        out["bn1.running_mean"] = _np(bn_s["mean"])
        out["bn1.running_var"] = _np(bn_s["var"])
    else:  # cifar stem
        _convbn(out, p["ConvBN_0"], s["ConvBN_0"], "conv1", "bn1")

    block_names = sorted(
        (k for k in p if k.startswith(("Bottleneck_", "BasicBlock_"))),
        key=lambda k: int(k.rsplit("_", 1)[1]),
    )
    idx = 0
    for stage, num_blocks in enumerate(stage_sizes):
        for j in range(num_blocks):
            name = block_names[idx]
            bp, bs = p[name], s[name]
            n_convbn = sum(1 for k in bp if k.startswith("ConvBN_"))
            is_bottleneck = name.startswith("Bottleneck_")
            n_main = 3 if is_bottleneck else 2
            prefix = f"layer{stage + 1}.{j}"
            for c in range(n_main):
                _convbn(
                    out, bp[f"ConvBN_{c}"], bs[f"ConvBN_{c}"],
                    f"{prefix}.conv{c + 1}", f"{prefix}.bn{c + 1}",
                )
            if n_convbn > n_main:  # downsample branch
                d = bp[f"ConvBN_{n_main}"]
                ds = bs[f"ConvBN_{n_main}"]
                out[f"{prefix}.downsample.0.weight"] = _conv(d["Conv_0"]["kernel"])
                out[f"{prefix}.downsample.1.weight"] = _np(d["BatchNorm_0"]["scale"])
                out[f"{prefix}.downsample.1.bias"] = _np(d["BatchNorm_0"]["bias"])
                out[f"{prefix}.downsample.1.running_mean"] = _np(ds["BatchNorm_0"]["mean"])
                out[f"{prefix}.downsample.1.running_var"] = _np(ds["BatchNorm_0"]["var"])
            idx += 1
    return out


def torchvision_to_detectron2(state: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """The reference converter's renaming
    (`detection/convert-pretrain-to-detectron2.py:~L10-30`):
    stem prefix for non-layer keys, layer{t}→res{t+1}, bn{t}→conv{t}.norm,
    downsample.0→shortcut, downsample.1→shortcut.norm."""
    out = {}
    for k, v in state.items():
        if "layer" not in k:
            k = "stem." + k
        for t in (1, 2, 3, 4):
            k = k.replace(f"layer{t}", f"res{t + 1}")
        for t in (1, 2, 3):
            k = k.replace(f"bn{t}", f"conv{t}.norm")
        k = k.replace("downsample.0", "shortcut")
        k = k.replace("downsample.1", "shortcut.norm")
        out[k] = v
    return out


def save_detectron2_pickle(state: Dict[str, np.ndarray], path: str) -> None:
    """Exactly the reference's output envelope (`~L30-35`)."""
    blob = {
        "model": torchvision_to_detectron2(state),
        "__author__": "MOCO",
        "matching_heuristics": True,
    }
    with open(path, "wb") as f:
        pickle.dump(blob, f)


def save_torch_state_dict(state: Dict[str, np.ndarray], path: str) -> None:
    """torch-loadable `.pth` of the torchvision-named backbone (fc absent —
    the linear probe / fine-tune attaches its own, as `main_lincls.py`
    does after its strict=False load)."""
    import torch

    torch.save({k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in state.items()}, path)


STAGE_SIZES = {
    "resnet18": (2, 2, 2, 2),
    "resnet34": (3, 4, 6, 3),
    "resnet50": (3, 4, 6, 3),
    "resnet101": (3, 4, 23, 3),
    "resnet152": (3, 8, 36, 3),
}


def vit_to_timm(
    backbone_params: Any, patch_size: int, image_size: int = 224
) -> Dict[str, np.ndarray]:
    """Flax ViT (moco_tpu.models.vit) → timm `vision_transformer` names,
    the GPU ecosystem's lingua franca for ViT weights (the upstream
    `moco-v3` repo ships `convert_to_deit.py` for the same purpose).

    Layout rules beyond the table in the module docstring:
    - attention q/k/v kernels (D, H, hd) fuse to timm's single
      `qkv.weight` (3D, D), rows ordered [q; k; v];
    - `attn.proj.weight` (D, D) from the out kernel (H, hd, D);
    - our position embedding is FIXED 2-D sin-cos (v3 paper choice), so
      timm's learnable `pos_embed` is exported as those values — loading
      with them frozen (or finetuning them) reproduces our forward.

    GELU caveat: flax's `nn.gelu` is the tanh approximation; timm's
    default act_layer is exact `nn.GELU`. For bit-level parity build the
    timm model with `act_layer=partial(nn.GELU, approximate='tanh')`;
    with the default the divergence is the usual tanh-vs-erf epsilon
    (harmless for finetuning, visible in feature-level comparisons).
    """
    from moco_tpu.models.vit import sincos_2d_posembed

    p = backbone_params
    out: Dict[str, np.ndarray] = {}
    kernel = _np(p["patch_embed"]["kernel"])  # (P, P, 3, D)
    dim = kernel.shape[-1]
    out["patch_embed.proj.weight"] = kernel.transpose(3, 2, 0, 1)
    out["patch_embed.proj.bias"] = _np(p["patch_embed"]["bias"])
    has_cls = "cls_token" in p  # gap-pooled backbones carry no cls token
    if has_cls:
        out["cls_token"] = _np(p["cls_token"])
    out["pos_embed"] = sincos_2d_posembed(
        dim, image_size // patch_size, cls_token=has_cls
    )

    blocks = sorted(
        (k for k in p if k.startswith("block_")), key=lambda k: int(k.split("_")[1])
    )
    for i, name in enumerate(blocks):
        b = p[name]
        pre = f"blocks.{i}"
        out[f"{pre}.norm1.weight"] = _np(b["LayerNorm_0"]["scale"])
        out[f"{pre}.norm1.bias"] = _np(b["LayerNorm_0"]["bias"])
        attn = b["MultiHeadDotProductAttention_0"]
        qkv_w = np.concatenate(
            [_np(attn[k]["kernel"]).reshape(dim, dim).T for k in ("query", "key", "value")]
        )  # (3D, D)
        qkv_b = np.concatenate(
            [_np(attn[k]["bias"]).reshape(dim) for k in ("query", "key", "value")]
        )
        out[f"{pre}.attn.qkv.weight"] = qkv_w
        out[f"{pre}.attn.qkv.bias"] = qkv_b
        out[f"{pre}.attn.proj.weight"] = _np(attn["out"]["kernel"]).reshape(dim, dim).T
        out[f"{pre}.attn.proj.bias"] = _np(attn["out"]["bias"])
        out[f"{pre}.norm2.weight"] = _np(b["LayerNorm_1"]["scale"])
        out[f"{pre}.norm2.bias"] = _np(b["LayerNorm_1"]["bias"])
        out[f"{pre}.mlp.fc1.weight"] = _np(b["MlpBlock_0"]["Dense_0"]["kernel"]).T
        out[f"{pre}.mlp.fc1.bias"] = _np(b["MlpBlock_0"]["Dense_0"]["bias"])
        out[f"{pre}.mlp.fc2.weight"] = _np(b["MlpBlock_0"]["Dense_1"]["kernel"]).T
        out[f"{pre}.mlp.fc2.bias"] = _np(b["MlpBlock_0"]["Dense_1"]["bias"])
    out["norm.weight"] = _np(p["final_norm"]["scale"])
    out["norm.bias"] = _np(p["final_norm"]["bias"])
    return out
