"""moco_tpu — a TPU-native momentum-contrast (MoCo) framework on JAX/XLA.

A ground-up re-design of the capabilities of the reference repo
(thudzj/moco, a fork of facebookresearch/moco): momentum-contrast
self-supervised pretraining (v1/v2 queue-based InfoNCE, v3 queue-free),
linear-probe evaluation, and detection-transfer export — built TPU-first:

- SPMD over a `jax.sharding.Mesh` (ICI/DCN) instead of one-process-per-GPU
  NCCL DDP (`main_moco.py:~L135-180` in the reference).
- Functional state (`params_q, params_k, queue, queue_ptr, opt_state`)
  threaded through a jitted `train_step`, replacing the reference's
  mutable `register_buffer` queue + in-place EMA (`moco/builder.py`).
- Deterministic same-seed permutation replaces the reference's
  broadcast-a-permutation Shuffle-BN (`moco/builder.py:~L79-126`).
- Batched on-device augmentation (crop/jitter/blur on the TPU) replaces
  the 32-worker PIL pipeline (`moco/loader.py`).
"""

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy top-level API (no jax import at package-import time).
    # NB: `train` is NOT aliased here — `moco_tpu.train` must always
    # mean the submodule.
    if name == "train_lincls":
        from moco_tpu.lincls import train_lincls

        return train_lincls
    if name == "knn_eval":
        from moco_tpu.knn import knn_eval

        return knn_eval
    raise AttributeError(f"module 'moco_tpu' has no attribute {name!r}")
