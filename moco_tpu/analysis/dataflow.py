"""Interprocedural dataflow summaries over the mocolint call graph.

One summary per analyzed function, computed to a fixpoint so chains of
helpers compose (`encode -> project -> einsum` three modules deep). A
summary answers, WITHOUT re-walking the callee at every call site:

- key-encoder taint (JX005): does the return value carry taint fed in
  through a parameter (`returns_taint_of`)? is the return intrinsically
  tainted (reads `params_k`/`batch_stats_k`/`queue` attributes itself)?
  does the function sanitize (route its result through `stop_gradient`
  or a known sanitizing helper)? which parameters reach a loss sink
  (matmul/einsum/cross_entropy) inside it unsanitized (`param_sinks`)?
- PRNG discipline (JX003): which rng-shaped parameters does the body
  actually CONSUME (pass to a sampler), as opposed to merely deriving
  children via `fold_in`/`split`-and-return — a pure derivation helper
  must not count as a use at its call sites;
- host-local values (JX008): does the return value depend on this
  process's identity or wall clock (`process_index`, `time.*`,
  `socket.gethostname`, `os.environ`, retry/decode counters)?
- collectives (JX008/JX010): which collectives does the function issue,
  directly or transitively, and through which axis expressions —
  including collectives whose axis is one of the function's OWN
  parameters, so a call site can bind the axis and the checker can
  compare it against the enclosing `shard_map` declaration.

The fixpoint is monotone over finite sets and bounded (`MAX_PASSES`),
so it terminates even on recursive call graphs; an unresolved call is
treated as the most permissive thing the rule can afford: it neither
taints nor sanitizes.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Optional

from moco_tpu.analysis.astutils import ModuleContext, walk_own
from moco_tpu.analysis.callgraph import FunctionInfo, Program

MAX_PASSES = 6

# -- key-encoder taint (JX005 vocabulary, shared with the rule) -----------
TAINT_ATTRS = {"params_k", "batch_stats_k", "queue"}
TAINT_PARAMS = {"params_k", "batch_stats_k", "queue"}
SANITIZER_NAMES = ("stop_gradient", "infonce_logits", "enqueue", "fused_infonce_loss")

# -- loss sinks ------------------------------------------------------------
SINK_EINSUM = "einsum"
SINK_MATMUL = "matmul"
SINK_XENT = "cross_entropy"

# -- PRNG vocabulary (JX003, shared) --------------------------------------
RNG_PARAM = re.compile(r"(^|_)rng(_|\d|$)|(^|_)prng(_|\d|$)|(^|_)key(_|\d|$)")
PRNG_DERIVE = {
    "jax.random.split",
    "jax.random.fold_in",
    "jax.random.clone",
    "jax.random.PRNGKey",
    "jax.random.key",
}

# -- host-local sources (JX008 vocabulary, shared with the rule) ----------
HOST_LOCAL_CALLS = (
    "process_index",  # jax.process_index and any *.process_index
    "getpid",
    "gethostname",
    "perf_counter",
    "monotonic",
    "time.time",
    "getenv",
)
HOST_LOCAL_PREFIXES = ("time.", "random.", "os.environ", "psutil.")
HOST_LOCAL_NAMES = re.compile(
    r"(^|_)(io_retries|decode_failures|heartbeat|retries|hostname|preempted)(_|$)"
)

# -- collectives (JX007/JX008/JX010 vocabulary) ---------------------------
COLLECTIVES_AXIS_ARG1 = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "psum_scatter",
    "all_to_all", "ppermute", "pshuffle",
}


def basename(qual: Optional[str]) -> str:
    return (qual or "").rsplit(".", 1)[-1]


def is_sanitizer_qual(qual: Optional[str]) -> bool:
    if not qual:
        return False
    return qual in SANITIZER_NAMES or qual.endswith(
        tuple("." + s for s in SANITIZER_NAMES)
    )


def is_host_local_qual(qual: Optional[str]) -> bool:
    if not qual:
        return False
    if any(qual == p.rstrip(".") or qual.startswith(p) for p in HOST_LOCAL_PREFIXES):
        return True
    base = basename(qual)
    for marker in HOST_LOCAL_CALLS:
        if "." in marker:
            if qual == marker or qual.endswith("." + marker):
                return True
        elif base == marker:
            return True
    return False


@dataclasses.dataclass
class CollectiveUse:
    """One collective call inside a function body. `axis_param` is set
    when the axis expression is (or contains only) one of the function's
    own parameters — the caller binds it; `axis_tokens` carries literal/
    constant tokens resolved in the defining module."""

    kind: str  # psum / all_gather / ...
    lineno: int
    axis_tokens: frozenset[str]
    axis_param: Optional[str] = None
    via: Optional[str] = None  # qualname of the callee that issues it, for
    # transitive uses surfaced at a call site


@dataclasses.dataclass
class Summary:
    """Interprocedural facts about one function (see module docstring)."""

    qualname: str
    # key taint
    returns_taint_of: set[str] = dataclasses.field(default_factory=set)
    returns_tainted: bool = False  # intrinsic (reads tainted attrs itself)
    sanitizes: bool = False
    param_sinks: dict[str, str] = dataclasses.field(default_factory=dict)
    # prng
    consumes_rng_params: set[str] = dataclasses.field(default_factory=set)
    derives_only_rng_params: set[str] = dataclasses.field(default_factory=set)
    # host-local
    returns_host_local: bool = False
    # collectives issued here or below
    collectives: list[CollectiveUse] = dataclasses.field(default_factory=list)

    def key(self) -> tuple:
        return (
            frozenset(self.returns_taint_of),
            self.returns_tainted,
            self.sanitizes,
            tuple(sorted(self.param_sinks.items())),
            frozenset(self.consumes_rng_params),
            frozenset(self.derives_only_rng_params),
            self.returns_host_local,
            len(self.collectives),
        )


def _axis_expr_of(ctx: ModuleContext, call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    base = basename(ctx.qual(call.func))
    if base in COLLECTIVES_AXIS_ARG1 and len(call.args) >= 2:
        return call.args[1]
    if base in ("axis_index", "axis_size") and call.args:
        return call.args[0]
    return None


def _axis_tokens(ctx: ModuleContext, expr: ast.AST) -> frozenset[str]:
    """String tokens an axis expression can denote: literals, module
    string constants, and constants IMPORTED from another analyzed
    module (`from parallel.mesh import DATA_AXIS` resolves to "data")."""
    tokens: set[str] = set()
    prog = getattr(ctx, "program", None)
    for n in ast.walk(expr):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            tokens.add(n.value)
        elif isinstance(n, ast.Name):
            if n.id in ctx.constants:
                tokens.add(ctx.constants[n.id])
            elif prog is not None and n.id in ctx.imports:
                origin = ctx.imports[n.id]
                mod, _, const = origin.rpartition(".")
                other = prog.by_module.get(mod)
                if other is not None and const in other.constants:
                    tokens.add(other.constants[const])
    return frozenset(tokens)


class SummaryTable:
    """qualname -> Summary, computed to a fixpoint over the call graph."""

    def __init__(self, program: Program):
        self.program = program
        self.summaries: dict[str, Summary] = {}
        for qual, info in program.functions.items():
            s = Summary(qualname=qual)
            s._param_names = info.param_names()  # type: ignore[attr-defined]
            self.summaries[qual] = s
        self._compute()

    def get(self, qual: Optional[str]) -> Optional[Summary]:
        if qual is None:
            return None
        return self.summaries.get(qual)

    def for_call(
        self, ctx: ModuleContext, call: ast.Call, enclosing: Optional[ast.FunctionDef]
    ) -> Optional[Summary]:
        info = self.program.resolve_call(ctx, call, enclosing=enclosing)
        return None if info is None else self.summaries.get(info.qualname)

    # -- fixpoint ---------------------------------------------------------

    def _compute(self) -> None:
        for _ in range(MAX_PASSES):
            changed = False
            for qual, info in self.program.functions.items():
                new = self._summarize(info)
                new._param_names = info.param_names()  # type: ignore[attr-defined]
                if new.key() != self.summaries[qual].key():
                    changed = True
                self.summaries[qual] = new
            if not changed:
                break

    # -- one function, using current callee summaries ---------------------

    def _summarize(self, info: FunctionInfo) -> Summary:
        fn, ctx = info.node, info.ctx
        s = Summary(qualname=info.qualname)
        params = set(info.param_names())
        rng_params = {p for p in params if RNG_PARAM.search(p)}

        # Data DEPENDENCE, not taint: name -> set of params it derives
        # from ("*" = derives from a tainted attribute read like
        # state.params_k). Every param seeds its own set — whether a
        # dependence is dangerous is the CALLER's call (it knows which
        # arguments were tainted); sanitization cuts the edge here.
        deps: dict[str, set[str]] = {p: {p} for p in params}
        host_names: set[str] = {
            p for p in params if HOST_LOCAL_NAMES.search(p)
        }

        def expr_deps(expr: ast.AST) -> set[str]:
            """Param origins an expression's value derives from; empty
            when the expression routes through a sanitizer."""
            if self._expr_sanitized(ctx, expr, info):
                return set()
            out: set[str] = set()
            for n in ast.walk(expr):
                if isinstance(n, ast.Name) and n.id in deps:
                    out |= deps[n.id]
                elif isinstance(n, ast.Attribute) and n.attr in TAINT_ATTRS:
                    out.add("*")
                elif isinstance(n, ast.Call):
                    cs = self.for_call(ctx, n, fn)
                    if cs is not None:
                        if cs.sanitizes:
                            continue
                        if cs.returns_tainted:
                            out.add("*")
                        names = self._callee_params(cs)
                        for i, arg in enumerate(n.args):
                            if i < len(names) and names[i] in cs.returns_taint_of:
                                out |= expr_deps(arg)
                        for kw in n.keywords:
                            if kw.arg in cs.returns_taint_of:
                                out |= expr_deps(kw.value)
            return out

        def expr_host_local(expr: ast.AST) -> bool:
            for n in ast.walk(expr):
                if isinstance(n, ast.Name) and (
                    n.id in host_names or HOST_LOCAL_NAMES.search(n.id)
                ):
                    return True
                if isinstance(n, ast.Attribute) and HOST_LOCAL_NAMES.search(n.attr):
                    return True
                if isinstance(n, ast.Call):
                    q = ctx.qual(n.func)
                    if is_host_local_qual(q):
                        return True
                    cs = self.for_call(ctx, n, fn)
                    if cs is not None and cs.returns_host_local:
                        return True
            return False

        rng_consumed: set[str] = set()
        rng_derived: set[str] = set()

        # SOURCE ORDER matters: a `queue = stop_gradient(queue)`
        # rebinding must be threaded before the einsum below it is
        # scanned (walk_own's stack order is arbitrary); position sort
        # approximates flow order at summary precision
        nodes = sorted(
            walk_own(fn),
            key=lambda n: (getattr(n, "lineno", 0), getattr(n, "col_offset", 0)),
        )
        for node in nodes:
            # -- assignments thread taint through locals ------------------
            if isinstance(node, ast.Assign) and node.value is not None:
                t = expr_deps(node.value)
                hl = expr_host_local(node.value)
                for tgt in node.targets:
                    names = (
                        [tgt] if isinstance(tgt, ast.Name)
                        else [e for e in getattr(tgt, "elts", []) if isinstance(e, ast.Name)]
                    )
                    for nm in names:
                        if t:
                            deps[nm.id] = set(t)
                        else:
                            deps.pop(nm.id, None)
                        if hl:
                            host_names.add(nm.id)
                        else:
                            host_names.discard(nm.id)
            # -- calls: prng use, collectives, sink hits ------------------
            if isinstance(node, ast.Call):
                q = ctx.qual(node.func)
                base = basename(q)
                # collectives issued directly
                if base in COLLECTIVES_AXIS_ARG1:
                    axis_expr = _axis_expr_of(ctx, node)
                    axis_param = None
                    tokens: frozenset[str] = frozenset()
                    if axis_expr is not None:
                        tokens = _axis_tokens(ctx, axis_expr)
                        if isinstance(axis_expr, ast.Name) and axis_expr.id in params:
                            axis_param = axis_expr.id
                    s.collectives.append(
                        CollectiveUse(
                            kind=base, lineno=node.lineno,
                            axis_tokens=tokens, axis_param=axis_param,
                        )
                    )
                # transitive collectives through resolved callees
                cs = self.for_call(ctx, node, fn)
                if cs is not None and cs.collectives:
                    names = self._callee_params(cs)
                    bound: dict[str, frozenset[str]] = {}
                    for i, arg in enumerate(node.args):
                        if i < len(names):
                            bound[names[i]] = _axis_tokens(ctx, arg)
                    for kw in node.keywords:
                        if kw.arg:
                            bound[kw.arg] = _axis_tokens(ctx, kw.value)
                    for use in cs.collectives:
                        tokens = use.axis_tokens
                        axis_param = None
                        if use.axis_param is not None:
                            if use.axis_param in bound:
                                tokens = bound[use.axis_param]
                            # the bound expr may itself be a param of OURS
                            for i, arg in enumerate(node.args):
                                if (
                                    i < len(names)
                                    and names[i] == use.axis_param
                                    and isinstance(arg, ast.Name)
                                    and arg.id in params
                                ):
                                    axis_param = arg.id
                            for kw in node.keywords:
                                if (
                                    kw.arg == use.axis_param
                                    and isinstance(kw.value, ast.Name)
                                    and kw.value.id in params
                                ):
                                    axis_param = kw.value.id
                        s.collectives.append(
                            CollectiveUse(
                                kind=use.kind, lineno=node.lineno,
                                axis_tokens=tokens, axis_param=axis_param,
                                via=cs.qualname,
                            )
                        )
                # prng: is a rng param consumed here?
                if rng_params:
                    is_derive = q in PRNG_DERIVE
                    callee_summary = cs
                    for arg in [*node.args, *[kw.value for kw in node.keywords]]:
                        if isinstance(arg, ast.Name) and arg.id in rng_params:
                            if is_derive:
                                rng_derived.add(arg.id)
                            elif callee_summary is not None:
                                # the callee's own summary decides
                                cnames = self._callee_params(callee_summary)
                                idx = node.args.index(arg) if arg in node.args else None
                                pname = (
                                    cnames[idx]
                                    if idx is not None and idx < len(cnames)
                                    else None
                                )
                                if (
                                    pname is not None
                                    and pname in callee_summary.derives_only_rng_params
                                ):
                                    rng_derived.add(arg.id)
                                else:
                                    rng_consumed.add(arg.id)
                            else:
                                rng_consumed.add(arg.id)
                # sinks: tainted operands reaching einsum/cross_entropy
                if base == SINK_EINSUM:
                    for arg in node.args[1:]:
                        for origin in expr_deps(arg):
                            if origin != "*" and origin in params:
                                s.param_sinks.setdefault(
                                    origin, f"einsum at line {node.lineno}"
                                )
                elif base == SINK_XENT:
                    for arg in node.args:
                        for origin in expr_deps(arg):
                            if origin != "*" and origin in params:
                                s.param_sinks.setdefault(
                                    origin, f"cross_entropy at line {node.lineno}"
                                )
            # NB: `@` matmuls are deliberately NOT recorded in
            # param_sinks — `x @ params["w"]` is every forward pass, and
            # flagging each `encode(params_k, ...)` call would bury the
            # real violations. The intra-function matmul sink in JX005
            # still covers direct products; interprocedurally only the
            # loss-shaped sinks (einsum / cross_entropy) count.
            # -- returns: what flows out ----------------------------------
            if isinstance(node, ast.Return) and node.value is not None:
                if self._expr_sanitized(ctx, node.value, info):
                    s.sanitizes = True
                else:
                    t = expr_deps(node.value)
                    if "*" in t:
                        s.returns_tainted = True
                    s.returns_taint_of |= {o for o in t if o in params}
                    if expr_host_local(node.value):
                        s.returns_host_local = True

        s.consumes_rng_params = rng_consumed
        s.derives_only_rng_params = rng_derived - rng_consumed
        # dedupe collectives (recursive graphs re-surface the same use
        # through `via` chains each fixpoint pass; cap keeps it bounded)
        seen: set[tuple] = set()
        unique: list[CollectiveUse] = []
        for use in s.collectives:
            k = (use.kind, use.lineno, use.axis_tokens, use.axis_param, use.via)
            if k not in seen:
                seen.add(k)
                unique.append(use)
        s.collectives = unique[:64]
        return s

    # -- helpers ----------------------------------------------------------

    @staticmethod
    def _callee_params(summary: Summary) -> list[str]:
        # stored on the summary's function info via the program
        return summary._param_names  # type: ignore[attr-defined]

    def _expr_sanitized(
        self, ctx: ModuleContext, expr: ast.AST, info: FunctionInfo
    ) -> bool:
        """Does the expression route through stop_gradient, a known
        sanitizing helper, or a resolved callee whose summary sanitizes?"""
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                q = ctx.qual(n.func)
                if is_sanitizer_qual(q):
                    return True
                cs = self.for_call(ctx, n, info.node)
                if cs is not None and cs.sanitizes:
                    return True
        return False


def build_summaries(program: Program) -> SummaryTable:
    """SummaryTable for a program, cached on it (`program.summaries`)."""
    cached = getattr(program, "summaries", None)
    if cached is None:
        cached = SummaryTable(program)
        program.summaries = cached  # type: ignore[attr-defined]
    return cached
