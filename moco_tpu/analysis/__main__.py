"""CLI: ``python -m moco_tpu.analysis [paths...]`` (a.k.a. mocolint).

Exit status 0 when every finding is suppressed (or none exist), 1 when
unsuppressed findings remain, 2 on usage errors — so CI can block on it
directly.
"""

from __future__ import annotations

import argparse
import sys

from moco_tpu.analysis.engine import (
    analyze_paths,
    iter_rules,
    render_json,
    render_text,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mocolint",
        description="JAX/TPU-aware static analysis for moco-tpu "
        "(impure jitted code, host transfers, PRNG reuse, recompile "
        "hazards, stop_gradient invariants, donation bugs, axis names)",
    )
    p.add_argument("paths", nargs="*", default=["moco_tpu"], help="files or directories")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("-o", "--output", default=None, help="write the report to a file")
    p.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--show-suppressed", action="store_true",
        help="include suppressed findings in text output",
    )
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule_id, summary in iter_rules():
            print(f"{rule_id}  {summary}")
        return 0
    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        known = {rid for rid, _ in iter_rules()}
        unknown = set(rules) - known
        if unknown:
            print(f"mocolint: unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
    findings = analyze_paths(args.paths, rules=rules)
    report = (
        render_json(findings)
        if args.format == "json"
        else render_text(findings, show_suppressed=args.show_suppressed)
    )
    if args.output:
        with open(args.output, "w", encoding="utf-8") as fh:
            fh.write(report + "\n")
    if args.format == "text" or not args.output:
        print(report)
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
