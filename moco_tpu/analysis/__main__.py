"""CLI: ``python -m moco_tpu.analysis [paths...]`` (a.k.a. mocolint).

Exit status 0 when every finding is suppressed or baselined (or none
exist), 1 when new findings remain, 2 on usage errors — so CI can block
on it directly.

Baseline workflow (incremental rule rollout)::

    # record today's findings (e.g. the lint fixtures under tests/)
    python -m moco_tpu.analysis moco_tpu/ scripts/ tests/ train.py --update-baseline
    # later runs auto-discover mocolint-baseline.json walking up from
    # the analyzed paths and fail only on findings NOT in it
    python -m moco_tpu.analysis moco_tpu/ scripts/ tests/ train.py
    # explicit control
    python -m moco_tpu.analysis tests/ --baseline mocolint-baseline.json
    python -m moco_tpu.analysis tests/ --no-baseline
"""

from __future__ import annotations

import argparse
import sys

from moco_tpu.analysis.astutils import ModuleContext
from moco_tpu.analysis.engine import (
    analyze_paths,
    discover_baseline,
    iter_rules,
    load_baseline,
    render_json,
    render_sarif,
    render_text,
    write_baseline,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mocolint",
        description="JAX/TPU-aware static analysis for moco-tpu "
        "(impure jitted code, host transfers, PRNG reuse, recompile "
        "hazards, stop_gradient invariants, donation bugs, axis names, "
        "SPMD divergence, mixed-precision hazards, sharding consistency, "
        "input-wire thread hygiene — interprocedural since v2)",
    )
    p.add_argument("paths", nargs="*", default=["moco_tpu"], help="files or directories")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("-o", "--output", default=None, help="write the report to a file")
    p.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--show-suppressed", action="store_true",
        help="include suppressed/baselined findings in text output",
    )
    p.add_argument("--list-rules", action="store_true")
    p.add_argument(
        "--sarif", default=None, metavar="FILE",
        help="also write a SARIF 2.1.0 report to FILE (for GitHub code "
        "scanning); the --format text/json report is unchanged",
    )
    p.add_argument(
        "--dump-contracts", default=None, metavar="FILE",
        help="also write the extracted cross-artifact contract registry "
        "(metric keys, HTTP routes, fault sites, ...) as JSON to FILE",
    )
    p.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="findings baseline to accept (default: auto-discover "
        "mocolint-baseline.json walking up from the analyzed paths)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline, including an auto-discovered one",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="(re)write the baseline file from this run's findings "
        "instead of failing on them",
    )
    p.add_argument(
        "--changed", metavar="GIT_REF", default=None,
        help="lint only files that differ from GIT_REF (plus untracked "
        "ones) inside the given paths — the fast CI pre-pass. NOTE: the "
        "interprocedural summaries then see only the changed subset, so "
        "the full baseline-gated run remains the gate; this one just "
        "fails earlier",
    )
    return p


def changed_files(ref: str, paths: list[str]) -> list[str]:
    """Python files under `paths` that differ from `ref` (per
    `git diff --name-only`, deletions excluded) or are untracked."""
    import subprocess

    from moco_tpu.analysis.engine import iter_python_files

    def _git(*args: str) -> list[str]:
        out = subprocess.run(
            ["git", *args], capture_output=True, text=True, check=True
        ).stdout
        return [l.strip() for l in out.splitlines() if l.strip()]

    top = _git("rev-parse", "--show-toplevel")[0]
    changed = set(
        _git("diff", "--name-only", "--diff-filter=d", ref, "--")
        + _git("ls-files", "--others", "--exclude-standard")
    )
    import os

    changed_abs = {os.path.normpath(os.path.join(top, c)) for c in changed}
    return [
        f
        for f in iter_python_files(paths)
        if os.path.normpath(os.path.abspath(f)) in changed_abs
    ]


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule_id, summary in iter_rules():
            print(f"{rule_id}  {summary}")
        return 0
    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        known = {rid for rid, _ in iter_rules()}
        unknown = set(rules) - known
        if unknown:
            print(f"mocolint: unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
    paths = args.paths
    if args.changed is not None:
        import subprocess

        try:
            paths = changed_files(args.changed, paths)
        except (subprocess.CalledProcessError, OSError, IndexError) as e:
            print(f"mocolint: cannot resolve --changed {args.changed!r}: {e}",
                  file=sys.stderr)
            return 2
        if not paths:
            print(f"mocolint: no python files changed vs {args.changed}")
            return 0
        print(
            f"mocolint: --changed {args.changed}: linting "
            f"{len(paths)} file(s)"
        )
    baseline_path = None
    if not args.no_baseline:
        baseline_path = args.baseline or discover_baseline(args.paths)
    if args.update_baseline:
        findings = analyze_paths(args.paths, rules=rules)
        from moco_tpu.analysis.engine import BASELINE_FILENAME

        target = args.baseline or baseline_path or BASELINE_FILENAME
        n = write_baseline(target, findings)
        print(f"mocolint: baseline written to {target} ({n} fingerprint(s))")
        return 0
    baseline = None
    if baseline_path is not None:
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print(f"mocolint: cannot read baseline {baseline_path}: {e}", file=sys.stderr)
            return 2
    findings = analyze_paths(paths, rules=rules, baseline=baseline)
    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as fh:
            fh.write(render_sarif(findings) + "\n")
    if args.dump_contracts:
        import json

        from moco_tpu.analysis import contracts as _contracts
        from moco_tpu.analysis.engine import iter_python_files, parse_module

        contexts = {}
        for path in iter_python_files(paths):
            with open(path, "r", encoding="utf-8") as fh:
                ctx = parse_module(fh.read(), path)
            if not isinstance(ctx, ModuleContext):
                continue  # syntax errors already reported as findings
            contexts[path] = ctx
        registry = _contracts.build_registry(contexts)
        with open(args.dump_contracts, "w", encoding="utf-8") as fh:
            json.dump(registry.to_json(), fh, indent=2, sort_keys=True)
            fh.write("\n")
    report = (
        render_json(findings)
        if args.format == "json"
        else render_text(findings, show_suppressed=args.show_suppressed)
    )
    if args.output:
        with open(args.output, "w", encoding="utf-8") as fh:
            fh.write(report + "\n")
    if args.format == "text" or not args.output:
        print(report)
    return 1 if any(f.active for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
