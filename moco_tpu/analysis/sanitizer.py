"""Runtime collective-schedule sanitizer — catch SPMD divergence BEFORE
the hang.

The static pass (JX008/JX010) catches divergence *patterns*; this is
the runtime arm for the ones it can't see (data-dependent retraces, a
host running a stale binary, a config that resolved differently on one
process). The failure mode it defends against is the worst one a pod
has: a collective mismatch does not error — every healthy host blocks
in its next collective waiting for the one host that took a different
path, forever, until the stall watchdog kills the job with nothing to
diagnose.

Mechanism (all out-of-band, nothing touches the step loop):

- every `comms.tag(site, kind, operand, ...)` call — the repo's
  existing collective site annotations — also records ``(site, kind,
  operand shape signature)`` into a process-local
  :class:`ScheduleRecorder` in FIRST-SEEN ORDER. Shapes and dtypes are
  static during tracing, so this is the process's *traced collective
  schedule*: exactly what must agree across hosts for the SPMD program
  to be one program. Recording is idempotent across retraces of the
  same schedule and costs a dict lookup; with no recorder installed the
  hook is a module-level None check (same zero-cost contract as
  `utils/faults.py`).
- on log steps the driver's :class:`ScheduleSanitizer` publishes the
  schedule + its sha1 to ``schedule.p<i>.json`` (atomic replace — the
  same heartbeat-file mechanism as `obs/fleet.py`) and cross-checks
  every peer file present. A hash mismatch renders a PER-SITE diff
  (missing sites, extra sites, kind/shape disagreements, order skew),
  writes it to ``schedule_diff.json``, and raises
  :class:`ScheduleDivergenceError` — turning tomorrow's silent hang
  into today's diagnosable abort.
- the `diverge@site=S` fault kind (`utils/faults.py`) perturbs this
  process's recorded entry at site S, so CI can prove the detector
  end-to-end (`scripts/sanitizer_smoke.py`, the `sanitizer_smoke` CI
  leg) without a real divergent pod.

No jax import here: shape signatures are computed by the caller
(`obs/comms.py`) where jax already lives. This module is NOT imported
by the static analyzer (`moco_tpu.analysis` itself stays stdlib-only
for CI's `--no-deps` install) — it is the runtime arm, pulled in by the
train driver and the comms instrumentation.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Optional

from moco_tpu.utils import faults


class ScheduleDivergenceError(RuntimeError):
    """Processes disagree on the collective schedule. Aborting now, with
    a per-site diff, beats deadlocking in the next collective."""


class ScheduleRecorder:
    """Ordered (site, kind, shape-signature) record of every tagged
    collective this process has traced. First-seen order IS the issue
    order (tracing walks the step in program order); a site whose
    kind/signature CHANGES on a retrace is recorded as a new entry, so
    a process that re-specialized mid-run also hashes differently."""

    def __init__(self, process_index: int = 0):
        self.process_index = int(process_index)
        self._lock = threading.Lock()
        self._entries: list[tuple[str, str, str]] = []
        self._seen: set[tuple[str, str, str]] = set()

    def record(self, site: str, kind: str, signature: str) -> None:
        # deterministic fault hook: diverge@site=S perturbs THIS
        # process's view of the site, for end-to-end detector tests
        marker = faults.diverge_marker(site)
        if marker:
            signature = f"{signature}{marker}"
        entry = (str(site), str(kind), signature)
        with self._lock:
            if entry not in self._seen:
                self._seen.add(entry)
                self._entries.append(entry)

    def entries(self) -> list[tuple[str, str, str]]:
        with self._lock:
            return list(self._entries)

    def schedule_hash(self) -> str:
        payload = "\n".join("|".join(e) for e in self.entries())
        return hashlib.sha1(payload.encode("utf-8")).hexdigest()

    def payload(self) -> dict:
        """Metrics-line field: short schedule hash (stable across a
        healthy run — dashboards watch it for FLATNESS, like
        compile_cache_misses)."""
        return {"collective_schedule_hash": self.schedule_hash()[:12]}


# -- module-level hook (called from obs/comms.py) -------------------------

_RECORDER: Optional[ScheduleRecorder] = None


def install_recorder(recorder: Optional[ScheduleRecorder]) -> Optional[ScheduleRecorder]:
    """Install (or clear, with None) the process-wide recorder; returns
    the previous one so tests can restore it."""
    global _RECORDER
    prev = _RECORDER
    _RECORDER = recorder
    return prev


def get_recorder() -> Optional[ScheduleRecorder]:
    return _RECORDER


def enabled() -> bool:
    return _RECORDER is not None


def on_tag(site: str, kind: str, signature: str) -> None:
    """comms.tag's hook — no-op unless a recorder is installed."""
    if _RECORDER is not None:
        _RECORDER.record(site, kind, signature)


# -- cross-process check ---------------------------------------------------


def schedule_path(workdir: str, process_index: int) -> str:
    return os.path.join(workdir, f"schedule.p{process_index}.json")


def _render_diff(mine: list, theirs: list, peer: int) -> list[str]:
    """Human-readable per-site diff between two schedules."""
    mine_t = [tuple(e) for e in mine]
    theirs_t = [tuple(e) for e in theirs]
    my_sites = {e[0]: e for e in mine_t}
    their_sites = {e[0]: e for e in theirs_t}
    lines: list[str] = []
    for site in sorted(set(my_sites) | set(their_sites)):
        a, b = my_sites.get(site), their_sites.get(site)
        if a == b:
            continue
        if b is None:
            lines.append(f"  site {site!r}: only THIS process issues it ({a[1]} {a[2]})")
        elif a is None:
            lines.append(f"  site {site!r}: only process {peer} issues it ({b[1]} {b[2]})")
        else:
            lines.append(
                f"  site {site!r}: this process {a[1]} {a[2]} vs "
                f"process {peer} {b[1]} {b[2]}"
            )
    if not lines:  # same site set, different order
        my_order = [e[0] for e in mine_t]
        their_order = [e[0] for e in theirs_t]
        lines.append(
            f"  same sites, different ISSUE ORDER: this process {my_order} "
            f"vs process {peer} {their_order}"
        )
    return lines


class ScheduleSanitizer:
    """Publish-and-cross-check driver arm (see module docstring).

    `check()` is cheap (one small JSON write + at most N-1 small reads)
    and runs on log steps only. Peers that have not published yet are
    skipped — the check converges within one log interval of every
    process reaching its first log step; a DEAD peer is the heartbeat
    monitor's job, not this one's.
    """

    def __init__(
        self,
        workdir: str,
        process_index: int = 0,
        num_processes: int = 1,
        recorder: Optional[ScheduleRecorder] = None,
    ):
        os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.process_index = int(process_index)
        self.num_processes = int(num_processes)
        self.recorder = recorder or ScheduleRecorder(process_index)
        self.path = schedule_path(workdir, self.process_index)
        self.diff_path = os.path.join(workdir, "schedule_diff.json")
        self._published_hash: Optional[str] = None

    def publish(self, step: int = 0) -> str:
        """Write this process's schedule file (atomic replace); returns
        the hash. Skips the write when the schedule is unchanged."""
        h = self.recorder.schedule_hash()
        if h == self._published_hash:
            return h
        rec = {
            "process": self.process_index,
            "step": int(step),
            "time": time.time(),
            "hash": h,
            "schedule": [list(e) for e in self.recorder.entries()],
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)
        self._published_hash = h
        return h

    def _read_peer(self, peer: int) -> Optional[dict]:
        try:
            with open(schedule_path(self.workdir, peer)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def check(self, step: int = 0) -> None:
        """Publish, then compare against every published peer. Raises
        :class:`ScheduleDivergenceError` with a per-site diff on any
        hash mismatch (also written to ``schedule_diff.json``)."""
        my_hash = self.publish(step)
        mine = [list(e) for e in self.recorder.entries()]
        diffs: list[str] = []
        divergent: list[int] = []
        for peer in range(self.num_processes):
            if peer == self.process_index:
                continue
            rec = self._read_peer(peer)
            if rec is None:
                continue  # not published yet / dead (heartbeat's job)
            if rec.get("hash") == my_hash:
                continue
            divergent.append(peer)
            diffs.append(
                f"process {self.process_index} (hash {my_hash[:12]}) vs "
                f"process {peer} (hash {str(rec.get('hash'))[:12]}):"
            )
            diffs.extend(_render_diff(mine, rec.get("schedule", []), peer))
        if not divergent:
            return
        artifact = {
            "step": int(step),
            "process": self.process_index,
            "divergent_peers": divergent,
            "diff": diffs,
            "schedule": mine,
        }
        tmp = self.diff_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f, indent=2)
        os.replace(tmp, self.diff_path)
        raise ScheduleDivergenceError(
            f"collective schedules diverged at step {step} — aborting before "
            "the pod deadlocks in a mismatched collective.\n"
            + "\n".join(diffs)
            + f"\n(full diff written to {self.diff_path})"
        )


__all__ = [
    "ScheduleDivergenceError",
    "ScheduleRecorder",
    "ScheduleSanitizer",
    "enabled",
    "get_recorder",
    "install_recorder",
    "on_tag",
    "schedule_path",
]
