"""Whole-program call graph for mocolint's interprocedural passes.

The per-function rules (JX001–JX007) go blind exactly where MoCo's
correctness chain lives: `stop_gradient` is applied in `ops/losses.py`,
the taint originates in `core/moco.py`, and the collective whose axis
must agree with the `shard_map` declaration sits two helper calls away
in `parallel/shuffle.py`. This module resolves module-level functions
and methods ACROSS the analyzed file set so the dataflow engine
(`analysis/dataflow.py`) can push taint and summaries through call
sites.

Resolution is deliberately approximate (same contract as `astutils`:
high-value findings, near-zero false positives — unresolvable calls
stay unresolved, they never guess):

- module names derive from file paths (`moco_tpu/parallel/shuffle.py`
  -> ``moco_tpu.parallel.shuffle``), anchored at the shallowest
  directory that makes every analyzed file addressable;
- a call's dotted qualname resolves through the caller module's import
  aliases (`from moco_tpu.core.queue import enqueue` / ``import
  moco_tpu.core.queue as q``), then matches module-level functions and
  ``Class.method`` definitions in the analyzed set;
- ``self.method()`` resolves within the enclosing class;
- anything else (attribute chains on locals, higher-order values,
  foreign libraries) resolves to None.

Everything here is stdlib-only: the analyzer must run in CI with no
heavy deps installed (`pip install -e . --no-deps`).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterator, Optional

from moco_tpu.analysis.astutils import (
    ModuleContext,
    decorator_qual,
    jit_kind,
    qualname,
)


@dataclasses.dataclass
class FunctionInfo:
    """One function or method definition in the analyzed program."""

    qualname: str  # "pkg.mod.fn" or "pkg.mod.Class.fn"
    module: str  # "pkg.mod"
    node: ast.FunctionDef
    ctx: ModuleContext
    cls: Optional[str] = None  # enclosing class name, None for module level

    @property
    def name(self) -> str:
        return self.node.name

    def param_names(self) -> list[str]:
        a = self.node.args
        return [p.arg for p in [*a.posonlyargs, *a.args, *a.kwonlyargs]]


def module_name_for(path: str, roots: Optional[list[str]] = None) -> str:
    """Dotted module name from a file path. `roots` are directory
    prefixes to strip (the analyzed tree's anchor points); without one
    that matches, the path's components become the name as-is."""
    norm = os.path.normpath(path)
    for root in roots or []:
        r = os.path.normpath(root)
        if norm.startswith(r + os.sep):
            norm = norm[len(r) + 1 :]
            break
    if norm.endswith(".py"):
        norm = norm[: -len(".py")]
    parts = [p for p in norm.split(os.sep) if p not in ("", ".")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _enclosing_classes(tree: ast.Module) -> dict[int, str]:
    """id(FunctionDef) -> immediate enclosing class name (one level)."""
    out: dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[id(child)] = node.name
    return out


class Program:
    """The analyzed file set as one unit: modules, functions, call graph.

    Built once per `analyze_paths` run and attached to every
    `ModuleContext` as ``ctx.program``; rules degrade to per-module
    behavior when it is absent (`analyze_source` on a lone string still
    builds a single-file Program, so cross-FUNCTION flows inside one
    file resolve either way).
    """

    def __init__(self, contexts: dict[str, ModuleContext]):
        # path -> ctx; module -> ctx; qualname -> FunctionInfo
        self.contexts = contexts
        roots = self._infer_roots(list(contexts))
        self.module_of_path: dict[str, str] = {
            path: module_name_for(path, roots) for path in contexts
        }
        self.by_module: dict[str, ModuleContext] = {
            self.module_of_path[path]: ctx for path, ctx in contexts.items()
        }
        self.functions: dict[str, FunctionInfo] = {}
        for path, ctx in contexts.items():
            module = self.module_of_path[path]
            ctx.module_name = module
            classes = _enclosing_classes(ctx.tree)
            for fn in ctx.functions:
                cls = classes.get(id(fn))
                qual = f"{module}.{cls}.{fn.name}" if cls else f"{module}.{fn.name}"
                # later definition wins on duplicates, like runtime rebinding
                self.functions[qual] = FunctionInfo(
                    qualname=qual, module=module, node=fn, ctx=ctx, cls=cls
                )
        self._by_node: dict[int, FunctionInfo] = {
            id(info.node): info for info in self.functions.values()
        }
        self._edges: Optional[dict[str, set[str]]] = None
        self._jitted: Optional[set[str]] = None

    # -- construction helpers -------------------------------------------

    @staticmethod
    def _infer_roots(paths: list[str]) -> list[str]:
        """Anchor directories so `moco_tpu/...` paths produce importable
        dotted names whether the analyzer runs from the repo root or is
        handed absolute paths."""
        roots: set[str] = set()
        for p in paths:
            norm = os.path.normpath(p)
            parts = norm.split(os.sep)
            for anchor in ("moco_tpu", "scripts", "tests"):
                if anchor in parts:
                    idx = parts.index(anchor)
                    if idx > 0:
                        roots.add(os.sep.join(parts[:idx]))
                    break
            else:
                d = os.path.dirname(norm)
                if d:
                    roots.add(d)
        # longest first: the most specific anchor strips the most
        return sorted(roots, key=len, reverse=True)

    # -- lookups ---------------------------------------------------------

    def info_for_node(self, fn: ast.FunctionDef) -> Optional[FunctionInfo]:
        return self._by_node.get(id(fn))

    def lookup(self, dotted: str) -> Optional[FunctionInfo]:
        """FunctionInfo for a dotted origin, trying `mod.fn` then
        `mod.Class.fn` (an import of a class followed by `.method`)."""
        return self.functions.get(dotted)

    def resolve_call(
        self, ctx: ModuleContext, call: ast.Call, enclosing: Optional[ast.FunctionDef] = None
    ) -> Optional[FunctionInfo]:
        """Resolve a call expression to a definition in the program."""
        func = call.func
        # self.method() -> method of the enclosing class
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and enclosing is not None
        ):
            info = self.info_for_node(enclosing)
            if info is not None and info.cls is not None:
                return self.functions.get(f"{info.module}.{info.cls}.{func.attr}")
            return None
        qual = qualname(func, ctx.imports)
        if qual is None:
            return None
        hit = self.functions.get(qual)
        if hit is not None:
            return hit
        # bare local name -> this module's function
        if isinstance(func, ast.Name):
            module = self.module_of(ctx)
            if module is not None:
                return self.functions.get(f"{module}.{func.id}")
        return None

    def module_of(self, ctx: ModuleContext) -> Optional[str]:
        return getattr(ctx, "module_name", None)

    # -- call graph ------------------------------------------------------

    def calls_in(self, info: FunctionInfo) -> Iterator[tuple[ast.Call, Optional[FunctionInfo]]]:
        """(call node, resolved callee or None) for every call in the
        function's own body (nested defs belong to themselves)."""
        from moco_tpu.analysis.astutils import walk_own

        for node in walk_own(info.node):
            if isinstance(node, ast.Call):
                yield node, self.resolve_call(info.ctx, node, enclosing=info.node)

    def edges(self) -> dict[str, set[str]]:
        """caller qualname -> {callee qualnames} over the whole program."""
        if self._edges is None:
            self._edges = {}
            for qual, info in self.functions.items():
                outs: set[str] = set()
                for _, callee in self.calls_in(info):
                    if callee is not None:
                        outs.add(callee.qualname)
                self._edges[qual] = outs
        return self._edges

    def callees_transitive(self, qual: str, limit: int = 200) -> set[str]:
        """All functions reachable from `qual` through resolved calls."""
        edges = self.edges()
        seen: set[str] = set()
        stack = [qual]
        while stack and len(seen) < limit:
            cur = stack.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return seen

    # -- cross-module jitted closure --------------------------------------

    def jitted(self) -> set[str]:
        """Qualnames of every function compiled by jit/shard_map/pmap,
        closed over RESOLVED call edges program-wide — the cross-module
        generalization of `ModuleContext.jitted` (a helper in
        `ops/losses.py` called from the jitted step in `core/moco.py` is
        in jitted scope even though its own module never mentions jit)."""
        if self._jitted is not None:
            return self._jitted
        roots: set[str] = set()
        for ctx in self.by_module.values():
            for fn in ctx.jitted:
                info = self.info_for_node(fn)
                if info is not None:
                    roots.add(info.qualname)
        # also: decorated defs anywhere (defensive; ctx.jitted covers it)
        for qual, info in self.functions.items():
            for dec in info.node.decorator_list:
                if jit_kind(decorator_qual(dec, info.ctx.imports)):
                    roots.add(qual)
        closed = set(roots)
        stack = list(roots)
        edges = self.edges()
        while stack:
            cur = stack.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in closed:
                    closed.add(nxt)
                    stack.append(nxt)
        self._jitted = closed
        return closed

    def in_jitted_scope(self, fn: ast.FunctionDef) -> bool:
        info = self.info_for_node(fn)
        return info is not None and info.qualname in self.jitted()


def build_program(contexts: dict[str, ModuleContext]) -> Program:
    """Construct and attach: every ctx gains a ``.program`` backref."""
    program = Program(contexts)
    for ctx in contexts.values():
        ctx.program = program
    return program
