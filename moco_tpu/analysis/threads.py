"""Thread-escape + lock-set model for the concurrency rules (JX012/JX013).

PRs 8–12 grew this repo a deeply threaded serving/input surface —
batcher and flusher threads, HTTP handler pools, ingest tails, prefetch
rings, async param gathers — and the bug classes that come with it
(unlocked shared counters, lock-order inversions, blocking calls under a
lock) hang or corrupt a replica in ways no test run reliably surfaces.
This module computes, per class ("component"), the facts those rules
need, with the same contract as the rest of mocolint: approximate,
near-zero false positives, unresolvable constructs stay unresolved.

The model answers three questions per component:

1. **Which threads reach each method?** Roots are: `threading.Thread(
   target=...)` targets, HTTP handler methods (``do_GET``/``do_POST``/…
   on a nested handler class — one thread PER REQUEST, so a handler
   root counts as two threads by itself), and callback escapes (a bound
   method passed by reference to any call — the batcher's `run_batch`,
   an alert engine's `on_fire`). Public methods additionally carry the
   calling ("main") thread. Roots propagate caller→callee over the
   intra-component call graph (`self.m()` and outer-alias calls — the
   repo's ``server = self`` / ``sink = self`` closure idiom resolves to
   the owning component).

2. **Which locks are provably held at each attribute access?** A
   lock-set walker tracks ``with self._lock:`` blocks (locks are
   recognized by constructor — `threading.Lock`/`RLock`/
   `tsan.make_lock` — or a ``lock``-ish name) and threads guaranteed
   locks through intra-component calls: a private method invoked ONLY
   under a lock inherits it (the intersection over its call sites, to a
   fixpoint), so `_handle_ingest`-style helpers don't false-positive.

3. **What does each lock acquisition order/block on?** Acquiring lock B
   while A is held contributes an A→B edge to the component's
   lock-order graph (JX013 reports cycles), and calls that can block
   unboundedly — `put`/`get` with no timeout, `Event.wait()` with no
   timeout, `urlopen`, `time.sleep`, `join`, `block_until_ready`,
   `device_get` — are recorded with the lock-set they run under.

`__init__` accesses are excluded everywhere: construction happens
strictly before any thread this model knows about starts (the
happens-before edge `Thread.start()` provides). A nested HTTP handler
class's OWN attributes are also excluded — `http.server` builds one
handler instance per request, so they are per-thread by construction;
only its accesses to the outer component (via the alias) are shared.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional

from moco_tpu.analysis.astutils import ModuleContext

# attribute values of these constructor shapes are thread-safe-by-design
# primitives (or are the synchronization itself) — never "shared mutable
# state" in the JX012 sense
_SAFE_CTOR_SUFFIXES = (
    ".Lock", ".RLock", ".Event", ".Condition", ".Semaphore",
    ".BoundedSemaphore", ".Barrier", ".local", ".Queue", ".SimpleQueue",
    ".LifoQueue", ".PriorityQueue", ".deque", ".make_lock", ".make_rlock",
)
_SAFE_CTOR_NAMES = {
    "Lock", "RLock", "Event", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "deque", "make_lock", "make_rlock",
}

_LOCK_CTOR_SUFFIXES = (".Lock", ".RLock", ".make_lock", ".make_rlock")
_LOCK_CTOR_NAMES = {"Lock", "RLock", "make_lock", "make_rlock"}

# container-mutating method names that count as a WRITE to the receiver
# attribute (self._pending.append(...) mutates self._pending)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "add", "update",
    "insert", "remove", "discard", "pop", "popleft", "popitem", "clear",
    "setdefault", "sort",
}

_HTTP_HANDLER_METHODS = {
    "do_GET", "do_POST", "do_PUT", "do_DELETE", "do_HEAD", "do_PATCH",
}

MAIN_ROOT = "main"


@dataclasses.dataclass
class Access:
    attr: str
    # "write"  = direct (re)assignment / subscript store
    # "mutate" = container-mutating method call (x.append, x.add, ...)
    # "read"   = deep use (x.count, x.query(...)) — reads mutable state
    # "ref"    = bare reference (x is None, passing x along) — races only
    #            when the attr itself is reassigned somewhere
    kind: str
    method: str
    lineno: int
    node: ast.AST
    locks: frozenset[str]

    @property
    def is_write(self) -> bool:
        return self.kind in ("write", "mutate")


@dataclasses.dataclass
class LockEdge:
    """Lock `held` was held while `acquired` was acquired."""

    held: str
    acquired: str
    method: str
    node: ast.AST


@dataclasses.dataclass
class BlockingCall:
    desc: str
    method: str
    node: ast.AST
    locks: frozenset[str]


class ComponentModel:
    """One class (plus its nested handler classes and closures) as a
    concurrency unit: methods, thread roots, attribute accesses with
    lock-sets, lock-order edges, blocking-under-lock sites."""

    def __init__(self, ctx: ModuleContext, cls: ast.ClassDef):
        self.ctx = ctx
        self.cls = cls
        self.name = cls.name
        # method name -> def node; nested handler-class methods join the
        # component under "Handler.do_GET"-style keys
        self.methods: dict[str, ast.FunctionDef] = {}
        # names aliasing the component instance inside method bodies
        # (the `server = self` closure idiom)
        self.aliases: set[str] = set()
        # method key -> set of root labels
        self.roots: dict[str, set[str]] = {}
        # attr -> constructor qualname it was assigned from (in __init__)
        self.attr_ctors: dict[str, str] = {}
        self.lock_attrs: set[str] = set()
        self.accesses: list[Access] = []
        self.lock_edges: list[LockEdge] = []
        self.blocking: list[BlockingCall] = []
        # (caller method, callee method, locks held at the call site)
        self.call_sites: list[tuple[str, str, frozenset[str]]] = []
        # every lock acquisition: (method, lock, with-item node)
        self._acquisitions: list[tuple[str, str, ast.AST]] = []
        # nested classes whose own `self` is per-request (HTTP handlers)
        self._handler_classes: set[str] = set()
        # id(method def) -> nested class name, for resolving `self.m()`
        # inside a nested class to that class's own methods
        self._nested_class_of: dict[int, str] = {}
        # @property defs are attribute reads, never callbacks
        self._properties: set[str] = set()
        self._collect()

    # -- structure discovery ------------------------------------------------

    def _collect(self) -> None:
        self._discover_methods()
        self._discover_aliases_and_ctors()
        entries = self._discover_roots()
        self._walk_methods()
        self._propagate(entries)
        self._apply_inherited_locks(entries)

    def _apply_inherited_locks(self, entries: dict[str, set[str]]) -> None:
        """A private method invoked ONLY under a lock inherits it: the
        intersection of locks over its intra-component call sites, to a
        fixpoint. Methods a caller thread can invoke directly (entries,
        public surface) inherit nothing."""
        TOP = None  # "not yet constrained" (universal set)
        inherited: dict[str, Optional[frozenset[str]]] = {}
        for name in self.methods:
            inherited[name] = frozenset() if entries.get(name) else TOP
        for _ in range(len(self.methods) + 1):
            changed = False
            for caller, callee, locks in self.call_sites:
                base = inherited.get(caller)
                if base is TOP:
                    continue
                site = locks | base
                cur = inherited.get(callee)
                new = site if cur is TOP else (cur & site)
                if new != cur:
                    inherited[callee] = new
                    changed = True
            if not changed:
                break
        extra = {
            m: locks for m, locks in inherited.items() if locks
        }
        if not extra:
            return
        self.accesses = [
            dataclasses.replace(a, locks=a.locks | extra[a.method])
            if a.method in extra
            else a
            for a in self.accesses
        ]
        self.blocking = [
            dataclasses.replace(b, locks=b.locks | extra[b.method])
            if b.method in extra
            else b
            for b in self.blocking
        ]
        # a lock acquired inside an always-under-lock helper orders after
        # the inherited lock(s) too
        for method, lock, node in self._acquisitions:
            for h in extra.get(method, ()):
                if h != lock:
                    self.lock_edges.append(LockEdge(h, lock, method, node))

    def _discover_methods(self) -> None:
        for node in self.cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[node.name] = node
                for dec in node.decorator_list:
                    q = self.ctx.qual(dec) or ""
                    if q == "property" or q.endswith(".setter") or q == "cached_property":
                        self._properties.add(node.name)
                # nested defs/classes inside a method body (closure thread
                # targets, per-request handler classes)
                in_nested_class: set[int] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.ClassDef):
                        if any(
                            isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                            and m.name in _HTTP_HANDLER_METHODS
                            for m in sub.body
                        ):
                            self._handler_classes.add(sub.name)
                        for m in sub.body:
                            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                                self.methods[f"{sub.name}.{m.name}"] = m
                                self._nested_class_of[id(m)] = sub.name
                                for inner in ast.walk(m):
                                    in_nested_class.add(id(inner))
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and sub is not node
                        and id(sub) not in in_nested_class
                        and sub.name not in self.methods
                    ):
                        self.methods[sub.name] = sub

    def _discover_aliases_and_ctors(self) -> None:
        for name, fn in list(self.methods.items()):
            if "." in name:
                continue  # nested-class methods have their own self
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                value = node.value
                # alias = self
                if isinstance(value, ast.Name) and value.id == "self":
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self.aliases.add(t.id)
                # self.attr = Ctor(...)  (plain or annotated assignment)
                if isinstance(value, ast.Call):
                    q = self.ctx.qual(value.func) or ""
                    for t in targets:
                        attr = self._self_attr(t, fn)
                        if attr is None:
                            continue
                        self.attr_ctors.setdefault(attr, q)
                        if self._is_lock_ctor(q):
                            self.lock_attrs.add(attr)
        # name-based fallback: an attr whose name says "lock" is one
        for name, fn in self.methods.items():
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) and "lock" in node.attr.lower():
                    if self._receiver_is_component(node.value, fn):
                        self.lock_attrs.add(node.attr)

    @staticmethod
    def _is_lock_ctor(qual: str) -> bool:
        return bool(qual) and (
            qual in _LOCK_CTOR_NAMES or qual.endswith(_LOCK_CTOR_SUFFIXES)
        )

    def attr_is_safe_type(self, attr: str) -> bool:
        q = self.attr_ctors.get(attr, "")
        return bool(q) and (
            q in _SAFE_CTOR_NAMES or q.endswith(_SAFE_CTOR_SUFFIXES)
        )

    def _receiver_is_component(
        self, recv: ast.AST, fn: ast.FunctionDef
    ) -> bool:
        """Does this expression denote the component instance? `self` in a
        direct method (NOT a nested handler class's method, whose `self`
        is its own per-request instance) or a recorded alias anywhere."""
        if not isinstance(recv, ast.Name):
            return False
        if recv.id in self.aliases:
            return True
        if recv.id == "self":
            # `self` belongs to the component only in its direct methods
            return any(
                f is fn and "." not in name for name, f in self.methods.items()
            )
        return False

    def _self_attr(self, target: ast.AST, fn: ast.FunctionDef) -> Optional[str]:
        if isinstance(target, ast.Attribute) and self._receiver_is_component(
            target.value, fn
        ):
            return target.attr
        return None

    # -- thread roots -------------------------------------------------------

    def _discover_roots(self) -> dict[str, set[str]]:
        """Seed roots: Thread targets, handler methods, callback escapes,
        and MAIN for public methods (anything a caller thread can invoke
        directly). `__init__` is excluded — it runs before any thread
        this model knows about starts."""
        entries: dict[str, set[str]] = {name: set() for name in self.methods}
        for name, fn in self.methods.items():
            base = name.rsplit(".", 1)[-1]
            if base in _HTTP_HANDLER_METHODS:
                entries[name].add(f"http:{base}")
            elif name != "__init__" and not base.startswith("_"):
                entries[name].add(MAIN_ROOT)
        for name, fn in self.methods.items():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                q = self.ctx.qual(node.func) or ""
                is_thread = q == "threading.Thread" or q.endswith(".Thread") or q == "Thread"
                if is_thread:
                    for kw in node.keywords:
                        if kw.arg == "target":
                            tgt = self._method_ref(kw.value, fn)
                            if tgt is not None:
                                entries[tgt].add(f"thread:{tgt}")
                else:
                    # callback escape: a component method passed BY
                    # REFERENCE (not called) to any call — it will run on
                    # whatever thread the receiver chooses
                    for arg in [*node.args, *[kw.value for kw in node.keywords]]:
                        tgt = self._method_ref(arg, fn)
                        if tgt is not None and tgt != "__init__":
                            entries[tgt].add(f"callback:{tgt}")
        return entries

    def _method_ref(self, expr: ast.AST, fn: ast.FunctionDef) -> Optional[str]:
        """`self.m` / `alias.m` / bare closure name -> method key.
        Properties are attribute READS, not callables escaping."""
        if isinstance(expr, ast.Attribute) and self._receiver_is_component(
            expr.value, fn
        ):
            if expr.attr in self.methods and expr.attr not in self._properties:
                return expr.attr
        if isinstance(expr, ast.Name) and expr.id in self.methods:
            # bare name: a closure/nested def used as a target
            if "." not in expr.id and expr.id not in self._properties:
                return expr.id
        return None

    def _propagate(self, entries: dict[str, set[str]]) -> None:
        """Roots flow caller -> callee over intra-component calls."""
        edges: dict[str, set[str]] = {name: set() for name in self.methods}
        for name, fn in self.methods.items():
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = self._called_method(node, fn)
                    if callee is not None:
                        edges[name].add(callee)
        roots = {name: set(r) for name, r in entries.items()}
        changed = True
        while changed:
            changed = False
            for caller, callees in edges.items():
                for callee in callees:
                    if callee == "__init__":
                        continue
                    before = len(roots[callee])
                    roots[callee] |= roots[caller]
                    changed = changed or len(roots[callee]) != before
        self.roots = roots

    def _called_method(self, call: ast.Call, fn: ast.FunctionDef) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Attribute) and self._receiver_is_component(
            func.value, fn
        ):
            if func.attr in self.methods:
                return func.attr
        # `self.m()` inside a nested class resolves to that class's own
        # methods ("Handler.do_POST" calling "Handler._handle_ingest")
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            nested = self._nested_class_of.get(id(fn))
            if nested is not None and f"{nested}.{func.attr}" in self.methods:
                return f"{nested}.{func.attr}"
        if isinstance(func, ast.Name) and func.id in self.methods:
            return func.id
        return None

    # -- lock-set walk ------------------------------------------------------

    def _lock_name(self, expr: ast.AST, fn: ast.FunctionDef) -> Optional[str]:
        """Canonical name of a lock expression, or None when it isn't
        one. Component locks normalize to `self.<attr>`; other receivers
        keep their dotted spelling so `metrics._lock` and `self._lock`
        stay distinct nodes in the order graph."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            is_lockish = attr in self.lock_attrs or "lock" in attr.lower()
            if not is_lockish:
                return None
            if self._receiver_is_component(expr.value, fn):
                return f"self.{attr}"
            parts = []
            node = expr
            while isinstance(node, ast.Attribute):
                parts.append(node.attr)
                node = node.value
            if isinstance(node, ast.Name):
                base = "self" if node.id in self.aliases else node.id
                return ".".join([base] + parts[::-1])
            return None
        if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
            return expr.id
        return None

    def _walk_methods(self) -> None:
        for name, fn in self.methods.items():
            self._walk(fn.body, name, fn, [])

    def _walk(
        self,
        stmts: list[ast.stmt],
        method: str,
        fn: ast.FunctionDef,
        held: list[str],
    ) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired: list[str] = []
                for item in stmt.items:
                    self._scan_expr(item.context_expr, method, fn, held + acquired)
                    lock = self._lock_name(item.context_expr, fn)
                    if lock is not None:
                        self._acquisitions.append((method, lock, item.context_expr))
                        for h in held + acquired:
                            if h != lock:
                                self.lock_edges.append(
                                    LockEdge(h, lock, method, item.context_expr)
                                )
                        acquired.append(lock)
                self._walk(stmt.body, method, fn, held + acquired)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: analyzed as its own method entry; a closure
                # body does NOT run under the enclosing with-block at def
                # time, so don't thread `held` into it here
                continue
            elif isinstance(stmt, ast.ClassDef):
                continue
            elif isinstance(stmt, (ast.If, ast.While)):
                self._scan_expr(stmt.test, method, fn, held)
                self._walk(stmt.body, method, fn, held)
                self._walk(stmt.orelse, method, fn, held)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_expr(stmt.iter, method, fn, held)
                self._walk(stmt.body, method, fn, held)
                self._walk(stmt.orelse, method, fn, held)
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body, method, fn, held)
                for handler in stmt.handlers:
                    self._walk(handler.body, method, fn, held)
                self._walk(stmt.orelse, method, fn, held)
                self._walk(stmt.finalbody, method, fn, held)
            else:
                self._scan_stmt(stmt, method, fn, held)

    def _scan_stmt(
        self, stmt: ast.stmt, method: str, fn: ast.FunctionDef, held: list[str]
    ) -> None:
        locks = frozenset(held)
        write_nodes: set[int] = set()
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            for t in targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Starred)):
                    base = base.value
                attr = self._self_attr(base, fn)
                if attr is not None:
                    self.accesses.append(
                        Access(attr, "write", method, stmt.lineno, stmt, locks)
                    )
                    write_nodes.add(id(base))
                    # AugAssign / subscript-store also READS the attr; the
                    # write record covers the hazard
        self._scan_expr(stmt, method, fn, held, skip=write_nodes)

    def _scan_expr(
        self,
        expr: ast.AST,
        method: str,
        fn: ast.FunctionDef,
        held: list[str],
        skip: Optional[set[int]] = None,
    ) -> None:
        locks = frozenset(held)
        skip = skip or set()
        # `self.x.anything` / `self.x[...]`: the inner `self.x` access is
        # a DEEP use (it reads the object's mutable state), vs a bare
        # reference like `self.x is None`
        deep: set[int] = set()
        for node in ast.walk(expr):
            if isinstance(node, (ast.Attribute, ast.Subscript)) and isinstance(
                node.value, ast.Attribute
            ):
                deep.add(id(node.value))
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(node, ast.Call):
                self._scan_call(node, method, fn, locks)
            elif isinstance(node, ast.Attribute) and id(node) not in skip:
                if self._receiver_is_component(node.value, fn):
                    if isinstance(node.ctx, ast.Store):
                        kind = "write"
                    else:
                        kind = "read" if id(node) in deep else "ref"
                    self.accesses.append(
                        Access(node.attr, kind, method, node.lineno, node, locks)
                    )

    def _scan_call(
        self, node: ast.Call, method: str, fn: ast.FunctionDef, locks: frozenset[str]
    ) -> None:
        func = node.func
        callee = self._called_method(node, fn)
        if callee is not None:
            self.call_sites.append((method, callee, locks))
        # mutator method on a component attr counts as a write to it
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _MUTATORS
            and isinstance(func.value, ast.Attribute)
            and self._receiver_is_component(func.value.value, fn)
        ):
            self.accesses.append(
                Access(func.value.attr, "mutate", method, node.lineno, node, locks)
            )
        if locks:
            desc = self._blocking_desc(node)
            if desc is not None:
                self.blocking.append(BlockingCall(desc, method, node, locks))

    def _blocking_desc(self, node: ast.Call) -> Optional[str]:
        """Can this call block unboundedly? (Only consulted under a lock.)"""
        kwargs = {kw.arg for kw in node.keywords}
        q = self.ctx.qual(node.func) or ""
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in ("put", "get") and "timeout" not in kwargs and "block" not in kwargs:
                if len(node.args) <= (1 if attr == "put" else 0):
                    return f"blocking queue .{attr}() with no timeout"
            if attr == "wait" and "timeout" not in kwargs and not node.args:
                return "event/condition .wait() with no timeout"
            if attr == "join" and "timeout" not in kwargs and not node.args:
                return ".join() with no timeout"
            if attr == "block_until_ready":
                return "device sync (.block_until_ready())"
        if q.endswith(".urlopen") or q == "urlopen":
            return "HTTP I/O (urlopen)"
        if q == "time.sleep":
            return "time.sleep()"
        if q.endswith(".device_get") or q == "jax.device_get":
            return "device transfer (device_get)"
        return None

    # -- consumers ----------------------------------------------------------

    def thread_weight(self, root: str) -> int:
        """HTTP handler roots are one thread PER REQUEST: two concurrent
        requests already race, so a handler root alone counts as 2."""
        return 2 if root.startswith("http:") else 1

    def roots_of_accesses(self, accesses: list[Access]) -> set[str]:
        out: set[str] = set()
        for a in accesses:
            out |= self.roots.get(a.method, set())
        return out

    def shared_attr_accesses(self) -> Iterator[tuple[str, list[Access], set[str]]]:
        """(attr, accesses, roots) for every attr written outside
        `__init__` whose accessing methods span ≥ 2 thread weight with at
        least one non-main root — the JX012 candidates. Safe-typed attrs
        (locks, queues, events, deques) are skipped."""
        by_attr: dict[str, list[Access]] = {}
        for a in self.accesses:
            if a.method == "__init__" or not self.roots.get(a.method):
                continue
            by_attr.setdefault(a.attr, []).append(a)
        for attr, accesses in sorted(by_attr.items()):
            if self.attr_is_safe_type(attr):
                continue
            if attr in self.lock_attrs:
                continue
            writes = [a for a in accesses if a.is_write]
            if not writes:
                continue
            # bare references (`self.x is None`, passing x along) race
            # only when the attr is directly REASSIGNED somewhere; for a
            # container mutated in place they are just identity reads
            if not any(a.kind == "write" for a in writes):
                accesses = [a for a in accesses if a.kind != "ref"]
            roots = self.roots_of_accesses(accesses)
            non_main = {r for r in roots if r != MAIN_ROOT}
            if not non_main:
                continue
            weight = sum(self.thread_weight(r) for r in roots)
            if weight < 2:
                continue
            yield attr, accesses, roots


def component_models(ctx: ModuleContext) -> list[ComponentModel]:
    """Cached per-module component models (one per top-level class)."""
    cached = getattr(ctx, "_thread_models", None)
    if cached is None:
        cached = []
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                cached.append(ComponentModel(ctx, node))
        ctx._thread_models = cached  # type: ignore[attr-defined]
    return cached
