"""JX014 — AOT freeze discipline: request-derived shapes must not reach
an unguarded compile seam.

The serving perf story depends on zero recompiles after warmup: the
engine AOT-compiles one executable per registered padded bucket
(`jit(...).lower(shape).compile()`), `freeze()` marks the table closed,
and any shape that would need a fresh trace must raise
(`EngineRecompileError` / the index's flavor) instead of silently
compiling on live traffic. Today that is enforced at RUNTIME — the pod
equivalent of "catch it before the hang". This rule catches the class
statically: in a freeze-disciplined class (one that assigns
``self._frozen`` or defines ``freeze``/``mark_warm``), a flow path where
a shape **not derived from the registered bucket table** reaches a
compile seam that is **not frozen-guarded** is a finding.

Vocabulary (deliberately approximate, near-zero false positives):

- *compile seam*: a ``.lower(...).compile()`` chain, a ``jax.jit(...)``
  call, or a call to an intra-class method that transitively contains
  one;
- *bucket-derived* (clean): iteration over / subscripts of a
  ``buckets``-named attribute, the result of ``bucket_for(...)``, and
  constants;
- *raw* (dirty): a method parameter, anything computed from one —
  crucially ``param.shape[...]`` — i.e. request-shaped data;
- *frozen-guarded*: the seam-carrying method opens with
  ``if self._frozen: raise ...`` (the engine's `_compile` idiom) — the
  runtime guard IS the discipline, so guarded seams are clean.

The known-bad shape::

    def run(self, images):                  # images: live request
        b = images.shape[0]
        if b not in self._compiled:
            self._compiled[b] = jit(f).lower(images).compile()   # JX014

and the clean one pads to ``self.bucket_for(b)`` first or guards the
seam with the frozen check.
"""

from __future__ import annotations

import ast
from typing import Optional

from moco_tpu.analysis.astutils import ModuleContext, walk_own
from moco_tpu.analysis.engine import rule

_BUCKET_SANITIZERS = ("bucket_for",)


def _is_freeze_disciplined(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in ("freeze", "mark_warm"):
                return True
        if isinstance(node, ast.Attribute) and node.attr in ("_frozen", "frozen"):
            if isinstance(node.ctx, ast.Store):
                return True
    return False


def _has_frozen_guard(fn: ast.FunctionDef) -> bool:
    """Does the method body raise under a `self._frozen`-style test?"""
    for node in walk_own(fn):
        if isinstance(node, ast.If):
            mentions_frozen = any(
                isinstance(n, ast.Attribute) and n.attr in ("_frozen", "frozen")
                for n in ast.walk(node.test)
            )
            if mentions_frozen and any(
                isinstance(b, ast.Raise) for b in ast.walk(ast.Module(body=node.body, type_ignores=[]))
            ):
                return True
    return False


def _is_jit_qual(q: Optional[str]) -> bool:
    return q in ("jax.jit", "jax.pjit") or (q or "").endswith((".jit", ".pjit"))


def _contains_compile_seam(
    ctx: ModuleContext, fn: ast.FunctionDef
) -> Optional[tuple[ast.Call, list[ast.AST]]]:
    """(seam call, shape-bearing argument exprs) inside `fn`, if any.

    Three spellings: ``<jit obj>.lower(shapes).compile()`` (shapes ride
    the inner lower), ``jit(f)(x)`` immediate invocation (shapes are the
    outer args), and a bare ``jit(...)`` whose result escapes (no shape
    args here — the per-call trace happens wherever it is called, which
    is exactly the hazard; the seam itself is the finding anchor)."""
    for node in walk_own(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "compile":
            inner = func.value
            if isinstance(inner, ast.Call) and isinstance(inner.func, ast.Attribute) \
                    and inner.func.attr == "lower":
                return node, list(inner.args) + [kw.value for kw in inner.keywords]
        if isinstance(func, ast.Call) and _is_jit_qual(ctx.qual(func.func)):
            return node, list(node.args) + [kw.value for kw in node.keywords]
    return None


def _is_bucket_expr(ctx: ModuleContext, expr: ast.AST, raw: set[str]) -> bool:
    """True when the expression is provably bucket-table-derived."""
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Call):
        func = expr.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if name in _BUCKET_SANITIZERS:
            return True
    if isinstance(expr, ast.Subscript):
        return _is_bucket_expr(ctx, expr.value, raw)
    if isinstance(expr, ast.Attribute) and "bucket" in expr.attr.lower():
        return True
    if isinstance(expr, ast.Name) and "bucket" in expr.id.lower() and expr.id not in raw:
        return True
    return False


def _raw_names_in(expr: ast.AST, raw: set[str]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in raw:
            return True
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            # .shape of anything non-bucket inside a seam argument is a
            # raw dynamic shape by definition
            inner = n.value
            if not (isinstance(inner, ast.Attribute) and "bucket" in inner.attr.lower()):
                return True
    return False


@rule("JX014", "request-derived shape reaching an unguarded jit/lower().compile() seam after freeze()")
def check(ctx: ModuleContext):
    for cls in ctx.tree.body:
        if not isinstance(cls, ast.ClassDef) or not _is_freeze_disciplined(cls):
            continue
        methods = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # seam carriers: methods containing a compile seam, split by
        # whether the frozen guard protects them
        guarded: set[str] = set()
        unguarded_seams: dict[str, tuple[ast.Call, list[ast.AST]]] = {}
        for name, fn in methods.items():
            seam = _contains_compile_seam(ctx, fn)
            if seam is None:
                continue
            if _has_frozen_guard(fn):
                guarded.add(name)
            else:
                unguarded_seams[name] = seam
        # helpers invoked intra-class are judged at their CALL SITES: a
        # carrier like `_compile(bucket)` is clean in itself — whether
        # `bucket` is raw depends on what each caller passes
        called_intra: set[str] = set()
        for fn in methods.values():
            for node in walk_own(fn):
                if isinstance(node, ast.Call):
                    f = node.func
                    if (
                        isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                    ):
                        called_intra.add(f.attr)
                    elif isinstance(f, ast.Name):
                        called_intra.add(f.id)
        for name, fn in methods.items():
            if name == "__init__":
                # construction happens before freeze() by definition
                continue
            params = {
                p.arg
                for p in [*fn.args.posonlyargs, *fn.args.args, *fn.args.kwonlyargs]
                if p.arg != "self"
            }
            raw = set() if name in called_intra else set(params)
            seam_here = name in unguarded_seams
            for node in sorted(
                walk_own(fn),
                key=lambda n: (getattr(n, "lineno", 0), getattr(n, "col_offset", 0)),
            ):
                # thread rawness through simple assignments
                if isinstance(node, ast.Assign):
                    dirty = _raw_names_in(node.value, raw) and not _is_bucket_expr(
                        ctx, node.value, raw
                    )
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            if dirty:
                                raw.add(t.id)
                            else:
                                raw.discard(t.id)
                if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
                    if _is_bucket_expr(ctx, node.iter, raw):
                        raw.discard(node.target.id)
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                callee = None
                if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name) \
                        and func.value.id == "self":
                    callee = func.attr
                elif isinstance(func, ast.Name):
                    callee = func.id
                is_seam_call = callee in unguarded_seams and callee != name
                is_direct_seam = seam_here and node is unguarded_seams[name][0]
                if not (is_seam_call or is_direct_seam):
                    continue
                if is_direct_seam:
                    args = unguarded_seams[name][1]
                else:
                    args = [*node.args, *[kw.value for kw in node.keywords]]
                for arg in args:
                    if _is_bucket_expr(ctx, arg, raw):
                        continue
                    if _raw_names_in(arg, raw):
                        yield node, (
                            f"shape not derived from the bucket table reaches "
                            f"compile seam "
                            f"{'self.' + callee if is_seam_call else 'jit/lower().compile()'} "
                            f"in freeze-disciplined class {cls.name} with no "
                            "frozen guard — after freeze() this traces on live "
                            "traffic (the EngineRecompileError class, caught "
                            "statically); pad through bucket_for()/the bucket "
                            "table or guard the seam with `if self._frozen: "
                            "raise`"
                        )
                        break
