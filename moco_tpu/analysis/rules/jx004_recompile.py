"""JX004 — recompile hazards around `jax.jit` static arguments.

Three hazards, all of which burn TPU time silently (every recompile of
the r50/224 step costs minutes — PROFILE.md):

1. `static_argnames` naming a parameter the wrapped function does not
   have (or `static_argnums` out of range): jax ignores or errors
   depending on version, and the intended argument stays traced — each
   distinct value then recompiles.
2. A non-hashable literal (list/dict/set) passed in a static position:
   raises at best; a hashable-but-fresh object (tuple rebuilt per call
   from arrays) recompiles every step.
3. Python `if` on `.shape` inside jitted scope: legal (shapes are
   static) but every distinct shape re-traces — on a pipeline with
   ragged batches this is an unbounded compile loop.
"""

from __future__ import annotations

import ast
from typing import Optional

from moco_tpu.analysis.astutils import ModuleContext, jit_kind, walk_own
from moco_tpu.analysis.engine import rule


def _static_spec(call: ast.Call) -> tuple[list[int], list[str]]:
    """(static_argnums, static_argnames) literals of a jit call."""
    nums: list[int] = []
    names: list[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.append(n.value)
        elif kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.append(n.value)
    return nums, names


def _param_names(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    return [p.arg for p in [*a.posonlyargs, *a.args]]


def _nonhashable(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(expr, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return "set"
    return None


@rule("JX004", "recompile hazard: bad static_argnums/argnames or shape branching in jitted scope")
def check(ctx: ModuleContext):
    # --- (1)+(2): every jit(...) call with static args ------------------
    jit_wrappers: dict[str, tuple[list[int], list[str]]] = {}
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and jit_kind(ctx.qual(node.func)) == "jit"):
            continue
        nums, names = _static_spec(node)
        if not nums and not names:
            continue
        wrapped = None
        if node.args and isinstance(node.args[0], ast.Name):
            defs = ctx.defs_by_name.get(node.args[0].id, [])
            wrapped = defs[-1] if defs else None
        if wrapped is not None:
            params = _param_names(wrapped)
            has_varargs = wrapped.args.vararg is not None
            for name in names:
                if name not in params and wrapped.args.kwarg is None:
                    yield node, (
                        f"static_argnames {name!r} is not a parameter of "
                        f"'{wrapped.name}' ({', '.join(params) or 'no args'}) — "
                        "the intended argument stays traced and every distinct "
                        "value recompiles"
                    )
            for num in nums:
                if not has_varargs and num >= len(params):
                    yield node, (
                        f"static_argnums {num} is out of range for "
                        f"'{wrapped.name}' ({len(params)} positional params)"
                    )
    # remember wrapper bindings: g = jax.jit(f, static_*) for call-site checks
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if jit_kind(ctx.qual(call.func)) == "jit":
                nums, names = _static_spec(call)
                if (nums or names) and len(node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name
                ):
                    jit_wrappers[node.targets[0].id] = (nums, names)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        # direct call of the jit expression: jax.jit(f, static_argnums=0)(x)
        if isinstance(node.func, ast.Call) and jit_kind(ctx.qual(node.func.func)) == "jit":
            nums, names = _static_spec(node.func)
        elif isinstance(node.func, ast.Name) and node.func.id in jit_wrappers:
            nums, names = jit_wrappers[node.func.id]
        else:
            continue
        for i, arg in enumerate(node.args):
            if i in nums:
                kind = _nonhashable(arg)
                if kind:
                    yield arg, (
                        f"non-hashable {kind} literal in static position {i} — "
                        "static args must be hashable (tuple it) or the call "
                        "raises/recompiles"
                    )
        for kw in node.keywords:
            if kw.arg in names:
                kind = _nonhashable(kw.value)
                if kind:
                    yield kw.value, (
                        f"non-hashable {kind} literal for static arg "
                        f"{kw.arg!r} — static args must be hashable"
                    )

    # --- (3): shape branching inside jitted scope -----------------------
    for fn in ctx.jitted:
        for node in walk_own(fn):
            if not isinstance(node, (ast.If, ast.IfExp)):
                continue
            for n in ast.walk(node.test):
                if isinstance(n, ast.Attribute) and n.attr == "shape":
                    yield node, (
                        f"Python branch on .shape inside jitted function "
                        f"'{fn.name}': every distinct shape re-traces and "
                        "recompiles — hoist the branch out of the compiled "
                        "function or make the kernel shape-polymorphic"
                    )
                    break
