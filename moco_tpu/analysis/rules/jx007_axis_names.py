"""JX007 — collective axis names vs the enclosing shard_map/pmap.

A collective naming an axis the surrounding `shard_map`/`pmap` does not
declare fails at trace time with an unbound-axis error — or, nastier,
silently binds to a DIFFERENT axis of the same mesh when names are
shuffled during a refactor (psum over 'model' where 'data' was meant
reduces over the wrong replica group and *runs*). The repo's axis names
live in `parallel/mesh.py` (`DATA_AXIS`/`MODEL_AXIS`) and must line up
between the decorator's PartitionSpecs and the collectives inside
(`parallel/shuffle.py`, `parallel/zero.py`, `parallel/dist.py`).

The check is conservative: axis tokens are compared symbolically
(`DATA_AXIS` to `DATA_AXIS`, "data" to "data", and constants resolve
through module-level NAME = "str" assignments). A spec expression that
cannot be resolved to PartitionSpec literals (e.g. built by a helper
function) leaves the axis set open and the wrap unchecked — no guessing.
"""

from __future__ import annotations

import ast
from typing import Optional

from moco_tpu.analysis.astutils import ModuleContext, jit_kind, qualname
from moco_tpu.analysis.engine import rule

_COLLECTIVES_AXIS_ARG1 = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "psum_scatter",
    "all_to_all", "ppermute", "pshuffle", "axis_size",
}
_COLLECTIVES_AXIS_ARG0 = {"axis_index"}


def _basename(qual: Optional[str]) -> str:
    return (qual or "").rsplit(".", 1)[-1]


def _is_pspec(qual: Optional[str]) -> bool:
    return qual is not None and (
        qual == "P" or _basename(qual) == "PartitionSpec"
    )


def _tokens_of(ctx: ModuleContext, expr: ast.AST) -> set[str]:
    """Axis tokens in a spec/axis expression: string values plus symbol
    names (symbols also resolve through module string constants, and —
    when a whole-program call graph is attached — through constants
    imported from another analyzed module)."""
    tokens: set[str] = set()
    prog = getattr(ctx, "program", None)
    for n in ast.walk(expr):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            tokens.add(n.value)
        elif isinstance(n, ast.Name):
            tokens.add(n.id)
            if n.id in ctx.constants:
                tokens.add(ctx.constants[n.id])
            elif prog is not None and n.id in ctx.imports:
                origin = ctx.imports[n.id]
                mod, _, const = origin.rpartition(".")
                other = prog.by_module.get(mod)
                if other is not None and const in other.constants:
                    tokens.add(other.constants[const])
    return tokens


def _spec_tokens(
    ctx: ModuleContext,
    expr: ast.AST,
    local_assigns: dict[str, ast.AST],
    depth: int = 0,
) -> tuple[set[str], bool]:
    """(declared axis tokens, closed?) for an in_specs/out_specs
    expression. Unresolvable names leave the world open."""
    tokens: set[str] = set()
    closed = True
    consumed: set[int] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            for fn_part in ast.walk(node.func):
                consumed.add(id(fn_part))
            if _is_pspec(qualname(node.func, ctx.imports)):
                for a in [*node.args, *[kw.value for kw in node.keywords]]:
                    tokens |= _tokens_of(ctx, a)
                    for part in ast.walk(a):
                        consumed.add(id(part))
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and id(node) not in consumed:
            if node.id in local_assigns and depth < 4:
                t, c = _spec_tokens(
                    ctx, local_assigns[node.id], local_assigns, depth + 1
                )
                tokens |= t
                closed &= c
            else:
                closed = False
    return tokens, closed


def _axis_expr(ctx: ModuleContext, call: ast.Call) -> Optional[ast.AST]:
    base = _basename(ctx.qual(call.func))
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    if base in _COLLECTIVES_AXIS_ARG0 and call.args:
        return call.args[0]
    if base in _COLLECTIVES_AXIS_ARG1 and len(call.args) >= 2:
        return call.args[1]
    return None


def _collectives(ctx: ModuleContext, fn: ast.FunctionDef):
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            base = _basename(ctx.qual(node.func))
            if base in _COLLECTIVES_AXIS_ARG1 | _COLLECTIVES_AXIS_ARG0:
                yield node


@rule("JX007", "collective axis name not declared by the enclosing shard_map/pmap")
def check(ctx: ModuleContext):
    # name -> RHS of simple assignments, per enclosing function + module
    module_assigns: dict[str, ast.AST] = {}
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            module_assigns[node.targets[0].id] = node.value

    def local_env(fn: Optional[ast.FunctionDef]) -> dict[str, ast.AST]:
        env = dict(module_assigns)
        if fn is not None:
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name
                ):
                    env[node.targets[0].id] = node.value
        return env

    # map each shard_map/pmap call to its enclosing function (for assigns)
    enclosing: dict[int, ast.FunctionDef] = {}
    for f in ctx.functions:
        for n in ast.walk(f):
            enclosing[id(n)] = f

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = jit_kind(ctx.qual(node.func))
        if kind not in ("shard_map", "pmap"):
            continue
        if not (node.args and isinstance(node.args[0], ast.Name)):
            continue
        defs = ctx.defs_by_name.get(node.args[0].id, [])
        if not defs:
            continue
        wrapped = defs[-1]
        env = local_env(enclosing.get(id(node)))

        declared: set[str] = set()
        closed = True
        if kind == "pmap":
            axis_kw = next(
                (kw.value for kw in node.keywords if kw.arg == "axis_name"), None
            )
            if axis_kw is not None:
                declared = _tokens_of(ctx, axis_kw)
            # pmap with no axis_name declares no named axis: any
            # collective inside is unbound — keep declared empty/closed
        else:
            spec_exprs = [
                kw.value
                for kw in node.keywords
                if kw.arg in ("in_specs", "out_specs")
            ]
            spec_exprs += node.args[2:4]
            if not spec_exprs:
                closed = False
            for expr in spec_exprs:
                t, c = _spec_tokens(ctx, expr, env)
                declared |= t
                closed &= c
        if not closed:
            continue
        for coll in _collectives(ctx, wrapped):
            axis = _axis_expr(ctx, coll)
            if axis is None:
                continue
            tokens = _tokens_of(ctx, axis)
            if not tokens:
                continue  # unresolvable axis expression: don't guess
            if tokens & declared:
                continue
            pretty = next(iter(sorted(tokens)))
            yield coll, (
                f"collective {_basename(ctx.qual(coll.func))}(axis={pretty!r}) "
                f"inside '{wrapped.name}' names an axis the enclosing "
                f"{kind} does not declare "
                f"(declared: {', '.join(sorted(declared)) or 'none'}) — "
                "unbound axis error, or a silent wrong-axis reduction after "
                "a rename"
            )
