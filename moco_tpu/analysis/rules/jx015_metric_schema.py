"""JX015: metric-schema consistency.

The metrics contract lives in `obs/schema.py`: every key a writer emits
must be covered by an explicit `FIELD_VALIDATORS` entry or a
`PREFIX_VALIDATORS` family, or `validate_file` silently waves it
through and the smoke gates prove nothing about it. The inverse drift
is just as real: a validator whose key no writer emits anymore is dead
weight that reads as coverage, and a prefix family every emission of
which is captured by longer families (or by nothing at all) is
shadowed — its validator can never run.

Three clauses over the program-wide contract registry
(`analysis/contracts.py`):

1. **emitted-but-unvalidated** — a literal metric key (or the literal
   head of an f-string family emission) stored into a payload dict with
   no explicit validator and no matching prefix family; anchored at the
   emission.
2. **dead validator** — an explicit `FIELD_VALIDATORS` key that is
   never emitted and whose literal appears nowhere outside the schema
   module; anchored at the schema entry. Only fires in the module that
   defines the validator table, so partial-tree runs stay quiet.
3. **dead/shadowed prefix family** — a `PREFIX_VALIDATORS` entry that
   is the longest match for NO emitted key or family head; anchored at
   the schema entry.

Validators come from the analyzed program when it defines the tables
(fixtures, the real schema module in whole-tree runs) and fall back to
importing `moco_tpu.obs.schema` for partial-tree runs, so the smoke
scripts' focused lint passes see the real contract.
"""

from __future__ import annotations

from moco_tpu.analysis import contracts
from moco_tpu.analysis.engine import rule


def _tables(reg):
    if reg.schema_paths:
        return reg.validator_keys(), reg.validator_prefixes()
    from moco_tpu.obs import schema

    return set(schema.FIELD_VALIDATORS), set(schema.PREFIX_VALIDATORS)


@rule("JX015", "metric key emitted without a schema validator, or dead/shadowed validator")
def check_metric_schema(ctx):
    reg = contracts.registry_for(ctx)
    fields, prefixes = _tables(reg)

    # 1) emissions in THIS module must be validated somewhere
    for item in reg.emitted_keys:
        if item.path != ctx.path:
            continue
        key = item.key
        if key in fields or any(key.startswith(p) for p in prefixes):
            continue
        yield (
            item.line,
            f"metric key {key!r} is emitted but no obs/schema.py validator "
            f"(field or prefix family) covers it",
        )
    for item in reg.emitted_prefixes:
        if item.path != ctx.path:
            continue
        head = item.prefix
        if any(head.startswith(p) for p in prefixes) or any(
            f.startswith(head) for f in fields
        ):
            continue
        yield (
            item.line,
            f"metric family {head!r}... is emitted but no obs/schema.py "
            f"prefix validator covers it",
        )

    # 2) + 3) anchor in the schema-defining module only
    if ctx.path not in reg.schema_paths:
        return

    emitted = {e.key for e in reg.emitted_keys}
    heads = {e.prefix for e in reg.emitted_prefixes}
    for item in reg.field_validators:
        if item.path != ctx.path:
            continue
        key = item.key
        live = (
            key in emitted
            or any(key.startswith(h) for h in heads)
            or any(
                p not in reg.schema_paths
                for p in reg.literal_strings.get(key, ())
            )
        )
        if not live:
            yield (
                item.line,
                f"validator for {key!r} is dead: no writer emits it and the "
                f"literal appears nowhere outside the schema module",
            )

    def longest(cands, value):
        hits = [p for p in cands if value.startswith(p)]
        return max(hits, key=len) if hits else None

    for item in reg.prefix_validators:
        if item.path != ctx.path:
            continue
        prefix = item.prefix
        selected = any(
            k not in fields and longest(prefixes, k) == prefix for k in emitted
        ) or any(longest(prefixes, h) == prefix for h in heads)
        if not selected:
            yield (
                item.line,
                f"prefix family {prefix!r} is the longest match for no emitted "
                f"key — dead, or fully shadowed by longer families",
            )
