"""JX013 — static lock-order cycles and blocking calls under a lock.

Two findings, both the "replica wedges with nothing to diagnose" class
the collective-schedule sanitizer exists for on the training side:

1. **Lock-order cycle** — the component's lock-order graph (lock A held
   while B is acquired ⇒ edge A→B, including acquisitions inside
   always-under-lock helpers) contains a cycle. Two threads walking the
   cycle from different entry points deadlock; no Python tool reports
   it, the process just stops serving. One finding per cycle, anchored
   at the lexically last acquisition in it.

2. **Blocking call under a lock** — `queue.put`/`get` with no timeout,
   `Event.wait()` with no timeout, `join()` with no timeout, HTTP I/O
   (`urlopen`), `time.sleep`, or a device sync (`block_until_ready` /
   `device_get`) issued while a lock is held. The blocked thread pins
   the lock; every thread contending for it stalls behind an operation
   with no bound — the held-lock flavor of the JX011 producer-leak.

The runtime arm (`analysis/tsan.py`, `--sanitize-threads`) watches the
same two invariants on live smoke runs; this rule catches the provable
cases before anything runs.
"""

from __future__ import annotations

from moco_tpu.analysis.astutils import ModuleContext
from moco_tpu.analysis.engine import rule
from moco_tpu.analysis.threads import component_models


def _sccs(nodes: set[str], edges: dict[str, set[str]]) -> list[set[str]]:
    """Strongly connected components with ≥ 2 nodes (iterative Tarjan
    is overkill at this scale: locks per class are single digits)."""
    reach: dict[str, set[str]] = {}
    for n in nodes:
        seen: set[str] = set()
        stack = [n]
        while stack:
            cur = stack.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        reach[n] = seen
    out: list[set[str]] = []
    claimed: set[str] = set()
    for n in sorted(nodes):
        if n in claimed:
            continue
        scc = {m for m in reach[n] if n in reach[m]}
        if len(scc) >= 2:
            out.append(scc)
            claimed |= scc
    return out


@rule("JX013", "lock-order cycle / blocking call while holding a lock")
def check(ctx: ModuleContext):
    for model in component_models(ctx):
        if model.lock_edges:
            nodes: set[str] = set()
            adj: dict[str, set[str]] = {}
            for e in model.lock_edges:
                nodes |= {e.held, e.acquired}
                adj.setdefault(e.held, set()).add(e.acquired)
            for scc in _sccs(nodes, adj):
                cycle_edges = [
                    e for e in model.lock_edges
                    if e.held in scc and e.acquired in scc
                ]
                anchor = max(cycle_edges, key=lambda e: getattr(e.node, "lineno", 0))
                order = " <-> ".join(sorted(scc))
                sites = ", ".join(
                    f"{e.held}->{e.acquired}@{getattr(e.node, 'lineno', '?')}"
                    for e in sorted(
                        cycle_edges, key=lambda e: getattr(e.node, "lineno", 0)
                    )
                )
                yield anchor.node, (
                    f"lock-order cycle in {model.name}: {order} "
                    f"(acquisitions: {sites}) — two threads entering from "
                    "different sides deadlock; pick ONE acquisition order "
                    "and apply it everywhere"
                )
        for b in model.blocking:
            locks = ", ".join(sorted(b.locks))
            yield b.node, (
                f"{b.desc} while holding {locks} in {model.name}.{b.method} — "
                "an unbounded wait pins the lock and stalls every contending "
                "thread; move the call outside the lock or bound it with a "
                "timeout"
            )
