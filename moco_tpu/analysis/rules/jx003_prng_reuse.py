"""JX003 — PRNG key reuse without an interleaving split/fold_in.

A JAX PRNG key passed to two samplers yields IDENTICAL randomness at
both sites — augmentations that repeat, dropout masks that correlate,
permutations that undo themselves. Correct code threads keys through
`jax.random.split` / `fold_in` so every consumer sees a fresh key.

What counts:
- key producers: `jax.random.PRNGKey/key`, the outputs of
  `split`/`fold_in`/`clone`, and function parameters whose name contains
  ``rng`` (the repo's naming idiom for keys);
- derivations: `fold_in(key, data)` never consumes (deriving many
  children from one parent with distinct data is the idiomatic pattern);
  `split(key)` consumes — calling it twice on the same key returns the
  same children;
- consumption: the key appearing as a direct argument to any other call.

The analysis is branch-aware (exclusive `if`/`else` arms don't sum) and
runs loop bodies twice, so a key consumed once per iteration without
re-derivation is caught.

Interprocedural since mocolint v2: a call to a RESOLVED function whose
dataflow summary proves it only DERIVES from its key parameter (a pure
`fold_in` wrapper) no longer counts as consumption — and a helper that
truly samples with the key still does. Unresolved calls keep the
conservative behavior (consume).
"""

from __future__ import annotations

import ast
import re

from moco_tpu.analysis.astutils import FlowVisitor, ModuleContext, stmt_exprs
from moco_tpu.analysis.engine import rule

_PRODUCERS = {"jax.random.PRNGKey", "jax.random.key", "jax.random.wrap_key_data"}
_DERIVE_NO_CONSUME = {"jax.random.fold_in", "jax.random.clone"}
_DERIVE_CONSUME = {"jax.random.split"}
_RNG_PARAM = re.compile(r"(^|_)rng(_|\d|$)|(^|_)prng(_|\d|$)")


class _KeyFlow(FlowVisitor):
    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.findings: list[tuple[ast.AST, str]] = []
        self._seen_lines: set[tuple[int, str]] = set()

    def enter_function(self, fn: ast.FunctionDef, state) -> None:
        args = fn.args
        for a in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
            if _RNG_PARAM.search(a.arg):
                state[a.arg] = (0, a.lineno)

    def fork(self, state):
        return dict(state)

    def merge(self, a, b):
        merged = dict(a)
        for name, (count, line) in b.items():
            if name in merged and merged[name][0] >= count:
                continue
            merged[name] = (count, line)
        return merged

    def _consume(self, name: str, node: ast.AST, state) -> None:
        count, line = state[name]
        if count >= 1:
            key = (node.lineno, name)
            if key not in self._seen_lines:
                self._seen_lines.add(key)
                self.findings.append(
                    (
                        node,
                        f"PRNG key '{name}' consumed again (previous use at "
                        f"line {line}) without an interleaving jax.random."
                        "split/fold_in — both sites see identical randomness",
                    )
                )
        state[name] = (count + 1, node.lineno)

    def _derive_only_params(self, node: ast.Call) -> set[str]:
        """Callee params the summary proves are derive-only (fold_in
        wrappers); empty when the call does not resolve."""
        prog = getattr(self.ctx, "program", None)
        if prog is None:
            return set()
        from moco_tpu.analysis.dataflow import build_summaries

        summary = build_summaries(prog).for_call(self.ctx, node, None)
        return set() if summary is None else summary.derives_only_rng_params

    def _scan_expr(self, expr: ast.AST, state) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            q = self.ctx.qual(node.func)
            if q in _DERIVE_NO_CONSUME:
                continue
            if q in _PRODUCERS:
                continue
            derive_only = self._derive_only_params(node)
            callee_params: list[str] = []
            if derive_only:
                prog = self.ctx.program
                info = prog.resolve_call(self.ctx, node, None)
                callee_params = info.param_names() if info is not None else []
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Name) and arg.id in state:
                    if i < len(callee_params) and callee_params[i] in derive_only:
                        continue  # proven pure derivation, not a use
                    self._consume(arg.id, node, state)
            for kw in node.keywords:
                arg = kw.value
                if isinstance(arg, ast.Name) and arg.id in state:
                    if kw.arg in derive_only:
                        continue
                    self._consume(arg.id, node, state)

    def visit_stmt(self, stmt: ast.stmt, state) -> None:
        for expr in stmt_exprs(stmt):
            self._scan_expr(expr, state)
        # (re)bindings AFTER consumption in the RHS
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            produces = isinstance(value, ast.Call) and self.ctx.qual(value.func) in (
                _PRODUCERS | _DERIVE_CONSUME | _DERIVE_NO_CONSUME
            )
            for t in targets:
                names = (
                    [t] if isinstance(t, ast.Name) else
                    [e for e in getattr(t, "elts", []) if isinstance(e, ast.Name)]
                )
                for n in names:
                    if produces:
                        state[n.id] = (0, n.lineno)
                    elif isinstance(value, ast.Name) and value.id in state:
                        state[n.id] = state[value.id]  # alias keeps the count
                    else:
                        state.pop(n.id, None)


@rule("JX003", "PRNG key consumed twice without an interleaving split/fold_in")
def check(ctx: ModuleContext):
    # nested defs are visited by the parent's flow walk (closures see the
    # parent's keys); start walks only at top-of-chain functions
    nested: set[ast.AST] = set()
    for g in ctx.functions:
        for n in ast.walk(g):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not g:
                nested.add(n)
    for fn in ctx.functions:
        if fn in nested:
            continue
        visitor = _KeyFlow(ctx)
        visitor.run(fn, {})
        yield from visitor.findings
