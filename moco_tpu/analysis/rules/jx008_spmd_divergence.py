"""JX008 — a collective issued under host-local control flow.

THE pod-deadlock bug class. SPMD correctness requires every process to
issue the same collectives in the same order: a collective reached
under a condition only SOME hosts satisfy leaves the others blocked in
the matching collective forever — no error, no timeout, just a hung pod
burning its reservation. PR 4 hit a live instance (the fleet stats
all_gather had to be re-keyed from per-host wall-clock state onto the
replicated log schedule so every host agrees on the gather steps).

Host-local sources (each process sees its own value):

- process identity: `jax.process_index()`, `os.getpid()`,
  `socket.gethostname()`;
- wall clock: `time.time()/perf_counter()/monotonic()`;
- environment reads, stdlib `random`;
- per-host counters: names matching io_retries / decode_failures /
  heartbeat / retries (the retry layer's and input wire's per-host
  state);
- exception handlers: an `except:` body runs only on the host where the
  exception fired — a collective inside one is divergent by
  construction.

The check is flow-aware (a name assigned from `jax.process_index()`
carries the taint into a later `if`) and interprocedural both ways: a
HELPER that returns a host-local value taints the caller's condition,
and a helper that ISSUES a collective (transitively, per the dataflow
summaries) counts as a collective at its call site.

Deterministic per-host branching with NO collective inside — `if
process_index == 0: log(...)` — is the correct idiom and stays silent.
"""

from __future__ import annotations

import ast
from typing import Optional

from moco_tpu.analysis.astutils import ModuleContext
from moco_tpu.analysis.engine import rule
from moco_tpu.analysis.dataflow import (
    COLLECTIVES_AXIS_ARG1,
    HOST_LOCAL_NAMES,
    basename,
    build_summaries,
    is_host_local_qual,
)


class _Walker:
    """Per-function walk threading host-taint through assignments and a
    stack of host-local conditions lexically in scope."""

    def __init__(self, ctx: ModuleContext, summaries):
        self.ctx = ctx
        self.summaries = summaries
        self.findings: list[tuple[ast.AST, str]] = []
        self._seen: set[int] = set()
        self.tainted: set[str] = set()

    # -- host-local taint of an expression -------------------------------

    def _expr_host_local(self, expr: ast.AST) -> Optional[str]:
        """A short description of the host-local source in `expr`, or
        None when the expression is replicated-safe."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Name):
                if n.id in self.tainted:
                    return f"'{n.id}' (host-local, assigned above)"
                if HOST_LOCAL_NAMES.search(n.id):
                    return f"'{n.id}' (per-host counter)"
            elif isinstance(n, ast.Attribute) and HOST_LOCAL_NAMES.search(n.attr):
                return f".{n.attr} (per-host counter)"
            elif isinstance(n, ast.Call):
                q = self.ctx.qual(n.func)
                if is_host_local_qual(q):
                    return f"{q}()"
                if self.summaries is not None:
                    s = self.summaries.for_call(self.ctx, n, None)
                    if s is not None and s.returns_host_local:
                        return f"{s.qualname}() (returns a host-local value)"
        return None

    # -- collectives in an expression ------------------------------------

    def _collectives_in(self, expr: ast.AST):
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            base = basename(self.ctx.qual(n.func))
            if base in COLLECTIVES_AXIS_ARG1:
                yield n, base
            elif self.summaries is not None:
                s = self.summaries.for_call(self.ctx, n, None)
                if s is not None and s.collectives:
                    kinds = sorted({u.kind for u in s.collectives})
                    yield n, f"{'/'.join(kinds)} via {s.qualname}()"

    def _flag(self, node: ast.AST, what: str, cond: str) -> None:
        if node.lineno in self._seen:
            return
        self._seen.add(node.lineno)
        self.findings.append(
            (
                node,
                f"collective {what} issued under host-local control flow "
                f"[{cond}] — hosts that take the other branch never enter "
                "the collective and the pod deadlocks silently; key the "
                "schedule on replicated state (see obs/fleet.py's "
                "log-schedule keying)",
            )
        )

    # -- statement walk ---------------------------------------------------

    def run(self, fn: ast.FunctionDef) -> None:
        self._block(fn.body, conds=[])

    def _scan(self, expr: ast.AST, conds: list[str]) -> None:
        if not conds:
            return
        for node, what in self._collectives_in(expr):
            self._flag(node, what, conds[-1])

    def _block(self, stmts: list[ast.stmt], conds: list[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.If, ast.While)):
                reason = self._expr_host_local(stmt.test)
                inner = conds + [f"condition depends on {reason}"] if reason else conds
                self._scan(stmt.test, conds)
                self._block(stmt.body, inner)
                self._block(stmt.orelse, inner)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                reason = self._expr_host_local(stmt.iter)
                inner = conds + [f"loop iterates over {reason}"] if reason else conds
                self._scan(stmt.iter, conds)
                self._block(stmt.body, inner)
                self._block(stmt.orelse, inner)
            elif isinstance(stmt, ast.Try):
                self._block(stmt.body, conds)
                for handler in stmt.handlers:
                    self._block(
                        handler.body,
                        conds + ["inside an exception handler (exceptions fire per host)"],
                    )
                self._block(stmt.orelse, conds)
                self._block(stmt.finalbody, conds)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._scan(item.context_expr, conds)
                self._block(stmt.body, conds)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested defs inherit the enclosing conditions: a closure
                # defined under a host-local branch still diverges when
                # called from there
                self._block(stmt.body, conds)
            elif isinstance(stmt, ast.ClassDef):
                continue
            else:
                # taint threading, then sinks
                if isinstance(stmt, ast.Assign) and stmt.value is not None:
                    reason = self._expr_host_local(stmt.value)
                    for tgt in stmt.targets:
                        names = (
                            [tgt] if isinstance(tgt, ast.Name)
                            else [e for e in getattr(tgt, "elts", []) if isinstance(e, ast.Name)]
                        )
                        for nm in names:
                            if reason:
                                self.tainted.add(nm.id)
                            else:
                                self.tainted.discard(nm.id)
                # ternaries count as conditions too
                for n in ast.walk(stmt):
                    if isinstance(n, ast.IfExp):
                        reason = self._expr_host_local(n.test)
                        if reason:
                            for cnode, what in self._collectives_in(n.body):
                                self._flag(cnode, what, f"condition depends on {reason}")
                            for cnode, what in self._collectives_in(n.orelse):
                                self._flag(cnode, what, f"condition depends on {reason}")
                self._scan(stmt, conds)


@rule("JX008", "collective issued under host-local control flow (SPMD divergence/deadlock)")
def check(ctx: ModuleContext):
    prog = getattr(ctx, "program", None)
    summaries = build_summaries(prog) if prog is not None else None
    nested: set[ast.AST] = set()
    for g in ctx.functions:
        for n in ast.walk(g):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not g:
                nested.add(n)
    for fn in ctx.functions:
        if fn in nested:
            continue
        w = _Walker(ctx, summaries)
        w.run(fn)
        yield from w.findings
