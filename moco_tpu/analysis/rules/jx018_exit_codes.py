"""JX018: single-source exit-code and port-offset constants.

The magic numbers 42 (watchdog stall), 75 (elastic rescale) and 113
(chaos kill) are load-bearing: harnesses gate on them, supervisors
dispatch on them. They live in `utils/contracts.py` (`EXIT_CODES`);
re-typing one inline means the next renumbering silently breaks every
copy. Same story for the port-offset rule: `base + process_index` (and
the `SERVE_PORT_STRIDE` collision shift) is implemented exactly once,
by `obs/sinks.py` `derive_metrics_port`/`resolve_serve_port` — a
hand-computed offset elsewhere will disagree with the resolver the
moment the collision rule changes.

Flagged shapes:

- an exit call (`sys.exit`/`os._exit`/`SystemExit`/`exit`) with an
  inline 42/75/113;
- a comparison of 42/75/113 against something exit-ish (`rc`,
  `returncode`, `exit`, `code`, `status` in the other operand);
- an exit-ish keyword (`expect_rc=`, `rc=`, `returncode=`,
  `exit_code=`) passed an inline code;
- `<something>port</something> + <something>index</something>`
  arithmetic, or any arithmetic on `SERVE_PORT_STRIDE`, outside the two
  sanctioned resolver functions.

The registry module itself is exempt (it is the single source).
"""

from __future__ import annotations

import ast
import re

from moco_tpu.analysis.engine import rule
from moco_tpu.utils import contracts as decl

_EXIT_CALLS = ("exit", "_exit", "SystemExit")
_EXIT_KWARGS = ("expect_rc", "expected_rc", "rc", "returncode", "exit_code")
_EXITISH_RE = re.compile(r"\b(rc|returncode|exitcode|exit_code|exit|code|status)\b")


def _last_segment(node) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _portish(node) -> bool:
    return "port" in _last_segment(node).lower()


def _indexish(node) -> bool:
    seg = _last_segment(node).lower()
    return seg in ("pidx", "rank") or seg.endswith("index")


def _strideish(node) -> bool:
    return _last_segment(node) == "SERVE_PORT_STRIDE"


@rule("JX018", "inline exit-code literal or hand-computed port offset — use the shared constants")
def check_exit_codes(ctx):
    if ctx.path.replace("\\", "/").endswith("utils/contracts.py"):
        return
    codes = set(decl.EXIT_CODES.values())
    by_code = {v: k for k, v in decl.EXIT_CODES.items()}

    def const_name(val: int) -> str:
        return {
            "stall": "STALL_EXIT_CODE",
            "rescale": "RESCALE_EXIT_CODE",
            "kill": "KILL_EXIT_CODE",
        }[by_code[val]]

    sanctioned: list[tuple[int, int]] = [
        (f.lineno, getattr(f, "end_lineno", f.lineno))
        for f in ctx.functions
        if f.name in ("derive_metrics_port", "resolve_serve_port")
    ]

    def in_sanctioned(line: int) -> bool:
        return any(a <= line <= b for a, b in sanctioned)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            base = (ctx.qual(node.func) or "").rsplit(".", 1)[-1]
            if base in _EXIT_CALLS:
                for a in node.args:
                    if isinstance(a, ast.Constant) and a.value in codes:
                        yield (
                            node.lineno,
                            f"inline exit code {a.value} — use "
                            f"utils/contracts.{const_name(a.value)}",
                        )
            for kw in node.keywords:
                if (
                    kw.arg in _EXIT_KWARGS
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value in codes
                ):
                    yield (
                        node.lineno,
                        f"inline exit code {kw.value.value} passed as "
                        f"{kw.arg}= — use utils/contracts."
                        f"{const_name(kw.value.value)}",
                    )
        elif isinstance(node, ast.Compare) and len(node.comparators) == 1:
            sides = (node.left, node.comparators[0])
            for a, b in (sides, sides[::-1]):
                if (
                    isinstance(a, ast.Constant)
                    and a.value in codes
                    and not isinstance(b, ast.Constant)
                    and _EXITISH_RE.search(ast.unparse(b).lower())
                ):
                    yield (
                        node.lineno,
                        f"exit code {a.value} compared inline — use "
                        f"utils/contracts.{const_name(a.value)}",
                    )
                    break
        elif isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Sub)):
            if in_sanctioned(node.lineno):
                continue
            l, r = node.left, node.right
            if _strideish(l) or _strideish(r):
                yield (
                    node.lineno,
                    "arithmetic on SERVE_PORT_STRIDE outside the sanctioned "
                    "resolver — use obs/sinks.resolve_serve_port",
                )
            elif (_portish(l) and _indexish(r)) or (_indexish(l) and _portish(r)):
                yield (
                    node.lineno,
                    "hand-computed port offset — use obs/sinks."
                    "derive_metrics_port / resolve_serve_port",
                )
