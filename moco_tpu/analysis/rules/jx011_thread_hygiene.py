"""JX011 — input-wire thread hygiene: join-on-close and poison pills.

The input wire runs on real threads (`data/pipeline.py`'s decode
producer, `data/device_prefetch.py`'s transfer ring), and PR 5's
producer-leak fix documents the failure mode this rule now enforces
statically: a producer thread blocked on a bounded `queue.Queue.put`
keeps its owner alive forever when the consumer abandons the iterator —
the decode pool stays pinned, epochs leak a thread each, and a
"graceful" shutdown hangs in `join()` that never comes.

Two findings:

1. **Thread without join-on-close** — a `threading.Thread(...)` that is
   `.start()`ed but whose binding is never `.join(...)`ed anywhere in
   the owning scope (the class for `self._thread`, the function for a
   local). Daemon threads are not exempt: daemonhood avoids blocking
   interpreter EXIT, not resource pinning during the run (a server
   thread's owner must `shutdown()` AND join in `close()`; see
   obs/sinks.py).

2. **Blocking put with no poison-pill path** — a `.put(item)` with no
   `timeout=` (and not `put_nowait`) on a BOUNDED queue (`maxsize`
   nonzero) owned by the same scope that also owns a thread. The
   repo-idiomatic fix is `_responsive_put` (timeout + stop-flag poll)
   or a drain-then-pill `close()` (`data/pipeline.py`).

Unbounded queues (`Queue()` / `maxsize=0`) never block a put and are
exempt from (2).
"""

from __future__ import annotations

import ast
from typing import Optional

from moco_tpu.analysis.astutils import ModuleContext
from moco_tpu.analysis.engine import rule


def _is_thread_ctor(ctx: ModuleContext, call: ast.Call) -> bool:
    q = ctx.qual(call.func)
    return q is not None and (q == "threading.Thread" or q.endswith(".Thread") or q == "Thread")


def _is_bounded_queue_ctor(ctx: ModuleContext, call: ast.Call) -> bool:
    q = ctx.qual(call.func)
    if q is None or not (q == "queue.Queue" or q.endswith(".Queue")):
        return False
    # Queue() and Queue(maxsize=0) are unbounded
    for kw in call.keywords:
        if kw.arg == "maxsize":
            return not (isinstance(kw.value, ast.Constant) and kw.value.value == 0)
    if call.args:
        arg = call.args[0]
        return not (isinstance(arg, ast.Constant) and arg.value == 0)
    return False


def _binding_of(target: ast.AST) -> Optional[str]:
    """'self.x' or 'x' for the assignment target, else None."""
    if isinstance(target, ast.Name):
        return target.id
    if (
        isinstance(target, ast.Attribute)
        and isinstance(target.value, ast.Name)
        and target.value.id == "self"
    ):
        return f"self.{target.attr}"
    return None


def _method_calls_on(scope: ast.AST, binding: str) -> set[str]:
    """Method names invoked on `binding` anywhere in `scope`."""
    out: set[str] = set()
    want_self = binding.startswith("self.")
    attr = binding[5:] if want_self else None
    for n in ast.walk(scope):
        if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)):
            continue
        recv = n.func.value
        if want_self:
            if (
                isinstance(recv, ast.Attribute)
                and recv.attr == attr
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
            ):
                out.add(n.func.attr)
        elif isinstance(recv, ast.Name) and recv.id == binding:
            out.add(n.func.attr)
    return out


def _scopes(ctx: ModuleContext):
    """(scope node, owner description) for classes, top-level functions,
    and the module body — the unit within which join/close must exist."""
    claimed: set[int] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            yield node, f"class {node.name}"
            for sub in ast.walk(node):
                claimed.add(id(sub))
    for fn in ctx.functions:
        if id(fn) not in claimed:
            yield fn, f"function {fn.name}"
            for sub in ast.walk(fn):
                claimed.add(id(sub))
    yield ctx.tree, "module scope"


@rule("JX011", "thread started without join-on-close / blocking put with no poison-pill path")
def check(ctx: ModuleContext):
    reported: set[int] = set()
    for scope, owner in _scopes(ctx):
        threads: list[tuple[str, ast.Call]] = []
        bounded_queues: set[str] = set()
        for node in ast.walk(scope):
            if id(node) in reported:
                continue
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                for tgt in node.targets:
                    binding = _binding_of(tgt)
                    if binding is None:
                        continue
                    if _is_thread_ctor(ctx, node.value):
                        threads.append((binding, node.value))
                    elif _is_bounded_queue_ctor(ctx, node.value):
                        bounded_queues.add(binding)
            # anonymous fire-and-forget: threading.Thread(...).start()
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "start"
                and isinstance(node.func.value, ast.Call)
                and _is_thread_ctor(ctx, node.func.value)
            ):
                reported.add(id(node))
                yield node, (
                    "threading.Thread(...).start() with no binding can never "
                    "be joined — keep a reference and join it on close "
                    "(abandoned threads pin their closure's resources; see "
                    "data/pipeline.py's producer-leak fix)"
                )
        for binding, ctor in threads:
            if id(ctor) in reported:
                continue
            calls = _method_calls_on(scope, binding)
            if "start" in calls and "join" not in calls:
                reported.add(id(ctor))
                yield ctor, (
                    f"thread '{binding}' is started but never joined in "
                    f"{owner} — add a close()/stop() that joins it (daemon=True "
                    "only unblocks interpreter exit, not the resources the "
                    "thread pins while the run continues)"
                )
        if not threads and not bounded_queues:
            continue
        # blocking puts on bounded queues in thread-owning scopes
        for node in ast.walk(scope):
            if id(node) in reported:
                continue
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "put"
            ):
                continue
            recv = _binding_of(node.func.value)
            if recv is None or recv not in bounded_queues:
                continue
            if any(kw.arg in ("timeout", "block") for kw in node.keywords):
                continue
            if len(node.args) > 1:  # put(item, block, timeout) positional
                continue
            reported.add(id(node))
            yield node, (
                f"blocking put() on bounded queue '{recv}' — a consumer that "
                "stops draining leaves this producer blocked forever and "
                "close()/join() hangs; use a timeout + stop-flag poll "
                "(_responsive_put in data/pipeline.py) or a drain-then-"
                "poison-pill close()"
            )
