"""JX009 — bf16/f16 operands reaching a reduction without f32 accumulation.

On TPU the MXU natively accumulates bf16 matmuls in f32 — but ONLY when
asked: `preferred_element_type=jnp.float32`. Without it, XLA is free to
accumulate a `bf16 @ bf16` product in bf16, and at MoCo scale the
damage is quantified: a 65536-key InfoNCE logit row sums 128-dim
products whose bf16 accumulation drifts ~1e-2 — enough to reorder
logits near the temperature scale. Same story for cross-replica `psum`
of bf16 gradients: each hop rounds to bf16, and an 8-host ring loses
~3 bits of mantissa on the way around. The repo's own kernels
(`ops/fused_infonce.py`, `ops/flash_attention.py`) all pass
`preferred_element_type=jnp.float32`; this rule keeps every new
matmul/einsum/psum site honest.

What counts as a low-precision value: anything routed through a
`bfloat16`/`float16` cast or dtype argument (`x.astype(jnp.bfloat16)`,
`jnp.asarray(x, "bfloat16")`, `dtype=compute_dtype` where the local
binding mentions bf16). An `.astype(jnp.float32)` rebinding cleans.

Sinks:
- `jnp.matmul`/`jnp.dot`/`jnp.einsum`/`lax.dot_general`/`@` with a
  low-precision operand and no `preferred_element_type` kwarg;
- `lax.psum`/`pmean`/`psum_scatter` on a low-precision operand (cast up
  before the reduction, down after — the wire cost is the point of
  bf16; the ACCUMULATION is not where to save).
"""

from __future__ import annotations

import ast
from typing import Optional

from moco_tpu.analysis.astutils import ModuleContext, walk_own
from moco_tpu.analysis.engine import rule
from moco_tpu.analysis.dataflow import basename

_LOW_TOKENS = ("bfloat16", "float16", "bf16", "fp16", "half")
_HIGH_TOKENS = ("float32", "f32", "float64")
_MATMUL_SINKS = {"matmul", "dot", "einsum", "dot_general", "tensordot"}
_REDUCE_SINKS = {"psum", "pmean", "psum_scatter"}


def _mentions(expr: ast.AST, tokens: tuple[str, ...]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and n.attr in tokens:
            return True
        if isinstance(n, ast.Constant) and isinstance(n.value, str) and n.value in tokens:
            return True
        if isinstance(n, ast.Name) and n.id in tokens:
            return True
    return False


def _has_preferred(call: ast.Call) -> bool:
    return any(kw.arg == "preferred_element_type" for kw in call.keywords)


class _PrecisionFlow:
    """Ordered walk of one function: names bound to low-precision values
    flow into sinks; `.astype(float32)` rebindings clean."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.findings: list[tuple[ast.AST, str]] = []
        self._seen: set[int] = set()
        self.low: set[str] = set()

    def _expr_low(self, expr: ast.AST) -> Optional[str]:
        """Name/description of a low-precision source in `expr`."""
        if _mentions(expr, _HIGH_TOKENS) and not _mentions(expr, _LOW_TOKENS):
            return None  # explicit f32 routing wins
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in self.low:
                return n.id
            if isinstance(n, ast.Call):
                # x.astype(jnp.bfloat16) / jnp.asarray(x, "bfloat16") /
                # cast-through-a-low-binding (dtype=compute_dtype)
                for arg in [*n.args, *[kw.value for kw in n.keywords]]:
                    if _mentions(arg, _LOW_TOKENS) or (
                        isinstance(arg, ast.Name) and arg.id in self.low
                    ):
                        return "a bf16/f16 cast"
        return None

    def _flag(self, node: ast.AST, sink: str, source: str, advice: str) -> None:
        if node.lineno in self._seen:
            return
        self._seen.add(node.lineno)
        self.findings.append(
            (
                node,
                f"low-precision operand ({source}) reaches {sink} without "
                f"f32 accumulation — {advice}",
            )
        )

    def run(self, fn: ast.FunctionDef) -> None:
        args = fn.args
        for a in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
            if any(t in a.arg for t in ("bf16", "fp16", "half")):
                self.low.add(a.arg)
        nodes = sorted(
            walk_own(fn),
            key=lambda n: (getattr(n, "lineno", 0), getattr(n, "col_offset", 0)),
        )
        for node in nodes:
            if isinstance(node, ast.Assign) and node.value is not None:
                src = self._expr_low(node.value)
                for tgt in node.targets:
                    names = (
                        [tgt] if isinstance(tgt, ast.Name)
                        else [e for e in getattr(tgt, "elts", []) if isinstance(e, ast.Name)]
                    )
                    for nm in names:
                        if src:
                            self.low.add(nm.id)
                        else:
                            self.low.discard(nm.id)
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                for side in (node.left, node.right):
                    src = self._expr_low(side)
                    if src:
                        self._flag(
                            node, "an `@` matmul", src,
                            "use jnp.matmul(..., preferred_element_type=jnp.float32) "
                            "or cast the operands up",
                        )
            elif isinstance(node, ast.Call):
                base = basename(self.ctx.qual(node.func))
                if base in _MATMUL_SINKS and not _has_preferred(node):
                    for arg in node.args:
                        src = self._expr_low(arg)
                        if src:
                            self._flag(
                                node, f"{base}()", src,
                                "pass preferred_element_type=jnp.float32 (MXU "
                                "accumulates bf16 in f32 only when asked; see "
                                "ops/fused_infonce.py)",
                            )
                            break
                elif base in _REDUCE_SINKS:
                    for arg in node.args[:1]:
                        src = self._expr_low(arg)
                        if src:
                            self._flag(
                                node, f"lax.{base}()", src,
                                "cast up before the cross-replica reduction "
                                "(each ring hop rounds to bf16) and down after",
                            )


@rule("JX009", "bf16/f16 operand reaches matmul/einsum/psum without f32 accumulation")
def check(ctx: ModuleContext):
    # every function analyzed as its own scope (walk_own stops at nested
    # defs, so inner step functions get their own fresh flow)
    for fn in ctx.functions:
        flow = _PrecisionFlow(ctx)
        flow.run(fn)
        yield from flow.findings
