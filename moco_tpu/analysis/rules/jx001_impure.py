"""JX001 — impure calls inside jit/shard_map-compiled functions.

`time.*`, stdlib `random.*`, `print`, and `global` mutation execute at
TRACE time only: the compiled program replays their first-call result
(or nothing at all) on every subsequent step. A `time.perf_counter()`
inside the step measures tracing, not the step; stdlib `random` bakes
one sample into the executable; `print` fires once and then never again
(and `jax.debug.print` is the working alternative). All of these are
silent on CPU smoke runs and wrong on TPU.
"""

from __future__ import annotations

import ast

from moco_tpu.analysis.astutils import ModuleContext, walk_own
from moco_tpu.analysis.engine import rule

# dotted-prefix -> why it's impure under trace
_IMPURE_PREFIXES = {
    "time.": "executes at trace time only (timing the trace, not the step)",
    "random.": "stdlib RNG is baked in at trace time — use jax.random with an explicit key",
    "os.environ": "environment reads are frozen at trace time",
}


@rule("JX001", "impure call (time.*/random.*/print/global mutation) inside jitted scope")
def check(ctx: ModuleContext):
    for fn in ctx.jitted:
        for node in walk_own(fn):
            if isinstance(node, ast.Global):
                yield node, (
                    f"`global {', '.join(node.names)}` inside jitted function "
                    f"'{fn.name}': mutation happens once at trace time, never "
                    "per step — thread state through the function instead"
                )
            if not isinstance(node, ast.Call):
                continue
            q = ctx.qual(node.func)
            if q is None:
                continue
            if q == "print" and "print" not in ctx.imports:
                yield node, (
                    f"print() inside jitted function '{fn.name}' fires only at "
                    "trace time — use jax.debug.print for per-step output"
                )
                continue
            for prefix, why in _IMPURE_PREFIXES.items():
                if q == prefix.rstrip(".") or q.startswith(prefix):
                    yield node, (
                        f"impure call {q}() inside jitted function "
                        f"'{fn.name}': {why}"
                    )
                    break
