"""JX002 — implicit host transfer on traced values inside jitted scope.

`float(x)`, `int(x)`, `bool(x)`, `np.asarray(x)`, and `x.item()` on a
traced array force a device->host sync: under `jit` they either raise a
`ConcretizationTypeError` at trace time or — worse, when they sneak into
a shape/static position — silently serialize the pipeline every step.
The hot path must be transfer-free; cast with `jnp.asarray`/`astype` and
read scalars on the host side of the step boundary (as the train driver
does on log steps only).

Shape-derived casts (`int(x.shape[0])`) and literal casts are static and
exempt.

Interprocedural since mocolint v2: jitted scope closes over RESOLVED
call edges program-wide (`callgraph.Program.jitted`), so a helper in
another module called from the compiled step is in scope — the
`float(loss)` two files away from the `@jax.jit` is exactly the one
review misses.
"""

from __future__ import annotations

import ast

from moco_tpu.analysis.astutils import ModuleContext, walk_own
from moco_tpu.analysis.engine import rule


def jitted_functions(ctx: ModuleContext) -> list[ast.FunctionDef]:
    """This module's functions in jitted scope: the module-local closure
    plus, when a whole-program call graph is attached, any function
    reached from a jitted root in ANOTHER module."""
    prog = getattr(ctx, "program", None)
    if prog is None:
        return sorted(ctx.jitted, key=lambda f: f.lineno)
    out = set(ctx.jitted)
    for fn in ctx.functions:
        if prog.in_jitted_scope(fn):
            out.add(fn)
    return sorted(out, key=lambda f: f.lineno)

_CAST_BUILTINS = {"float", "int", "bool"}
_NUMPY_SINKS = {
    "numpy.asarray",
    "numpy.array",
    "numpy.float32",
    "numpy.float64",
    "numpy.int32",
    "numpy.int64",
}
_HOST_METHODS = {"item", "tolist", "block_until_ready"}


def _is_static_cast(arg: ast.AST) -> bool:
    """Casts of literals or of anything shape-derived are trace-static."""
    if isinstance(arg, ast.Constant):
        return True
    for n in ast.walk(arg):
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim", "size", "dtype"):
            return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) and n.func.id == "len":
            return True
    return False


@rule("JX002", "implicit host transfer (float()/int()/bool()/np.asarray/.item()) in jitted scope")
def check(ctx: ModuleContext):
    for fn in jitted_functions(ctx):
        for node in walk_own(fn):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_METHODS
                and not node.args
            ):
                yield node, (
                    f".{node.func.attr}() inside jitted function '{fn.name}' "
                    "forces a device->host transfer per step — keep scalars on "
                    "device and fetch them outside the compiled region"
                )
                continue
            q = ctx.qual(node.func)
            if q in _CAST_BUILTINS and q not in ctx.imports and len(node.args) == 1:
                if not _is_static_cast(node.args[0]):
                    yield node, (
                        f"{q}() on a traced value inside jitted function "
                        f"'{fn.name}' is a host sync (or a trace error) — use "
                        f"jnp casts / astype and read scalars outside the step"
                    )
            elif q in _NUMPY_SINKS:
                yield node, (
                    f"{q}() inside jitted function '{fn.name}' materializes a "
                    "host array mid-trace — use jnp.asarray on device instead"
                )
