"""JX005 — key-encoder / queue tensors reaching a loss without stop_gradient.

THE MoCo invariant (He et al., arXiv:1911.05722): the key encoder is
updated only by EMA; no gradient may flow into `params_k` or the
negative queue. In torch the reference enforces it with
`torch.no_grad()` blocks; functionally there is no such scope — a key
embedding that reaches the InfoNCE matmul un-stopped silently turns
MoCo into end-to-end contrastive learning with a stale tower, which
*trains* (loss falls!) but learns the wrong thing. Nothing at runtime
catches it.

Known-good sanitizing patterns this rule models:
- `ops/losses.py:36` — `infonce_logits` stop-gradients `k` and `queue`
  internally before the einsums;
- `core/queue.py:37` — `enqueue` stop-gradients the key block before
  the FIFO write.

Taint: values produced from `params_k` / `batch_stats_k` arguments, or
named `queue`. Sanitization: passing through `stop_gradient` (a
rebinding like ``k = lax.stop_gradient(k)`` cleans the name).
Sinks: `@` matmuls, `einsum` calls, and `cross_entropy` calls whose
operand is tainted-and-unsanitized.
"""

from __future__ import annotations

import ast

from moco_tpu.analysis.astutils import FlowVisitor, ModuleContext, stmt_exprs
from moco_tpu.analysis.engine import rule

# attribute reads of these are ALWAYS tainted (state.params_k can't be
# sanitized in place); bare local names track through the flow state so
# a `queue = stop_gradient(queue)` rebinding clears them
_TAINT_ATTRS = {"params_k", "batch_stats_k", "queue"}
_TAINT_PARAMS = {"params_k", "batch_stats_k", "queue"}

# helpers that stop-gradient their key/queue inputs internally — the
# known-good patterns; values built through them are clean
_SANITIZERS = ("stop_gradient", "infonce_logits", "enqueue", "fused_infonce_loss")


def _terminal_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _sanitized(ctx: ModuleContext, expr: ast.AST) -> bool:
    """Does `expr` route its tensors through stop_gradient (or one of the
    helpers known to apply it internally)?"""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            q = ctx.qual(n.func)
            if q and (q in _SANITIZERS or q.endswith(tuple("." + s for s in _SANITIZERS))):
                return True
    return False


class _TaintFlow(FlowVisitor):
    """state: name -> line where it became tainted."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.findings: list[tuple[ast.AST, str]] = []
        self._seen: set[int] = set()

    def enter_function(self, fn: ast.FunctionDef, state) -> None:
        args = fn.args
        for a in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
            if a.arg in _TAINT_PARAMS:
                state[a.arg] = a.lineno

    def fork(self, state):
        return dict(state)

    def merge(self, a, b):
        return {**b, **a}

    def _tainted_in(self, expr: ast.AST, state) -> str | None:
        """First tainted name occurring in `expr`, unless the expression
        routes through stop_gradient / a sanitizing helper."""
        if _sanitized(self.ctx, expr):
            return None
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in state:
                return n.id
            if isinstance(n, ast.Attribute) and n.attr in _TAINT_ATTRS:
                return n.attr
        return None

    def _source_taints(self, expr: ast.AST, state) -> bool:
        """Does evaluating `expr` produce a key-derived value?  True for
        calls taking params_k/batch_stats_k/queue, direct reads of them,
        reads of tainted locals — unless routed through a sanitizer."""
        return self._tainted_in(expr, state) is not None

    def _flag(self, node: ast.AST, name: str, what: str) -> None:
        if node.lineno in self._seen:
            return
        self._seen.add(node.lineno)
        self.findings.append(
            (
                node,
                f"key-encoder/queue tensor '{name}' flows into {what} without "
                "stop_gradient — gradients would leak into the EMA tower "
                "(MoCo invariant; see ops/losses.py:36, core/queue.py:37 for "
                "the sanitizing patterns)",
            )
        )

    def _scan_sinks(self, expr: ast.AST, state) -> bool:
        fired = False
        for node in ast.walk(expr):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                for side in (node.left, node.right):
                    name = self._tainted_in(side, state)
                    if name:
                        self._flag(node, name, "a matmul feeding the loss")
                        fired = True
            elif isinstance(node, ast.Call):
                q = self.ctx.qual(node.func) or ""
                if q == "einsum" or q.endswith(".einsum"):
                    for arg in node.args[1:]:  # skip the spec string
                        name = self._tainted_in(arg, state)
                        if name:
                            self._flag(node, name, "an einsum feeding the loss")
                            fired = True
                elif q == "cross_entropy" or q.endswith(".cross_entropy"):
                    for arg in node.args:
                        name = self._tainted_in(arg, state)
                        if name:
                            self._flag(node, name, "cross_entropy")
                            fired = True
        return fired

    def visit_stmt(self, stmt: ast.stmt, state) -> None:
        fired = False
        for expr in stmt_exprs(stmt):
            fired = self._scan_sinks(expr, state) or fired
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            taints = not fired and value is not None and self._source_taints(value, state)
            for t in targets:
                names = (
                    [t] if isinstance(t, ast.Name) else
                    [e for e in getattr(t, "elts", []) if isinstance(e, ast.Name)]
                )
                for n in names:
                    if taints:
                        state[n.id] = n.lineno
                    else:
                        state.pop(n.id, None)


@rule("JX005", "key-encoder/queue tensor reaches a loss without stop_gradient")
def check(ctx: ModuleContext):
    nested: set[ast.AST] = set()
    for g in ctx.functions:
        for n in ast.walk(g):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not g:
                nested.add(n)
    for fn in ctx.functions:
        if fn in nested:
            continue
        visitor = _TaintFlow(ctx)
        visitor.run(fn, {})
        yield from visitor.findings
