"""JX005 — key-encoder / queue tensors reaching a loss without stop_gradient.

THE MoCo invariant (He et al., arXiv:1911.05722): the key encoder is
updated only by EMA; no gradient may flow into `params_k` or the
negative queue. In torch the reference enforces it with
`torch.no_grad()` blocks; functionally there is no such scope — a key
embedding that reaches the InfoNCE matmul un-stopped silently turns
MoCo into end-to-end contrastive learning with a stale tower, which
*trains* (loss falls!) but learns the wrong thing. Nothing at runtime
catches it.

Known-good sanitizing patterns this rule models:
- `ops/losses.py:36` — `infonce_logits` stop-gradients `k` and `queue`
  internally before the einsums;
- `core/queue.py:37` — `enqueue` stop-gradients the key block before
  the FIFO write.

Taint: values produced from `params_k` / `batch_stats_k` arguments, or
named `queue`. Sanitization: passing through `stop_gradient` (a
rebinding like ``k = lax.stop_gradient(k)`` cleans the name).
Sinks: `@` matmuls, `einsum` calls, and `cross_entropy` calls whose
operand is tainted-and-unsanitized.

Interprocedural since mocolint v2 (the MoCo chain flows ACROSS
`core/moco.py` → `ops/losses.py` → `core/queue.py`):

- a call to a resolved helper whose dataflow summary says its return
  carries its argument's taint (``k = encode(params_k, x)``) taints the
  result even though the helper lives in another module;
- a helper whose summary proves it sanitizes (routes its return through
  `stop_gradient`) cleans, without being on the hard-coded list;
- passing a tainted value to a helper parameter that the summary shows
  reaching a matmul/einsum/cross_entropy inside the callee fires AT THE
  CALL SITE — the cross-function violation the per-function pass was
  blind to.
"""

from __future__ import annotations

import ast
from typing import Optional

from moco_tpu.analysis.astutils import FlowVisitor, ModuleContext, stmt_exprs
from moco_tpu.analysis.engine import rule

# attribute reads of these are ALWAYS tainted (state.params_k can't be
# sanitized in place); bare local names track through the flow state so
# a `queue = stop_gradient(queue)` rebinding clears them
_TAINT_ATTRS = {"params_k", "batch_stats_k", "queue"}
_TAINT_PARAMS = {"params_k", "batch_stats_k", "queue"}

# helpers that stop-gradient their key/queue inputs internally — the
# known-good patterns; values built through them are clean
_SANITIZERS = ("stop_gradient", "infonce_logits", "enqueue", "fused_infonce_loss")


def _bind_args(call: ast.Call, param_names: list[str]) -> list[tuple[str, ast.AST]]:
    """(callee param name, argument expr) pairs for a resolved call."""
    out: list[tuple[str, ast.AST]] = []
    for i, arg in enumerate(call.args):
        if i < len(param_names):
            out.append((param_names[i], arg))
    for kw in call.keywords:
        if kw.arg:
            out.append((kw.arg, kw.value))
    return out


def _terminal_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _sanitized(ctx: ModuleContext, expr: ast.AST) -> bool:
    """Does `expr` route its tensors through stop_gradient (or one of the
    helpers known to apply it internally)?"""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            q = ctx.qual(n.func)
            if q and (q in _SANITIZERS or q.endswith(tuple("." + s for s in _SANITIZERS))):
                return True
    return False


class _TaintFlow(FlowVisitor):
    """state: name -> line where it became tainted."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.findings: list[tuple[ast.AST, str]] = []
        self._seen: set[int] = set()
        self._summaries = None
        prog = getattr(ctx, "program", None)
        if prog is not None:
            from moco_tpu.analysis.dataflow import build_summaries

            self._summaries = build_summaries(prog)

    def _callee(self, call: ast.Call):
        """(summary, param_names) for a resolved call, else (None, [])."""
        if self._summaries is None:
            return None, []
        info = self.ctx.program.resolve_call(self.ctx, call, None)
        if info is None:
            return None, []
        return self._summaries.get(info.qualname), info.param_names()

    def enter_function(self, fn: ast.FunctionDef, state) -> None:
        args = fn.args
        for a in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
            if a.arg in _TAINT_PARAMS:
                state[a.arg] = a.lineno

    def fork(self, state):
        return dict(state)

    def merge(self, a, b):
        return {**b, **a}

    def _summary_sanitized(self, expr: ast.AST) -> bool:
        """A resolved callee in the expression whose summary proves it
        stop-gradients its return (beyond the hard-coded helper list)."""
        if self._summaries is None:
            return False
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                summary, _ = self._callee(n)
                if summary is not None and summary.sanitizes:
                    return True
        return False

    def _call_returns_taint(self, call: ast.Call, state) -> Optional[str]:
        """Tainted name flowing OUT of a resolved call per its summary."""
        summary, names = self._callee(call)
        if summary is None:
            return None
        if summary.sanitizes:
            return None
        if summary.returns_tainted:
            return f"{call.func.attr if isinstance(call.func, ast.Attribute) else getattr(call.func, 'id', '?')}()"
        bound = _bind_args(call, names)
        for pname, arg in bound:
            if pname in summary.returns_taint_of:
                name = self._tainted_in(arg, state)
                if name:
                    return name
        return None

    def _direct_taint(self, expr: ast.AST, state) -> str | None:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in state:
                return n.id
            if isinstance(n, ast.Attribute) and n.attr in _TAINT_ATTRS:
                return n.attr
        return None

    def _tainted_in(self, expr: ast.AST, state) -> str | None:
        """First tainted name occurring in `expr`, unless the expression
        routes through stop_gradient / a sanitizing helper (hard-coded
        or summary-proven)."""
        if _sanitized(self.ctx, expr) or self._summary_sanitized(expr):
            return None
        name = self._direct_taint(expr, state)
        if name:
            return name
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                name = self._call_returns_taint(n, state)
                if name:
                    return name
        return None

    def _source_taints(self, expr: ast.AST, state) -> bool:
        """Does evaluating `expr` produce a key-derived value?  True for
        calls taking params_k/batch_stats_k/queue, direct reads of them,
        reads of tainted locals — unless routed through a sanitizer."""
        return self._tainted_in(expr, state) is not None

    def _flag(self, node: ast.AST, name: str, what: str) -> None:
        if node.lineno in self._seen:
            return
        self._seen.add(node.lineno)
        self.findings.append(
            (
                node,
                f"key-encoder/queue tensor '{name}' flows into {what} without "
                "stop_gradient — gradients would leak into the EMA tower "
                "(MoCo invariant; see ops/losses.py:36, core/queue.py:37 for "
                "the sanitizing patterns)",
            )
        )

    def _scan_sinks(self, expr: ast.AST, state) -> bool:
        fired = False
        # nodes under a sanitizing call are clean territory: the whole
        # `stop_gradient(helper(params_k, ...))` expression is the fix,
        # not a finding
        shielded: set[int] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                q = self.ctx.qual(node.func)
                summary, _ = self._callee(node)
                if (
                    (q and (q in _SANITIZERS or q.endswith(tuple("." + s for s in _SANITIZERS))))
                    or (summary is not None and summary.sanitizes)
                ):
                    for sub in ast.walk(node):
                        if sub is not node:
                            shielded.add(id(sub))
        for node in ast.walk(expr):
            if id(node) in shielded:
                continue
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                for side in (node.left, node.right):
                    name = self._tainted_in(side, state)
                    if name:
                        self._flag(node, name, "a matmul feeding the loss")
                        fired = True
            elif isinstance(node, ast.Call):
                q = self.ctx.qual(node.func) or ""
                if q == "einsum" or q.endswith(".einsum"):
                    for arg in node.args[1:]:  # skip the spec string
                        name = self._tainted_in(arg, state)
                        if name:
                            self._flag(node, name, "an einsum feeding the loss")
                            fired = True
                elif q == "cross_entropy" or q.endswith(".cross_entropy"):
                    for arg in node.args:
                        name = self._tainted_in(arg, state)
                        if name:
                            self._flag(node, name, "cross_entropy")
                            fired = True
                elif not (
                    q in _SANITIZERS or q.endswith(tuple("." + s for s in _SANITIZERS))
                ):
                    # interprocedural sink: a tainted value handed to a
                    # helper parameter that reaches a loss sink INSIDE
                    # the callee (summary-proven) fires at the call site.
                    # The hard-coded sanitizers take key/queue tensors
                    # raw BY CONTRACT (they stop-gradient internally).
                    summary, names = self._callee(node)
                    if summary is not None and not summary.sanitizes and summary.param_sinks:
                        for pname, arg in _bind_args(node, names):
                            if pname not in summary.param_sinks:
                                continue
                            name = self._tainted_in(arg, state)
                            if name:
                                self._flag(
                                    node, name,
                                    f"{summary.qualname}() which feeds it to "
                                    f"a loss sink ({summary.param_sinks[pname]})",
                                )
                                fired = True
        return fired

    def visit_stmt(self, stmt: ast.stmt, state) -> None:
        fired = False
        for expr in stmt_exprs(stmt):
            fired = self._scan_sinks(expr, state) or fired
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            taints = not fired and value is not None and self._source_taints(value, state)
            for t in targets:
                names = (
                    [t] if isinstance(t, ast.Name) else
                    [e for e in getattr(t, "elts", []) if isinstance(e, ast.Name)]
                )
                for n in names:
                    if taints:
                        state[n.id] = n.lineno
                    else:
                        state.pop(n.id, None)


@rule("JX005", "key-encoder/queue tensor reaches a loss without stop_gradient")
def check(ctx: ModuleContext):
    nested: set[ast.AST] = set()
    for g in ctx.functions:
        for n in ast.walk(g):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not g:
                nested.add(n)
    for fn in ctx.functions:
        if fn in nested:
            continue
        visitor = _TaintFlow(ctx)
        visitor.run(fn, {})
        yield from visitor.findings
