"""JX006 — donated buffer used after the jitted call.

`donate_argnums` hands the argument's device buffer to XLA for reuse:
after the call the Python reference points at INVALIDATED memory.
Reading it raises `RuntimeError: Array has been deleted` on backends
that track it — and on backends/versions that don't, it reads garbage.
The classic slip: `new_state = step(state, batch)` followed by a debug
read of `state.step`.

Detection: wrapper bindings `g = jax.jit(f, donate_argnums=...)`, then a
flow walk of every function that calls `g` — positional args in donated
slots become dead names; a later Load before rebinding is the finding.
"""

from __future__ import annotations

import ast

from moco_tpu.analysis.astutils import FlowVisitor, ModuleContext, jit_kind, stmt_exprs
from moco_tpu.analysis.engine import rule


def _donated_nums(call: ast.Call) -> list[int]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return [
                n.value
                for n in ast.walk(kw.value)
                if isinstance(n, ast.Constant) and isinstance(n.value, int)
            ]
    return []


class _DonationFlow(FlowVisitor):
    """state: name -> line where it was donated (dead after that line)."""

    def __init__(self, ctx: ModuleContext, wrappers: dict[str, list[int]]):
        self.ctx = ctx
        self.wrappers = wrappers
        self.findings: list[tuple[ast.AST, str]] = []
        self._seen: set[int] = set()

    def fork(self, state):
        return dict(state)

    def merge(self, a, b):
        return {**a, **b}

    def visit_stmt(self, stmt: ast.stmt, state) -> None:
        # reads of dead names first (RHS evaluates before rebinding)
        newly_dead: dict[str, int] = {}
        for expr in stmt_exprs(stmt):
            for node in ast.walk(expr):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in state
                    and node.lineno not in self._seen
                ):
                    self._seen.add(node.lineno)
                    self.findings.append(
                        (
                            node,
                            f"'{node.id}' was donated to a jitted call at line "
                            f"{state[node.id]} (donate_argnums) — its buffer is "
                            "invalidated; reading it again raises or returns "
                            "garbage",
                        )
                    )
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    nums = self.wrappers.get(node.func.id)
                    if nums:
                        for i, arg in enumerate(node.args):
                            if i in nums and isinstance(arg, ast.Name):
                                newly_dead[arg.id] = node.lineno
        state.update(newly_dead)
        # rebinding revives the name
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            for t in targets:
                names = (
                    [t] if isinstance(t, ast.Name) else
                    [e for e in getattr(t, "elts", []) if isinstance(e, ast.Name)]
                )
                for n in names:
                    state.pop(n.id, None)


@rule("JX006", "buffer passed via donate_argnums is read again after the jitted call")
def check(ctx: ModuleContext):
    wrappers: dict[str, list[int]] = {}
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and jit_kind(ctx.qual(node.value.func)) == "jit"
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            nums = _donated_nums(node.value)
            if nums:
                wrappers[node.targets[0].id] = nums
    if not wrappers:
        return
    nested: set[ast.AST] = set()
    for g in ctx.functions:
        for n in ast.walk(g):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not g:
                nested.add(n)
    for fn in ctx.functions:
        if fn in nested:
            continue
        visitor = _DonationFlow(ctx, wrappers)
        visitor.run(fn, {})
        yield from visitor.findings
