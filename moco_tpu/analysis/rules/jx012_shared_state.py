"""JX012 — shared mutable attribute written without a common lock.

The serving stack's bug class: an attribute of a thread-owning object
(`ServeServer.ingested_rows`, a metrics counter, a stats dict) written
on one thread and read or written on another with no lock both sides
agree on. On CPython the GIL hides most of the torn-write risk but none
of the lost-update risk (`x += 1` is a read-modify-write), and none of
the consistency risk (a /stats snapshot interleaving with an ingest).

The thread-escape model (`analysis/threads.py`) computes, per class,
which methods run on which threads — `threading.Thread` targets, HTTP
handler methods (one thread per request: a handler alone counts as two),
and callback escapes (a bound method handed to a batcher/alert engine) —
and which locks are provably held at each attribute access, including
locks inherited from call sites by always-under-lock private helpers.

A finding fires for every attribute that is written outside `__init__`,
is reachable from ≥ 2 thread weight, and has NO lock common to all its
accesses:

- when some lock guards the writes, each access missing it is reported
  (the "`_index_lock` guards ingest but not the stats read" shape);
- when no lock is held anywhere, one finding anchors at the first write.

Thread-safe-by-construction attributes (locks, `queue.Queue`, `Event`,
`deque`, `threading.local`) are exempt; so are attributes of per-request
HTTP handler instances (fresh object per thread).
"""

from __future__ import annotations

from moco_tpu.analysis.engine import rule
from moco_tpu.analysis.astutils import ModuleContext
from moco_tpu.analysis.threads import component_models


@rule("JX012", "shared mutable attribute written without a common lock across its accessing threads")
def check(ctx: ModuleContext):
    for model in component_models(ctx):
        for attr, accesses, roots in model.shared_attr_accesses():
            common = None
            for a in accesses:
                common = a.locks if common is None else (common & a.locks)
            if common:
                continue
            roots_str = ", ".join(sorted(roots))
            writes = [a for a in accesses if a.is_write]
            write_locks: dict[str, int] = {}
            for w in writes:
                for lock in w.locks:
                    write_locks[lock] = write_locks.get(lock, 0) + 1
            if write_locks:
                # some lock guards (some of) the writes: report every
                # access that skips it — the torn-snapshot shape
                guard = sorted(write_locks, key=lambda k: (-write_locks[k], k))[0]
                seen: set[int] = set()
                for a in sorted(accesses, key=lambda a: (a.lineno, a.kind)):
                    if guard in a.locks or a.lineno in seen:
                        continue
                    seen.add(a.lineno)
                    yield a.node, (
                        f"attribute '{attr}' of {model.name} is "
                        f"{'written' if a.is_write else 'read'} without "
                        f"lock '{guard}' that guards its writes elsewhere "
                        f"(accessed from threads: {roots_str}) — hold the same "
                        "lock on every access or snapshot under it"
                    )
            else:
                first = min(writes, key=lambda a: a.lineno)
                yield first.node, (
                    f"attribute '{attr}' of {model.name} is written from "
                    f"multiple threads ({roots_str}) with no lock — a lost "
                    "update or torn snapshot; guard every access with one "
                    "lock (tsan.make_lock gives the runtime sanitizer "
                    "visibility too)"
                )
