"""Rule modules — importing this package registers every rule with the
engine. One module per rule id, each with paired known-bad/known-good
fixtures under ``tests/fixtures/lint/``."""

from moco_tpu.analysis.rules import (  # noqa: F401
    jx001_impure,
    jx002_host_transfer,
    jx003_prng_reuse,
    jx004_recompile,
    jx005_stop_gradient,
    jx006_donation,
    jx007_axis_names,
    jx008_spmd_divergence,
    jx009_mixed_precision,
    jx010_sharding_consistency,
    jx011_thread_hygiene,
    jx012_shared_state,
    jx013_lock_order,
    jx014_aot_freeze,
    jx015_metric_schema,
    jx016_http_protocol,
    jx017_fault_sites,
    jx018_exit_codes,
)
