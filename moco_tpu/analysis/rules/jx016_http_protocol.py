"""JX016: HTTP-protocol consistency across the fleet.

The router <-> replica surface is declared once, in
`utils/contracts.py` `ROUTES` (methods, required headers, idempotence,
which server handles it). This rule keeps both sides honest against
that declaration, program-wide:

1. **handler side** — a `do_GET`/`do_POST` comparing the request path
   against a route literal the registry doesn't declare (new endpoint
   shipped without its registry entry), or handling it under an
   undeclared method.
2. **client side** — a `urllib.request.Request`/`urlopen` call whose
   URL resolves to an undeclared route (typo, removed endpoint), the
   wrong method for a declared route (GET to a POST-only route and vice
   versa), or a POST to a route with required headers
   (`X-Image-Shape`, `X-Rows-Shape`) where the enclosing function never
   mentions the header literal.
3. **retry/hedge idempotence** — a `retry_call` wrapper whose guarding
   route-membership tuple admits a route outside the declared
   idempotent set. The canonical violation this exists to prevent: the
   router retrying `/ingest` (appends queue rows — a retried ingest
   double-writes; only the fan-out writer may re-post, reconciling by
   row count).

Route extraction trusts literals only (`base + "/healthz"`, f-string
literal chunks); fully dynamic URLs — e.g. the router's own proxy
forwarding `self.path` verbatim — are out of scope by design.
Deliberately-invalid probes (404 tests) carry inline suppressions.
"""

from __future__ import annotations

import ast

from moco_tpu.analysis import contracts
from moco_tpu.analysis.engine import rule
from moco_tpu.utils import contracts as decl


def _mentions(fn, reg, path, header: str) -> bool:
    if fn is None:
        return header in reg.module_headers.get(path, set())
    for n in ast.walk(fn):
        if isinstance(n, ast.Constant) and n.value == header:
            return True
    return False


@rule("JX016", "HTTP route/method/header drift from the declared registry, or non-idempotent retry")
def check_http_protocol(ctx):
    reg = contracts.registry_for(ctx)

    for h in reg.handler_routes:
        if h.path != ctx.path:
            continue
        r = decl.ROUTES.get(h.route)
        if r is None:
            yield (
                h.line,
                f"handler serves undeclared route {h.route!r} — ship a "
                f"utils/contracts.py ROUTES entry with it",
            )
            continue
        if h.method not in r.methods:
            yield (
                h.line,
                f"handler serves {h.route!r} via {h.method} but the registry "
                f"declares methods {r.methods}",
            )
            continue
        hdrs = reg.class_headers.get(f"{ctx.path}::{h.cls}", set()) | (
            reg.module_headers.get(ctx.path, set())
        )
        for header in r.headers:
            if header not in hdrs:
                yield (
                    h.line,
                    f"handler for {h.route!r} never reads required header "
                    f"{header!r} declared in the registry",
                )
        # propagated headers (opt_headers) bind the HANDLER side only:
        # a plain client may omit X-Trace-Id, but every server of the
        # route must adopt it or the trace silently breaks at this hop.
        for header in r.opt_headers:
            if header not in hdrs:
                yield (
                    h.line,
                    f"handler for {h.route!r} never reads propagated header "
                    f"{header!r} declared in the registry (opt_headers)",
                )

    for c in reg.client_calls:
        if c.path != ctx.path:
            continue
        r = decl.ROUTES.get(c.route)
        if r is None:
            yield (
                c.line,
                f"client calls route {c.route!r} that no handler declares "
                f"(not in utils/contracts.py ROUTES)",
            )
            continue
        if c.method not in r.methods:
            yield (
                c.line,
                f"client calls {c.route!r} via {c.method} but the registry "
                f"declares methods {r.methods}",
            )
            continue
        for header in r.headers:
            if not _mentions(c.func, reg, ctx.path, header):
                yield (
                    c.line,
                    f"client posts to {c.route!r} without required header "
                    f"{header!r}",
                )

    for w in reg.retry_wraps:
        if w.path != ctx.path:
            continue
        for route in w.routes:
            if route in decl.ROUTES and route not in decl.IDEMPOTENT_ROUTES:
                yield (
                    w.line,
                    f"retry/hedge wrapper reachable by non-idempotent route "
                    f"{route!r} — only {decl.IDEMPOTENT_ROUTES} may be retried",
                )
