"""JX017: fault-grammar site resolution.

A `kind@site=...` spec only does anything if some hook calls
`faults.maybe_<kind>(site)` (or `tsan.make_lock(site)` for deadlock@)
with that exact string — the grammar has no unknown-site error at
install time, so a renamed stage silently turns a chaos leg into a
no-op that still passes. Three clauses over the contract registry:

1. **unresolvable spec** — a `slow@site=`/`delay@site=`/`io@site=`/
   `deadlock@site=` literal (in code, tests, or docstrings — doc drift
   is drift) naming a site that is neither registered in
   `utils/contracts.py` `FAULT_SITES` nor extracted from any hook call
   in the analyzed program. Placeholder sites (`<lock>`, bare `S`) are
   skipped; `kill@`/`stall@`/`nan@`... are site-less; `diverge@` sites
   are dynamic comms tags validated at runtime.
2. **unregistered hook** — a hook call whose literal site is missing
   from the declared `FAULT_SITES` vocabulary: ship the registry entry
   with the new site. Unit tests (`test_*.py`) are exempt — they probe
   the grammar machinery itself with synthetic sites on purpose.
3. **untested serve stage** — whole-tree runs only (the program
   includes both the registry module AND the test corpus, so partial
   and `moco_tpu/`-only scopes stay quiet): a serve-stage `maybe_slow`
   hook whose site appears in no `slow@site=` spec anywhere — no chaos
   leg or test would notice the stage's fault attribution breaking.
"""

from __future__ import annotations

import os

from moco_tpu.analysis import contracts
from moco_tpu.analysis.contracts import _SITE_RE
from moco_tpu.analysis.engine import rule
from moco_tpu.utils import contracts as decl


@rule("JX017", "fault spec site no hook can fire, or hook site unregistered/untested")
def check_fault_sites(ctx):
    reg = contracts.registry_for(ctx)

    for s in reg.spec_literals:
        if s.path != ctx.path:
            continue
        declared = decl.FAULT_SITES.get(s.kind)
        if declared is None:
            continue  # site-less kind, or dynamic site space (diverge@)
        site = s.params.get("site")
        if site is None or not _SITE_RE.match(site):
            continue  # dynamic or placeholder site
        if site not in declared and site not in reg.hook_site_set(s.kind):
            yield (
                s.line,
                f"spec {s.raw!r} names site {site!r} that no {s.kind} hook "
                f"can fire (not registered, not extracted from any hook call)",
            )

    is_test_module = os.path.basename(ctx.path).startswith("test_")
    for h in reg.hook_sites:
        if h.path != ctx.path or is_test_module:
            continue
        declared = decl.FAULT_SITES.get(h.kind)
        if declared is not None and h.site not in declared:
            yield (
                h.line,
                f"{h.kind} hook site {h.site!r} is not registered in "
                f"utils/contracts.py FAULT_SITES — ship a registry entry",
            )

    has_test_corpus = any(
        os.path.basename(p).startswith("test_") for p in reg.paths
    )
    if not (reg.has_registry_module and has_test_corpus):
        return
    exercised = {
        s.params.get("site")
        for s in reg.spec_literals
        if s.kind == "slow" and s.params.get("site")
    }
    for h in reg.hook_sites:
        if h.path != ctx.path or h.kind != "slow":
            continue
        if h.site in decl.SERVE_STAGE_SITES and h.site not in exercised:
            yield (
                h.line,
                f"no test or chaos leg exercises slow@site={h.site} — the "
                f"stage's fault hook is unverified",
            )
