"""JX010 — interprocedural sharding/axis-name consistency.

JX007 checks collectives LEXICALLY inside the wrapped function against
the enclosing `shard_map`/`pmap` declaration. But this repo's
collectives live in helpers: the shard_map'd step in `core/moco.py`
calls `parallel/shuffle.py`'s `balanced_shuffle(rng, x, axis_name)`,
which issues the `all_to_all` — two modules away from the declaration
it must agree with. After a mesh-axis rename, the helper's collective
silently binds the WRONG axis of the same mesh and the reduction runs
over the wrong replica group ("trains", learns garbage). This is also
the precondition for the ZeRO-3 work: persistently sharded optimizer
state threads PartitionSpecs through several helper layers.

This rule closes the gap with the dataflow summaries:

- every function's summary carries its collectives, transitively, with
  axis expressions resolved through call-site bindings (a helper whose
  collective names its OWN `axis_name` parameter is resolved by the
  caller's argument — including constants imported from another
  module, e.g. `DATA_AXIS` from `parallel/mesh.py`);
- for each `shard_map(f, ...)`/`pmap(f, axis_name=...)` wrap with a
  resolvable declaration, the TRANSITIVE collectives of `f` are checked
  against the declared axes; lexically-direct collectives are left to
  JX007 (no double findings) — this rule fires on the ones reached
  `via` a helper, anchored at the helper call's line in the wrapped
  function.

Unresolvable specs or axis expressions leave the wrap unchecked — same
no-guessing contract as JX007.
"""

from __future__ import annotations

import ast
from typing import Optional

from moco_tpu.analysis.astutils import ModuleContext, jit_kind
from moco_tpu.analysis.engine import rule
from moco_tpu.analysis.dataflow import build_summaries
from moco_tpu.analysis.rules.jx007_axis_names import (
    _spec_tokens,
    _tokens_of,
)


def _declared_axes(
    ctx: ModuleContext, node: ast.Call, kind: str, env: dict[str, ast.AST]
) -> Optional[set[str]]:
    """Axis tokens a shard_map/pmap wrap declares, or None when the
    declaration cannot be resolved (leave unchecked)."""
    declared: set[str] = set()
    closed = True
    if kind == "pmap":
        axis_kw = next((kw.value for kw in node.keywords if kw.arg == "axis_name"), None)
        if axis_kw is not None:
            declared = _tokens_of(ctx, axis_kw)
    else:
        spec_exprs = [
            kw.value for kw in node.keywords if kw.arg in ("in_specs", "out_specs")
        ]
        spec_exprs += node.args[2:4]
        if not spec_exprs:
            closed = False
        for expr in spec_exprs:
            t, c = _spec_tokens(ctx, expr, env)
            declared |= t
            closed &= c
    return declared if closed else None


@rule("JX010", "helper-issued collective's axis disagrees with the shard_map declaration")
def check(ctx: ModuleContext):
    prog = getattr(ctx, "program", None)
    if prog is None:
        return
    summaries = build_summaries(prog)

    module_assigns: dict[str, ast.AST] = {}
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            module_assigns[node.targets[0].id] = node.value

    enclosing: dict[int, ast.FunctionDef] = {}
    for f in ctx.functions:
        for n in ast.walk(f):
            enclosing[id(n)] = f

    def local_env(fn: Optional[ast.FunctionDef]) -> dict[str, ast.AST]:
        env = dict(module_assigns)
        if fn is not None:
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name
                ):
                    env[node.targets[0].id] = node.value
        return env

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = jit_kind(ctx.qual(node.func))
        if kind not in ("shard_map", "pmap"):
            continue
        if not (node.args and isinstance(node.args[0], ast.Name)):
            continue
        wrapped_info = prog.resolve_call(
            ctx, ast.Call(func=node.args[0], args=[], keywords=[]), None
        )
        if wrapped_info is None:
            defs = ctx.defs_by_name.get(node.args[0].id, [])
            wrapped_info = prog.info_for_node(defs[-1]) if defs else None
        if wrapped_info is None:
            continue
        declared = _declared_axes(
            ctx, node, kind, local_env(enclosing.get(id(node)))
        )
        if declared is None:
            continue
        if wrapped_info.ctx is not ctx:
            continue  # findings must anchor to lines of THIS file
        summary = summaries.get(wrapped_info.qualname)
        if summary is None:
            continue
        for use in summary.collectives:
            if use.via is None:
                continue  # lexically direct: JX007's jurisdiction
            if use.axis_param is not None:
                continue  # still bound to the wrapped fn's own param: the
                # axis comes in as data, unresolvable here
            if not use.axis_tokens:
                continue  # no-guessing
            if use.axis_tokens & declared:
                continue
            pretty = sorted(use.axis_tokens)[0]
            yield use.lineno, (
                f"collective {use.kind}(axis={pretty!r}) reached via "
                f"{use.via}() from '{wrapped_info.name}' names an axis the "
                f"enclosing {kind} does not declare "
                f"(declared: {', '.join(sorted(declared)) or 'none'}) — "
                "after a mesh-axis rename this binds the WRONG axis and "
                "reduces over the wrong replica group, or deadlocks the pod"
            )
