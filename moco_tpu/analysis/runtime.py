"""Runtime arm of mocolint: tracer-leak checking + recompile accounting.

The static pass catches hazard *patterns*; this module catches the
*events* on a live run, at zero step-loop cost (everything piggybacks on
the driver's log-step host sync):

- :func:`enable_strict_tracing` — turns on `jax.check_tracer_leaks`, so
  a traced value escaping the compiled region (the classic source of
  silent recompiles and "leaked tracer" crashes hours later) fails
  loudly at the point of escape.
- :class:`CompileMonitor` — counts compilations of the jitted step via
  the executable cache (`_cache_size`), falling back to a process-wide
  `jax.monitoring` compile-event counter on jax versions without it.
  Surfaced as `compile_cache_misses` on every metrics.jsonl log line
  under `--strict-tracing`.
- :class:`RecompileGuard` — the abort-on-recompile-after-step-N guard:
  warm-up steps may compile freely (first trace, donation variants); a
  compile after that means a shape/dtype/static-arg leak in the input
  pipeline, and every occurrence costs minutes of TPU time (PROFILE.md
  r50/224 compile). Failing fast turns a silent 100x slowdown into a
  diagnosable crash.
"""

from __future__ import annotations

from typing import Callable, Optional


def enable_strict_tracing() -> None:
    """Fail loudly when a tracer escapes its trace (leaked into a
    closure, a global, or host state). Debug-grade checking — opt-in via
    `train.py --strict-tracing`."""
    import jax

    jax.config.update("jax_check_tracer_leaks", True)


class _MonitoringCounter:
    """Process-wide compile counter from jax.monitoring events (fallback
    when the jitted callable does not expose its executable cache)."""

    _installed: Optional["_MonitoringCounter"] = None

    def __init__(self) -> None:
        self.count = 0

    @classmethod
    def install(cls) -> "_MonitoringCounter":
        if cls._installed is None:
            counter = cls()

            def _on_event(event: str, **kw) -> None:
                if "compile" in event:
                    counter.count += 1

            import jax

            jax.monitoring.register_event_listener(_on_event)
            cls._installed = counter
        return cls._installed


class CompileMonitor:
    """Compilation count of one jitted callable.

    `misses()` is the number of distinct executables compiled so far —
    exactly the number of times the step function was (re)traced. Stable
    after warm-up on a healthy run; each later increment is a recompile
    some input change triggered.
    """

    def __init__(self, fn: Callable):
        self._fn = fn
        self._cache_size = getattr(fn, "_cache_size", None)
        self._fallback: Optional[_MonitoringCounter] = None
        if not callable(self._cache_size):
            self._cache_size = None
            self._fallback = _MonitoringCounter.install()

    def misses(self) -> int:
        if self._cache_size is not None:
            try:
                return int(self._cache_size())
            except Exception:
                return 0
        return self._fallback.count if self._fallback else 0


class RecompileError(RuntimeError):
    """The jitted step recompiled after the warm-up window."""


class RecompileGuard:
    """Abort-on-recompile-after-step-N.

    `update(step, misses)` returns None while healthy. Past
    `warmup_steps`, a growing miss count returns a human-readable
    diagnosis string (the driver logs it to metrics.jsonl, then raises
    :class:`RecompileError`). Counting is driven by the caller so the
    check costs nothing between log steps.
    """

    def __init__(self, warmup_steps: int):
        self.warmup_steps = warmup_steps
        self.baseline: Optional[int] = None

    def update(self, step: int, misses: int) -> Optional[str]:
        if step <= self.warmup_steps or self.baseline is None:
            self.baseline = misses
            return None
        if misses > self.baseline:
            return (
                f"step function recompiled after warm-up: {misses} compile "
                f"cache misses at step {step} vs {self.baseline} at the end "
                f"of warm-up (step {self.warmup_steps}) — look for varying "
                "shapes/dtypes from the input pipeline, non-hashable or "
                "fresh static args, or host branching on batch content "
                "(run mocolint for the static pattern)"
            )
        return None
