"""Shared AST machinery for the mocolint rules.

Everything here is deliberately *approximate*: a linter wants high-value
findings at near-zero false-positive rate, not soundness. The key
primitives:

- import-alias resolution (`jnp.einsum` -> ``jax.numpy.einsum``,
  ``from jax import lax`` -> ``jax.lax``), so rules match on dotted
  qualnames instead of guessing at surface spellings;
- jitted-scope discovery: functions decorated with or passed to
  `jax.jit`/`shard_map`/`pmap`, closed transitively over module-local
  calls and nested defs (``step_fn`` passed to ``shard_map`` pulls its
  helper ``loss_fn`` into scope);
- a small branch-aware statement walker for the flow-sensitive rules
  (PRNG reuse, stop_gradient taint, donated-buffer liveness): `if`
  branches analyze independently and merge, loop bodies run twice so
  cross-iteration reuse is seen.
"""

from __future__ import annotations

import ast
from typing import Callable, Iterator, Optional


# ---------------------------------------------------------------------------
# import / qualname resolution


def collect_imports(tree: ast.Module) -> dict[str, str]:
    """Local binding -> dotted origin, e.g. {'jnp': 'jax.numpy',
    'lax': 'jax.lax', 'shard_map': 'moco_tpu.parallel.compat.shard_map'}."""
    imports: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    imports[a.asname] = a.name
                else:
                    root = a.name.split(".")[0]
                    imports[root] = root
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                origin = f"{mod}.{a.name}" if mod else a.name
                imports[a.asname or a.name] = origin
    return imports


def qualname(node: ast.AST, imports: dict[str, str]) -> Optional[str]:
    """Dotted name of an expression through the import map, or None for
    anything that isn't a plain Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return ".".join([imports.get(node.id, node.id)] + parts[::-1])
    return None


def jit_kind(qual: Optional[str]) -> Optional[str]:
    """'jit' / 'shard_map' / 'pmap' when `qual` names a compile wrapper."""
    if not qual:
        return None
    if qual in ("jax.jit", "jax.pjit") or qual.endswith((".jit", ".pjit")):
        return "jit"
    if qual == "shard_map" or qual.endswith(".shard_map"):
        return "shard_map"
    if qual == "pmap" or qual.endswith(".pmap"):
        return "pmap"
    return None


def decorator_qual(dec: ast.AST, imports: dict[str, str]) -> Optional[str]:
    """Resolve a decorator to the wrapper it applies: handles bare names,
    attribute chains, `@jax.jit(...)` calls, and `@partial(jax.jit, ...)`."""
    if isinstance(dec, ast.Call):
        q = qualname(dec.func, imports)
        if q and (q == "partial" or q.endswith(".partial")) and dec.args:
            return qualname(dec.args[0], imports)
        return q
    return qualname(dec, imports)


# ---------------------------------------------------------------------------
# module context


class ModuleContext:
    """Everything a rule needs about one parsed file."""

    def __init__(self, tree: ast.Module, source: str, path: str):
        self.tree = tree
        self.path = path
        self.source_lines = source.splitlines()
        # whole-program backrefs, attached by analysis.callgraph when the
        # engine analyzes a file SET; None for a lone-module analysis
        self.program = None
        self.module_name: Optional[str] = None
        self.imports = collect_imports(tree)
        self.functions: list[ast.FunctionDef] = [
            n
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self.defs_by_name: dict[str, list[ast.FunctionDef]] = {}
        for f in self.functions:
            self.defs_by_name.setdefault(f.name, []).append(f)
        self.constants = self._module_constants(tree)
        self.jitted = self._find_jitted()

    @staticmethod
    def _module_constants(tree: ast.Module) -> dict[str, str]:
        """Module-level NAME = "string" assignments (axis-name constants)."""
        out: dict[str, str] = {}
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.value.value
        return out

    def _find_jitted(self) -> set[ast.FunctionDef]:
        """Functions compiled by jit/shard_map/pmap, closed over nested
        defs and module-local calls (one trace pulls all of them in)."""
        roots: list[ast.FunctionDef] = []
        for f in self.functions:
            for dec in f.decorator_list:
                if jit_kind(decorator_qual(dec, self.imports)):
                    roots.append(f)
                    break
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and jit_kind(qualname(node.func, self.imports)):
                if node.args and isinstance(node.args[0], ast.Name):
                    roots.extend(self.defs_by_name.get(node.args[0].id, []))
        jitted: set[ast.FunctionDef] = set()
        stack = list(roots)
        while stack:
            f = stack.pop()
            if f in jitted:
                continue
            jitted.add(f)
            for n in ast.walk(f):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not f:
                    stack.append(n)
                elif isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
                    stack.extend(self.defs_by_name.get(n.func.id, []))
        return jitted

    def qual(self, node: ast.AST) -> Optional[str]:
        return qualname(node, self.imports)


def walk_own(fn: ast.FunctionDef) -> Iterator[ast.AST]:
    """Walk a function's own body, NOT descending into nested function /
    class definitions (those are analyzed as their own scopes)."""
    stack: list[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def names_in(node: ast.AST) -> Iterator[ast.Name]:
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            yield n


def call_args(node: ast.Call) -> Iterator[ast.AST]:
    """All argument expressions of a call (positional, *args, keywords)."""
    for a in node.args:
        yield a.value if isinstance(a, ast.Starred) else a
    for kw in node.keywords:
        yield kw.value


def stmt_exprs(stmt: ast.stmt) -> list[ast.AST]:
    """The expressions a flow rule should scan for one statement: the
    whole node for simple statements, only the controlling expression for
    compound ones (bodies are walked separately by FlowVisitor)."""
    if isinstance(stmt, ast.If) or isinstance(stmt, ast.While):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    return [stmt]


# ---------------------------------------------------------------------------
# branch-aware statement walker for flow-sensitive rules


class FlowVisitor:
    """Sequential statement walk with `if` branch forking/merging and a
    double pass over loop bodies (so a key consumed once per iteration
    without re-derivation is seen as reused).

    Subclasses implement `visit_stmt(stmt, state)` mutating `state`, plus
    `fork(state)` and `merge(a, b)`. Nested function defs are visited in
    place with the enclosing state (closures capture it); their
    parameters are reported through `enter_function`.
    """

    def run(self, fn: ast.FunctionDef, state) -> None:
        self.enter_function(fn, state)
        self._block(fn.body, state)

    def enter_function(self, fn: ast.FunctionDef, state) -> None:  # override
        pass

    def fork(self, state):  # override
        raise NotImplementedError

    def merge(self, a, b):  # override
        raise NotImplementedError

    def visit_stmt(self, stmt: ast.stmt, state) -> None:  # override
        pass

    @staticmethod
    def _terminates(stmts: list[ast.stmt]) -> bool:
        """Does this branch leave the enclosing block (no fall-through)?"""
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
        )

    def _block(self, stmts: list[ast.stmt], state) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                self.visit_stmt(stmt, state)  # the test expression itself
                body_state = self.fork(state)
                else_state = self.fork(state)
                self._block(stmt.body, body_state)
                self._block(stmt.orelse, else_state)
                # a branch that returns/raises contributes nothing to the
                # fall-through state (early-return idiom)
                if self._terminates(stmt.body) and not self._terminates(stmt.orelse):
                    merged = else_state
                elif self._terminates(stmt.orelse) and not self._terminates(stmt.body):
                    merged = body_state
                elif self._terminates(stmt.body) and self._terminates(stmt.orelse):
                    merged = self.fork(state)  # code below is unreachable
                else:
                    merged = self.merge(body_state, else_state)
                state.clear()
                state.update(merged)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self.visit_stmt(stmt, state)
                for _ in range(2):  # second pass exposes cross-iteration reuse
                    self._block(stmt.body, state)
                self._block(stmt.orelse, state)
            elif isinstance(stmt, ast.Try):
                self._block(stmt.body, state)
                for handler in stmt.handlers:
                    h_state = self.fork(state)
                    self._block(handler.body, h_state)
                    merged = self.merge(state, h_state)
                    state.clear()
                    state.update(merged)
                self._block(stmt.orelse, state)
                self._block(stmt.finalbody, state)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self.visit_stmt(stmt, state)
                self._block(stmt.body, state)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner = self.fork(state)
                self.enter_function(stmt, inner)
                self._block(stmt.body, inner)
            else:
                self.visit_stmt(stmt, state)
