"""mocolint engine: rule registry, suppression comments, baselines,
reporting.

Rules live in `moco_tpu/analysis/rules/` — one module per rule, each
registering itself with :func:`rule`. A rule is a callable
``(ModuleContext) -> Iterable[(ast_node_or_line, message)]``; the engine
stamps rule id / path / position, applies suppression comments, and
renders text or JSON. Since the interprocedural engine landed,
`analyze_paths` parses the WHOLE file set first and attaches a
`callgraph.Program` (+ dataflow summaries) to every module context, so
rules can follow taint and collectives across files.

Suppression is per statement, per rule — the comment may sit on ANY
line of the statement's extent (first line, a continuation line, or the
closing paren of a multi-line call)::

    risky_line()  # mocolint: disable=JX003  (why this is intentional)
    other()       # mocolint: disable=JX001,JX002
    x = helper(
        arg,
    )  # mocolint: disable=JX005  (closing-line suppression works)

Suppressed findings are kept (with ``suppressed=True``) so reports can
audit them; only unsuppressed findings affect the exit code.

Baselines gate rule rollout: ``write_baseline`` records the current
findings' fingerprints (rule, path, line); a later run with the
baseline loaded marks exactly those findings ``baselined=True`` so new
rules can land without first cleaning a thousand legacy sites — CI
fails only on findings NOT in the baseline.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Iterable, Iterator, Optional, Union

from moco_tpu.analysis.astutils import ModuleContext

RuleResult = Iterable[tuple[Union[ast.AST, int], str]]
RuleFn = Callable[[ModuleContext], RuleResult]

_RULES: dict[str, tuple[str, RuleFn]] = {}

_SUPPRESS_RE = re.compile(r"#\s*mocolint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    message: str
    path: str
    line: int
    col: int = 0
    suppressed: bool = False
    baselined: bool = False

    @property
    def active(self) -> bool:
        """Counts toward the nonzero exit code."""
        return not self.suppressed and not self.baselined

    def fingerprint(self) -> str:
        """Baseline identity: rule + normalized path + line. Line-based
        on purpose — a baseline is a snapshot, regenerated with
        `--update-baseline` when the baselined files move."""
        return f"{self.rule}:{norm_path(self.path)}:{self.line}"

    def render(self) -> str:
        tag = (
            " (suppressed)" if self.suppressed
            else " (baselined)" if self.baselined
            else ""
        )
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}{tag}"


def norm_path(path: str) -> str:
    """Repo-stable path form for fingerprints: forward slashes, anchored
    at the repo's top-level package/dir names — the same file must
    fingerprint identically whether the analyzer was invoked as
    `mocolint tests/`, `mocolint ./tests`, or with absolute paths."""
    p = os.path.normpath(path).replace(os.sep, "/")
    parts = p.split("/")
    for anchor in ("moco_tpu", "scripts", "tests"):
        if anchor in parts[:-1]:
            return "/".join(parts[parts.index(anchor):])
    if p.startswith("./"):
        p = p[2:]
    return parts[-1] if os.path.isabs(path) else p


def rule(rule_id: str, summary: str) -> Callable[[RuleFn], RuleFn]:
    """Decorator registering a rule under its JXnnn id."""

    def deco(fn: RuleFn) -> RuleFn:
        _RULES[rule_id] = (summary, fn)
        return fn

    return deco


def _load_rules() -> None:
    # importing the package registers every rule module
    import moco_tpu.analysis.rules  # noqa: F401


def iter_rules() -> list[tuple[str, str]]:
    """[(rule_id, one-line summary)] for --list-rules and the README table."""
    _load_rules()
    return sorted((rid, summary) for rid, (summary, _) in _RULES.items())


def _suppressed_rules(line: str) -> set[str]:
    m = _SUPPRESS_RE.search(line)
    if not m:
        return set()
    return {tok.strip().upper() for tok in m.group(1).split(",") if tok.strip()}


def _stmt_extents(tree: ast.Module) -> list[tuple[int, int]]:
    """(first_line, last_line) of every statement's own extent.

    A compound statement (if/for/while/with) contributes only its HEADER
    lines — its body statements carry their own extents — so a
    suppression inside a function body never leaks to sibling findings.
    Function/class defs and try blocks are pure containers here.
    """
    extents: list[tuple[int, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        if isinstance(node, (ast.If, ast.While)):
            end = getattr(node.test, "end_lineno", None)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            end = getattr(node.iter, "end_lineno", None)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            ends = [getattr(i.context_expr, "end_lineno", None) for i in node.items]
            end = max((e for e in ends if e), default=None)
        elif isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Try)
        ):
            continue
        else:
            end = getattr(node, "end_lineno", None)
        extents.append((node.lineno, end or node.lineno))
    return extents


def _suppression_extent(extents: list[tuple[int, int]], line: int) -> tuple[int, int]:
    """The smallest statement extent containing `line` (the statement the
    finding anchors to); the line itself when no statement covers it."""
    best: Optional[tuple[int, int]] = None
    for start, end in extents:
        if start <= line <= end:
            if best is None or (end - start) < (best[1] - best[0]):
                best = (start, end)
    return best or (line, line)


def analyze_source(
    source: str,
    path: str,
    rules: Optional[Iterable[str]] = None,
    ctx: Optional[ModuleContext] = None,
) -> list[Finding]:
    """All findings (suppressed ones flagged, not dropped) for one file.

    Called directly (tests, one-off strings) it builds a single-file
    program so cross-function resolution works within the module; the
    multi-file path (`analyze_paths`) passes a pre-built `ctx` already
    carrying the whole-program backref.
    """
    _load_rules()
    if ctx is None:
        ctx = parse_module(source, path)
        if isinstance(ctx, Finding):
            return [ctx]
        from moco_tpu.analysis.callgraph import build_program

        build_program({path: ctx})
    selected = set(rules) if rules is not None else set(_RULES)
    extents = _stmt_extents(ctx.tree)
    findings: list[Finding] = []
    for rule_id, (_, fn) in sorted(_RULES.items()):
        if rule_id not in selected:
            continue
        for node, message in fn(ctx):
            line = node if isinstance(node, int) else getattr(node, "lineno", 1)
            col = 0 if isinstance(node, int) else getattr(node, "col_offset", 0)
            # suppression anchored to the FULL statement extent: the
            # comment may sit on the closing line of a multi-line call
            # while the finding anchors to the statement's first line
            start, end = _suppression_extent(extents, line)
            suppressed_here: set[str] = set()
            for ln in range(start, min(end, len(ctx.source_lines)) + 1):
                if 0 < ln <= len(ctx.source_lines):
                    suppressed_here |= _suppressed_rules(ctx.source_lines[ln - 1])
            findings.append(
                Finding(
                    rule=rule_id,
                    message=message,
                    path=path,
                    line=line,
                    col=col,
                    suppressed=rule_id.upper() in suppressed_here
                    or "ALL" in suppressed_here,
                )
            )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def parse_module(source: str, path: str) -> Union[ModuleContext, Finding]:
    """Parse one file into a ModuleContext, or a PARSE Finding."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return Finding(
            rule="PARSE",
            message=f"syntax error: {e.msg}",
            path=path,
            line=e.lineno or 1,
            col=e.offset or 0,
        )
    return ModuleContext(tree, source, path)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs if d != "__pycache__" and not d.startswith(".")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def analyze_paths(
    paths: Iterable[str],
    rules: Optional[Iterable[str]] = None,
    baseline: Optional[set[str]] = None,
) -> list[Finding]:
    """Analyze a file set as ONE program: every module is parsed first,
    the call graph + dataflow summaries are built over all of them, and
    only then do the rules run — so taint crosses file boundaries.
    `baseline` is a set of fingerprints to mark (not drop)."""
    _load_rules()
    contexts: dict[str, ModuleContext] = {}
    findings: list[Finding] = []
    for f in iter_python_files(paths):
        with open(f, "r", encoding="utf-8") as fh:
            parsed = parse_module(fh.read(), f)
        if isinstance(parsed, Finding):
            findings.append(parsed)
        else:
            contexts[f] = parsed
    if contexts:
        from moco_tpu.analysis.callgraph import build_program

        build_program(contexts)
    for f, ctx in contexts.items():
        source = "\n".join(ctx.source_lines)
        findings.extend(analyze_source(source, f, rules=rules, ctx=ctx))
    if baseline:
        findings = [
            dataclasses.replace(fi, baselined=True)
            if not fi.suppressed and fi.fingerprint() in baseline
            else fi
            for fi in findings
        ]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---------------------------------------------------------------------------
# baselines

BASELINE_FILENAME = "mocolint-baseline.json"


def load_baseline(path: str) -> set[str]:
    """Fingerprints from a baseline file written by `write_baseline`."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if isinstance(data, dict):
        entries = data.get("findings", [])
    else:  # bare list form is accepted too
        entries = data
    out: set[str] = set()
    for e in entries:
        if isinstance(e, str):
            out.add(e)
        elif isinstance(e, dict) and {"rule", "path", "line"} <= set(e):
            out.add(f"{e['rule']}:{norm_path(e['path'])}:{e['line']}")
    return out


def write_baseline(path: str, findings: list[Finding]) -> int:
    """Record every unsuppressed finding's fingerprint (suppressed ones
    already carry their justification in-source). Returns the count."""
    by_fp: dict[str, dict] = {}
    for f in findings:
        if not f.suppressed:
            by_fp.setdefault(
                f.fingerprint(),
                {
                    "rule": f.rule,
                    "path": norm_path(f.path),
                    "line": f.line,
                    "message": f.message,  # for humans diffing the baseline
                },
            )
    entries = [by_fp[k] for k in sorted(by_fp)]
    payload = {
        "version": 1,
        "note": (
            "mocolint findings baseline — regenerate with "
            "`python -m moco_tpu.analysis <paths> --update-baseline`; "
            "CI fails on any finding NOT fingerprinted here"
        ),
        "findings": entries,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return len(entries)


def discover_baseline(paths: Iterable[str]) -> Optional[str]:
    """Walk up from each analyzed path looking for the repo's checked-in
    baseline file; first hit wins. Keeps the acceptance command
    (`python -m moco_tpu.analysis moco_tpu/ scripts/ tests/ train.py`)
    baseline-aware without flags; `--no-baseline` opts out."""
    seen: set[str] = set()
    for p in paths:
        d = os.path.abspath(p if os.path.isdir(p) else os.path.dirname(p) or ".")
        while d not in seen:
            seen.add(d)
            candidate = os.path.join(d, BASELINE_FILENAME)
            if os.path.isfile(candidate):
                return candidate
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return None


def render_text(findings: list[Finding], show_suppressed: bool = False) -> str:
    shown = [f for f in findings if show_suppressed or f.active]
    lines = [f.render() for f in shown]
    active = sum(1 for f in findings if f.active)
    suppressed = sum(1 for f in findings if f.suppressed)
    baselined = sum(1 for f in findings if f.baselined)
    lines.append(
        f"mocolint: {active} finding(s)"
        + (f", {suppressed} suppressed" if suppressed else "")
        + (f", {baselined} baselined" if baselined else "")
    )
    return "\n".join(lines)


def render_json(findings: list[Finding]) -> str:
    return json.dumps(
        {
            "version": 1,
            "counts": {
                "active": sum(1 for f in findings if f.active),
                "suppressed": sum(1 for f in findings if f.suppressed),
                "baselined": sum(1 for f in findings if f.baselined),
            },
            "findings": [dataclasses.asdict(f) for f in findings],
        },
        indent=2,
    )


def render_sarif(findings: list[Finding]) -> str:
    """SARIF 2.1.0 — the GitHub code-scanning upload format. Suppressed
    and baselined findings are included but carry a `suppressions`
    entry, so code scanning shows them as dismissed rather than open."""
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": norm_path(f.path),
                            "uriBaseId": "%SRCROOT%",
                        },
                        "region": {
                            "startLine": f.line,
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
        }
        if f.suppressed:
            result["suppressions"] = [
                {"kind": "inSource", "justification": "mocolint: disable comment"}
            ]
        elif f.baselined:
            result["suppressions"] = [
                {"kind": "external", "justification": "mocolint-baseline.json"}
            ]
        results.append(result)
    payload = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "mocolint",
                        "informationUri": "https://example.invalid/mocolint",
                        "rules": [
                            {
                                "id": rule_id,
                                "shortDescription": {"text": summary},
                            }
                            for rule_id, summary in iter_rules()
                        ],
                    }
                },
                "columnKind": "utf16CodeUnits",
                "results": results,
            }
        ],
    }
    return json.dumps(payload, indent=2)
