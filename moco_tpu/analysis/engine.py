"""mocolint engine: rule registry, suppression comments, reporting.

Rules live in `moco_tpu/analysis/rules/` — one module per rule, each
registering itself with :func:`rule`. A rule is a callable
``(ModuleContext) -> Iterable[(ast_node_or_line, message)]``; the engine
stamps rule id / path / position, applies suppression comments, and
renders text or JSON.

Suppression is per line, per rule::

    risky_line()  # mocolint: disable=JX003  (why this is intentional)
    other()       # mocolint: disable=JX001,JX002
    anything()    # mocolint: disable=all

Suppressed findings are kept (with ``suppressed=True``) so reports can
audit them; only unsuppressed findings affect the exit code.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Iterable, Iterator, Optional, Union

from moco_tpu.analysis.astutils import ModuleContext

RuleResult = Iterable[tuple[Union[ast.AST, int], str]]
RuleFn = Callable[[ModuleContext], RuleResult]

_RULES: dict[str, tuple[str, RuleFn]] = {}

_SUPPRESS_RE = re.compile(r"#\s*mocolint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    message: str
    path: str
    line: int
    col: int = 0
    suppressed: bool = False

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}{tag}"


def rule(rule_id: str, summary: str) -> Callable[[RuleFn], RuleFn]:
    """Decorator registering a rule under its JXnnn id."""

    def deco(fn: RuleFn) -> RuleFn:
        _RULES[rule_id] = (summary, fn)
        return fn

    return deco


def _load_rules() -> None:
    # importing the package registers every rule module
    import moco_tpu.analysis.rules  # noqa: F401


def iter_rules() -> list[tuple[str, str]]:
    """[(rule_id, one-line summary)] for --list-rules and the README table."""
    _load_rules()
    return sorted((rid, summary) for rid, (summary, _) in _RULES.items())


def _suppressed_rules(line: str) -> set[str]:
    m = _SUPPRESS_RE.search(line)
    if not m:
        return set()
    return {tok.strip().upper() for tok in m.group(1).split(",") if tok.strip()}


def analyze_source(
    source: str, path: str, rules: Optional[Iterable[str]] = None
) -> list[Finding]:
    """All findings (suppressed ones flagged, not dropped) for one file."""
    _load_rules()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                rule="PARSE",
                message=f"syntax error: {e.msg}",
                path=path,
                line=e.lineno or 1,
                col=e.offset or 0,
            )
        ]
    ctx = ModuleContext(tree, source, path)
    selected = set(rules) if rules is not None else set(_RULES)
    findings: list[Finding] = []
    for rule_id, (_, fn) in sorted(_RULES.items()):
        if rule_id not in selected:
            continue
        for node, message in fn(ctx):
            line = node if isinstance(node, int) else getattr(node, "lineno", 1)
            col = 0 if isinstance(node, int) else getattr(node, "col_offset", 0)
            src_line = (
                ctx.source_lines[line - 1] if 0 < line <= len(ctx.source_lines) else ""
            )
            suppressed_here = _suppressed_rules(src_line)
            findings.append(
                Finding(
                    rule=rule_id,
                    message=message,
                    path=path,
                    line=line,
                    col=col,
                    suppressed=rule_id.upper() in suppressed_here
                    or "ALL" in suppressed_here,
                )
            )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs if d != "__pycache__" and not d.startswith(".")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def analyze_paths(
    paths: Iterable[str], rules: Optional[Iterable[str]] = None
) -> list[Finding]:
    findings: list[Finding] = []
    for f in iter_python_files(paths):
        with open(f, "r", encoding="utf-8") as fh:
            findings.extend(analyze_source(fh.read(), f, rules=rules))
    return findings


def render_text(findings: list[Finding], show_suppressed: bool = False) -> str:
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    lines = [f.render() for f in shown]
    active = sum(1 for f in findings if not f.suppressed)
    muted = len(findings) - active
    lines.append(
        f"mocolint: {active} finding(s)"
        + (f", {muted} suppressed" if muted else "")
    )
    return "\n".join(lines)


def render_json(findings: list[Finding]) -> str:
    return json.dumps(
        {
            "version": 1,
            "counts": {
                "active": sum(1 for f in findings if not f.suppressed),
                "suppressed": sum(1 for f in findings if f.suppressed),
            },
            "findings": [dataclasses.asdict(f) for f in findings],
        },
        indent=2,
    )
