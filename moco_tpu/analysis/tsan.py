"""Runtime lock-order / held-lock sanitizer — `--sanitize-threads`.

The static pass (JX012/JX013) catches the provable races and
inversions; this is the runtime arm for the ones it can't see (locks
acquired through foreign code, data-dependent paths, the cross-object
nesting a per-class analysis doesn't model). The failure mode it
defends against is the serving twin of the collective-schedule
deadlock: two threads acquire the same two locks in opposite orders,
nothing errors, the replica just stops answering until the SLO burn
pages a human — with no artifact saying which two stacks wedged it.

Mechanism (the `analysis/sanitizer.py` idiom, applied to locks):

- **Injectable lock factory** — `make_lock(name)` / `make_rlock(name)`
  return a :class:`TracedLock` wrapping the stdlib primitive. With no
  recorder installed the wrapper costs one module-global None check per
  acquire (the `utils/faults.py` zero-cost contract), so production
  code adopts the factory unconditionally; the serving stack's locks
  (`serve.index`, `serve.metrics`, `obs.prometheus`) already do.
- **Order recording** — an installed :class:`LockOrderRecorder` keeps a
  per-thread stack of held locks. Acquiring B while holding A records
  the edge A→B with the acquiring stack, first-seen. The edge set IS
  the process's lock-order graph; a cycle appearing at acquire time —
  BEFORE the acquire blocks — means two code paths disagree on the
  global order. The recorder dumps ``lock_order_diff.json`` with BOTH
  edges' per-thread stacks and (strict mode) raises
  :class:`LockOrderError`, turning tomorrow's wedged replica into
  today's diagnosable abort.
- **Held-lock blocking ops** — `install_profile()` hooks
  `sys.setprofile`/`threading.setprofile` and records calls that can
  block unboundedly (queue `put`/`get`, `urlopen`,
  `block_until_ready`, `time.sleep`) issued while a traced lock is
  held — the runtime shadow of JX013's second finding. Informational:
  they land in `report()` (and the smoke artifacts), they don't abort;
  some critical sections hold a lock across device work BY DESIGN
  (the engine call under `serve.index`).
- **Chaos hook** — `deadlock@site=<lock>` (`utils/faults.py`) forces an
  inverted acquisition order at the tagged lock: when it is acquired
  while another lock is held, the recorder also records the edge the
  OTHER order would have produced, as if a second thread had raced the
  critical section backwards. Deterministic cycle, real detection path,
  no actual deadlock risk — how CI proves the detector end-to-end
  (the serve_smoke `--sanitize-threads` leg).

Stdlib-only (the analyzer's `--no-deps` CI install); jax never imported
— blocking-op matching is by code-object name, not identity.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import traceback
from typing import Optional

# NB: `moco_tpu.utils.faults` is imported INSIDE the recorder hook, not
# here — the obs modules adopt the lock factory at import time, and a
# module-level utils import would close the cycle
# obs.trace -> tsan -> utils/__init__ -> checkpoint -> obs.trace.


class LockOrderError(RuntimeError):
    """Two code paths acquire the same locks in opposite orders —
    aborting with both stacks beats deadlocking under load."""


class TracedLock:
    """A named lock that reports acquisition order to the installed
    recorder (no recorder: one global None check of overhead)."""

    def __init__(self, name: str, rlock: bool = False):
        self.name = str(name)
        self._lock = threading.RLock() if rlock else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        rec = _RECORDER
        if rec is not None:
            rec.on_acquire_intent(self.name)
        got = self._lock.acquire(blocking, timeout)
        if rec is not None:
            if got:
                rec.on_acquired(self.name)
            else:
                rec.on_acquire_abandoned(self.name)
        return got

    def release(self) -> None:
        rec = _RECORDER
        self._lock.release()
        if rec is not None:
            rec.on_release(self.name)

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()


def make_lock(name: str) -> TracedLock:
    """The injectable factory: a drop-in `threading.Lock()` replacement
    that the runtime sanitizer can see."""
    return TracedLock(name)


def make_rlock(name: str) -> TracedLock:
    return TracedLock(name, rlock=True)


def _stack(limit: int = 12) -> list[str]:
    """Compact acquiring-stack summary, tsan/this module frames pruned."""
    frames = traceback.extract_stack()[:-2]
    out = [
        f"{os.path.basename(f.filename)}:{f.lineno} in {f.name}"
        for f in frames
        if "analysis/tsan" not in f.filename.replace(os.sep, "/")
    ]
    return out[-limit:]


class LockOrderRecorder:
    """Per-thread held-lock stacks + the process's lock-order graph.

    `strict=True` raises :class:`LockOrderError` at the acquire that
    closes a cycle (unit tests, the train driver); `strict=False`
    records the violation and keeps serving (the smoke legs assert on
    `report()` / the dumped artifact instead of crashing mid-request).
    """

    def __init__(self, workdir: Optional[str] = None, strict: bool = True):
        self.workdir = workdir
        self.strict = strict
        self._tls = threading.local()
        self._mu = threading.Lock()  # guards the graph, never user locks
        # (held, acquired) -> {"thread", "stack", "injected"} first-seen
        self.edges: dict[tuple[str, str], dict] = {}
        self.cycles: list[dict] = []
        self.blocking_ops: list[dict] = []
        self.acquisitions = 0

    # -- per-thread state --------------------------------------------------

    def _held(self) -> list[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_locks(self) -> list[str]:
        return list(self._held())

    # -- acquire/release hooks --------------------------------------------

    def on_acquire_intent(self, name: str) -> None:
        """Called BEFORE blocking on the lock: record the would-be
        edges and check for a cycle while this thread can still abort."""
        held = self._held()
        if not held:
            return
        stack = _stack()
        thread = threading.current_thread().name
        new_edges: list[tuple[str, str, bool]] = [
            (h, name, False) for h in held if h != name
        ]
        # deadlock@site=<lock>: the fault forces the INVERTED order to be
        # recorded too, as if another thread raced the opposite way — a
        # deterministic cycle through the real detection path
        from moco_tpu.utils import faults

        if faults.deadlock_marker(name):
            new_edges.extend((name, h, True) for h in held if h != name)
        with self._mu:
            for a, b, injected in new_edges:
                self.edges.setdefault(
                    (a, b),
                    {"thread": thread, "stack": stack, "injected": injected},
                )
            cycle = self._find_cycle(name)
        if cycle is not None:
            self._report_cycle(cycle, name, stack, thread)

    def on_acquired(self, name: str) -> None:
        self._held().append(name)
        with self._mu:
            self.acquisitions += 1

    def on_acquire_abandoned(self, name: str) -> None:
        pass  # non-blocking acquire that failed: nothing held

    def on_release(self, name: str) -> None:
        held = self._held()
        if name in held:
            held.reverse()
            held.remove(name)  # innermost occurrence (RLock re-entry)
            held.reverse()

    # -- blocking ops (profile hook) --------------------------------------

    def on_blocking_op(self, desc: str) -> None:
        held = self._held()
        if not held:
            return
        with self._mu:
            if len(self.blocking_ops) < 256:
                self.blocking_ops.append(
                    {
                        "op": desc,
                        "held": list(held),
                        "thread": threading.current_thread().name,
                        "stack": _stack(),
                    }
                )

    # -- cycle detection ---------------------------------------------------

    def _find_cycle(self, start: str) -> Optional[list[str]]:
        """A cycle through `start` in the edge graph (call with _mu held).
        Lock counts are single digits; DFS is plenty."""
        adj: dict[str, list[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        path = [start]
        seen = {start}

        def dfs(cur: str) -> Optional[list[str]]:
            for nxt in sorted(adj.get(cur, ())):
                if nxt == start:
                    return path + [start]
                if nxt not in seen:
                    seen.add(nxt)
                    path.append(nxt)
                    hit = dfs(nxt)
                    if hit is not None:
                        return hit
                    path.pop()
            return None

        return dfs(start)

    def _report_cycle(
        self, cycle: list[str], name: str, stack: list[str], thread: str
    ) -> None:
        with self._mu:
            edge_dump = [
                {
                    "held": a,
                    "acquired": b,
                    "thread": info["thread"],
                    "injected": info["injected"],
                    "stack": info["stack"],
                }
                for (a, b), info in sorted(self.edges.items())
                if a in cycle and b in cycle
            ]
            record = {
                "cycle": cycle,
                "acquiring": {"lock": name, "thread": thread, "stack": stack},
                "edges": edge_dump,
            }
            self.cycles.append(record)
        path = self.dump(record)
        msg = (
            f"lock-order cycle: {' -> '.join(cycle)} — thread {thread!r} "
            f"acquiring {name!r} closes an order another path recorded "
            "inverted; both acquisition stacks in "
            + (path or "report()")
        )
        if self.strict:
            raise LockOrderError(msg)
        print(f"WARNING: {msg}", flush=True)

    def dump(self, record: dict) -> Optional[str]:
        """Write ``lock_order_diff.json`` (atomic replace) when a workdir
        is configured; returns the path."""
        if not self.workdir:
            return None
        os.makedirs(self.workdir, exist_ok=True)
        path = os.path.join(self.workdir, "lock_order_diff.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=2)
        os.replace(tmp, path)
        return path

    # -- reporting ---------------------------------------------------------

    def report(self) -> dict:
        """The run's lock-order summary — the smoke artifact next to
        `schedule.p<i>.json`: edges observed, cycles caught, blocking
        ops seen under a lock (informational)."""
        with self._mu:
            return {
                "acquisitions": self.acquisitions,
                "edges": [
                    {"held": a, "acquired": b, "injected": info["injected"]}
                    for (a, b), info in sorted(self.edges.items())
                ],
                "cycles": [dict(c) for c in self.cycles],
                "blocking_ops_under_lock": [dict(b) for b in self.blocking_ops],
            }


# -- module-level hook (read by every TracedLock) --------------------------

_RECORDER: Optional[LockOrderRecorder] = None


def install_recorder(
    recorder: Optional[LockOrderRecorder],
) -> Optional[LockOrderRecorder]:
    """Install (or clear, with None) the process-wide recorder; returns
    the previous one so tests can restore it."""
    global _RECORDER
    prev = _RECORDER
    _RECORDER = recorder
    return prev


def get_recorder() -> Optional[LockOrderRecorder]:
    return _RECORDER


def enabled() -> bool:
    return _RECORDER is not None


# -- blocking-op profile hook ----------------------------------------------

# code-object names that can block unboundedly, matched per call event;
# (co_name, filename fragment or None)
_BLOCKING_CO = {
    ("put", "queue.py"),
    ("get", "queue.py"),
    ("urlopen", "request.py"),
    ("block_until_ready", None),
    ("_wait_for_tstate_lock", "threading.py"),  # Thread.join's blocking core
}

_PREV_PROFILE = None
_PREV_THREAD_PROFILE = None


def _profile(frame, event, arg):
    rec = _RECORDER
    if rec is None:
        return
    if event == "c_call":  # builtins come through as c_call, arg = the fn
        if getattr(arg, "__module__", None) == "time" and getattr(
            arg, "__name__", ""
        ) == "sleep":
            rec.on_blocking_op("time.sleep")
        return
    if event != "call":
        return
    co = frame.f_code
    for name, frag in _BLOCKING_CO:
        if co.co_name != name:
            continue
        if frag is not None and frag not in co.co_filename:
            continue
        # queue put/get with a timeout are bounded — not a finding
        if name in ("put", "get"):
            loc = frame.f_locals
            if loc.get("timeout") is not None or loc.get("block") is False:
                return
        rec.on_blocking_op(f"{name} ({os.path.basename(co.co_filename)})")
        return


def install_profile() -> None:
    """Watch for blocking calls under a traced lock, process-wide (new
    threads via `threading.setprofile`, the caller via `sys.setprofile`).
    Smoke-run tooling: profile hooks cost real CPU — never on in
    production serving."""
    global _PREV_PROFILE, _PREV_THREAD_PROFILE
    _PREV_PROFILE = sys.getprofile()
    threading.setprofile(_profile)
    sys.setprofile(_profile)


def uninstall_profile() -> None:
    threading.setprofile(None)
    sys.setprofile(_PREV_PROFILE)


class ThreadSanitizer:
    """The `--sanitize-threads` driver arm: install the recorder (+
    profile hook), run, `close()` to restore and write the report.

    `strict` follows the context: True for the train driver (abort the
    run at the cycle, like ScheduleDivergenceError), False inside a
    serving smoke (record, dump, keep answering; the smoke asserts on
    the artifacts)."""

    def __init__(
        self,
        workdir: Optional[str] = None,
        strict: bool = True,
        profile: bool = True,
    ):
        self.recorder = LockOrderRecorder(workdir=workdir, strict=strict)
        self._prev = install_recorder(self.recorder)
        self._profiling = bool(profile)
        if self._profiling:
            install_profile()

    def check(self) -> None:
        """Raise if any cycle was recorded (non-strict recorders defer
        the abort decision to this, the log-step-shaped hook)."""
        if self.recorder.cycles:
            raise LockOrderError(
                f"{len(self.recorder.cycles)} lock-order cycle(s) recorded — "
                "see lock_order_diff.json"
            )

    def report(self) -> dict:
        return self.recorder.report()

    def close(self) -> dict:
        """Restore hooks, write ``lock_order.json`` (when a workdir is
        configured), return the report."""
        if self._profiling:
            uninstall_profile()
            self._profiling = False
        install_recorder(self._prev)
        rep = self.report()
        if self.recorder.workdir:
            os.makedirs(self.recorder.workdir, exist_ok=True)
            path = os.path.join(self.recorder.workdir, "lock_order.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(rep, f, indent=2)
            os.replace(tmp, path)
        return rep


__all__ = [
    "LockOrderError",
    "LockOrderRecorder",
    "ThreadSanitizer",
    "TracedLock",
    "enabled",
    "get_recorder",
    "install_profile",
    "install_recorder",
    "make_lock",
    "make_rlock",
    "uninstall_profile",
]
