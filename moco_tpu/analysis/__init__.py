"""mocolint — JAX/TPU-aware static analysis for this repository.

The invariants MoCo correctness and TPU throughput hang on are invisible
to Python's type system: the key encoder must only move via EMA under
`stop_gradient` (He et al., arXiv:1911.05722), PRNG keys must never be
consumed twice, the jitted hot path must contain zero host round-trips
and zero recompile hazards, and — the pod-scale one — every host must
issue the SAME collectives in the SAME order or the fleet deadlocks
silently. `mocolint` checks these *before* the run:

====  =========================================================
Rule  Checks
====  =========================================================
JX001 impure calls (`time.*`, stdlib `random.*`, `print`, `global`
      mutation) inside jit/shard_map-compiled functions
JX002 implicit host transfer on traced values (`float()`, `int()`,
      `bool()`, `np.asarray`, `.item()`) inside jitted scope —
      interprocedural: jitted scope closes over resolved calls
      ACROSS modules
JX003 PRNG key reuse — one key consumed by two samplers without an
      interleaving `split`/`fold_in`; helper calls resolve through
      dataflow summaries (a pure fold_in wrapper is not a use)
JX004 recompile hazards — non-hashable literals in static args,
      `static_argnames` not in the wrapped signature, Python
      branching on `.shape` inside jitted scope
JX005 key-encoder/queue tensors reaching a loss without
      `stop_gradient` (the MoCo invariant) — interprocedural:
      taint crosses helper returns, summary-proven sanitizers
      clean, and a tainted argument handed to a helper whose
      parameter reaches an einsum/cross_entropy inside fires at
      the call site
JX006 `donate_argnums` buffers read again after the jitted call
JX007 collective axis names inconsistent with the enclosing
      `shard_map`/`pmap` axis declaration (lexical)
JX008 SPMD divergence — a collective issued under HOST-LOCAL
      control flow (`process_index`, wall clock, per-host retry/
      decode counters, exception handlers): the silent-pod-
      deadlock bug class
JX009 mixed-precision hazards — bf16/f16 operands reaching
      matmul/einsum/`@`/psum without `preferred_element_type=`
      f32 accumulation (or a cast up before the reduction)
JX010 interprocedural sharding consistency — a HELPER-issued
      collective (resolved through call-site axis bindings, JX007
      generalized across functions and modules) naming an axis
      the enclosing shard_map does not declare
JX011 input-wire thread hygiene — threads started without
      join-on-close; blocking `put` on a bounded queue with no
      poison-pill/timeout path (the PR-5 producer-leak shape)
JX012 shared mutable attribute written without a common lock
      across its accessing threads — thread-escape analysis over
      Thread targets, HTTP handler methods (one thread per
      request), and callback escapes, with lock-sets inherited
      through always-under-lock helpers (analysis/threads.py)
JX013 lock-order cycles (lock A held while B is acquired, and
      elsewhere the inverse — the static deadlock) and blocking
      calls under a held lock (queue put/get with no timeout,
      `Event.wait()`, `urlopen`, `time.sleep`, device syncs)
JX014 AOT freeze discipline — a shape not derived from the
      registered bucket table reaching an unguarded
      `jit`/`lower().compile()` seam in a freeze-disciplined
      class: the `EngineRecompileError` class, caught statically
====  =========================================================

Since v2 the engine is a real analysis stack: `analysis/callgraph.py`
builds a whole-program call graph (module + method resolution across
every analyzed file) and `analysis/dataflow.py` computes per-function
summaries (taint propagation, sanitization, PRNG consumption,
host-local returns, transitive collectives with axis bindings) to a
fixpoint — so the rules above follow values across function and module
boundaries instead of stopping at the `def`.

Usage::

    python -m moco_tpu.analysis moco_tpu/ scripts/ tests/ train.py
    python -m moco_tpu.analysis moco_tpu/ --format json -o report.json

Suppress a finding with a justification — the comment may sit on ANY
line of the statement, including the closing line of a multi-line
call::

    x = balanced_unshuffle(rng, y)  # mocolint: disable=JX003  (involution reuses the key on purpose)

Baselines gate incremental rule rollout: ``--update-baseline`` writes
`mocolint-baseline.json` fingerprinting today's findings; later runs
auto-discover it (walking up from the analyzed paths; ``--no-baseline``
opts out) and fail only on NEW findings. CI lints `tests/` this way —
the lint fixtures' intentional findings live in the baseline.

The runtime arm complements the static pass inside the train driver:

- `--strict-tracing` (`analysis/runtime.py`): `jax.check_tracer_leaks`,
  a `compile_cache_misses` counter on every metrics.jsonl log line, and
  abort-on-recompile-after-warm-up;
- `--sanitize-collectives` (`analysis/sanitizer.py`): every
  `obs/comms.py`-tagged collective site records its (site, kind,
  operand-shape) into the process's traced schedule; log steps publish
  the schedule hash out-of-band (`schedule.p<i>.json`) and cross-check
  every peer, aborting with a per-site diff — and a
  `collective_schedule_hash` metrics field — BEFORE a schedule mismatch
  can deadlock the pod. `diverge@site=S` (`utils/faults.py`) injects a
  deterministic divergence for CI (`scripts/sanitizer_smoke.py`);
- `--sanitize-threads` (`analysis/tsan.py`): every lock built by the
  injectable `tsan.make_lock(name)` factory (serve.index,
  serve.metrics, obs.*, data.*) reports its acquisition order to a
  per-thread recorder; an order CYCLE — two paths nesting the same
  locks opposite ways, tomorrow's wedged replica — aborts (or, in the
  serving smokes, records) with BOTH acquisition stacks in
  `lock_order_diff.json`, and a stdlib profile hook logs blocking ops
  issued while a lock is held. `deadlock@site=<lock>` forces an
  inverted acquisition order at the tagged lock for the CI proof
  (the serve_smoke `--sanitize-threads` chaos leg).

`mocolint --changed <git-ref>` lints only the files differing from the
ref (plus untracked ones) — the fast CI pre-pass; the full
baseline-gated run stays the authoritative gate.
"""

from __future__ import annotations

from moco_tpu.analysis.engine import (
    Finding,
    analyze_paths,
    analyze_source,
    iter_rules,
    load_baseline,
    render_json,
    render_text,
    write_baseline,
)

__all__ = [
    "Finding",
    "analyze_paths",
    "analyze_source",
    "iter_rules",
    "load_baseline",
    "render_json",
    "render_text",
    "write_baseline",
]
