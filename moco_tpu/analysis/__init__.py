"""mocolint — JAX/TPU-aware static analysis for this repository.

The invariants MoCo correctness and TPU throughput hang on are invisible
to Python's type system: the key encoder must only move via EMA under
`stop_gradient` (He et al., arXiv:1911.05722), PRNG keys must never be
consumed twice, and the jitted hot path must contain zero host
round-trips and zero recompile hazards — a stray `float(loss)` inside
the step burns an hour of TPU time before anyone notices. `mocolint`
checks these *before* the run:

====  =========================================================
Rule  Checks
====  =========================================================
JX001 impure calls (`time.*`, stdlib `random.*`, `print`, `global`
      mutation) inside jit/shard_map-compiled functions
JX002 implicit host transfer on traced values (`float()`, `int()`,
      `bool()`, `np.asarray`, `.item()`) inside jitted scope
JX003 PRNG key reuse — one key consumed by two samplers without an
      interleaving `split`/`fold_in`
JX004 recompile hazards — non-hashable literals in static args,
      `static_argnames` not in the wrapped signature, Python
      branching on `.shape` inside jitted scope
JX005 key-encoder/queue tensors reaching a loss without
      `stop_gradient` (the MoCo invariant; `ops/losses.py:36` and
      `core/queue.py:37` are the known-good sanitizing patterns)
JX006 `donate_argnums` buffers read again after the jitted call
JX007 collective axis names inconsistent with the enclosing
      `shard_map`/`pmap` axis declaration
====  =========================================================

Usage::

    python -m moco_tpu.analysis moco_tpu/ scripts/ train.py
    python -m moco_tpu.analysis moco_tpu/ --format json -o report.json

Suppress a finding on its line with a justification::

    x = balanced_unshuffle(rng, y)  # mocolint: disable=JX003  (involution reuses the key on purpose)

The runtime arm (`moco_tpu/analysis/runtime.py`) complements the static
pass inside the train driver: `--strict-tracing` turns on
`jax.check_tracer_leaks`, surfaces a `compile_cache_misses` counter on
every metrics.jsonl log line, and aborts when the step function
recompiles after warm-up.
"""

from __future__ import annotations

from moco_tpu.analysis.engine import (
    Finding,
    analyze_paths,
    analyze_source,
    iter_rules,
    render_json,
    render_text,
)

__all__ = [
    "Finding",
    "analyze_paths",
    "analyze_source",
    "iter_rules",
    "render_json",
    "render_text",
]
