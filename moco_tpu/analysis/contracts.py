"""Cross-artifact contract registry + runtime contract-coverage recorder.

The serving fleet is held together by stringly-typed contracts that no
single-file rule can check: metric keys must have a validator in
`obs/schema.py`, HTTP clients must call routes some handler actually
serves (with the headers it requires), `kind@site=` fault specs must
name sites a hook can fire, and the magic exit codes / port-offset rule
must come from `utils/contracts.py` instead of being re-typed inline.

Two arms share this module:

- **Static** (`build_registry` / `registry_for`): one pass over the
  whole analyzed program extracting every side of every contract —
  metric emissions and validator tables, handler routes and
  urlopen-client calls (methods, headers, status codes), fault hook
  sites and spec literals, exit-code/port literals. The JX015-JX018
  rules are thin checks over this registry; it is built once per
  program and cached, so four rules cost one extraction.

- **Runtime** (`ContractCoverageRecorder`): the `--contract-coverage`
  arm of the smoke scripts. Install a recorder and every applied schema
  validator (`obs/schema.py` callback), every handled route
  (`record_route` calls in serve/server.py + serve/router.py) and every
  reached fault hook (`utils/faults.py` callback) is counted;
  `check_coverage` then fails the leg on any registered contract that
  never fired — the "newly-dead contract" CI gate.

Like the rest of mocolint this is approximate on purpose: extraction
only trusts literals (and module-level string constants) and skips
anything dynamic, trading recall for a near-zero false-positive rate.
"""

from __future__ import annotations

import ast
import json
import re
import threading
from typing import Iterable, Optional

from moco_tpu.analysis.astutils import ModuleContext
from moco_tpu.utils import contracts as decl

# ---------------------------------------------------------------------------
# extraction helpers

# a metric key / prefix family: lowercase family name, a slash, rest
_METRIC_KEY_RE = re.compile(r"^[a-z][a-z0-9_]*/")

# a resolvable fault site: lowercase dotted name (placeholders like
# `<lock>` or a bare `S` in grammar docs never match)
_SITE_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")

# kind@params tokens inside any string (docstrings included — doc drift
# is drift). `\x00` marks an f-string placeholder, see _joined_literal.
_SPEC_RE = re.compile(
    r"\b(ckpt_truncate|io|nan|stall|preempt|delay|diverge|slow|kill|deadlock)"
    r"@([A-Za-z0-9_.=:\x00-]+)"
)

_HTTP_METHODS = ("GET", "POST", "PUT", "DELETE", "HEAD", "PATCH")

_PLACEHOLDER = "\x00"


def _joined_literal(node: ast.JoinedStr) -> str:
    """An f-string as text, formatted values replaced by `\\x00`."""
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            parts.append(_PLACEHOLDER)
    return "".join(parts)


def _literal_head(node: ast.JoinedStr) -> Optional[str]:
    """The leading literal chunk of an f-string ('serve/trace_' of
    f"serve/trace_{stage}_ms"), or None when it starts dynamic."""
    if node.values and isinstance(node.values[0], ast.Constant):
        v = node.values[0].value
        if isinstance(v, str):
            return v
    return None


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def parse_fault_specs(text: str) -> list[dict]:
    """Every `kind@k=v[:k=v...]` token in a string. Values containing an
    f-string placeholder come back as None (dynamic, unverifiable)."""
    out = []
    for m in _SPEC_RE.finditer(text):
        kind, body = m.group(1), m.group(2)
        params: dict = {}
        for tok in body.split(":"):
            key, eq, val = tok.partition("=")
            if not eq:
                params.setdefault(key, None)
                continue
            params[key] = None if _PLACEHOLDER in val else val
        out.append({"kind": kind, "params": params, "raw": m.group(0)})
    return out


def _route_from_url(node: ast.AST) -> tuple[Optional[str], bool]:
    """(route, found_literal) for a client URL expression.

    Handles `"http://h:p/stats"`, `base + "/healthz"`, and
    f"{base}/admin/drain?replica={i}" shapes; anything fully dynamic
    returns (None, False). Query strings are stripped — the route is
    the path."""
    texts: list[str] = []
    s = _str_const(node)
    if s is not None:
        texts.append(s)
    elif isinstance(node, ast.JoinedStr):
        texts.extend(
            v.value
            for v in node.values
            if isinstance(v, ast.Constant) and isinstance(v.value, str)
        )
    elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        for side in (node.left, node.right):
            r, found = _route_from_url(side)
            if found:
                return r, True
        return None, False
    for text in texts:
        m = re.search(r"https?://[^/\s]+(/[^\s\"']*)", text)
        if m:
            text = m.group(1)
        if text.startswith("/"):
            route = text.split("?")[0].rstrip()
            if route and route != "/":
                return route, True
    return None, False


class _Item:
    """One extracted contract occurrence (a location plus fields)."""

    __slots__ = ("path", "line", "data")

    def __init__(self, path: str, line: int, **data):
        self.path = path
        self.line = line
        self.data = data

    def __getattr__(self, name):
        try:
            return self.data[name]
        except KeyError:
            raise AttributeError(name)


class ContractRegistry:
    """Every side of every extracted contract, program-wide."""

    def __init__(self):
        # metric schema
        self.emitted_keys: list[_Item] = []  # key=
        self.emitted_prefixes: list[_Item] = []  # prefix=
        self.field_validators: list[_Item] = []  # key=
        self.prefix_validators: list[_Item] = []  # prefix=
        self.schema_paths: set[str] = set()
        # any string constant occurrence: value -> set of paths
        self.literal_strings: dict[str, set[str]] = {}
        # http
        self.handler_routes: list[_Item] = []  # route=, method=, cls=
        self.client_calls: list[_Item] = []  # route=, method=, func= (node|None)
        self.retry_wraps: list[_Item] = []  # routes=tuple
        self.class_headers: dict[str, set[str]] = {}  # "path::Class" -> X- headers
        self.module_headers: dict[str, set[str]] = {}  # path -> X- headers
        self.handler_status: list[_Item] = []  # code=
        self.client_status: list[_Item] = []  # code=
        # faults
        self.hook_sites: list[_Item] = []  # kind=, site=
        self.spec_literals: list[_Item] = []  # kind=, params=, raw=
        # registry-module presence gates the whole-tree-only clauses
        self.has_registry_module: bool = False
        # every analyzed path — scope gates (e.g. "is the test corpus
        # in this program?") key off it
        self.paths: set[str] = set()

    def hook_site_set(self, kind: str) -> set[str]:
        return {h.site for h in self.hook_sites if h.kind == kind}

    def validator_keys(self) -> set[str]:
        return {v.key for v in self.field_validators}

    def validator_prefixes(self) -> set[str]:
        return {v.prefix for v in self.prefix_validators}

    def to_json(self) -> dict:
        def items(seq):
            return [dict(i.data, path=i.path, line=i.line) for i in seq]

        return {
            "emitted_keys": items(self.emitted_keys),
            "emitted_prefixes": items(self.emitted_prefixes),
            "field_validators": items(self.field_validators),
            "prefix_validators": items(self.prefix_validators),
            "handler_routes": items(self.handler_routes),
            "client_calls": [
                {k: v for k, v in dict(i.data, path=i.path, line=i.line).items()
                 if k != "func"}
                for i in self.client_calls
            ],
            "retry_wraps": items(self.retry_wraps),
            "handler_status": items(self.handler_status),
            "client_status": items(self.client_status),
            "hook_sites": items(self.hook_sites),
            "spec_literals": items(self.spec_literals),
        }


def build_registry(contexts: dict[str, ModuleContext]) -> ContractRegistry:
    reg = ContractRegistry()
    for path, ctx in contexts.items():
        reg.paths.add(path)
        _extract_module(reg, path, ctx)
    return reg


def registry_for(ctx: ModuleContext) -> ContractRegistry:
    """The program-wide registry for this module's program, built once
    and cached on the Program object (single-module fallback when the
    context was never attached to a program)."""
    program = ctx.program
    if program is None:
        return build_registry({ctx.path: ctx})
    cached = getattr(program, "_contract_registry", None)
    if cached is None:
        cached = build_registry(program.contexts)
        program._contract_registry = cached
    return cached


# ---------------------------------------------------------------------------
# per-module extraction


def _extract_module(reg: ContractRegistry, path: str, ctx: ModuleContext) -> None:
    tree = ctx.tree
    if path.replace("\\", "/").endswith("utils/contracts.py") or any(
        isinstance(n, ast.Assign)
        and any(
            isinstance(t, ast.Name) and t.id == "SERVE_STAGE_SITES"
            for t in n.targets
        )
        for n in tree.body
    ):
        reg.has_registry_module = True

    validator_dicts: set[int] = set()  # Dict node ids to skip as emissions
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict)):
            continue
        names = {t.id for t in node.targets if isinstance(t, ast.Name)}
        kind = (
            "field"
            if "FIELD_VALIDATORS" in names
            else "prefix"
            if "PREFIX_VALIDATORS" in names
            else None
        )
        if kind is None:
            continue
        validator_dicts.add(id(node.value))
        reg.schema_paths.add(path)
        for k in node.value.keys:
            key = _str_const(k)
            if key is None:
                continue
            item = _Item(path, k.lineno, **{("key" if kind == "field" else "prefix"): key})
            (reg.field_validators if kind == "field" else reg.prefix_validators).append(
                item
            )

    # innermost-enclosing-function lookup for client header checks
    fn_spans = sorted(
        (
            (f.lineno, getattr(f, "end_lineno", f.lineno), f)
            for f in ctx.functions
        ),
        key=lambda t: (t[1] - t[0]),
    )

    def enclosing_fn(line: int) -> Optional[ast.FunctionDef]:
        for start, end, f in fn_spans:
            if start <= line <= end:
                return f
        return None

    mod_headers = reg.module_headers.setdefault(path, set())

    for node in ast.walk(tree):
        # -- string liveness + fault spec literals -------------------------
        text = None
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            text = node.value
            reg.literal_strings.setdefault(text, set()).add(path)
            if text.startswith("X-"):
                mod_headers.add(text)
        elif isinstance(node, ast.JoinedStr):
            text = _joined_literal(node)
        if text and "@" in text:
            for spec in parse_fault_specs(text):
                reg.spec_literals.append(_Item(path, node.lineno, **spec))

        # -- metric emissions ----------------------------------------------
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Subscript):
                    key = _str_const(t.slice)
                    if key is not None and _METRIC_KEY_RE.match(key):
                        reg.emitted_keys.append(_Item(path, t.lineno, key=key))
                    elif isinstance(t.slice, ast.JoinedStr):
                        head = _literal_head(t.slice)
                        if head and _METRIC_KEY_RE.match(head):
                            reg.emitted_prefixes.append(
                                _Item(path, t.lineno, prefix=head)
                            )
        if isinstance(node, ast.Dict) and id(node) not in validator_dicts:
            for k in node.keys:
                key = _str_const(k)
                if key is not None and _METRIC_KEY_RE.match(key):
                    reg.emitted_keys.append(_Item(path, k.lineno, key=key))
                elif isinstance(k, ast.JoinedStr):
                    head = _literal_head(k)
                    if head and _METRIC_KEY_RE.match(head):
                        reg.emitted_prefixes.append(_Item(path, k.lineno, prefix=head))

        if not isinstance(node, ast.Call):
            continue
        qual = ctx.qual(node.func) or ""
        base = qual.rsplit(".", 1)[-1]

        # -- fault hooks ----------------------------------------------------
        hook_kind = {
            "maybe_slow": "slow",
            "maybe_delay": "delay",
            "maybe_io_error": "io",
            "make_lock": "deadlock",
            "make_rlock": "deadlock",
        }.get(base)
        if hook_kind and node.args:
            site = _str_const(node.args[0])
            if site is None and isinstance(node.args[0], ast.Name):
                site = ctx.constants.get(node.args[0].id)
            # skip the grammar's own delegating defs (arg is a parameter)
            if site is not None and not path.replace("\\", "/").endswith(
                ("utils/faults.py", "analysis/tsan.py")
            ):
                reg.hook_sites.append(_Item(path, node.lineno, kind=hook_kind, site=site))

        # -- retry / hedge wrappers -----------------------------------------
        if base == "retry_call":
            fn = enclosing_fn(node.lineno)
            routes: list[str] = []
            if fn is not None:
                for n in ast.walk(fn):
                    if (
                        isinstance(n, ast.Compare)
                        and n.lineno <= node.lineno
                        and len(n.ops) == 1
                        and isinstance(n.ops[0], (ast.In, ast.NotIn))
                        and isinstance(n.comparators[0], (ast.Tuple, ast.List, ast.Set))
                    ):
                        for el in n.comparators[0].elts:
                            r = _str_const(el)
                            if r and r.startswith("/"):
                                routes.append(r)
            reg.retry_wraps.append(
                _Item(path, node.lineno, routes=tuple(dict.fromkeys(routes)))
            )

        # -- urlopen clients -------------------------------------------------
        is_request = qual.endswith("urllib.request.Request") or qual == "Request"
        is_urlopen = base == "urlopen"
        if is_request or is_urlopen:
            url_arg = node.args[0] if node.args else None
            route, found = (
                _route_from_url(url_arg) if url_arg is not None else (None, False)
            )
            if found:
                method = "GET"
                if (
                    len(node.args) > 1
                    and not (
                        isinstance(node.args[1], ast.Constant)
                        and node.args[1].value is None
                    )
                ) or any(
                    kw.arg == "data"
                    and not (
                        isinstance(kw.value, ast.Constant) and kw.value.value is None
                    )
                    for kw in node.keywords
                ):
                    method = "POST"
                for kw in node.keywords:
                    if kw.arg == "method":
                        m = _str_const(kw.value)
                        if m:
                            method = m.upper()
                reg.client_calls.append(
                    _Item(
                        path,
                        node.lineno,
                        route=route,
                        method=method,
                        func=enclosing_fn(node.lineno),
                    )
                )

        # -- status codes (registry data for reports/coverage) ---------------
        if base in ("send_response", "send_error") and node.args:
            code = node.args[0]
            if isinstance(code, ast.Constant) and isinstance(code.value, int):
                reg.handler_status.append(_Item(path, node.lineno, code=code.value))

    # -- handler routes: do_* methods keyed by innermost class ---------------
    class _ClassWalker(ast.NodeVisitor):
        def __init__(self):
            self.stack: list[ast.ClassDef] = []

        def visit_ClassDef(self, node: ast.ClassDef):
            self.stack.append(node)
            key = f"{path}::{node.name}"
            hdrs = reg.class_headers.setdefault(key, set())
            for n in ast.walk(node):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    if n.value.startswith("X-"):
                        hdrs.add(n.value)
            self.generic_visit(node)
            self.stack.pop()

        def visit_FunctionDef(self, node: ast.FunctionDef):
            if self.stack and node.name.startswith("do_"):
                method = node.name[3:].upper()
                if method in _HTTP_METHODS:
                    cls = self.stack[-1].name
                    seen: set[tuple] = set()
                    for n in ast.walk(node):
                        lits: list[tuple[str, int]] = []
                        if isinstance(n, ast.Compare):
                            for cand in [n.left, *n.comparators]:
                                s = _str_const(cand)
                                if s and s.startswith("/"):
                                    lits.append((s, cand.lineno))
                                elif isinstance(cand, (ast.Tuple, ast.List, ast.Set)):
                                    for el in cand.elts:
                                        s = _str_const(el)
                                        if s and s.startswith("/"):
                                            lits.append((s, el.lineno))
                        elif (
                            isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Attribute)
                            and n.func.attr == "startswith"
                            and n.args
                        ):
                            s = _str_const(n.args[0])
                            if s and s.startswith("/"):
                                lits.append((s.split("?")[0], n.args[0].lineno))
                        for route, line in lits:
                            route = route.split("?")[0]
                            if (route, method) not in seen and route != "/":
                                seen.add((route, method))
                                reg.handler_routes.append(
                                    _Item(
                                        path, line, route=route, method=method, cls=cls
                                    )
                                )
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

    _ClassWalker().visit(tree)

    # client-observed status codes: `e.code == 503` comparisons
    for node in ast.walk(tree):
        if isinstance(node, ast.Compare) and len(node.comparators) == 1:
            sides = (node.left, node.comparators[0])
            for a, b in (sides, sides[::-1]):
                if (
                    isinstance(a, ast.Attribute)
                    and a.attr in ("code", "status")
                    and isinstance(b, ast.Constant)
                    and isinstance(b.value, int)
                ):
                    reg.client_status.append(_Item(path, node.lineno, code=b.value))


# ---------------------------------------------------------------------------
# runtime contract-coverage recorder


class ContractCoverageRecorder:
    """Thread-safe counters for contracts observed at runtime.

    Sections: `validators` (schema keys/prefixes that applied), `routes`
    ("METHOD /path" handled), `fault_hooks` ("kind@site" hook reached),
    `headers` (propagated trace headers parsed/injected — obs/ctxprop).
    Multi-process runs dump per-process files and merge with
    `merge_coverage`."""

    def __init__(self):
        self._lock = threading.Lock()
        self.validators: dict[str, int] = {}
        self.routes: dict[str, int] = {}
        self.fault_hooks: dict[str, int] = {}
        self.headers: dict[str, int] = {}

    def _bump(self, table: dict, key: str) -> None:
        with self._lock:
            table[key] = table.get(key, 0) + 1

    def record_validator(self, key: str) -> None:
        self._bump(self.validators, key)

    def record_route(self, method: str, path: str) -> None:
        self._bump(self.routes, f"{method.upper()} {path.split('?')[0]}")

    def record_fault_hook(self, kind: str, site: Optional[str]) -> None:
        self._bump(self.fault_hooks, f"{kind}@{site}" if site else kind)

    def record_header(self, name: str) -> None:
        self._bump(self.headers, name)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "validators": dict(self.validators),
                "routes": dict(self.routes),
                "fault_hooks": dict(self.fault_hooks),
                "headers": dict(self.headers),
            }

    def dump(self, path: str) -> dict:
        snap = self.snapshot()
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(snap, fh, indent=2, sort_keys=True)
            fh.write("\n")
        return snap


_RECORDER: Optional[ContractCoverageRecorder] = None


def install_recorder(
    rec: Optional[ContractCoverageRecorder] = None,
) -> ContractCoverageRecorder:
    """Install (and wire into obs/schema + utils/faults) a recorder."""
    global _RECORDER
    _RECORDER = rec or ContractCoverageRecorder()
    from moco_tpu.obs import ctxprop as _ctxprop
    from moco_tpu.obs import schema as _schema
    from moco_tpu.utils import faults as _faults

    _schema.set_coverage_callback(_RECORDER.record_validator)
    _faults.set_coverage_callback(_RECORDER.record_fault_hook)
    _ctxprop.set_coverage_callback(_RECORDER.record_header)
    return _RECORDER


def uninstall_recorder() -> None:
    global _RECORDER
    _RECORDER = None
    from moco_tpu.obs import ctxprop as _ctxprop
    from moco_tpu.obs import schema as _schema
    from moco_tpu.utils import faults as _faults

    _schema.set_coverage_callback(None)
    _faults.set_coverage_callback(None)
    _ctxprop.set_coverage_callback(None)


def get_recorder() -> Optional[ContractCoverageRecorder]:
    return _RECORDER


def record_route(method: str, path: str) -> None:
    """Zero-cost-when-off route hook for the HTTP handlers."""
    if _RECORDER is not None:
        _RECORDER.record_route(method, path)


def maybe_install_from_env() -> Optional[ContractCoverageRecorder]:
    """Child-process arm: `MOCO_CONTRACT_COVERAGE=1` in the environment
    (set by a smoke script before spawning replicas) installs a
    recorder; the replica dumps it on graceful shutdown."""
    import os

    if os.environ.get("MOCO_CONTRACT_COVERAGE"):
        return install_recorder()
    return None


def merge_coverage(snapshots: Iterable[dict]) -> dict:
    """Union per-process coverage dumps (counts added)."""
    out: dict = {"validators": {}, "routes": {}, "fault_hooks": {}, "headers": {}}
    for snap in snapshots:
        for section in out:
            for k, v in (snap.get(section) or {}).items():
                out[section][k] = out[section].get(k, 0) + int(v)
    return out


def check_coverage(
    coverage: dict,
    routes: Iterable[str] = (),
    fault_sites: Iterable[str] = (),
    validators: Iterable[str] = (),
    headers: Iterable[str] = (),
) -> list[str]:
    """Missing-contract descriptions (empty list = gate passes).

    `routes` entries are "METHOD /path"; `fault_sites` are "kind@site"
    (or a bare kind); `validators` are schema keys/prefixes; `headers`
    are propagated trace-header names (obs/ctxprop)."""
    missing = []
    seen_routes = set(coverage.get("routes") or {})
    for r in routes:
        if r not in seen_routes:
            missing.append(f"route never handled: {r}")
    seen_hooks = set(coverage.get("fault_hooks") or {})
    for s in fault_sites:
        if s not in seen_hooks:
            missing.append(f"fault hook never reached: {s}")
    seen_validators = set(coverage.get("validators") or {})
    for v in validators:
        if v not in seen_validators:
            missing.append(f"schema validator never applied: {v}")
    seen_headers = set(coverage.get("headers") or {})
    for h in headers:
        if h not in seen_headers:
            missing.append(f"trace header never propagated: {h}")
    return missing


def declared_route_gates(server: Optional[str] = None) -> list[str]:
    """The "METHOD /path" gate list from the declared ROUTES registry,
    optionally restricted to routes a given server ("replica"/"router")
    participates in."""
    out = []
    for path, r in sorted(decl.ROUTES.items()):
        if server is not None and r.server not in (server, "both"):
            continue
        for m in r.methods:
            out.append(f"{m} {path}")
    return out
