"""kNN evaluation monitor.

The reference's only quality signals are the per-step (K+1)-way contrast
accuracy and the full linear probe (SURVEY.md §4) — the probe costs 100
epochs of training. The standard cheap middle ground in the SSL
literature (Wu et al. instance discrimination; used by every MoCo
reproduction) is weighted-kNN on frozen backbone features: no training,
minutes not hours, correlates well with probe top-1. This gives the
rebuild an early-warning metric the reference lacks.

Classifier: cosine-similarity kNN with temperature-weighted voting —
    score(class c) = Σ_{i ∈ topk} 1[y_i = c] · exp(sim_i / T)

The cosine top-k scan itself is the serving subsystem's shared kernel
(`serve/index.py:topk_cosine`) — the same scan `/neighbors` answers
with, so a serving-side kernel regression is caught by the kNN tests
and vice versa (tests/test_serve.py pins bitwise equivalence against
the pre-refactor inline scan).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.obs.trace import span as obs_span
from moco_tpu.ops.losses import l2_normalize
from moco_tpu.parallel.mesh import DATA_AXIS


def extract_features(
    backbone,
    params,
    batch_stats,
    dataset,
    batch_size: int = 256,
    image_size: Optional[int] = None,
    mesh=None,
) -> tuple[np.ndarray, np.ndarray]:
    """L2-normalized backbone features + labels for a whole dataset.
    Center-crop-free: datasets decode to a fixed canvas already.

    With `mesh`, full batches are sharded over the `data` axis so
    extraction data-parallelizes across the mesh (params replicated);
    the ragged tail batch runs single-device."""
    from moco_tpu.data.augment import get_recipe, normalize

    recipe = get_recipe(False, image_size or 224)

    def forward_fn(raw):
        x = raw.astype(jnp.float32) / 255.0
        x = normalize(x, recipe.mean, recipe.std)
        feats = backbone.apply(
            {"params": params, "batch_stats": batch_stats}, x, train=False
        )
        return l2_normalize(feats)

    forward = jax.jit(forward_fn)
    shard = None
    # Single-controller only: plain device_put cannot target a mesh with
    # non-addressable devices; multi-host falls back to per-process
    # single-device extraction (the bank/test sets are small).
    if mesh is not None and jax.process_count() == 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = NamedSharding(mesh, P(DATA_AXIS))
        forward_sharded = jax.jit(forward_fn, out_shardings=NamedSharding(mesh, P()))
        # keep every full batch divisible by the data axis so the sharded
        # path actually serves them (not just shapes that happen to fit)
        n_axis = mesh.shape[DATA_AXIS]
        batch_size = -(-batch_size // n_axis) * n_axis

    feats_out, labels_out = [], []
    n = len(dataset)
    for start in range(0, n, batch_size):
        idx = np.arange(start, min(start + batch_size, n))
        if hasattr(dataset, "load_batch"):
            raw, labels = dataset.load_batch(idx)
        else:
            loads = [dataset.load(int(i)) for i in idx]
            raw = np.stack([im for im, _ in loads])
            labels = np.asarray([l for _, l in loads], np.int32)
        if shard is not None and len(idx) % mesh.shape[DATA_AXIS] == 0:
            feats = forward_sharded(jax.device_put(raw, shard))
        else:  # no mesh, or ragged tail: single device
            feats = forward(jnp.asarray(raw))
        feats_out.append(np.asarray(feats))
        labels_out.append(np.asarray(labels, np.int32))
    return np.concatenate(feats_out), np.concatenate(labels_out)


def knn_classify(
    train_feats: np.ndarray,  # (N, C) L2-normalized
    train_labels: np.ndarray,  # (N,)
    test_feats: np.ndarray,  # (M, C)
    num_classes: int,
    k: int = 200,
    temperature: float = 0.07,
    batch_size: int = 512,
) -> np.ndarray:
    """Predicted labels for test_feats via temperature-weighted kNN."""
    k = min(k, train_feats.shape[0])
    bank = jnp.asarray(train_feats)
    bank_labels = jnp.asarray(train_labels)

    from moco_tpu.serve.index import topk_cosine

    @jax.jit
    def classify(q):
        top_sims, top_idx = topk_cosine(q, bank, k)  # (m, k) cosine scan
        weights = jnp.exp(top_sims / temperature)  # (m, k)
        votes = jax.nn.one_hot(bank_labels[top_idx], num_classes)  # (m, k, C)
        scores = jnp.einsum("mk,mkc->mc", weights, votes)
        return jnp.argmax(scores, axis=-1)

    preds = []
    for start in range(0, test_feats.shape[0], batch_size):
        preds.append(np.asarray(classify(jnp.asarray(test_feats[start : start + batch_size]))))
    return np.concatenate(preds)


def knn_eval(
    backbone,
    params,
    batch_stats,
    train_dataset,
    test_dataset,
    num_classes: int,
    k: int = 200,
    temperature: float = 0.07,
    batch_size: int = 256,
    image_size: Optional[int] = None,
    mesh=None,
) -> float:
    """kNN top-1 (%) of frozen features — the cheap probe proxy.
    `mesh` data-parallelizes feature extraction over its `data` axis."""
    with obs_span("knn_eval", bank=len(train_dataset), test=len(test_dataset)):
        with obs_span("knn_extract_bank"):
            train_f, train_y = extract_features(
                backbone, params, batch_stats, train_dataset, batch_size, image_size, mesh=mesh
            )
        with obs_span("knn_extract_test"):
            test_f, test_y = extract_features(
                backbone, params, batch_stats, test_dataset, batch_size, image_size, mesh=mesh
            )
        with obs_span("knn_classify"):
            preds = knn_classify(train_f, train_y, test_f, num_classes, k, temperature)
        return float(100.0 * np.mean(preds == test_y))
