"""Linear probe ("lincls") — the TPU-native `main_lincls.py`.

Reference semantics reproduced exactly (SURVEY.md §3.2, §2.2 row 10):
- checkpoint surgery: keep only the pretrained query encoder's *backbone*
  (`main_lincls.py:~L170-195` keeps `module.encoder_q.*`, drops the
  projection head / fc). Here backbone and head are separate modules, so
  surgery is a key lookup, not string munging — and the
  `assert missing_keys == {fc.weight, fc.bias}` check becomes structural.
- fresh classifier: weight ~ N(0, 0.01), bias = 0 (`~L160-165`).
- ONLY the classifier trains: SGD(lr=30.0, momentum=0.9, wd=0), step
  schedule [60, 80] over 100 epochs (`~L200-210`).
- the backbone runs in EVAL mode during probe training — frozen BN
  running statistics, the quirk called out in SURVEY.md §7 hard-part 4
  (`train()` calls `model.eval()`, `~L300`).
- `sanity_check()`: after training, every backbone weight is bit-identical
  to the pretrained checkpoint (`~L380-400`).
- `model_best` snapshot by validation top-1 (`~L250-260`).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from moco_tpu.core.moco import MocoState, build_encoder, create_state
from moco_tpu.data.pipeline import EvalPipeline, LabeledPipeline
from moco_tpu.models import LinearClassifier
from moco_tpu.ops.losses import cross_entropy, topk_accuracy
from moco_tpu.parallel import create_mesh, shard_map
from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.utils.checkpoint import (
    CheckpointManager,
    best_exists,
    restore_best,
    save_best,
)
from moco_tpu.utils.config import (
    DataConfig,
    OptimConfig,
    ProbeConfig,
    TrainConfig,
    config_from_dict,
    config_to_dict,
    dataclass_from_dict,
)
from moco_tpu.utils.metrics import AverageMeter, MetricWriter, ProgressMeter
from moco_tpu.utils.schedules import build_optimizer


class ProbeState(struct.PyTreeNode):
    step: jax.Array
    fc_params: Any  # the only trainable leaves
    backbone_params: Any  # frozen
    backbone_stats: Any  # frozen BN running statistics
    opt_state: Any


def restore_pretrain_state(
    workdir: str,
    config: Optional[TrainConfig] = None,
    unshard: tuple = ("q",),
) -> tuple[MocoState, TrainConfig]:
    """Restore the full pretraining MocoState + its resolved config —
    the shared eval-side entry the probe surgery, the converters, and
    the serve engine all build on.

    With `config=None` the training config stored in the checkpoint's
    extras is used, so the exact model/optimizer template (arch, v3
    predictor, sgd/lars/adamw opt_state tree) is rebuilt without the
    caller re-specifying flags.

    `unshard`: which encoder sides ("q"/"k") to gather back to true
    shapes when the checkpoint persists ZeRO-2/3 (n, m) flat shards
    (full_param_shapes supplies the shapes; the sharded layout doesn't
    record them). Only the requested sides pay the one-shot host gather
    — this is the eval-side unshard every downstream tool
    (convert_pretrain, eval_lincls, export, serve) inherits."""
    from moco_tpu.core.moco import build_predictor
    from moco_tpu.utils.config import config_from_dict
    from moco_tpu.utils.schedules import build_optimizer

    mgr = CheckpointManager(workdir)
    # extras are needed to discover the config and/or the ZeRO mesh width;
    # skip the metadata round-trip entirely on the explicit-config,
    # replicated-opt-state fast path
    extra: dict = {}
    if config is None or config.parallel.shard_weight_update:
        extra = mgr.read_extra()
    if config is None:
        if "config" not in extra:
            raise KeyError(
                f"checkpoint under {workdir} carries no config — pass one explicitly"
            )
        config = config_from_dict(extra["config"])
    encoder = build_encoder(config.moco)
    predictor = build_predictor(config.moco)
    # the template's opt_state tree must match the saved one exactly, so
    # build the same optimizer family the pretrain driver used — including
    # the ZeRO layout: shard_weight_update saves (num_data, m) opt-state
    # leaves, with num_data = the TRAIN-time mesh width from extras (the
    # config alone may say "all devices")
    tx = build_optimizer(config.optim, steps_per_epoch=1)
    zero_num_data = None
    if config.parallel.shard_weight_update:
        zero_num_data = extra.get("num_data") or config.parallel.num_data
        if zero_num_data is None:
            raise ValueError(
                "ZeRO checkpoint carries no train-time num_data and "
                "config.parallel.num_data is unset — cannot size the "
                "opt-state restore template"
            )
    sample = jnp.zeros((1, config.data.image_size, config.data.image_size, 3), jnp.float32)
    template = create_state(
        jax.random.PRNGKey(0), config, encoder, tx, sample, predictor=predictor,
        zero_num_data=zero_num_data,
    )
    state, _ = mgr.restore(template)
    mgr.close()
    if config.parallel.shard_weight_update and config.parallel.zero_stage >= 2:
        # ZeRO-2/3: one-shot host gather of the requested sides back to
        # the true shapes (both encoders persist in the same (n, m)
        # layout, so one path covers both)
        from moco_tpu.core.moco import full_param_shapes
        from moco_tpu.parallel.zero import unshard_tree_host

        shapes = full_param_shapes(config, encoder, predictor)
        replaced = {}
        if "q" in unshard:
            replaced["params_q"] = unshard_tree_host(state.params_q, shapes["enc"])
        if "k" in unshard:
            replaced["params_k"] = unshard_tree_host(state.params_k, shapes["enc"])
        state = state.replace(**replaced)
    return state, config


def load_pretrained_backbone(
    workdir: str, config: Optional[TrainConfig] = None, side: str = "q"
) -> tuple[Any, Any, TrainConfig]:
    """Checkpoint surgery: restore the pretraining state and keep
    `params_<side>.backbone` + `batch_stats_<side>.backbone` — the
    functional equivalent of keeping `module.encoder_q.*` minus the head.

    `side` selects the encoder: "q" (query — the probe/export default,
    matching the reference's `module.encoder_q.*` surgery) or "k" (the
    EMA key encoder — the serving default: the slow-moving stable
    representation, per "How to Scale Your EMA" arXiv:2307.13813).
    Returns (backbone_params, backbone_stats, config)."""
    if side not in ("q", "k"):
        raise ValueError(f"side must be 'q' or 'k', got {side!r}")
    state, config = restore_pretrain_state(workdir, config, unshard=(side,))
    params = state.params_q if side == "q" else state.params_k
    stats = state.batch_stats_q if side == "q" else state.batch_stats_k
    missing = {k for k in ("backbone", "head") if k not in params}
    if missing:
        raise KeyError(f"pretrained params_{side} missing {missing}")
    return params["backbone"], stats.get("backbone", {}), config


def _build_probe_model(config: TrainConfig, num_classes: int):
    from moco_tpu.core.moco import create_backbone

    backbone = create_backbone(config.moco)  # resnet or vit, per the config
    classifier = LinearClassifier(num_classes=num_classes)
    return backbone, classifier


def make_probe_step(backbone, classifier, tx, mesh):
    """Jitted probe train step: frozen-backbone eval-mode forward,
    classifier-only grads, psum over the data axis."""

    def step_fn(state: ProbeState, images, labels):
        def loss_fn(fc_params):
            feats = backbone.apply(
                {"params": state.backbone_params, "batch_stats": state.backbone_stats},
                images,
                train=False,  # eval-mode BN — the reference's model.eval() quirk
            )
            feats = lax.stop_gradient(feats)
            logits = classifier.apply({"params": fc_params}, feats)
            return cross_entropy(logits, labels), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.fc_params)
        grads = lax.pmean(grads, DATA_AXIS)
        metrics = {"loss": loss, **topk_accuracy(logits, labels)}
        metrics = lax.pmean(metrics, DATA_AXIS)
        updates, opt_state = tx.update(grads, state.opt_state, state.fc_params)
        fc_params = optax.apply_updates(state.fc_params, updates)
        return state.replace(step=state.step + 1, fc_params=fc_params, opt_state=opt_state), metrics

    specs = ProbeState(step=P(), fc_params=P(), backbone_params=P(), backbone_stats=P(), opt_state=P())
    sharded = shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(specs, P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_eval_step(backbone, classifier, mesh):
    """Jitted eval step returning masked *sums* (not means), so padded
    tail batches score exactly the valid examples (`main_lincls.py`
    evaluates the full split)."""

    def eval_fn(state: ProbeState, images, labels, mask):
        feats = backbone.apply(
            {"params": state.backbone_params, "batch_stats": state.backbone_stats},
            images,
            train=False,
        )
        logits = classifier.apply({"params": state.fc_params}, feats)
        logz = jax.nn.logsumexp(logits, axis=-1)
        per_ex_loss = logz - jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
        _, top5 = lax.top_k(logits, 5)
        correct = top5 == labels[:, None]
        sums = {
            "loss": jnp.sum(per_ex_loss * mask),
            "correct1": jnp.sum(correct[:, 0] * mask),
            "correct5": jnp.sum(jnp.any(correct, axis=1) * mask),
            "count": jnp.sum(mask),
        }
        return lax.psum(sums, DATA_AXIS)

    specs = ProbeState(step=P(), fc_params=P(), backbone_params=P(), backbone_stats=P(), opt_state=P())
    sharded = shard_map(
        eval_fn,
        mesh=mesh,
        in_specs=(specs, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)


def sanity_check(state: ProbeState, pretrained_backbone: Any) -> None:
    """`main_lincls.py:~L380-400`: every backbone weight must be
    bit-identical to the pretrained checkpoint after probe training."""
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(state.backbone_params),
        jax.tree_util.tree_leaves_with_path(pretrained_backbone),
    ):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            raise AssertionError(f"backbone weight changed during probe training: {path}")


def _probe_tx(probe: ProbeConfig, steps_per_epoch: int):
    """The probe optimizer (`main_lincls.py:~L200-210` semantics) —
    shared by training and the evaluate-only restore template, which
    must rebuild the exact opt-state pytree."""
    optim_cfg = OptimConfig(
        optimizer="sgd",
        lr=probe.lr,
        momentum=probe.momentum,
        weight_decay=probe.weight_decay,
        cos=False,
        schedule=probe.schedule,
        epochs=probe.epochs,
    )
    return build_optimizer(optim_cfg, steps_per_epoch)


def _probe_template(
    tx,
    backbone,
    classifier,
    backbone_params,
    backbone_stats,
) -> ProbeState:
    """ProbeState with the exact trees train_lincls checkpoints — built
    from the SAME tx instance the caller steps/restores with, so the
    opt-state tree cannot drift. `backbone_params/stats` may be concrete
    arrays (training) or ShapeDtypeStructs (evaluate-only restore
    template)."""
    fc_vars = classifier.init(
        jax.random.PRNGKey(2), jnp.zeros((1, backbone.num_features), jnp.float32)
    )
    return ProbeState(
        step=jnp.zeros((), jnp.int32),
        fc_params=fc_vars["params"],
        backbone_params=backbone_params,
        backbone_stats=backbone_stats,
        opt_state=tx.init(fc_vars["params"]),
    )


def train_lincls(
    pretrain_workdir: str,
    probe: ProbeConfig,
    pretrain_config: Optional[TrainConfig] = None,
    data: Optional[DataConfig] = None,
    workdir: Optional[str] = None,
    train_dataset=None,
    val_dataset=None,
    log_every: int = 10,
) -> dict:
    """Full linear-probe run; returns {'best_acc1', 'acc1', 'acc5', ...}.

    `pretrain_config=None` reads the config stored in the checkpoint."""
    workdir = workdir or (pretrain_workdir.rstrip("/") + "_lincls")
    mesh = create_mesh(num_model=1)

    backbone_params, backbone_stats, pretrain_config = load_pretrained_backbone(
        pretrain_workdir, pretrain_config
    )
    data = data or pretrain_config.data
    backbone, classifier = _build_probe_model(pretrain_config, probe.num_classes)

    train_pipe = LabeledPipeline(data, mesh, seed=1, dataset=train_dataset)
    val_pipe = EvalPipeline(data, mesh, train=False, dataset=val_dataset)
    steps_per_epoch = train_pipe.steps_per_epoch

    tx = _probe_tx(probe, steps_per_epoch)
    state = _probe_template(tx, backbone, classifier, backbone_params, backbone_stats)
    rep = NamedSharding(mesh, P())
    state = jax.tree.map(lambda x: jax.device_put(x, rep), state)

    step_fn = make_probe_step(backbone, classifier, tx, mesh)
    eval_fn = make_eval_step(backbone, classifier, mesh)
    writer = MetricWriter(workdir)
    ckpt = CheckpointManager(workdir, keep=1)

    best_acc1, last_val = 0.0, {}
    for epoch in range(probe.epochs):
        losses = AverageMeter("Loss", ":.4e")
        top1 = AverageMeter("Acc@1", ":6.2f")
        top5 = AverageMeter("Acc@5", ":6.2f")
        progress = ProgressMeter(steps_per_epoch, [losses, top1, top5], prefix=f"Epoch: [{epoch}]")
        for i, (images, labels) in enumerate(train_pipe.epoch(epoch)):
            state, metrics = step_fn(state, images, labels)
            if i % log_every == 0 or i == steps_per_epoch - 1:
                m = {k: float(v) for k, v in metrics.items()}
                losses.update(m["loss"], data.global_batch)
                top1.update(m["acc1"], data.global_batch)
                top5.update(m["acc5"], data.global_batch)
                progress.display(i)
                writer.write(int(state.step), {"epoch": epoch, "split": "train", **m})

        last_val = validate(eval_fn, state, val_pipe)
        writer.write(int(state.step), {"epoch": epoch, "split": "val", **last_val})
        print(f" * Acc@1 {last_val['acc1']:.3f} Acc@5 {last_val['acc5']:.3f}")
        # config-carrying like the pretrain checkpoints: evaluate-only
        # rebuilds the exact template (opt-state tree shape depends on
        # wd/momentum; fc shape on num_classes) without the caller
        # re-typing the training flags
        ckpt.save(
            epoch,
            state,
            extra={
                "epoch": epoch,
                "acc1": last_val["acc1"],
                "probe": dataclasses.asdict(probe),
                "pretrain_config": config_to_dict(pretrain_config),
                # the RESOLVED data config this probe actually used —
                # evaluate-only must score the same dataset, not the
                # pretrain default the caller may have overridden
                "data": dataclasses.asdict(data),
            },
        )
        if last_val["acc1"] > best_acc1:
            best_acc1 = last_val["acc1"]
            save_best(workdir, state, metric=best_acc1)

    sanity_check(state, backbone_params)
    writer.close()
    ckpt.close()
    return {"best_acc1": best_acc1, **last_val}


def evaluate_lincls(
    pretrain_workdir: str,
    probe: ProbeConfig,
    pretrain_config: Optional[TrainConfig] = None,
    data: Optional[DataConfig] = None,
    workdir: Optional[str] = None,
    val_dataset=None,
    data_overrides: Optional[dict] = None,
) -> dict:
    """Validation-only mode (`main_lincls.py`'s `--evaluate` flag): load
    a finished probe run's best snapshot (falling back to the latest
    epoch checkpoint) and score the full val split — no training.
    `data_overrides`: field overrides applied on top of the data config
    resolved from the checkpoint (the CLI's flag passthrough).

    `workdir` is the PROBE workdir (default: the train_lincls naming,
    `<pretrain_workdir>_lincls`). Probe checkpoints carry their own
    probe + pretrain configs, so the restore template is rebuilt from
    the checkpoint — the caller's flags are NOT trusted for
    template-shaping fields (wd/momentum change the opt-state tree,
    num_classes the fc shape) — and the probe checkpoint alone is
    sufficient: nothing is read from the pretrain workdir unless the
    probe checkpoint predates config-carrying extras."""
    workdir = workdir or (pretrain_workdir.rstrip("/") + "_lincls")
    mesh = create_mesh(num_model=1)

    mgr = CheckpointManager(workdir, keep=1)
    extra = mgr.read_extra()
    if "probe" in extra:
        probe = dataclass_from_dict(ProbeConfig, extra["probe"])
        pretrain_config = config_from_dict(extra["pretrain_config"])
    elif pretrain_config is None:
        # pre-config-carrying probe checkpoint: the pretrain workdir's
        # extras supply the config (a JSON read — no state restore)
        pre_mgr = CheckpointManager(pretrain_workdir)
        pretrain_config = config_from_dict(pre_mgr.read_extra()["config"])
        pre_mgr.close()
    if data is None:
        # prefer the data config the probe ACTUALLY trained with (saved
        # in its extras); the pretrain default is the legacy fallback
        data = (
            dataclass_from_dict(DataConfig, extra["data"])
            if "data" in extra
            else pretrain_config.data
        )
    if data_overrides:
        data = dataclasses.replace(data, **data_overrides)
    backbone, classifier = _build_probe_model(pretrain_config, probe.num_classes)
    val_pipe = EvalPipeline(data, mesh, train=False, dataset=val_dataset)

    # abstract backbone trees: eval needs no pretrain-state read — the
    # probe checkpoint holds every weight; eval_shape gives the template
    sample = jnp.zeros((1, data.image_size, data.image_size, 3), jnp.float32)
    var_shapes = jax.eval_shape(
        lambda: backbone.init(jax.random.PRNGKey(0), sample, train=False)
    )
    template = _probe_template(
        _probe_tx(probe, max(val_pipe.steps_per_epoch, 1)),
        backbone,
        classifier,
        var_shapes["params"],
        var_shapes.get("batch_stats", {}),
    )
    legacy_probe_flags = "probe" not in extra
    try:
        if best_exists(workdir):
            state, best_metric = restore_best(workdir, template)
            print(f"evaluating model_best (saved Acc@1 {best_metric:.3f})")
        else:
            state, extra = mgr.restore(template)
            print(f"no model_best; evaluating latest epoch {extra.get('epoch')}")
    except Exception as e:
        if legacy_probe_flags:
            # pre-config-carrying probe checkpoint: the template was shaped
            # from the CLI probe flags, so a wd/momentum/num-classes
            # mismatch with the original probe run surfaces as an Orbax
            # tree-structure error here — say so instead of the raw trace
            raise RuntimeError(
                "probe checkpoint restore failed and this checkpoint predates "
                "config-carrying extras, so the restore template was built from "
                "the probe flags you passed — if they differ from the ORIGINAL "
                "probe training flags (--lr/--wd/--momentum affect the optimizer "
                "state tree, num_classes the fc shape), pass the original values"
            ) from e
        raise
    mgr.close()
    rep = NamedSharding(mesh, P())
    state = jax.tree.map(lambda x: jax.device_put(x, rep), state)

    eval_fn = make_eval_step(backbone, classifier, mesh)
    out = validate(eval_fn, state, val_pipe)
    print(f" * Acc@1 {out['acc1']:.3f} Acc@5 {out['acc5']:.3f}")
    return out


def validate(eval_fn, state: ProbeState, val_pipe: EvalPipeline) -> dict:
    """Top-1/top-5 over the FULL val split (`main_lincls.py:~L330-370`)."""
    loss = c1 = c5 = n = 0.0
    for images, labels, mask in val_pipe:
        s = eval_fn(state, images, labels, mask)
        loss += float(s["loss"])
        c1 += float(s["correct1"])
        c5 += float(s["correct5"])
        n += float(s["count"])
    n = max(n, 1.0)
    return {"loss": loss / n, "acc1": 100.0 * c1 / n, "acc5": 100.0 * c5 / n, "count": n}
