"""Weight-update (optimizer-state / parameter) sharding over the data axis.

TPU-native ZeRO, after "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (arXiv:2004.13336, the XLA/TPU paper
retrieved in PAPERS.md): in plain data parallelism every replica holds
the full optimizer state and applies the identical full weight update —
redundant memory AND redundant compute. Two stages live here:

**Stage 1** (`sharded_update`, the original): params stay replicated
between steps; inside the step

    grads --psum_scatter--> per-replica 1/n grad shard  (one collective,
                            same volume as the all-reduce it replaces)
    optimizer update on the shard only   (1/n state, 1/n update FLOPs)
    params <--all_gather-- updated shards

**Stage 2/3** (`BucketPlan` + the Zero23 step in core/moco.py): the
parameters themselves persist BETWEEN steps as `P(data)`-sharded flat
shards — same (n, m) layout as the stage-1 optimizer state — so the
at-rest replica cost of params_q + params_k + opt state is ~3/n of a
model instead of 2 + 1/n. The EMA key-encoder update becomes a
shard-local elementwise op (NO collective at all), the parameter
all_gather moves from the end of step k to the start of step k+1 where
the software-pipelined driver hoists it under step k's compute
(`AsyncParamGather`), and the gathered full params are donated to the
step so XLA frees them after the backward instead of keeping a second
replica alive.

Collectives are **bucketed**: leaves are greedily packed (in pytree
order, per dtype) into fusion buckets of ~`bucket_bytes`, ONE
all_gather / psum_scatter per bucket instead of per leaf — fewer
collective launches, big enough payloads to saturate ICI, and a
per-bucket `comms.tag` site (`zero.gather_q.b<i>`, `zero.scatter.b<i>`,
...) so the PR-4 ledger and the schedule sanitizer see each bucket.
The bucket transforms PRESERVE the per-leaf (n, m) partitioning —
element e of leaf L lands on the same replica row whether the
collective is bucketed or per-leaf — so the bucketed update is
bit-identical to stage 1's and the checkpoint layout stays per-leaf.

Each parameter leaf is flattened, zero-padded to a multiple of the axis
size, and viewed as (n, m): replica r owns row r. Optimizer state leaves
are stored GLOBALLY as (n, m) arrays sharded `P(data)` on the leading
dim, so checkpoints carry exactly each replica's rows and resume is
topology-stable for the same mesh (and host-side reshard helpers below
convert between layouts/mesh widths on resume).

**Layer-granular stage 2/3** (`GroupPlan` + the layer schedule in
core/moco.py, `parallel.zero_layer_granular`): the whole-tree gather
still materializes every full parameter at once inside the step, so
peak — not at-rest — memory caps the per-chip batch. The group plan
partitions the leaves into schedule-ordered layer groups (stem, blocks,
head), each with its own fusion buckets and its own
`comms/zero.gather.<group>` ledger site; the step gathers each group
just-in-time and the rematerialized segment boundaries free it after
its forward/backward contribution, so the transient cost drops from
full-tree to at most two adjacent groups (the one-group-ahead
prefetch).

Element-wise optimizers only (SGD momentum, AdamW): their update is
position-independent, so updating a flat shard equals sharding the full
update. LARS is NOT eligible (per-layer trust ratios need whole-tensor
norms) — callers must reject it.
"""

from __future__ import annotations

import dataclasses
import queue as queue_mod
import threading
import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from moco_tpu import obs
from moco_tpu.obs import comms
from moco_tpu.parallel.compat import axis_size
from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.utils import faults


def padded_cols(numel: int, n: int) -> int:
    """Columns of the (n, m) sharded view of a flat leaf of `numel`."""
    return -(-max(numel, 1) // n)


def shard_template(tree, n: int):
    """(n, m)-shaped zero arrays matching each leaf's sharded flat layout
    — what `tx.init` consumes to build a SHARDED optimizer state."""
    return jax.tree.map(
        lambda x: jnp.zeros((n, padded_cols(x.size, n)), x.dtype), tree
    )


def scatter_mean(x: jax.Array, axis_name: str = DATA_AXIS) -> jax.Array:
    """Mean-reduce a full local grad leaf across the axis AND keep only
    this replica's (m,) shard — one psum_scatter, the fused collective
    that makes sharded weight update cost no extra communication."""
    n = axis_size(axis_name)
    m = padded_cols(x.size, n)
    flat = jnp.pad(x.reshape(-1), (0, n * m - x.size))
    return lax.psum_scatter(flat, axis_name, scatter_dimension=0, tiled=True) / n


def local_shard(x: jax.Array, axis_name: str = DATA_AXIS) -> jax.Array:
    """This replica's (m,) rows of a replicated full leaf."""
    n = axis_size(axis_name)
    r = lax.axis_index(axis_name)
    m = padded_cols(x.size, n)
    flat = jnp.pad(x.reshape(-1), (0, n * m - x.size))
    return lax.dynamic_slice(flat, (r * m,), (m,))

def unshard(shard: jax.Array, like: jax.Array, axis_name: str = DATA_AXIS) -> jax.Array:
    """all_gather the (m,) shards back into a full leaf shaped `like`."""
    full = lax.all_gather(shard, axis_name, tiled=True)
    return full[: like.size].reshape(like.shape).astype(like.dtype)


def squeeze_opt_state(opt_state):
    """Local view inside shard_map: (1, m) sharded leaves -> (m,);
    scalars (e.g. Adam's count) pass through."""
    return jax.tree.map(lambda x: x[0] if x.ndim == 2 else x, opt_state)


def expand_opt_state(opt_state):
    """Inverse of squeeze: (m,) leaves -> (1, m) for the P(data) out-spec."""
    return jax.tree.map(lambda x: x[None] if x.ndim == 1 else x, opt_state)


def sharded_update(tx, grads, opt_state, trainable, axis_name: str = DATA_AXIS):
    """Stage-1 sharded weight update: returns (new_trainable_full,
    new_opt_state_local_expanded). Call inside shard_map; `grads` are the
    LOCAL (pre-reduction) gradients, `trainable` the replicated params,
    `opt_state` the local (1, m)/scalar view of the sharded state."""
    n = axis_size(axis_name)
    with comms.tag("zero.grad_reduce_scatter", "psum_scatter", grads, n):
        grad_sh = jax.tree.map(lambda g: scatter_mean(g, axis_name), grads)
    param_sh = jax.tree.map(lambda p: local_shard(p, axis_name), trainable)
    updates, new_opt = tx.update(grad_sh, squeeze_opt_state(opt_state), param_sh)
    new_param_sh = jax.tree.map(lambda p, u: p + u, param_sh, updates)
    with comms.tag("zero.params_all_gather", "all_gather", new_param_sh, n):
        new_trainable = jax.tree.map(
            lambda s, p: unshard(s, p, axis_name), new_param_sh, trainable
        )
    return new_trainable, expand_opt_state(new_opt)


# ---------------------------------------------------------------------------
# Stage 2/3: persistent shard layout + bucketed collectives
# ---------------------------------------------------------------------------

DEFAULT_BUCKET_MB = 4.0


def shard_tree(tree, n: int):
    """Full-shape param tree -> the persistent (n, m) sharded-flat layout
    (per leaf; row r belongs to replica r). jnp ops, jit-safe."""
    def _one(x):
        m = padded_cols(x.size, n)
        return jnp.pad(x.reshape(-1), (0, n * m - x.size)).reshape(n, m)

    return jax.tree.map(_one, tree)


def shard_leaf_host(x, n: int) -> np.ndarray:
    """Host (numpy) variant of `shard_tree` for one leaf — checkpoint
    resharding runs on restored host arrays, no mesh required."""
    x = np.asarray(x)
    m = padded_cols(x.size, n)
    return np.pad(x.reshape(-1), (0, n * m - x.size)).reshape(n, m)


def unshard_leaf_host(x, shape, dtype=None) -> np.ndarray:
    """Host inverse: (n, m) sharded-flat -> the full leaf of `shape`."""
    x = np.asarray(x)
    size = int(np.prod(shape)) if shape else 1
    out = x.reshape(-1)[:size].reshape(shape)
    return out.astype(dtype) if dtype is not None else out


def unshard_tree_host(tree, template):
    """Gather a whole persistently-sharded param tree back to full
    shapes on the host (numpy) — the eval/export one-shot gather.
    `template` leaves provide shape/dtype (e.g. from `jax.eval_shape`
    of the encoder init). Single-controller: every (n, m) leaf must be
    host-addressable (true for the eval tools, which run one process)."""
    return jax.tree.map(
        lambda x, t: unshard_leaf_host(x, t.shape, t.dtype), tree, template
    )


@dataclasses.dataclass(frozen=True)
class _LeafSlot:
    """One leaf's place inside a fusion bucket."""

    index: int  # position in jax.tree.leaves order
    size: int  # true element count
    m: int  # padded cols = padded_cols(size, n)
    offset: int  # column offset inside the bucket's (n, total_m) view
    shape: tuple
    dtype: Any


@dataclasses.dataclass(frozen=True)
class Bucket:
    slots: tuple
    total_m: int
    dtype: Any


class BucketPlan:
    """Static packing of a param tree's leaves into fusion buckets.

    Greedy in pytree-leaves order, one open bucket per dtype (leaves of
    different dtypes cannot share a concatenated payload); a bucket
    closes once it holds ≥ `bucket_bytes` of shard payload, so the last
    bucket per dtype is the ragged tail (possibly much smaller). A leaf
    larger than `bucket_bytes` gets its own bucket.

    The transforms preserve per-leaf (n, m) partitioning: bucket row r
    is the concatenation of every member leaf's row r, so one
    collective per bucket moves exactly what per-leaf collectives would
    — same bits per replica, fewer launches.
    """

    def __init__(self, leaves: Sequence, n: int, bucket_bytes: Optional[int] = None):
        """`leaves`: shape/dtype-carrying leaf descriptors (e.g. from
        `jax.eval_shape`), in `jax.tree.leaves` order of the tree the
        runtime methods will be fed."""
        self.n = int(n)
        bucket_bytes = int(
            bucket_bytes
            if bucket_bytes is not None
            else DEFAULT_BUCKET_MB * 1024 * 1024
        )
        buckets: list[Bucket] = []
        open_slots: dict = {}  # dtype -> (slots list, cols, bytes)
        for i, leaf in enumerate(leaves):
            shape = tuple(leaf.shape)
            dtype = jnp.dtype(leaf.dtype)
            size = int(np.prod(shape)) if shape else 1
            m = padded_cols(size, self.n)
            slots, cols, nbytes = open_slots.setdefault(dtype, ([], 0, 0))
            slots.append(
                _LeafSlot(index=i, size=size, m=m, offset=cols, shape=shape, dtype=dtype)
            )
            cols += m
            nbytes += m * dtype.itemsize  # shard payload per replica
            if nbytes >= bucket_bytes:
                buckets.append(Bucket(slots=tuple(slots), total_m=cols, dtype=dtype))
                del open_slots[dtype]
            else:
                open_slots[dtype] = (slots, cols, nbytes)
        for dtype, (slots, cols, _) in open_slots.items():  # ragged tails
            buckets.append(Bucket(slots=tuple(slots), total_m=cols, dtype=dtype))
        self.buckets = tuple(buckets)
        self.num_leaves = len(list(leaves))

    # -- persistent-layout construction ---------------------------------
    def shard_leaves(self, full_leaves: Sequence) -> list:
        """Full leaves -> (n, m) persistent layout, leaf-by-leaf."""
        return [
            jnp.pad(x.reshape(-1), (0, self.n * padded_cols(x.size, self.n) - x.size))
            .reshape(self.n, padded_cols(x.size, self.n))
            for x in full_leaves
        ]

    # -- in-step transforms (call inside shard_map) ---------------------
    def gather(self, shard_leaves: Sequence, site: str, axis_name: str = DATA_AXIS) -> list:
        """Local (m,) shards -> FULL leaves, one tiled all_gather per
        bucket, each under its own `comms.tag` site `<site>.b<i>`."""
        out: list = [None] * self.num_leaves
        n = self.n
        for bi, bucket in enumerate(self.buckets):
            concat = jnp.concatenate([shard_leaves[s.index] for s in bucket.slots])
            with comms.tag(f"{site}.b{bi}", "all_gather", concat, n):
                full = lax.all_gather(concat, axis_name, tiled=True)
            rows = full.reshape(n, bucket.total_m)
            for s in bucket.slots:
                flat = rows[:, s.offset : s.offset + s.m].reshape(-1)[: s.size]
                out[s.index] = flat.reshape(s.shape).astype(s.dtype)
        return out

    def scatter_mean(
        self, grad_leaves: Sequence, site: str = "zero.scatter", axis_name: str = DATA_AXIS
    ) -> list:
        """Full local (pre-reduction) grad leaves -> this replica's (m,)
        reduced shards, one tiled psum_scatter per bucket. Bit-identical
        to per-leaf `scatter_mean`: element -> chunk assignment is
        unchanged, so the ring reduction order per element is too."""
        out: list = [None] * self.num_leaves
        n = self.n
        for bi, bucket in enumerate(self.buckets):
            parts = []
            for s in bucket.slots:
                g = grad_leaves[s.index].reshape(-1)
                parts.append(jnp.pad(g, (0, n * s.m - s.size)).reshape(n, s.m))
            block = jnp.concatenate(parts, axis=1).reshape(-1)
            with comms.tag(f"{site}.b{bi}", "psum_scatter", block, n):
                shard = (
                    lax.psum_scatter(block, axis_name, scatter_dimension=0, tiled=True)
                    / n
                )
            for s in bucket.slots:
                out[s.index] = shard[s.offset : s.offset + s.m]
        return out

    def describe(self) -> list[dict]:
        """Static bucket table (bench/report surface)."""
        return [
            {
                "bucket": i,
                "leaves": len(b.slots),
                "dtype": str(b.dtype),
                "shard_bytes": b.total_m * b.dtype.itemsize,
            }
            for i, b in enumerate(self.buckets)
        ]


@dataclasses.dataclass(frozen=True)
class _Group:
    """One layer group of a GroupPlan: a named, contiguous-in-schedule
    slice of the tree's leaves with its own fusion-bucket plan."""

    name: str
    indices: tuple  # leaf positions in jax.tree.leaves order
    plan: BucketPlan
    full_bytes: int  # bytes of the group's FULL (unsharded) leaves


class GroupPlan:
    """Layer-granular extension of `BucketPlan`: an ordered partition of
    a param tree's leaves into named layer groups, each with its own
    bucket plan, so the step can gather ONE group's full params
    just-in-time (site `zero.gather.<prefix>_<group>.b<i>`) instead of
    materializing the whole tree at once.

    The partition must cover every leaf exactly once — a leaf the group
    map misses would silently never be gathered, so that is a
    construction-time error, not a runtime surprise. Group order is the
    schedule order (stem → stages → head); `peak_full_bytes` is the
    analytic transient high-water mark of the one-group-ahead pipeline:
    the largest sum of two ADJACENT groups' full bytes (group g's params
    are still live while group g+1 prefetches).
    """

    def __init__(
        self,
        leaves: Sequence,
        groups: Sequence,
        n: int,
        bucket_bytes: Optional[int] = None,
    ):
        """`leaves`: shape/dtype descriptors in `jax.tree.leaves` order;
        `groups`: ordered `(name, leaf_indices)` pairs partitioning
        `range(len(leaves))`."""
        self.n = int(n)
        leaves = list(leaves)
        seen: set = set()
        built = []
        for name, indices in groups:
            indices = tuple(int(i) for i in indices)
            overlap = seen.intersection(indices)
            if overlap:
                raise ValueError(
                    f"group {name!r} re-claims leaves {sorted(overlap)}"
                )
            seen.update(indices)
            full_bytes = 0
            for i in indices:
                shape = tuple(leaves[i].shape)
                size = int(np.prod(shape)) if shape else 1
                full_bytes += size * jnp.dtype(leaves[i].dtype).itemsize
            built.append(
                _Group(
                    name=str(name),
                    indices=indices,
                    plan=BucketPlan([leaves[i] for i in indices], n, bucket_bytes),
                    full_bytes=full_bytes,
                )
            )
        missing = sorted(set(range(len(leaves))) - seen)
        if missing:
            raise ValueError(f"group map misses leaves {missing}")
        self.groups = tuple(built)
        self.num_leaves = len(leaves)

    def group_shards(self, shard_leaves: Sequence, gi: int) -> list:
        """The (m,)/(n, m) shard leaves belonging to group `gi`, in the
        group's own leaf order (what `gather_group` consumes)."""
        return [shard_leaves[i] for i in self.groups[gi].indices]

    def gather_group(
        self,
        group_shard_leaves: Sequence,
        gi: int,
        site_prefix: str = "zero.gather",
        axis_name: str = DATA_AXIS,
    ) -> list:
        """One group's local shards -> its FULL leaves (group leaf
        order), bucketed all_gathers under the group-named ledger site
        `<site_prefix>.<group>` — the per-group seam the comms ledger
        and the schedule sanitizer observe."""
        g = self.groups[gi]
        return g.plan.gather(
            group_shard_leaves, site=f"{site_prefix}.{g.name}", axis_name=axis_name
        )

    def scatter_leaves(self, full_leaves: Sequence, gi: int) -> list:
        """Full leaves of group `gi` -> (n, m) persistent layout."""
        return self.groups[gi].plan.shard_leaves(full_leaves)

    def peak_full_bytes(self) -> int:
        """Transient full-param high-water mark of the one-group-ahead
        schedule: max over adjacent group pairs (a single group when
        there is only one)."""
        sizes = [g.full_bytes for g in self.groups]
        if not sizes:
            return 0
        if len(sizes) == 1:
            return sizes[0]
        return max(a + b for a, b in zip(sizes, sizes[1:]))

    def total_full_bytes(self) -> int:
        return sum(g.full_bytes for g in self.groups)

    def describe(self) -> list[dict]:
        """Static per-group table (bench/report surface)."""
        return [
            {
                "group": g.name,
                "leaves": len(g.indices),
                "buckets": len(g.plan.buckets),
                "full_bytes": g.full_bytes,
            }
            for g in self.groups
        ]


class AsyncParamGather:
    """Hoists the stage-2/3 per-bucket params all_gather for step k+1
    under step k's compute — the software-pipelined driver's wire for
    the weight-update collectives.

    Two contracts, learned the hard way on the 8-virtual-device mesh:

    1. DISPATCH STAYS ON THE CALLER'S THREAD. `submit()` itself
       enqueues the jitted gather (jax dispatch is async and returns
       immediately): two threads racing `Execute` over the same
       multi-device set can enqueue in different per-device orders and
       deadlock the collective rendezvous — observed as a wedged scalar
       all-reduce with ranks 0-2 never arriving. Every multi-device
       executable in the driver (step, augment, gather) is enqueued
       from one thread, preserving a single per-device order.
    2. `take()` NEVER WAITS FOR DEVICE COMPLETION. The gathered tree is
       an async value; jax's dependency tracking orders step k+1 behind
       the gather on-device, and blocking the host on readiness would
       re-serialize the very pipeline the hoist exists to build. What
       `take()` waits for is only the stall the worker ABSORBS off the
       critical path: the deterministic `delay@site=zero.gather` fault
       — the synthetic slow collective the overlap smoke injects.

    `overlap` reports how much of that absorbed stall hid under the
    driver's iteration (dispatches, input wait, the in-flight
    throttle):

        overlap = 1 - wait / duration    (clamped to [0, 1];
                  None when nothing was absorbed — no stall, nothing
                  to hide; DEVICE-side gather/compute overlap is read
                  from the merged trace, where the worker's
                  `zero_gather` span covers delay + time-to-ready)

    After handing the result over, the worker ripens it
    (block_until_ready) purely so the trace span shows the gather's
    real extent; an async error in the gather then surfaces where jax
    always surfaces it — at the consumer — not on this thread.

    Thread hygiene (mocolint JX011 contract): bounded handoff queues,
    poison-pill `close()` that joins the worker, pre-handoff errors
    propagate to `take()` instead of dying silently on the thread.
    """

    FAULT_SITE = "zero.gather"

    def __init__(self, gather_fn: Callable):
        self._gather_fn = gather_fn
        self._submit: queue_mod.Queue = queue_mod.Queue(maxsize=1)
        self._done: queue_mod.Queue = queue_mod.Queue(maxsize=1)
        self._outstanding = 0  # submits not yet taken (driver thread only)
        self._closed = False
        self.last_overlap: Optional[float] = None
        self.last_duration: Optional[float] = None
        self._thread = threading.Thread(
            target=self._run, name="zero-param-gather", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._submit.get()
            if item is None:  # poison pill
                return
            out, step = item
            t0 = time.perf_counter()
            handed = False
            try:
                with obs.span("zero_gather", step=step):
                    faults.maybe_delay(self.FAULT_SITE)
                    self._done.put(("ok", out, time.perf_counter() - t0))
                    handed = True
                    # ripen AFTER the hand-off: take() must not wait for
                    # device completion (contract 2 in the class doc);
                    # the span end then marks when the gather was truly
                    # ready, which is what the merged trace overlays
                    # against the driver's step spans
                    jax.block_until_ready(out)
            except BaseException as e:
                if not handed:  # surface on take(), not the thread
                    self._done.put(("err", e, time.perf_counter() - t0))
                # post-hand-off failures are async-value errors; they
                # surface at the consumer exactly as un-hoisted jax would

    def submit(self, state, step: int = 0) -> None:
        """Enqueue the gather for `state` (the params step k+1 will
        consume) on THIS thread — see the class docstring for why the
        dispatch must not move to the worker — then hand the async
        result to the worker to ripen. Exactly one submit must be
        outstanding per take."""
        if self._closed:
            raise RuntimeError("AsyncParamGather is closed")
        out = self._gather_fn(state)
        self._outstanding += 1
        self._submit.put((out, step))

    def take(self):
        """Block until the worker has absorbed the submitted gather's
        stall; returns the (async) gathered tree. Updates
        `last_overlap`/`last_duration`."""
        t0 = time.perf_counter()
        kind, payload, duration = self._done.get()
        self._outstanding -= 1
        wait = time.perf_counter() - t0
        self.last_duration = duration
        self.last_overlap = (
            max(0.0, min(1.0, 1.0 - wait / duration))
            # sub-ms "absorption" is span/queue overhead, not a stall —
            # reporting a ratio of noise would read as a real gauge
            if duration > 1e-3
            else None
        )
        if kind == "err":
            raise payload
        return payload

    def resubmit(self, state, step: int = 0) -> None:
        """Drop any parked result (poisoned lineage after a NaN
        rollback) and gather `state` instead."""
        while self._outstanding:
            try:
                self.take()
            except Exception:
                pass  # a poisoned gather's error dies with its lineage
        self.submit(state, step)

    def payload(self) -> dict:
        """Metrics-line fields: the hoisted gather's overlap efficiency
        (None until the first take)."""
        return {"overlap/zero": self.last_overlap}

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._submit.put(None)
        self._thread.join(timeout=30.0)
