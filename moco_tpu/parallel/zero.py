"""Weight-update (optimizer-state) sharding over the data axis.

TPU-native ZeRO-1, after "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (arXiv:2004.13336, the XLA/TPU paper
retrieved in PAPERS.md): in plain data parallelism every replica holds
the full optimizer state and applies the identical full weight update —
redundant memory AND redundant compute. Instead:

    grads --psum_scatter--> per-replica 1/n grad shard  (one collective,
                            same volume as the all-reduce it replaces)
    optimizer update on the shard only   (1/n state, 1/n update FLOPs)
    params <--all_gather-- updated shards

Each parameter leaf is flattened, zero-padded to a multiple of the axis
size, and viewed as (n, m): replica r owns row r. Optimizer state leaves
are stored GLOBALLY as (n, m) arrays sharded `P(data)` on the leading
dim, so checkpoints carry exactly each replica's rows and resume is
topology-stable for the same mesh.

Element-wise optimizers only (SGD momentum, AdamW): their update is
position-independent, so updating a flat shard equals sharding the full
update. LARS is NOT eligible (per-layer trust ratios need whole-tensor
norms) — callers must reject it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from moco_tpu.obs import comms
from moco_tpu.parallel.compat import axis_size
from moco_tpu.parallel.mesh import DATA_AXIS


def padded_cols(numel: int, n: int) -> int:
    """Columns of the (n, m) sharded view of a flat leaf of `numel`."""
    return -(-max(numel, 1) // n)


def shard_template(tree, n: int):
    """(n, m)-shaped zero arrays matching each leaf's sharded flat layout
    — what `tx.init` consumes to build a SHARDED optimizer state."""
    return jax.tree.map(
        lambda x: jnp.zeros((n, padded_cols(x.size, n)), x.dtype), tree
    )


def scatter_mean(x: jax.Array, axis_name: str = DATA_AXIS) -> jax.Array:
    """Mean-reduce a full local grad leaf across the axis AND keep only
    this replica's (m,) shard — one psum_scatter, the fused collective
    that makes sharded weight update cost no extra communication."""
    n = axis_size(axis_name)
    m = padded_cols(x.size, n)
    flat = jnp.pad(x.reshape(-1), (0, n * m - x.size))
    return lax.psum_scatter(flat, axis_name, scatter_dimension=0, tiled=True) / n


def local_shard(x: jax.Array, axis_name: str = DATA_AXIS) -> jax.Array:
    """This replica's (m,) rows of a replicated full leaf."""
    n = axis_size(axis_name)
    r = lax.axis_index(axis_name)
    m = padded_cols(x.size, n)
    flat = jnp.pad(x.reshape(-1), (0, n * m - x.size))
    return lax.dynamic_slice(flat, (r * m,), (m,))

def unshard(shard: jax.Array, like: jax.Array, axis_name: str = DATA_AXIS) -> jax.Array:
    """all_gather the (m,) shards back into a full leaf shaped `like`."""
    full = lax.all_gather(shard, axis_name, tiled=True)
    return full[: like.size].reshape(like.shape).astype(like.dtype)


def squeeze_opt_state(opt_state):
    """Local view inside shard_map: (1, m) sharded leaves -> (m,);
    scalars (e.g. Adam's count) pass through."""
    return jax.tree.map(lambda x: x[0] if x.ndim == 2 else x, opt_state)


def expand_opt_state(opt_state):
    """Inverse of squeeze: (m,) leaves -> (1, m) for the P(data) out-spec."""
    return jax.tree.map(lambda x: x[None] if x.ndim == 1 else x, opt_state)


def sharded_update(tx, grads, opt_state, trainable, axis_name: str = DATA_AXIS):
    """Full sharded weight update: returns (new_trainable_full,
    new_opt_state_local_expanded). Call inside shard_map; `grads` are the
    LOCAL (pre-reduction) gradients, `trainable` the replicated params,
    `opt_state` the local (1, m)/scalar view of the sharded state."""
    n = axis_size(axis_name)
    with comms.tag("zero.grad_reduce_scatter", "psum_scatter", grads, n):
        grad_sh = jax.tree.map(lambda g: scatter_mean(g, axis_name), grads)
    param_sh = jax.tree.map(lambda p: local_shard(p, axis_name), trainable)
    updates, new_opt = tx.update(grad_sh, squeeze_opt_state(opt_state), param_sh)
    new_param_sh = jax.tree.map(lambda p, u: p + u, param_sh, updates)
    with comms.tag("zero.params_all_gather", "all_gather", new_param_sh, n):
        new_trainable = jax.tree.map(
            lambda s, p: unshard(s, p, axis_name), new_param_sh, trainable
        )
    return new_trainable, expand_opt_state(new_opt)
