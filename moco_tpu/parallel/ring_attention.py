"""Ring attention: exact sequence-parallel attention over a mesh axis.

The reference has no sequence dimension at all (SURVEY.md §5.7) — this
subsystem makes long-context a first-class capability of the rebuild:
sequences too long for one chip's HBM/VMEM are sharded over a mesh axis,
and attention over the FULL sequence is computed by rotating key/value
shards around the ring with `lax.ppermute` (XLA lowers neighbor
permutes to ICI transfers) while queries stay put.

Per ring step each device runs blockwise (flash) attention of its local
queries against the visiting K/V shard — `moco_tpu.ops.flash_attention`
returns (out, logsumexp), which is exactly what the numerically-stable
streaming merge needs:

    m'   = max(m, lse_blk)
    num  = num * e^(m-m') + out_blk * e^(lse_blk-m')
    den  = den * e^(m-m') + e^(lse_blk-m')

After n steps every device holds attention of its queries over the
whole sequence; K/V have completed a full rotation (back to their
owners). Communication per step is the K/V shard — the same volume a
single all_gather would move in total, but with O(S/n) peak memory
instead of O(S), and compute/comm naturally pipelined across steps.

Non-causal (bidirectional ViT-style); use inside `shard_map` with the
sequence axis named.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from moco_tpu.obs import comms
from moco_tpu.ops.flash_attention import flash_attention_with_lse
from moco_tpu.parallel.compat import axis_size

NEG_INF = -1e30


def ring_attention(
    q: jax.Array,  # (B, H, S_local, D) — this device's query shard
    k: jax.Array,  # (B, H, S_local, D) — this device's key shard
    v: jax.Array,
    axis_name: str,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Exact attention over the axis-sharded sequence; call under shard_map.

    Returns this device's (B, H, S_local, D) output slice.
    """
    n = axis_size(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, h, s_local, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    num0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    m0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    den0 = jnp.zeros((b, h, s_local), jnp.float32)

    def body(_, carry):
        num, m, den, k_cur, v_cur = carry
        out_blk, lse_blk = flash_attention_with_lse(
            q, k_cur, v_cur, scale, block_q, block_k, interpret
        )
        m_new = jnp.maximum(m, lse_blk)
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(lse_blk - m_new)
        num = num * c_old[..., None] + out_blk.astype(jnp.float32) * c_new[..., None]
        den = den * c_old + c_new
        # rotate K/V to the next device; after n steps they are home again
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return num, m_new, den, k_nxt, v_nxt

    # the ring rotates the K/V shards n times per call (the fori_loop
    # body traces once but executes n ppermute hops)
    with comms.tag("ring_attention.kv_ppermute", "ppermute", (k, v), n, calls_per_step=n):
        num, m, den, _, _ = jax.lax.fori_loop(0, n, body, (num0, m0, den0, k, v))
    return (num / den[..., None]).astype(q.dtype)
