from moco_tpu.parallel.compat import shard_map
from moco_tpu.parallel.dist import (
    ProcessDataPartition,
    device_row_ranges,
    maybe_initialize_multihost,
)
from moco_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    create_mesh,
    create_multislice_mesh,
    initialize_multihost,
    replicated_sharding,
    shard_batch,
)
from moco_tpu.parallel.shuffle import (
    make_permutation,
    balanced_shuffle,
    balanced_unshuffle,
    shuffle_gather,
    unshuffle_gather,
)
from moco_tpu.parallel.ring_attention import ring_attention

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "ProcessDataPartition",
    "device_row_ranges",
    "maybe_initialize_multihost",
    "batch_sharding",
    "create_mesh",
    "create_multislice_mesh",
    "initialize_multihost",
    "replicated_sharding",
    "shard_batch",
    "make_permutation",
    "balanced_shuffle",
    "balanced_unshuffle",
    "shuffle_gather",
    "unshuffle_gather",
    "ring_attention",
    "shard_map",
]
