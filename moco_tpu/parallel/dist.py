"""Per-process (multi-host) data sharding.

The reference shards input across ranks with `DistributedSampler`
(`main_moco.py:~L258`): each of the 8 GPU processes loads 1/8 of every
batch. The JAX equivalent on a multi-host pod: each host process decodes
ONLY the rows of the global batch that land on its addressable devices,
then the per-host shards are assembled into one global `jax.Array`
(`jax.make_array_from_single_device_arrays`) that the SPMD train step
consumes exactly as if a single controller had `device_put` the whole
batch.

`ProcessDataPartition` computes the row ranges once from the batch
sharding itself (not from process arithmetic), so any mesh layout —
1-D data, (data, model) with replication over the model axis,
multi-slice hybrid meshes — gets a correct, collision-free partition:
the sharding's `devices_indices_map` is the single source of truth.
On a single process it degenerates to "load everything", so the same
code path runs everywhere (and is exercised by every CI test).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding


def device_row_ranges(
    sharding: NamedSharding, global_batch: int
) -> dict[jax.Device, tuple[int, int]]:
    """Map every device in the sharding to its [start, stop) row range of
    the global batch's leading dimension. Devices that hold replicas of
    the same rows (e.g. across a model axis) map to the same range."""
    imap = sharding.devices_indices_map((global_batch,))
    out = {}
    for d, idx in imap.items():
        sl = idx[0]
        start = 0 if sl.start is None else int(sl.start)
        stop = global_batch if sl.stop is None else int(sl.stop)
        out[d] = (start, stop)
    return out


class ProcessDataPartition:
    """This process's slice of every global batch, plus the assembler
    that turns host-decoded local rows into the global sharded array.

    `addressable_devices` overrides the real process boundary — tests
    use it to simulate multi-host partitions on a single process.
    """

    def __init__(
        self,
        sharding: NamedSharding,
        global_batch: int,
        addressable_devices: Optional[Sequence[jax.Device]] = None,
    ):
        self.sharding = sharding
        self.global_batch = global_batch
        ranges = device_row_ranges(sharding, global_batch)
        if addressable_devices is None:
            addressable_devices = sharding.addressable_devices
        mine = {d: ranges[d] for d in ranges if d in set(addressable_devices)}
        if not mine:
            raise ValueError("no addressable devices in sharding")
        # unique row ranges this host must decode (replicas share ranges)
        uniq = sorted(set(mine.values()))
        self.local_positions = (
            np.concatenate([np.arange(a, b) for a, b in uniq])
            if uniq
            else np.zeros((0,), np.int64)
        )
        offsets, off = {}, 0
        for a, b in uniq:
            offsets[(a, b)] = off
            off += b - a
        self.local_rows = off
        # deterministic device order for the assembled shard list
        self._dev_ranges = [
            (d, mine[d], offsets[mine[d]])
            for d in sorted(mine, key=lambda d: d.id)
        ]

    @property
    def is_trivial(self) -> bool:
        """True when this process holds every row (single-host case)."""
        return self.local_rows == self.global_batch

    def local_indices(self, global_indices: np.ndarray) -> np.ndarray:
        """Dataset indices this process must load for one step, given the
        step's global-batch index array (identical on every host — the
        epoch shuffle is seeded)."""
        return np.asarray(global_indices)[self.local_positions]

    def assemble(self, local_data: np.ndarray) -> jax.Array:
        """Global sharded array from this process's decoded rows
        (row i of `local_data` is global row `local_positions[i]`)."""
        if local_data.shape[0] != self.local_rows:
            raise ValueError(
                f"expected {self.local_rows} local rows, got {local_data.shape[0]}"
            )
        shape = (self.global_batch,) + tuple(local_data.shape[1:])
        # the ONE intentional per-step H2D site (mocolint JX002
        # allowlist): the device prefetch ring calls this off-thread so
        # the transfer overlaps compute, and accounts the bytes to the
        # `input.h2d` comms ledger — eager host code, uint8 on the wire
        arrays = [
            jax.device_put(local_data[off : off + (b - a)], d)  # mocolint: disable=JX002
            for d, (a, b), off in self._dev_ranges
        ]
        return jax.make_array_from_single_device_arrays(shape, self.sharding, arrays)


def maybe_initialize_multihost() -> bool:
    """Auto-detect a multi-host launch and run the rendezvous.

    The reference requires the user to pass `--dist-url/--world-size/
    --rank` (`main_moco.py:~L70-85`); on TPU pods the coordinator is
    discoverable, so the driver just calls this. Returns True when
    `jax.distributed.initialize` was invoked. Detection: any of the
    standard coordinator variables, or an explicit MOCO_MULTIHOST=1.
    """
    import os

    already = getattr(jax.distributed, "is_initialized", None)
    if callable(already) and already():
        return False
    env = os.environ
    wants = (
        env.get("MOCO_MULTIHOST") == "1"
        or "JAX_COORDINATOR_ADDRESS" in env
        or "COORDINATOR_ADDRESS" in env
        or "MEGASCALE_COORDINATOR_ADDRESS" in env
    )
    if not wants:
        return False
    from moco_tpu.parallel.mesh import initialize_multihost

    initialize_multihost()
    return True
