"""Device-mesh construction — the TPU replacement for the reference's
process-group world (`main_moco.py:~L70-85, ~L150`: NCCL
`init_process_group`, one process per GPU).

A single `jax.sharding.Mesh` covers every scale the reference reaches
(and beyond): 1 chip, one ICI slice, or multi-slice/multi-host over DCN —
the rank/world-size/dist-url machinery disappears into mesh axes. The
default is a 1-D `data` axis (the reference is data-parallel only,
SURVEY.md §2.3); an optional `model` axis shards the negative queue and
the InfoNCE logits matmul for very large dictionaries.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def create_mesh(
    num_data: Optional[int] = None,
    num_model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data[, model]) mesh over the available devices.

    `num_data=None` uses all devices (divided by `num_model`). On real
    TPU slices `jax.devices()` is already ordered so contiguous
    model-axis groups ride ICI neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        if len(devices) % num_model:
            raise ValueError(f"{len(devices)} devices not divisible by model={num_model}")
        num_data = len(devices) // num_model
    n = num_data * num_model
    if n > len(devices):
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(num_data, num_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host rendezvous — the TPU-native replacement for the
    reference's `dist.init_process_group(nccl, dist_url, world_size,
    rank)` (`main_moco.py:~L150`, SURVEY.md §2.4).

    On Cloud TPU pods all arguments are discovered from the environment
    (call with no args, once per host, before any jax op); elsewhere pass
    them explicitly. After this, `jax.devices()` spans every host and the
    same `create_mesh`/`create_multislice_mesh` code covers the pod.
    """
    import jax

    # pass each argument through independently — jax.distributed.initialize
    # auto-detects whichever are None (dropping explicit num_processes/
    # process_id just because the address is auto-detected would silently
    # build the wrong world)
    kwargs = {
        k: v
        for k, v in dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        ).items()
        if v is not None
    }
    jax.distributed.initialize(**kwargs)


def create_multislice_mesh(num_model: int = 1) -> Mesh:
    """(data, model) mesh for a multi-slice deployment: the data axis
    spans DCN (across slices) x ICI (within a slice), so gradient psum
    does its ring reduction over ICI first and only the per-slice partial
    crosses DCN — the layout 'How to Scale Your Model' prescribes for
    pure data parallelism across slices."""
    from jax.experimental import mesh_utils

    devices = jax.devices()
    num_slices = max(getattr(d, "slice_index", 0) for d in devices) + 1
    if num_slices == 1:
        return create_mesh(num_model=num_model)
    per_slice = len(devices) // num_slices
    if per_slice % num_model:
        raise ValueError(f"{per_slice} chips/slice not divisible by model={num_model}")
    arr = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(per_slice // num_model, num_model),
        dcn_mesh_shape=(num_slices, 1),
        devices=devices,
    )
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dimension sharded over the data axis, rest replicated.

    This is the input pipeline's WIRE layout: the device prefetch ring
    (`data/device_prefetch.py`) stages uint8 batches into it from its
    transfer thread (per-device shards assembled by
    `dist.ProcessDataPartition`), and the jitted train step consumes it
    without a resharding copy."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch):
    """Device_put a host batch with the leading dim sharded over `data`.

    One-shot staging (benches, eval, tests). The TRAINING hot path does
    not go through here — per-step batches ride the device prefetch
    ring, which also accounts its wire bytes to the `input.h2d` comms
    ledger; a one-off staged batch is deliberately not a ledger entry
    (it is not per-step traffic)."""
    s = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, s), batch)
