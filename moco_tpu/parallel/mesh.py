"""Device-mesh construction — the TPU replacement for the reference's
process-group world (`main_moco.py:~L70-85, ~L150`: NCCL
`init_process_group`, one process per GPU).

A single `jax.sharding.Mesh` covers every scale the reference reaches
(and beyond): 1 chip, one ICI slice, or multi-slice/multi-host over DCN —
the rank/world-size/dist-url machinery disappears into mesh axes. The
default is a 1-D `data` axis (the reference is data-parallel only,
SURVEY.md §2.3); an optional `model` axis shards the negative queue and
the InfoNCE logits matmul for very large dictionaries.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def create_mesh(
    num_data: Optional[int] = None,
    num_model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data[, model]) mesh over the available devices.

    `num_data=None` uses all devices (divided by `num_model`). On real
    TPU slices `jax.devices()` is already ordered so contiguous
    model-axis groups ride ICI neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        if len(devices) % num_model:
            raise ValueError(f"{len(devices)} devices not divisible by model={num_model}")
        num_data = len(devices) // num_model
    n = num_data * num_model
    if n > len(devices):
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(num_data, num_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dimension sharded over the data axis, rest replicated."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch):
    """Device_put a host batch with the leading dim sharded over `data`."""
    s = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, s), batch)
