"""Cross-replica batch shuffling (Shuffle-BN) — TPU-native redesigns.

Reference: `moco/builder.py:~L79-126` (`_batch_shuffle_ddp` /
`_batch_unshuffle_ddp`, "*** Only support DDP model. ***"). There, rank 0
draws a random permutation of the global key batch and *broadcasts* it
over NCCL; every rank all-gathers the images, takes its permuted slice,
runs `encoder_k` with per-GPU BatchNorm, and the embeddings are
all-gathered back and inverse-permuted. Purpose: per-device BN statistics
must not contain a query's own positive key (the BN "cheating" signature
leak).

TPU-native redesigns (all used inside `shard_map` over the `data` axis):

1. `gather_perm` (reference-exact semantics): the broadcast is replaced
   by *deterministic same-seed randomness* — every replica computes the
   identical permutation from the replicated step RNG, so no collective
   is needed to agree on it. Data still moves via `all_gather` exactly as
   upstream.

2. `a2a` (cheaper, statistically equivalent decorrelation): a *balanced
   random permutation* — local permutation, `all_to_all` chunk exchange,
   local permutation. Every device's key batch then contains a random
   B/n-sized slice from each device, so the positive key is normalized
   with (in expectation) only 1/n of its own co-batch — the same
   expected composition a uniform global permutation gives — while
   moving only (n-1)/n of the batch over ICI instead of a full
   all_gather. (An earlier `ring` mode that ppermuted batches *intact*
   was removed: moving an unchanged batch to another device leaves BN
   statistics bit-identical to no shuffle at all — composition, not
   device identity, is what leaks.)

A third alternative — no shuffle, subgroup cross-replica BN (SyncBN, as
the reference's detection configs use) — lives in the model's
`bn_cross_replica_axis` knob, not here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from moco_tpu.obs import comms
from moco_tpu.parallel.compat import axis_size


def _merge_gather(x: jax.Array, axis_name: str, site: str) -> jax.Array:
    """all_gather with the device dim folded into the batch dim:
    (N_global, ...). `site` names the collective in the comms ledger +
    HLO metadata (obs/comms.py)."""
    with comms.tag(site, "all_gather", x, axis_size(axis_name)):
        g = lax.all_gather(x, axis_name)  # (n_dev, B_local, ...)
    return g.reshape((-1,) + g.shape[2:])


def make_permutation(rng: jax.Array, global_batch: int) -> tuple[jax.Array, jax.Array]:
    """(perm, inv_perm) for the global batch. Called with a *replicated* rng
    inside the step so every device computes the same permutation —
    deterministic seeding replaces the reference's `broadcast(src=0)`."""
    perm = jax.random.permutation(rng, global_batch)
    inv_perm = jnp.argsort(perm)
    return perm, inv_perm


def shuffle_gather(x: jax.Array, perm: jax.Array, axis_name: str) -> jax.Array:
    """Give this device the rows `perm[rank*B:(rank+1)*B]` of the global batch."""
    local_b = x.shape[0]
    rank = lax.axis_index(axis_name)
    x_all = _merge_gather(x, axis_name, "shuffle.gather_images")
    my_rows = lax.dynamic_slice_in_dim(perm, rank * local_b, local_b)
    return jnp.take(x_all, my_rows, axis=0)


def unshuffle_gather(
    k: jax.Array, inv_perm: jax.Array, axis_name: str
) -> tuple[jax.Array, jax.Array]:
    """Invert `shuffle_gather` on the key embeddings.

    Returns (k_local, k_global): this device's keys in original order, and
    the full global key batch in original order (reused for the queue
    update, saving the reference's third all_gather in
    `_dequeue_and_enqueue`).
    """
    local_b = k.shape[0]
    rank = lax.axis_index(axis_name)
    # this gather is ALSO the queue's key source (the enqueue reuses
    # k_global, saving the reference's third all_gather)
    k_all = _merge_gather(k, axis_name, "shuffle.gather_keys")  # rows in perm order
    k_global = jnp.take(k_all, inv_perm, axis=0)  # original order
    k_local = lax.dynamic_slice_in_dim(k_global, rank * local_b, local_b)
    return k_local, k_global


def _local_perms(rng: jax.Array, local_b: int, axis_name: str) -> tuple[jax.Array, jax.Array]:
    """Per-device (pre, post) permutations of the local batch, derived from
    the replicated step rng + the device's rank."""
    rank = lax.axis_index(axis_name)
    pre = jax.random.permutation(jax.random.fold_in(jax.random.fold_in(rng, 17), rank), local_b)
    post = jax.random.permutation(jax.random.fold_in(jax.random.fold_in(rng, 29), rank), local_b)
    return pre, post


def balanced_shuffle(rng: jax.Array, x: jax.Array, axis_name: str) -> jax.Array:
    """Random *balanced* permutation of the global batch: each device ends
    up with a random B/n-slice from every device.

    local-perm → tiled all_to_all (device d's chunk j → device j) →
    local-perm. Requires local batch divisible by the axis size."""
    n = axis_size(axis_name)
    b = x.shape[0]
    if b % n:
        raise ValueError(f"a2a shuffle needs local batch {b} divisible by axis size {n}")
    pre, post = _local_perms(rng, b, axis_name)
    x = jnp.take(x, pre, axis=0)
    with comms.tag("shuffle.a2a", "all_to_all", x, n):
        x = lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0, tiled=True)
    return jnp.take(x, post, axis=0)


def balanced_unshuffle(rng: jax.Array, y: jax.Array, axis_name: str) -> jax.Array:
    """Exact inverse of `balanced_shuffle` with the same rng (the tiled
    chunk exchange is an involution; the local perms invert via argsort)."""
    n = axis_size(axis_name)
    b = y.shape[0]
    pre, post = _local_perms(rng, b, axis_name)
    y = jnp.take(y, jnp.argsort(post), axis=0)
    with comms.tag("shuffle.a2a_unshuffle", "all_to_all", y, n):
        y = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0, tiled=True)
    return jnp.take(y, jnp.argsort(pre), axis=0)
