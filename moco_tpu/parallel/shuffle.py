"""Cross-replica batch shuffling (Shuffle-BN) — TPU-native redesigns.

Reference: `moco/builder.py:~L79-126` (`_batch_shuffle_ddp` /
`_batch_unshuffle_ddp`, "*** Only support DDP model. ***"). There, rank 0
draws a random permutation of the global key batch and *broadcasts* it
over NCCL; every rank all-gathers the images, takes its permuted slice,
runs `encoder_k` with per-GPU BatchNorm, and the embeddings are
all-gathered back and inverse-permuted. Purpose: per-device BN statistics
must not contain a query's own positive key (the BN "cheating" signature
leak).

TPU-native redesigns (all used inside `shard_map` over the `data` axis):

1. `gather_perm` (reference-exact semantics): the broadcast is replaced
   by *deterministic same-seed randomness* — every replica computes the
   identical permutation from the replicated step RNG, so no collective
   is needed to agree on it. Data still moves via `all_gather` exactly as
   upstream.

2. `ring` (cheaper, same leak-prevention guarantee): a `ppermute` ring
   shift by one — device d computes keys for device d+1's batch, so no
   device ever normalizes a batch containing its own queries' positives.
   Two point-to-point ICI hops total (images out, embeddings back)
   instead of two all-gathers.

A third alternative — no shuffle, subgroup cross-replica BN (SyncBN, as
the reference's detection configs use) — lives in the model's
`bn_cross_replica_axis` knob, not here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _merge_gather(x: jax.Array, axis_name: str) -> jax.Array:
    """all_gather with the device dim folded into the batch dim: (N_global, ...)."""
    g = lax.all_gather(x, axis_name)  # (n_dev, B_local, ...)
    return g.reshape((-1,) + g.shape[2:])


def make_permutation(rng: jax.Array, global_batch: int) -> tuple[jax.Array, jax.Array]:
    """(perm, inv_perm) for the global batch. Called with a *replicated* rng
    inside the step so every device computes the same permutation —
    deterministic seeding replaces the reference's `broadcast(src=0)`."""
    perm = jax.random.permutation(rng, global_batch)
    inv_perm = jnp.argsort(perm)
    return perm, inv_perm


def shuffle_gather(x: jax.Array, perm: jax.Array, axis_name: str) -> jax.Array:
    """Give this device the rows `perm[rank*B:(rank+1)*B]` of the global batch."""
    local_b = x.shape[0]
    rank = lax.axis_index(axis_name)
    x_all = _merge_gather(x, axis_name)
    my_rows = lax.dynamic_slice_in_dim(perm, rank * local_b, local_b)
    return jnp.take(x_all, my_rows, axis=0)


def unshuffle_gather(
    k: jax.Array, inv_perm: jax.Array, axis_name: str
) -> tuple[jax.Array, jax.Array]:
    """Invert `shuffle_gather` on the key embeddings.

    Returns (k_local, k_global): this device's keys in original order, and
    the full global key batch in original order (reused for the queue
    update, saving the reference's third all_gather in
    `_dequeue_and_enqueue`).
    """
    local_b = k.shape[0]
    rank = lax.axis_index(axis_name)
    k_all = _merge_gather(k, axis_name)  # rows in perm order
    k_global = jnp.take(k_all, inv_perm, axis=0)  # original order
    k_local = lax.dynamic_slice_in_dim(k_global, rank * local_b, local_b)
    return k_local, k_global


def ring_shift(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Send this device's batch to rank+shift (mod n) over the ICI ring."""
    n = lax.axis_size(axis_name)
    pairs = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, pairs)


def ring_unshift(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    return ring_shift(x, axis_name, shift=-shift)
