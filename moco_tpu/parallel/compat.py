"""Version-compat shims for jax APIs that moved between releases.

The codebase targets the modern `jax.shard_map` surface (top-level
export, `check_vma=` kwarg). Older jax lines (< 0.6) ship the same
transform as `jax.experimental.shard_map.shard_map` with the flag
spelled `check_rep=`. Every call site routes through :func:`shard_map`
here so ONE module gates the difference — on an old jax the alternative
is an `AttributeError` at trace time in every shard_map consumer (the
whole train step, the probe, the shuffle tests), which reads like a
training bug rather than what it is: a missing-API environment.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` where available, else the experimental spelling
    with `check_vma` mapped onto its older `check_rep` name."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )


@jax.custom_vjp
def optimization_barrier(x):
    """`lax.optimization_barrier` with a gradient on every jax: older
    releases ship the primitive without a differentiation rule, so the
    barrier (an identity for values) carries an identity VJP — the
    backward pass sees the same gradients either way."""
    return jax.lax.optimization_barrier(x)


def _barrier_fwd(x):
    return optimization_barrier(x), None


def _barrier_bwd(_, g):
    return (g,)


optimization_barrier.defvjp(_barrier_fwd, _barrier_bwd)


def axis_size(axis_name) -> int:
    """`jax.lax.axis_size` where available; on older jax `psum(1, axis)`
    — which under shard_map is a static Python int, so shape arithmetic
    downstream (reshape by the axis size) keeps working."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
