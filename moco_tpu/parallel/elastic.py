"""Elastic training: heartbeat-triggered checkpoint-and-rescale.

PR 1 can only restart a fixed-shape job from its last checkpoint; PR 4's
heartbeats can only *name* a dead host. This module closes the loop the
ROADMAP's "elastic fleet" item describes: when a host stops beating
(crash, preemption, the `kill@host=i` chaos fault), the survivors

1. **detect** the loss out-of-band — `ElasticCoordinator.stale_hosts()`
   reads the per-process `heartbeat.p<i>.json` files (obs/fleet.py) and
   flags any whose age exceeds the configurable `--heartbeat-timeout`;
2. **agree** on the event — `agree()` is a rescale-consensus barrier in
   the style of the collective-schedule sanitizer's out-of-band exchange
   (analysis/sanitizer.py): each survivor atomically publishes its plan
   to `rescale.p<i>.json` and polls until every surviving peer published
   a matching one, so no process reshapes alone while another is still
   dispatching collectives on the old mesh;
3. **checkpoint** — the driver takes an emergency save of the last
   known-finite state (the fault-tolerance layer's save-first path);
4. **reshard + rescale** — `plan_rescale()` picks the widest surviving
   mesh that preserves the queue/batch divisibility invariants
   (`K % global_batch == 0`, per-device batch held constant) and
   re-derives the momentum/LR hyperparameters through the `--auto-scale`
   rule (utils/config.py `apply_auto_scale`): with κ = new_batch /
   ref_batch, the EMA momentum scales as m^κ ("How to Scale Your EMA",
   arXiv:2307.13813; Momentum² Teacher, arXiv:2101.07525) and the LR
   linearly — a principled rescale, not silent hyperparameter drift;
5. **resume in-process** — the driver re-enters its setup with the
   shrunk config; the existing layout-aware resume restores the
   emergency checkpoint into ITS OWN layout and converts host-side
   through `reshard_state` (core/moco.py) / the ZeRO flat-shard
   converters (parallel/zero.py), so params, optimizer shards, and the
   queue land on the surviving mesh without a from-scratch restart.

Single-process fake-fleet simulation (CI, `scripts/elastic_smoke.py`):
each virtual device doubles as a "host"; the kill fault stamps a stale
heartbeat and the whole loop — detection, consensus, checkpoint,
reshard, rescale, resume — runs for real in one process. On a real
multi-process fleet the same detection + consensus + checkpoint path
runs, but the JAX distributed runtime cannot shrink in-process: every
survivor exits with `RESCALE_EXIT_CODE` after the durable save, and the
launcher restarts the surviving hosts with the derived config (the
resume then reshards exactly as in the simulated path).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional, Sequence

from moco_tpu.utils.config import TrainConfig, apply_auto_scale

# Exit code a multi-process survivor leaves with after the consensus +
# emergency checkpoint (the launcher's signal to relaunch the surviving
# hosts with the derived config). Distinct from the watchdog's stall
# code and the kill fault's KILL_EXIT_CODE; hosted by
# utils/contracts.py (single-source exit codes, JX018) and re-exported
# here for existing importers.
from moco_tpu.utils.contracts import RESCALE_EXIT_CODE  # noqa: F401


@dataclasses.dataclass(frozen=True)
class RescalePlan:
    """The agreed rescale: which hosts died, at which step, and the
    derived mesh/batch shape every survivor must adopt."""

    step: int
    dead_hosts: tuple  # ALL dead host indices (cumulative across rescales)
    old_num_data: int
    new_num_data: int
    old_global_batch: int
    new_global_batch: int

    def consensus_key(self) -> dict:
        """The fields survivors must agree on byte-for-byte (step is
        excluded: wall-clock staleness may be observed one log step
        apart across hosts; the plan they derive from it may not
        differ)."""
        return {
            "dead_hosts": sorted(int(h) for h in self.dead_hosts),
            "new_num_data": int(self.new_num_data),
            "new_global_batch": int(self.new_global_batch),
        }


class ElasticRescale(RuntimeError):
    """Raised by the driver's log-step elastic check after the
    emergency checkpoint is durable; `train()` catches it, adopts
    `new_config`, and re-enters the setup on the surviving mesh."""

    def __init__(self, plan: RescalePlan, new_config: TrainConfig, info: dict):
        super().__init__(
            f"elastic rescale at step {plan.step}: hosts {list(plan.dead_hosts)} "
            f"lost, mesh {plan.old_num_data} -> {plan.new_num_data}, global "
            f"batch {plan.old_global_batch} -> {plan.new_global_batch}"
        )
        self.plan = plan
        self.new_config = new_config
        self.info = info


def feasible_width(
    survivors: int, per_device_batch: int, num_negatives: int
) -> int:
    """The widest data-axis width ≤ `survivors` that keeps the training
    invariants intact at a constant per-device batch: the queue's
    `K % global_batch == 0` FIFO invariant (core/queue.py) must hold for
    the shrunk global batch. Raises when no width survives (the fleet
    is below the minimum viable mesh)."""
    if survivors < 1:
        raise ValueError("no surviving hosts — nothing to rescale onto")
    for n in range(survivors, 0, -1):
        if num_negatives > 0 and num_negatives % (per_device_batch * n):
            continue
        return n
    raise ValueError(
        f"no mesh width <= {survivors} keeps K={num_negatives} divisible by "
        f"the global batch (per-device batch {per_device_batch})"
    )


def surviving_devices(dead_hosts: Sequence[int]):
    """The device list a post-rescale mesh builds over. Multi-process:
    a dead host's devices are the dead process's. Single process
    (fake-fleet simulation): device index i IS host i — the same
    one-device-per-host convention the FleetAggregator uses."""
    import jax

    dead = set(int(h) for h in dead_hosts)
    if jax.process_count() > 1:
        return [d for d in jax.devices() if d.process_index not in dead]
    return [d for i, d in enumerate(jax.devices()) if i not in dead]


def plan_rescale(
    ref_config: TrainConfig,
    num_data: int,
    num_model: int,
    dead_hosts: Sequence[int],
    step: int,
) -> tuple[RescalePlan, TrainConfig, dict]:
    """Derive the post-loss world from the reference config: surviving
    devices → feasible mesh width (per-device batch constant) → new
    global batch → re-derived momentum/LR via the auto-scale rule.

    `ref_config` carries the REFERENCE hyperparameters (lr/momentum at
    `auto_scale` ref_batch), so repeated rescales always derive from the
    same anchor rather than compounding already-scaled values. Returns
    (plan, new reference config, derived-hyperparameter info)."""
    if num_model != 1:
        raise ValueError("elastic rescale supports num_model=1 meshes only")
    per_dev = ref_config.data.global_batch // num_data
    if per_dev * num_data != ref_config.data.global_batch:
        raise ValueError(
            f"global batch {ref_config.data.global_batch} not divisible by "
            f"the data axis {num_data}"
        )
    survivors = len(surviving_devices(dead_hosts)) // num_model
    new_n = feasible_width(survivors, per_dev, ref_config.moco.num_negatives)
    new_batch = per_dev * new_n
    plan = RescalePlan(
        step=int(step),
        dead_hosts=tuple(sorted(int(h) for h in dead_hosts)),
        old_num_data=int(num_data),
        new_num_data=int(new_n),
        old_global_batch=int(ref_config.data.global_batch),
        new_global_batch=int(new_batch),
    )
    new_ref = dataclasses.replace(
        ref_config,
        data=dataclasses.replace(ref_config.data, global_batch=new_batch),
        parallel=dataclasses.replace(ref_config.parallel, num_data=new_n),
    )
    _, info = apply_auto_scale(new_ref)
    return plan, new_ref, dict(info or {})


def rescale_path(workdir: str, process_index: int) -> str:
    return os.path.join(workdir, f"rescale.p{process_index}.json")


class ElasticCoordinator:
    """Per-process detection + consensus for the elastic loop.

    `stale_hosts()` is the heartbeat-staleness detector (called by the
    driver on log steps, off the hot path); `agree()` is the
    rescale-consensus barrier over atomic `rescale.p<i>.json` files —
    the same out-of-band publish/poll pattern the collective-schedule
    sanitizer uses, so it needs no working collective (the dead host may
    be wedged inside one)."""

    def __init__(
        self,
        workdir: str,
        process_index: int = 0,
        num_processes: int = 1,
        timeout: float = 120.0,
        known_dead: Sequence[int] = (),
        barrier_timeout: float = 60.0,
        poll_interval: float = 0.05,
    ):
        self.workdir = workdir
        self.process_index = int(process_index)
        self.num_processes = int(num_processes)
        self.timeout = float(timeout)
        self.known_dead = set(int(h) for h in known_dead)
        self.barrier_timeout = float(barrier_timeout)
        self.poll_interval = float(poll_interval)

    def stale_hosts(self, now: Optional[float] = None) -> list[int]:
        """Host indices whose heartbeat file is older than the timeout —
        newly dead only (self and already-rescaled-away hosts are
        excluded). A host with NO heartbeat file is not reported: it
        never joined this run's fleet (simulated hosts appear only once
        the chaos harness stamps them)."""
        from moco_tpu.obs.fleet import read_heartbeats

        now = time.time() if now is None else now
        stale = []
        for p, rec in read_heartbeats(self.workdir).items():
            if p == self.process_index or p in self.known_dead:
                continue
            if now - float(rec.get("time", 0.0)) > self.timeout:
                stale.append(p)
        return sorted(stale)

    def agree(self, plan: RescalePlan) -> RescalePlan:
        """Publish this process's plan and block until every surviving
        peer published a MATCHING one (consensus_key equality). Returns
        the agreed plan; raises RuntimeError on barrier timeout or a
        conflicting peer plan — both mean the fleet does not share one
        view of who died, and proceeding would re-shard into a split
        brain."""
        key = plan.consensus_key()
        path = rescale_path(self.workdir, self.process_index)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"process": self.process_index, "time": time.time(), **key}, f)
        os.replace(tmp, path)
        survivors = [
            p
            for p in range(self.num_processes)
            if p != self.process_index and p not in set(plan.dead_hosts)
        ]
        deadline = time.time() + self.barrier_timeout
        pending = set(survivors)
        while pending:
            for p in sorted(pending):
                try:
                    with open(rescale_path(self.workdir, p)) as f:
                        peer = json.load(f)
                except (OSError, ValueError):
                    continue
                peer_key = {k: peer.get(k) for k in key}
                if peer_key == key:
                    pending.discard(p)
                elif peer.get("time", 0.0) >= time.time() - self.barrier_timeout:
                    raise RuntimeError(
                        f"rescale consensus conflict: process {p} proposes "
                        f"{peer_key}, this process {key}"
                    )
            if pending and time.time() > deadline:
                raise RuntimeError(
                    f"rescale consensus barrier timed out after "
                    f"{self.barrier_timeout:g}s waiting for processes "
                    f"{sorted(pending)}"
                )
            if pending:
                time.sleep(self.poll_interval)
        return plan


__all__ = [
    "RESCALE_EXIT_CODE",
    "ElasticCoordinator",
    "ElasticRescale",
    "RescalePlan",
    "feasible_width",
    "plan_rescale",
    "rescale_path",
    "surviving_devices",
]
