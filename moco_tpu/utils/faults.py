"""Deterministic fault injection — the harness that makes the
fault-tolerance layer *verifiable* rather than hopeful.

A plan is installed from a spec string (usually the `MOCO_FAULTS` env
var; `scripts/chaos_smoke.sh` and the tests drive it). Faults are keyed
on deterministic counters — a global step number, the Nth read at a call
site — never randomness, so a chaos run is exactly reproducible.

Spec grammar: comma-separated faults, each `kind@key=val[:key=val...]`:

    ckpt_truncate@step=N          truncate the checkpoint written at id N
                                  (largest file under its state/ halved)
                                  after the write completes — a partial/
                                  torn write the restore path must survive
    io@site=S:at=K[:times=M]      raise IOError on the Kth (1-based) read
                                  at call site S (M consecutive reads;
                                  default 1) — exercises the retry layer
    delay@site=S:seconds=X[:at=K:times=M]
                                  sleep X seconds on calls K..K+M-1
                                  (1-based; default: every call) at site
                                  S — deterministic stage slow-downs
                                  (S="input.h2d" is the synthetic slow
                                  wire the overlap tests/smoke use;
                                  S="data.read" slows host decode)
    nan@step=N[:times=M]          the loss observed at global steps
                                  N..N+M-1 becomes NaN — exercises the
                                  non-finite guard
    stall@step=N:seconds=S        sleep S seconds at global step N (once)
                                  — exercises the stall watchdog
    preempt@step=N                SIGTERM this process at global step N
                                  (once) — deterministic preemption
    diverge@site=S                perturb THIS process's recorded
                                  collective schedule at comms site S
                                  (analysis/sanitizer.py appends a
                                  divergence marker to the site's shape
                                  signature) — exercises the runtime
                                  collective-schedule sanitizer without
                                  a real divergent pod
    deadlock@site=L               force an INVERTED lock-acquisition
                                  order at tagged lock L: when the
                                  tsan-traced lock named L is acquired
                                  while another lock is held, the
                                  lock-order recorder (analysis/tsan.py)
                                  also records the edge the opposite
                                  nesting would have produced, as if a
                                  second thread raced the critical
                                  section backwards — a deterministic
                                  order cycle through the real
                                  detection path, with no actual
                                  deadlock risk (the serve_smoke
                                  --sanitize-threads chaos leg)
    kill@host=i[:at=K]             host i dies at global step K (default:
                                  the first step observed). In a real
                                  multi-process fleet the faulted
                                  process stops beating and exits
                                  immediately (exit code KILL_EXIT_CODE,
                                  no checkpoint — sudden death, not a
                                  graceful preemption); on a
                                  single-process fake-fleet mesh the
                                  harness instead stamps simulated host
                                  i's out-of-band heartbeat file
                                  (heartbeat.p<i>.json) with an
                                  infinitely stale timestamp, so the
                                  survivors' REAL staleness-detection
                                  path (obs/fleet.py heartbeats ->
                                  parallel/elastic.py) observes the
                                  loss deterministically — the elastic
                                  checkpoint-and-rescale chaos harness
                                  (scripts/elastic_smoke.py)
    kill@replica=i[:at=K]         serving replica i dies (os._exit with
                                  KILL_EXIT_CODE, no cleanup) while
                                  handling its Kth /embed or /neighbors
                                  POST (1-based; default 1) — sudden
                                  replica death mid-request, the fleet
                                  chaos harness: the router must retry
                                  the in-flight request elsewhere and
                                  the ReplicaSupervisor must restart +
                                  re-warm the corpse
                                  (scripts/fleet_serve_smoke.py). The
                                  supervisor strips kill@replica rules
                                  from the reborn process's MOCO_FAULTS
                                  (strip_replica_kills) so one rule is
                                  one death, not a crash loop
    slow@site=S:ms=X[:at=K:times=M]
                                  sleep X *milliseconds* on calls
                                  K..K+M-1 (1-based; default: every
                                  call) at serving-stage site S —
                                  deterministic tail-latency injection
                                  for the request-trace waterfall.
                                  Sites are the serve stage hooks:
                                  serve.ingress, serve.batch_assemble,
                                  serve.engine_execute,
                                  serve.index_query, serve.scatter,
                                  serve.respond. The sleep happens
                                  INSIDE the stage's stamped interval,
                                  so the flight recorder must attribute
                                  the injected tail to exactly that
                                  stage (the serve_smoke SLO leg's
                                  acceptance check)

Example:
    MOCO_FAULTS="ckpt_truncate@step=8,io@site=data.read:at=3,nan@step=6"

Zero-cost when disabled: all hooks early-return on a module-level None
check, and the step-loop hooks are only ever called inside the existing
`i % log_every` host-sync block (see ISSUE acceptance: no new host-side
work in the step loop).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from collections import Counter
from typing import Optional

KINDS = (
    "ckpt_truncate", "io", "nan", "stall", "preempt", "delay", "diverge",
    "slow", "kill", "deadlock",
)

# Exit code of a kill@host-faulted process in a real multi-process fleet
# (distinct from the watchdog's stall code): sudden death the survivors
# must detect via heartbeat staleness, not a graceful shutdown. Hosted
# by utils/contracts.py (single-source exit codes, JX018) and
# re-exported here for existing importers.
from moco_tpu.utils.contracts import KILL_EXIT_CODE  # noqa: F401

_INT_KEYS = ("step", "at", "times", "host", "replica")
_FLOAT_KEYS = ("seconds", "ms")
_STR_KEYS = ("site",)


class FaultPlan:
    """Parsed spec + the deterministic trigger counters."""

    def __init__(self, spec: str):
        self.spec = spec
        self.rules: list[tuple[str, dict]] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            kind, _, params = part.partition("@")
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} in {part!r} (known: {KINDS})"
                )
            kv: dict = {}
            for tok in params.split(":"):
                if not tok:
                    continue
                k, _, v = tok.partition("=")
                if k in _INT_KEYS:
                    kv[k] = int(v)
                elif k in _FLOAT_KEYS:
                    kv[k] = float(v)
                elif k in _STR_KEYS:
                    kv[k] = v
                else:
                    raise ValueError(f"unknown fault param {k!r} in {part!r}")
            if kind == "kill" and "host" not in kv and "replica" not in kv:
                raise ValueError(
                    f"kill fault {part!r} needs host=<process index> "
                    f"or replica=<serving replica index>"
                )
            if kind == "kill" and "host" in kv and "replica" in kv:
                raise ValueError(
                    f"kill fault {part!r}: host= (training harness) and "
                    f"replica= (serving harness) are mutually exclusive"
                )
            self.rules.append((kind, kv))
        self._lock = threading.Lock()
        self._io_counts: Counter = Counter()  # site -> reads seen
        self._fired: set = set()  # once-only rule ids that already fired

    def describe(self) -> list:
        return [(k, dict(p)) for k, p in self.rules]

    # -- hooks -----------------------------------------------------------
    def maybe_io_error(self, site: str) -> None:
        with self._lock:
            self._io_counts[site] += 1
            n = self._io_counts[site]
        for kind, p in self.rules:
            if kind != "io" or p.get("site", site) != site:
                continue
            at = p.get("at", 1)
            if at <= n < at + p.get("times", 1):
                raise IOError(f"injected fault: read #{n} at site {site!r}")

    def maybe_delay(self, site: str) -> None:
        """Deterministic per-site sleep (stage slow-down, not an error):
        counted on its own counter namespace so io@ and delay@ rules on
        the same site don't perturb each other's schedules."""
        key = f"delay:{site}"
        with self._lock:
            self._io_counts[key] += 1
            n = self._io_counts[key]
        for kind, p in self.rules:
            if kind != "delay" or p.get("site", site) != site:
                continue
            at = p.get("at", 1)
            times = p.get("times")
            if n >= at and (times is None or n < at + times):
                time.sleep(p["seconds"])

    def maybe_slow(self, site: str) -> None:
        """Millisecond-scale serving-stage sleep — `delay@`'s twin for
        the request path, on its own counter namespace so a slow@ and a
        delay@ rule on one site can't perturb each other's schedules.
        The serve stage hooks call this inside the stamped interval, so
        injected tail latency is attributed to the right stage."""
        key = f"slow:{site}"
        with self._lock:
            self._io_counts[key] += 1
            n = self._io_counts[key]
        for kind, p in self.rules:
            if kind != "slow" or p.get("site", site) != site:
                continue
            at = p.get("at", 1)
            times = p.get("times")
            if n >= at and (times is None or n < at + times):
                time.sleep(p["ms"] / 1e3)

    def corrupt_loss(self, loss: float, step: int) -> float:
        for kind, p in self.rules:
            if kind == "nan" and p["step"] <= step < p["step"] + p.get("times", 1):
                return float("nan")
        return loss

    def maybe_stall(self, step: int) -> None:
        for i, (kind, p) in enumerate(self.rules):
            if kind == "stall" and p["step"] == step and self._fire_once(i):
                print(f"injected fault: stalling {p['seconds']}s at step {step}", flush=True)
                time.sleep(p["seconds"])

    def maybe_preempt(self, step: int) -> None:
        for i, (kind, p) in enumerate(self.rules):
            if kind == "preempt" and p["step"] == step and self._fire_once(i):
                print(f"injected fault: SIGTERM self at step {step}", flush=True)
                os.kill(os.getpid(), signal.SIGTERM)

    def maybe_kill_host(
        self, step: int, workdir: str, process_index: int, num_processes: int = 1
    ) -> None:
        """`kill@host=i[:at=K]` — deterministic host loss for the elastic
        chaos harness. Multi-process fleet: the faulted process stops
        beating and exits with KILL_EXIT_CODE (sudden death). Single
        process (fake-fleet simulation, one virtual device per "host"):
        stamp simulated host i's heartbeat file with an infinitely stale
        timestamp so the survivors' real staleness detection fires."""
        for i, (kind, p) in enumerate(self.rules):
            # replica-keyed kills belong to maybe_kill_replica (the
            # serving fleet harness), not the training-host path
            if kind != "kill" or "host" not in p or step < p.get("at", 1):
                continue
            host = p["host"]
            if num_processes > 1:
                if process_index == host and self._fire_once(i):
                    print(
                        f"injected fault: killing host {host} (this process) "
                        f"at step {step}",
                        flush=True,
                    )
                    os._exit(KILL_EXIT_CODE)  # no beats, no cleanup: sudden death
            elif self._fire_once(i):
                # same filename convention as obs/fleet.py heartbeat_path
                # (kept inline: this module stays stdlib-only); time=0.0
                # is "stale since the epoch" — deterministic, no sleeping
                import json

                path = os.path.join(workdir, f"heartbeat.p{host}.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(
                        {"process": host, "host": f"killed@step={step}",
                         "pid": 0, "time": 0.0, "step": int(step), "epoch": 0},
                        f,
                    )
                os.replace(tmp, path)
                print(
                    f"injected fault: simulated host {host} stopped beating "
                    f"at step {step}",
                    flush=True,
                )

    def maybe_kill_replica(self, replica_index: int) -> None:
        """`kill@replica=i[:at=K]` — sudden serving-replica death, keyed
        on this replica's own request counter (the Kth /embed//neighbors
        POST it handles), so the death lands mid-burst deterministically
        regardless of how the router spread the load. `os._exit`: no
        drain, no metrics flush, the socket just resets — exactly the
        failure the router's breaker + retry path must absorb."""
        key = f"kill_replica:{int(replica_index)}"
        with self._lock:
            self._io_counts[key] += 1
            n = self._io_counts[key]
        for kind, p in self.rules:
            if kind != "kill" or p.get("replica") != int(replica_index):
                continue
            if n >= p.get("at", 1):
                print(
                    f"injected fault: killing replica {replica_index} "
                    f"(this process) on request #{n}",
                    flush=True,
                )
                os._exit(KILL_EXIT_CODE)  # sudden death: no cleanup, no flush

    def deadlock_marker(self, site: str) -> bool:
        """True when a `deadlock@site=L` rule targets this tsan lock
        name — the lock-order recorder then records the inverted
        acquisition edge too (see analysis/tsan.py)."""
        for kind, p in self.rules:
            if kind == "deadlock" and p.get("site") == site:
                return True
        return False

    def diverge_marker(self, site: str) -> str:
        """Non-empty divergence marker when a `diverge@site=S` rule
        targets this comms site — the schedule recorder appends it to
        the site's shape signature, making THIS process's schedule hash
        differ deterministically."""
        for kind, p in self.rules:
            if kind == "diverge" and p.get("site") == site:
                return "#diverged"
        return ""

    def on_checkpoint_saved(self, directory: str, step: int, wait=None) -> None:
        for i, (kind, p) in enumerate(self.rules):
            if kind == "ckpt_truncate" and p["step"] == step and self._fire_once(i):
                if wait is not None:
                    wait()  # async writes must land before we can corrupt them
                _truncate_step_dir(directory, step)

    def _fire_once(self, rule_id: int) -> bool:
        with self._lock:
            if rule_id in self._fired:
                return False
            self._fired.add(rule_id)
            return True


def _truncate_step_dir(directory: str, step: int) -> None:
    """Halve the largest file under `<directory>/<step>/state` — the
    shape of a torn write: the checkpoint directory looks committed, its
    metadata parses, but the tensor payload is short."""
    state_dir = os.path.join(directory, str(step), "state")
    files = []
    for root, _, names in os.walk(state_dir):
        for name in names:
            p = os.path.join(root, name)
            if os.path.isfile(p):
                files.append(p)
    if not files:
        raise RuntimeError(f"injected ckpt_truncate: no files under {state_dir}")
    target = max(files, key=os.path.getsize)
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.truncate(max(1, size // 2))
    print(
        f"injected fault: truncated {target} ({size} -> {max(1, size // 2)} bytes)",
        flush=True,
    )


# -- module-level registry (one plan per process) ------------------------
_PLAN: Optional[FaultPlan] = None


def install(spec: Optional[str]) -> Optional[FaultPlan]:
    """Install a fresh plan (counters reset); None/empty clears."""
    global _PLAN
    _PLAN = FaultPlan(spec) if spec else None
    return _PLAN


def install_from_env() -> Optional[FaultPlan]:
    """Install from `MOCO_FAULTS` when set; otherwise leave the current
    plan alone (tests install programmatically)."""
    spec = os.environ.get("MOCO_FAULTS")
    if spec:
        return install(spec)
    return _PLAN


def clear() -> None:
    install(None)


def enabled() -> bool:
    return _PLAN is not None


def describe() -> list:
    return _PLAN.describe() if _PLAN else []


# Runtime contract-coverage arm (analysis/contracts.py): when a
# callback is installed, every hook invocation reports (kind, site) —
# plan or no plan — so a smoke leg can prove each registered fault site
# is still reachable. None-checked per call: zero cost when off.
_COVERAGE_CB = None


def set_coverage_callback(cb) -> None:
    """Install/clear the `cb(kind, site)` hook-reached callback."""
    global _COVERAGE_CB
    _COVERAGE_CB = cb


# thin delegating hooks — all no-ops when no plan is installed
def maybe_io_error(site: str) -> None:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("io", site)
    if _PLAN is not None:
        _PLAN.maybe_io_error(site)


def maybe_delay(site: str) -> None:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("delay", site)
    if _PLAN is not None:
        _PLAN.maybe_delay(site)


def maybe_slow(site: str) -> None:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("slow", site)
    if _PLAN is not None:
        _PLAN.maybe_slow(site)


def corrupt_loss(loss: float, step: int) -> float:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("nan", None)
    if _PLAN is not None:
        return _PLAN.corrupt_loss(loss, step)
    return loss


def maybe_stall(step: int) -> None:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("stall", None)
    if _PLAN is not None:
        _PLAN.maybe_stall(step)


def maybe_preempt(step: int) -> None:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("preempt", None)
    if _PLAN is not None:
        _PLAN.maybe_preempt(step)


def maybe_kill_host(
    step: int, workdir: str, process_index: int, num_processes: int = 1
) -> None:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("kill", "host")
    if _PLAN is not None:
        _PLAN.maybe_kill_host(step, workdir, process_index, num_processes)


def maybe_kill_replica(replica_index: int) -> None:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("kill", "replica")
    if _PLAN is not None:
        _PLAN.maybe_kill_replica(replica_index)


def strip_replica_kills(spec: Optional[str]) -> str:
    """Remove `kill@replica=...` rules from a spec string — the
    ReplicaSupervisor rewrites a reborn replica's MOCO_FAULTS with this
    so a kill rule fires exactly once instead of crash-looping the
    respawn. Other rules pass through verbatim (order preserved)."""
    if not spec:
        return ""
    kept = []
    for part in spec.split(","):
        token = part.strip()
        kind, _, params = token.partition("@")
        if kind == "kill" and any(
            tok.partition("=")[0] == "replica" for tok in params.split(":")
        ):
            continue
        if token:
            kept.append(token)
    return ",".join(kept)


def diverge_marker(site: str) -> str:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("diverge", site)
    if _PLAN is not None:
        return _PLAN.diverge_marker(site)
    return ""


def deadlock_marker(site: str) -> bool:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("deadlock", site)
    if _PLAN is not None:
        return _PLAN.deadlock_marker(site)
    return False


def on_checkpoint_saved(directory: str, step: int, wait=None) -> None:
    if _COVERAGE_CB is not None:
        _COVERAGE_CB("ckpt_truncate", None)
    if _PLAN is not None:
        _PLAN.on_checkpoint_saved(directory, step, wait=wait)
