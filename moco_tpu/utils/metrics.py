"""Training metrics: meters, progress display, JSONL writer, profiler.

Reference: `AverageMeter` / `ProgressMeter` (`main_moco.py:~L322-360`)
print `Epoch: [e][i/n] Time ... Data ... Loss ... Acc@1 ... Acc@5 ...`
every `--print-freq` steps; non-master ranks are silenced
(`main_moco.py:~L145`). Structured logging lives in `moco_tpu.obs`
(span tracer, sink registry, step-time probe, health gauges) — this
module keeps the reference-shaped console surface plus back-compat
aliases: `MetricWriter` IS the obs JSONL sink (refactored out in the
telemetry PR; same constructor, same crash-safe flush contract).

Multi-host semantics (reference behavior): only process 0 prints
console lines; every process keeps writing its own JSONL/sinks —
per-host metrics matter (a sick host shows up in ITS file), stdout
interleaving from N hosts does not.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Tuple

import jax

from moco_tpu.obs.sinks import JsonlSink


def is_primary() -> bool:
    """True on the process that owns console output (process 0; always
    True single-host). Tolerates being called before any backend/
    distributed init."""
    try:
        return jax.process_index() == 0
    except Exception:
        return True


def print0(*args, **kwargs) -> None:
    """`print` on process 0 only — the reference's non-master silencing
    (`main_moco.py:~L145`) for the driver's informational lines."""
    if is_primary():
        print(*args, **kwargs)


class AverageMeter:
    """Running value/average, formatted like the reference's meter."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name, self.fmt = name, fmt
        self.reset()

    def reset(self) -> None:
        self.val = self.sum = self.count = 0.0

    def update(self, val: float, n: int = 1) -> None:
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.sum / max(self.count, 1)

    def __str__(self) -> str:
        return ("{name} {val" + self.fmt + "} ({avg" + self.fmt + "})").format(
            name=self.name, val=self.val, avg=self.avg
        )


class ProgressMeter:
    """`Epoch: [e][ i/n] <meters>` lines, as `main_moco.py:~L340-360`.

    `display` prints on process 0 only (reference: non-master ranks are
    silenced, `main_moco.py:~L145`) but always returns the formatted
    line, so per-process callers/tests can still observe it."""

    def __init__(self, num_batches: int, meters: list[AverageMeter], prefix: str = ""):
        num_digits = len(str(num_batches))
        self.batch_fmtstr = "[{:" + str(num_digits) + "d}/" + str(num_batches) + "]"
        self.meters = meters
        self.prefix = prefix

    def display(self, batch: int) -> str:
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(m) for m in self.meters]
        line = "\t".join(entries)
        if is_primary():
            print(line, flush=True)
        return line


class MetricWriter(JsonlSink):
    """Back-compat name for the JSONL sink (see obs/sinks.py): the
    original single-destination writer grew into the sink registry; this
    alias keeps the constructor signature and crash-safe flush contract
    every existing call site (and the chaos harness) relies on."""


# -- jax.profiler management ---------------------------------------------
#
# `jax.profiler.start_trace` is process-global and refuses to start
# while a trace is active. A naive context manager has two failure
# modes: (a) nested/overlapping regions crash the outer one, and (b) a
# region that died between start and stop (exception in user code that
# skipped the finally, or a prior library leaving a trace running)
# poisons every LATER region — start_trace raises forever and the run
# loses profiling. The bookkeeping below makes regions reentrant
# (inner region = no-op) and start-failure self-healing (stop the
# dangler, retry once).

_profiler_state = {"active": False}


def _start_profiler(logdir: str) -> bool:
    """Start a trace; returns True when THIS call owns the stop. A
    dangling trace from a previous failed region is stopped and the
    start retried once."""
    if _profiler_state["active"]:
        return False  # reentrant region: outer owns the trace
    try:
        jax.profiler.start_trace(logdir)
    except Exception:
        # a trace someone else started and never stopped — clear it and
        # retry once; a second failure is a real error and propagates
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        jax.profiler.start_trace(logdir)
    _profiler_state["active"] = True
    return True


def _stop_profiler() -> None:
    _profiler_state["active"] = False
    jax.profiler.stop_trace()


@contextlib.contextmanager
def profiler_trace(logdir: Optional[str]):
    """`jax.profiler` trace (TensorBoard/Perfetto-viewable) around a
    code region; no-op when logdir is None; reentrancy-safe (an inner
    region under an active one is a no-op rather than a crash)."""
    if not logdir:
        yield
        return
    owns = _start_profiler(logdir)
    try:
        yield
    finally:
        if owns:
            _stop_profiler()


class ProfilerWindow:
    """Windowed `--profile-steps a:b` capture: trace exactly global
    steps [a, b) instead of the whole run. Whole-run traces of long
    jobs are gigabytes of mostly-identical steps; a window placed after
    warmup is what one actually loads into Perfetto. Drive with
    `on_step(gstep)` once per loop iteration; `close()` stops a
    still-open window (early exit, preemption)."""

    def __init__(self, logdir: str, start_step: int, end_step: int):
        if end_step <= start_step:
            raise ValueError(f"empty profile window [{start_step}, {end_step})")
        self.logdir = logdir
        self.start_step = int(start_step)
        self.end_step = int(end_step)
        self._owns = False
        self._done = False

    def on_step(self, gstep: int) -> None:
        """Called with the step about to run; starts/stops the window."""
        if self._done:
            return
        if not self._owns and self.start_step <= gstep < self.end_step:
            self._owns = _start_profiler(self.logdir)
        elif self._owns and gstep >= self.end_step:
            self.close()

    def close(self) -> None:
        if self._owns:
            self._owns = False
            _stop_profiler()
        self._done = True


def parse_profile_steps(spec: str) -> Tuple[int, int]:
    """`"a:b"` -> (a, b) with validation (CLI surface for ProfilerWindow)."""
    try:
        a, b = spec.split(":")
        lo, hi = int(a), int(b)
    except ValueError:
        raise ValueError(f"--profile-steps wants 'a:b' (global steps), got {spec!r}")
    if hi <= lo or lo < 0:
        raise ValueError(f"--profile-steps window [{lo}, {hi}) is empty or negative")
    return lo, hi
