"""Training metrics: meters, progress display, JSONL writer, profiler.

Reference: `AverageMeter` / `ProgressMeter` (`main_moco.py:~L322-360`)
print `Epoch: [e][i/n] Time ... Data ... Loss ... Acc@1 ... Acc@5 ...`
every `--print-freq` steps; non-master ranks are silenced
(`main_moco.py:~L145`). There is no structured logging in the reference
(SURVEY.md §5.5) — the JSONL writer and `jax.profiler` hook here are the
TPU-native observability upgrade (§5.1).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import time
from typing import Optional

import jax


class AverageMeter:
    """Running value/average, formatted like the reference's meter."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name, self.fmt = name, fmt
        self.reset()

    def reset(self) -> None:
        self.val = self.sum = self.count = 0.0

    def update(self, val: float, n: int = 1) -> None:
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.sum / max(self.count, 1)

    def __str__(self) -> str:
        return ("{name} {val" + self.fmt + "} ({avg" + self.fmt + "})").format(
            name=self.name, val=self.val, avg=self.avg
        )


class ProgressMeter:
    """`Epoch: [e][ i/n] <meters>` lines, as `main_moco.py:~L340-360`."""

    def __init__(self, num_batches: int, meters: list[AverageMeter], prefix: str = ""):
        num_digits = len(str(num_batches))
        self.batch_fmtstr = "[{:" + str(num_digits) + "d}/" + str(num_batches) + "]"
        self.meters = meters
        self.prefix = prefix

    def display(self, batch: int) -> str:
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(m) for m in self.meters]
        line = "\t".join(entries)
        print(line, flush=True)
        return line


class MetricWriter:
    """Append-only JSONL metrics (one object per log event) + stdout.

    Crash-safe tail (fault-tolerance layer): every line is flushed to
    the OS as it is written, so a SIGKILL mid-epoch loses at most the
    line being formatted — the retry/guard counters that land here are
    precisely the events one needs to post-mortem a killed run. `fsync`
    makes the tail durable across a host crash; the train driver calls
    it at preemption/stall/abort, and `close` always does.

    Line schema (see README "metrics.jsonl line format"): `step`/`time`
    always; training lines add `epoch`/`lr`/`loss`/`acc1`/`acc5`;
    fault counters `nan_steps`/`decode_failures`/`io_retries` appear
    only when nonzero; `compile_cache_misses` appears on every line
    under `--strict-tracing` (dashboards watch it for flatness); event
    lines carry `event` ("nonfinite_loss" | "stall" |
    "recompile_after_warmup") instead of the metric fields."""

    def __init__(self, workdir: str, filename: str = "metrics.jsonl"):
        os.makedirs(workdir, exist_ok=True)
        self.path = os.path.join(workdir, filename)
        self._f = open(self.path, "a", buffering=1)

    def write(self, step: int, payload: dict) -> None:
        rec = {"step": int(step), "time": time.time()}
        rec.update(
            {
                k: (float(v) if hasattr(v, "__float__") else v)
                for k, v in payload.items()
            }
        )
        # NaN/Inf are not valid JSON (json.dumps would emit a literal a
        # strict reader rejects); a non-finite metric becomes null — the
        # guard writes its own explicit event for non-finite losses.
        rec = {
            k: (None if isinstance(v, float) and not math.isfinite(v) else v)
            for k, v in rec.items()
        }
        self._f.write(json.dumps(rec, allow_nan=False) + "\n")
        self._f.flush()

    def fsync(self) -> None:
        """Force the written tail to disk (preemption/abort paths)."""
        if not self._f.closed:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        if not self._f.closed:
            self.fsync()
            self._f.close()


@contextlib.contextmanager
def profiler_trace(logdir: Optional[str]):
    """`jax.profiler` trace (TensorBoard-viewable) around a code region;
    no-op when logdir is None."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
