"""Marker-delimited report sections.

The evidence scripts (`scripts/learning_signal.py`,
`scripts/ablate_shuffle.py`, `scripts/profile_input.py`) each own one
`<!-- name:begin -->…<!-- name:end -->` block of REPORT.md / PROFILE.md
and must be re-runnable without clobbering each other's sections.
"""

from __future__ import annotations

import os
import re


def replace_marker_block(path: str, name: str, section: str) -> None:
    """Insert or replace the `name`-delimited block in `path`, preserving
    everything else (creates the file if missing)."""
    begin, end = f"<!-- {name}:begin -->", f"<!-- {name}:end -->"
    block = f"{begin}\n{section}\n{end}\n"
    text = ""
    if os.path.exists(path):
        with open(path) as f:
            text = f.read()
    if begin in text and end in text:
        pre = text[: text.index(begin)]
        post = text[text.index(end) + len(end) :].lstrip("\n")
        text = pre + block + post
    else:
        text = text.rstrip("\n") + "\n\n" + block if text else block
    with open(path, "w") as f:
        f.write(text)


def extract_marker_blocks(text: str) -> list[str]:
    """All marker-delimited blocks in `text`, in order — used when a
    tool regenerates a report body and must carry the other tools'
    sections across."""
    return [
        m.group(0)
        for m in re.finditer(r"<!-- ([\w-]+):begin -->.*?<!-- \1:end -->", text, re.S)
    ]
