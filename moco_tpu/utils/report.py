"""Marker-delimited report sections.

The evidence scripts (`scripts/learning_signal.py`,
`scripts/ablate_shuffle.py`, `scripts/profile_input.py`) each own one
`<!-- name:begin -->…<!-- name:end -->` block of REPORT.md / PROFILE.md
and must be re-runnable without clobbering each other's sections.
"""

from __future__ import annotations

import os
import re


def replace_marker_block(path: str, name: str, section: str) -> None:
    """Insert or replace the `name`-delimited block in `path`, preserving
    everything else (creates the file if missing)."""
    begin, end = f"<!-- {name}:begin -->", f"<!-- {name}:end -->"
    block = f"{begin}\n{section}\n{end}\n"
    text = ""
    if os.path.exists(path):
        with open(path) as f:
            text = f.read()
    begin_idx = text.find(begin)
    # search for end only AFTER begin: an orphan end marker before begin
    # (truncated write, hand edit) must not drive the splice backwards
    end_idx = text.find(end, begin_idx) if begin_idx != -1 else -1
    if begin_idx != -1 and end_idx == -1:
        raise ValueError(
            f"{path}: unbalanced marker block {name!r} (begin without a "
            f"following end) — fix the file before regenerating the section"
        )
    if begin_idx != -1:
        pre = text[:begin_idx]
        post = text[end_idx + len(end) :].lstrip("\n")
        text = pre + block + post
    else:
        text = text.rstrip("\n") + "\n\n" + block if text else block
    with open(path, "w") as f:
        f.write(text)


def extract_marker_blocks(text: str) -> list[str]:
    """All marker-delimited blocks in `text`, in order — used when a
    tool regenerates a report body and must carry the other tools'
    sections across."""
    return [
        m.group(0)
        for m in re.finditer(r"<!-- ([\w-]+):begin -->.*?<!-- \1:end -->", text, re.S)
    ]
