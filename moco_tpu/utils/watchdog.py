"""Stall watchdog: detect a wedged train step and fail loudly.

The reference's observed failure mode is "NCCL hangs, restart by hand"
(SURVEY.md §5.3); the TPU equivalent is a hung collective or wedged chip
(`utils/platform.py` documents a lease wedge measured at 1h+). A hung
device call blocks the main thread indefinitely — no Python-level
timeout can interrupt it — so the only robust answer is a sidecar
thread: the train loop `beat()`s every iteration, and when beats stop
for longer than `timeout`, the watchdog dumps every thread's stack
(the post-mortem for *where* it hung), runs a bounded `on_stall`
callback (the driver's emergency checkpoint), and hard-exits nonzero so
the supervisor restarts the process into the `--resume` path.

`startup_grace` covers the first step's XLA compilation (minutes for
big programs): until the first beat arrives, the effective timeout is
`max(timeout, startup_grace)`.

`exit_fn` is injectable so unit tests can observe the firing without
killing the test process; production uses `os._exit` — a wedged device
runtime cannot be trusted to run atexit handlers or release locks.
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
from typing import Callable, Optional

# Hosted by utils/contracts.py (single-source exit codes, JX018);
# re-exported here so `from moco_tpu.utils.watchdog import
# STALL_EXIT_CODE` keeps working.
from moco_tpu.utils.contracts import STALL_EXIT_CODE  # noqa: F401


class StepWatchdog:
    def __init__(
        self,
        timeout: float,
        on_stall: Optional[Callable[[], None]] = None,
        dump_path: Optional[str] = None,
        startup_grace: float = 900.0,
        poll: Optional[float] = None,
        exit_code: int = STALL_EXIT_CODE,
        exit_fn: Callable[[int], None] = os._exit,
    ):
        if timeout <= 0:
            raise ValueError("watchdog timeout must be > 0 (use no watchdog to disable)")
        self.timeout = float(timeout)
        self.on_stall = on_stall
        self.dump_path = dump_path
        self.startup_grace = float(startup_grace)
        self.poll = poll if poll is not None else max(0.2, min(5.0, timeout / 4.0))
        self.exit_code = exit_code
        self.exit_fn = exit_fn
        self._last = time.monotonic()
        self._beats = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "StepWatchdog":
        self._last = time.monotonic()  # mocolint: disable=JX012  (lock-free by design: beat() sits on the step hot path; a monotonic float STORE is GIL-atomic and the watchdog thread only READS it, tolerating one poll of staleness)
        self._thread = threading.Thread(
            target=self._run, name="moco-step-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def beat(self) -> None:
        """One step-loop iteration completed; called from the train loop
        (a timestamp assignment — no locks, no device work)."""
        self._last = time.monotonic()
        self._beats += 1  # mocolint: disable=JX012  (single writer — only the train loop beats; the watchdog thread reads it solely to pick the startup-grace limit, where a stale value is harmless)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll)

    # -- internals -------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.poll):
            limit = self.timeout if self._beats else max(self.timeout, self.startup_grace)
            idle = time.monotonic() - self._last
            if idle > limit:
                self._fire(idle)
                return

    def _fire(self, idle: float) -> None:
        print(
            f"WATCHDOG: no step completed for {idle:.1f}s "
            f"(timeout {self.timeout:.1f}s, {self._beats} beats) — dumping stacks",
            file=sys.stderr,
            flush=True,
        )
        if self.dump_path:
            try:
                with open(self.dump_path, "w") as f:
                    faulthandler.dump_traceback(file=f, all_threads=True)
            except OSError:
                pass
        faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        if self.on_stall is not None:
            try:
                self.on_stall()
            except Exception as e:  # the emergency path must not mask the exit
                print(f"WATCHDOG: on_stall raised {e!r}", file=sys.stderr, flush=True)
        self.exit_fn(self.exit_code)
