"""Dataclass config system.

Replaces the reference's argparse blocks duplicated across
`main_moco.py:~L30-100` and `main_lincls.py:~L30-95`. Field names and
defaults mirror the reference flags (`--moco-dim 128 --moco-k 65536
--moco-m 0.999 --moco-t 0.07`, `--lr 0.03`, `--schedule 120 160`, v2
switches `--mlp --aug-plus --cos --moco-t 0.2`). Presets correspond to
BASELINE.json's config list.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class MocoConfig:
    arch: str = "resnet50"
    dim: int = 128  # --moco-dim
    num_negatives: int = 65536  # --moco-k
    momentum: float = 0.999  # --moco-m
    # Cosine-anneal the EMA momentum from `momentum` to 1.0 over training
    # (moco-v3's --moco-m-cos; the EMA-scaling literature's recipe).
    momentum_cos: bool = False
    temperature: float = 0.07  # --moco-t (0.2 for v2 recipe)
    mlp: bool = False  # --mlp (v2)
    # BN decorrelation strategy: 'gather_perm' (reference-exact Shuffle-BN),
    # 'a2a' (balanced all_to_all permutation), 'syncbn' (subgroup cross-replica BN, no shuffle),
    # 'none' (single-device / ablation).
    shuffle: str = "gather_perm"
    syncbn_group_size: int = 0  # 0 = whole data axis, else subgroups of this size
    # Training BN statistics from the first N rows of each device's
    # batch (0 = full batch). Byte-reduction lever for the BN-bound step
    # (PROFILE.md: stats reductions are 55% of step time) that matches
    # the reference's statistics granularity — upstream's per-GPU BN
    # estimates from 32 rows (batch 256 / 8 GPUs, main_moco.py:~L172).
    # Interacts with the shuffle gate: a fixed first-N-rows sample makes
    # the BN-statistics leak STRONGER than whole-batch per-device BN, so
    # build_encoder rejects it with shuffle='none' on a multi-device
    # data axis (fine single-device, where it is a pure perf lever).
    bn_stats_rows: int = 0
    # With bn_stats_rows: fusion barrier around the subset slice
    # (BatchNorm.stats_barrier) — numerically identical; candidate
    # workaround for the r50/224 TPU compile pathology (PROFILE.md r4,
    # scripts/bn_compile_repro.py).
    bn_stats_barrier: bool = False
    # Virtual Shuffle-BN on few devices: per-group BN statistics over G
    # contiguous row-groups of each device's batch (the reference's
    # per-GPU BN semantics inside one chip), and the key batch is
    # permuted in-batch even on a single device so group composition
    # decorrelates — a G-GPU recipe on one TPU. 0 = off.
    bn_virtual_groups: int = 0
    # EXPLICIT opt-in for leak-demonstration configs: lets shuffle='none'
    # compose with bn_virtual_groups / bn_stats_rows, which build_encoder
    # otherwise rejects loudly (per-group statistics with UNPERMUTED keys
    # are the exact intra-batch leak Shuffle-BN exists to prevent,
    # `moco/builder.py:~L79-126`). Exists so the BN-cheat positive
    # control (scripts/ablate_shuffle.py arm 'none' with virtual groups
    # on one chip) can reproduce the phenomenon deliberately; never set
    # it in a training recipe.
    allow_leaky_bn: bool = False
    # Momentum-statistics BN ("Momentum² Teacher", arXiv:2101.07525):
    # every training BN normalizes with — and stores — the
    # momentum-updated running statistics m*ra + (1-m)*batch instead of
    # the raw batch statistics, decoupling normalization precision from
    # the per-batch sample. The huge-batch alternative to cross-replica
    # BN statistics (statistics quality comes from history, so nothing
    # needs syncing as the batch grows). ResNet only; mutually
    # exclusive with bn_stats_rows / bn_virtual_groups.
    bn_momentum_stats: bool = False
    # Key-encoder BatchNorm from RUNNING statistics (the EMAN recipe,
    # arXiv:2101.08482, re-derived TPU-first): the key forward runs
    # eval-mode BN against batch_stats_k, which is EMA-updated each
    # step toward the query encoder's RUNNING statistics — the
    # BN-momentum-smoothed buffers, exactly as EMAN tracks buffers,
    # NOT the step's raw batch mean/var — on the params' momentum
    # schedule. Three effects on the HBM-bound step
    # (PROFILE.md: BN statistics reads are 55% of step time, one third
    # of that on the key forward): the key-side statistics pass
    # disappears entirely; the BN-composition leak Shuffle-BN exists to
    # prevent disappears BY CONSTRUCTION (no batch statistics on keys),
    # so the shuffle collectives go too; and multi-chip key forwards
    # need zero communication. Changes training semantics vs the
    # reference recipe — and the measured accuracy arm (REPORT.md
    # "EMAN key forward": 35.6 ± 4.5 vs 53.7 ± 0.6 kNN at the CI
    # budget, likely a stats-EMA warmup artifact at 160 steps but
    # unproven beyond it) keeps this EXPERIMENTAL and default-off.
    # Requires shuffle='none' (or 'syncbn' for the query side); the
    # v2-step lever only (the v3 step has its own momentum encoder).
    key_bn_running_stats: bool = False
    # Fast-tracking warmup for the key-stats EMA (EMAN lever only):
    # stats momentum min(m_params(step), (1+step)/(10+step)) — the
    # classic num_updates moving-average schedule. Addresses the r4
    # accuracy-arm mechanism (at m=0.99 over 160 steps the key BN
    # normalized with ~60-step-stale statistics); at ImageNet scale the
    # schedule converges to the params momentum within one epoch.
    key_bn_stats_warmup: bool = True
    cifar_stem: bool = False
    compute_dtype: str = "bfloat16"
    # MoCo v3 (queue-free symmetric contrastive): set num_negatives=0,
    # v3=True adds the prediction head.
    v3: bool = False
    # v3 stability trick (arXiv:2104.02057 §5): keep the ViT patch-embed
    # projection frozen at its random init.
    freeze_patch_embed: bool = True
    # Override the ViT patch size (None = the arch's default, 16);
    # small-image tests/smoke configs use 4.
    vit_patch_size: Optional[int] = None
    # ViT attention via the Pallas flash kernel (moco_tpu/ops); the
    # parameter tree is identical to the dense path, so checkpoints are
    # interchangeable. Pays off at long sequences (high-res/video).
    vit_flash_attention: bool = False
    # ViT feature pooling: "cls" (v3 default) or "gap" (global average
    # pool — required by sequence parallelism).
    vit_pool: str = "cls"
    # Sequence parallelism for the ViT: shard the token axis over the
    # mesh's MODEL axis and run ring attention across it (long-sequence
    # regime: high-res images / video token counts). Requires v3, gap
    # pooling, and tokens divisible by num_model.
    vit_sequence_parallel: bool = False
    # Streaming pallas InfoNCE (no (B, 1+K) logits materialization):
    # None = auto (on for TPU + replicated tile-divisible queue).
    fused_infonce: Optional[bool] = None
    # Queue tile size streamed through VMEM per grid step; 0 = the
    # kernel's DEFAULT_BLOCK_K. Small values let tests drive the real
    # kernel (not the dense fallback) at toy K.
    fused_block_k: int = 0
    # Rematerialize the query-encoder forward in the backward pass
    # (jax.checkpoint): trades ~30% more FLOPs for O(depth) less
    # activation HBM — for big models / big per-chip batches.
    remat: bool = False


@dataclasses.dataclass(frozen=True)
class OptimConfig:
    optimizer: str = "sgd"  # sgd | lars | adamw
    lr: float = 0.03
    momentum: float = 0.9
    weight_decay: float = 1e-4
    cos: bool = False  # cosine schedule (--cos)
    schedule: Tuple[int, ...] = (120, 160)  # step-decay epochs (--schedule)
    warmup_epochs: int = 0
    epochs: int = 200
    # LARS extras for the pod-scale large-batch config
    trust_coefficient: float = 0.001


@dataclasses.dataclass(frozen=True)
class DataConfig:
    dataset: str = "synthetic"  # synthetic | cifar10 | imagefolder
    data_dir: Optional[str] = None
    image_size: int = 224
    global_batch: int = 256
    aug_plus: bool = False  # v2 aug recipe (jitter+blur), main_moco.py:~L225-255
    # Geometric-only two-crop recipe (RRC + flip + normalize): the
    # BN-leak positive control's setting — overrides aug_plus.
    crops_only: bool = False
    num_workers: int = 4
    on_device_augment: bool = True
    # Sample RandomResizedCrop boxes on the HOST against the ORIGINAL
    # image geometry and decode-once/crop-N in the loader (torchvision-
    # exact crop distribution + 224² instead of 256² over PCIe). Applies
    # to datasets exposing the host-crop protocol (imagefolder); others
    # keep the on-device crop from the decode canvas.
    host_rrc: bool = True
    # Decode-once packed RGB cache (moco_tpu/data/cache.py): build on
    # first use under this dir, then epochs read raw full-geometry
    # pixels from an mmap instead of re-decoding JPEGs — the answer to
    # few-core TPU hosts where codec work bounds the input pipeline.
    cache_dir: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    num_data: Optional[int] = None  # None = all devices
    num_model: int = 1  # shards the queue/logits for very large K
    # Sharded weight update (ZeRO over the data axis, arXiv:2004.13336
    # — moco_tpu/parallel/zero.py): optimizer state and update sharded
    # 1/n per replica via psum_scatter + all_gather. Element-wise
    # optimizers only (sgd/adamw).
    shard_weight_update: bool = False
    # ZeRO stage (meaningful with shard_weight_update): 1 = sharded
    # optimizer state only, params re-gathered inside every step (the
    # original). 2/3 (both spellings select the same implementation) =
    # params_q/params_k/predictor ALSO persist between steps as
    # P(data)-sharded flat shards: ~3/n at-rest model memory, the EMA
    # key update runs shard-local (no collective), and the per-bucket
    # params all_gather for step k+1 is hoisted under step k's compute
    # by the pipelined driver (parallel/zero.py module docstring).
    zero_stage: int = 1
    # Fusion-bucket size for the stage-2/3 bucketed collectives: leaves
    # pack into ~this many MB of SHARD payload per all_gather /
    # psum_scatter launch (one collective per bucket, not per leaf).
    zero_bucket_mb: float = 4.0
    # Hoist the stage-2/3 params gather onto the AsyncParamGather worker
    # so it overlaps the previous step (default); False runs gather +
    # step inline (A/B lever; the overlap/zero gauge is then absent).
    zero_overlap_gather: bool = True
    # Layer-granular stage 2/3 (true ZeRO-3): the step gathers each
    # layer group's full params just-in-time (per-group fusion buckets,
    # `comms/zero.gather.<group>` sites) and the rematerialized group
    # segments free them after their forward/backward contribution, so
    # transient model memory drops from full-tree to ~two adjacent
    # groups — the per-chip-batch capacity unlock. Bit-identical loss
    # trajectory to the whole-tree stages (tests assert it). Requires
    # zero_stage >= 2, num_model == 1, and an elementwise optimizer;
    # checkpoint layout is unchanged (the same (n, m) shards), so
    # resume round-trips freely across zero1/zero23/layer-granular.
    zero_layer_granular: bool = False


@dataclasses.dataclass(frozen=True)
class ProbeConfig:
    """Linear-probe hyperparameters (`main_lincls.py:~L30-95, ~L200-210`):
    SGD(lr=30.0, momentum=0.9, wd=0), step schedule [60, 80], 100 epochs,
    frozen backbone with BN in eval mode."""

    lr: float = 30.0
    momentum: float = 0.9
    weight_decay: float = 0.0
    schedule: Tuple[int, ...] = (60, 80)
    epochs: int = 100
    num_classes: int = 1000


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    moco: MocoConfig = dataclasses.field(default_factory=MocoConfig)
    optim: OptimConfig = dataclasses.field(default_factory=OptimConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    seed: int = 0
    workdir: str = "/tmp/moco_tpu"
    log_every: int = 10  # --print-freq
    checkpoint_every_epochs: int = 1
    # Retention: keep the last N checkpoints; 0 keeps EVERY one (the
    # reference's behavior — per-epoch checkpoint_{epoch:04d}.pth.tar,
    # main_moco.py:~L275-280).
    checkpoint_keep: int = 3
    # Overlap checkpoint serialization with training (Orbax async): the
    # save returns after the host snapshot; the write happens on a
    # background thread. The preemption path always waits for durability.
    checkpoint_async: bool = False
    steps_per_epoch: Optional[int] = None  # None = derive from dataset size
    # Periodic weighted-kNN monitor on frozen backbone features (the
    # cheap probe proxy the reference lacks — moco_tpu/knn.py): run every
    # N epochs; 0 disables. Requires a labeled dataset (train=False split
    # buildable from config.data, or knn_datasets passed to train()).
    knn_every_epochs: int = 0
    knn_k: int = 200
    knn_temperature: float = 0.07
    # Non-finite-loss guard (fault-tolerance layer): checked on log steps
    # only (piggybacks on the existing `i % log_every` device fetch — no
    # extra host sync in the step loop). A NaN/Inf loss skips that step's
    # update (params/opt/queue roll back to the last finite log step's
    # state; the step counter keeps advancing so checkpoint ids stay
    # monotonic) and is counted + written to metrics.jsonl; after
    # `nan_guard_threshold` such events the run aborts with diagnostics
    # instead of burning the fleet on a diverged model.
    nan_guard_threshold: int = 10
    # Stall watchdog: seconds without a completed step-loop iteration
    # before the process dumps all-thread stacks, attempts an emergency
    # checkpoint, and exits nonzero (a hung collective blocks the main
    # thread in a device call forever — only a sidecar thread can see
    # it). 0 disables. Must exceed the worst-case log interval; the
    # first step additionally gets a compilation grace period.
    watchdog_timeout: float = 0.0
    # Strict tracing mode (mocolint runtime arm, --strict-tracing):
    # enables jax.check_tracer_leaks, surfaces a `compile_cache_misses`
    # counter on every metrics.jsonl log line, and aborts when the step
    # function recompiles after `recompile_warmup_steps` (each silent
    # recompile of the r50/224 step costs minutes — PROFILE.md). Checked
    # on log steps only, so the step loop stays sync-free.
    strict_tracing: bool = False
    # Steps during which compiles are free (first trace + donation
    # variants); a compile-cache miss after this aborts under
    # --strict-tracing.
    recompile_warmup_steps: int = 8
    # Runtime collective-schedule sanitizer (mocolint runtime arm,
    # analysis/sanitizer.py, --sanitize-collectives): every comms-tagged
    # collective site records its (site, kind, operand-shape) into a
    # per-process schedule; on log steps the schedule hash is published
    # out-of-band (schedule.p<i>.json, heartbeat-style) and cross-checked
    # against every peer. A mismatch aborts with a per-site diff BEFORE
    # the pod deadlocks in the mismatched collective. Off the hot path
    # (recording happens at trace time; the check piggybacks on the log
    # step's host sync).
    sanitize_collectives: bool = False
    # Runtime lock-order sanitizer (mocolint v3 runtime arm,
    # analysis/tsan.py, --sanitize-threads): every tsan-factory lock
    # (serve.index, serve.metrics, obs.*, data.*) reports its
    # acquisition order to a per-process recorder; an order cycle —
    # two code paths nesting the same locks opposite ways — aborts
    # with both acquisition stacks (lock_order_diff.json) BEFORE the
    # deadlock wedges the process, and blocking ops issued under a
    # held lock are recorded for the run report (lock_order.json).
    # Smoke-run tooling: the profile hook costs real CPU.
    sanitize_threads: bool = False
    # -- telemetry (moco_tpu/obs) ---------------------------------------
    # Metric sinks, comma list from the obs sink registry ("jsonl",
    # "csv", "tensorboard"); the JSONL sink is always included — the
    # fault counters, chaos harness, and obs_report key on it.
    sinks: str = "jsonl"
    # Serve Prometheus text format on http://<metrics_host>:<port +
    # process_index>/metrics (in-process daemon thread; scraping long
    # runs). 0 = off. The per-process port shift keeps co-hosted
    # processes from colliding on one bind.
    metrics_port: int = 0
    # Bind address for the Prometheus endpoint; "0.0.0.0" exposes it to
    # off-box scrapers (the old hardcoded loopback made pod-wide
    # scraping impossible).
    metrics_host: str = "127.0.0.1"
    # MoCo health gauges computed INSIDE the jitted step (EMA drift,
    # InfoNCE logit stats, collapse detection, queue staleness —
    # obs/health.py) and returned through the metrics dict. Cheap
    # reductions (one extra pass over params for the drift norm), but a
    # lever exists for steps where every byte counts.
    health_metrics: bool = True
    # Step-time breakdown probe: every N steps, block_until_ready the
    # step's outputs to split host dispatch from device compute
    # (t_dispatch/t_device on the next log line). Off the hot path
    # otherwise; 0 disables sampling (t_data/t_step still logged from
    # host timers, which cost nothing).
    obs_probe_every: int = 50
    # -- fleet observability (obs/fleet.py, obs/alerts.py) --------------
    # Cross-host aggregation: on log steps every process contributes a
    # fixed-width stats vector (t_data/t_step/dispatch lag/io retries/
    # decode failures/live HBM) to a jitted all_gather; process 0's
    # metrics lines then carry fleet min/mean/max/argmax per field and
    # the straggler_skew gauge, and every process writes an out-of-band
    # heartbeat file (merged by obs_report when a host dies mid-run).
    fleet_metrics: bool = True
    # Declarative alert rules evaluated in-stream against every logged
    # payload (obs/alerts.py grammar): "default" = the built-in set
    # (step-time spike, data starvation, straggler skew, EMA runaway,
    # queue staleness, non-finite loss, stall, heartbeat loss);
    # "default,<spec>" extends it; "none" disables. Fired alerts land in
    # workdir/alerts.jsonl + an `event: "alert"` metrics line (which the
    # Prometheus sink exposes as a per-rule gauge).
    alert_rules: str = "default"
    # Abort on any fired alert, after an emergency checkpoint (reuses
    # the fault-tolerance layer's save-first-die-second path).
    alerts_fatal: bool = False
    # -- input wire (data/device_prefetch.py) ---------------------------
    # Device prefetch ring: a dedicated transfer thread stages the next
    # `prefetch_depth` batches on device (sharded uint8 device_put)
    # while the current step runs, so decode, the wire, and compute
    # overlap instead of taking turns (the reference hides this cost
    # behind 32 DataLoader workers + pinned-memory async H2D). Off =
    # the synchronous in-line path (one producer thread does decode →
    # transfer → dispatch serially).
    device_prefetch: bool = True
    prefetch_depth: int = 2
    # Donate the consumed staging slot's uint8 buffer to the augment
    # step so XLA reuses its HBM for the normalized output instead of
    # allocating a fresh batch-sized buffer. Ignored (harmless) on
    # backends without donation support (CPU).
    prefetch_donate: bool = False
    # -- elastic training (parallel/elastic.py) -------------------------
    # Heartbeat-triggered checkpoint-and-rescale: on heartbeat loss the
    # survivors agree on the event (rescale-consensus barrier), take an
    # emergency checkpoint, rebuild a smaller mesh over the surviving
    # devices, reshard params/optimizer/queue onto it (reshard_state),
    # re-derive momentum/LR from the shrunk global batch via the
    # auto-scale rule, and resume in-process — no restart from scratch.
    # Requires num_model == 1.
    elastic: bool = False
    # Heartbeat-staleness threshold in seconds: a host whose out-of-band
    # heartbeat file is older than this is declared lost — by the alert
    # engine's default heartbeat_loss rule AND (with elastic=True) the
    # rescale trigger. Replaces the previously hard-coded 120 s in the
    # alert default spec.
    heartbeat_timeout: float = 120.0
    # Principled batch scaling ("How to Scale Your EMA", arXiv:2307.13813;
    # Momentum² Teacher, arXiv:2101.07525), spec "ref_batch=N": treat
    # optim.lr and moco.momentum as REFERENCE values at global batch N
    # and derive the live values from the actual global batch with
    # κ = global_batch / N — LR linearly (lr·κ), the EMA momentum as
    # m^κ. Warmup needs no re-derivation: warmup_epochs is
    # epoch-denominated, and steps-per-epoch already shifts with the
    # batch. "" disables; elastic runs default it to the original batch
    # so a rescale re-derives against the pre-loss anchor.
    auto_scale: str = ""


def config_to_dict(cfg: TrainConfig) -> dict:
    """JSON-serializable dict (tuples become lists) — stored in every
    checkpoint so downstream tools (linear probe, converters) can rebuild
    the exact model/optimizer without the user re-specifying flags."""
    return dataclasses.asdict(cfg)


def dataclass_from_dict(cls, sub: dict):
    """Rebuild a config dataclass from checkpointed JSON: unknown keys
    are dropped (forward/backward compatibility across field changes)
    and lists become tuples."""
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in sub:
            continue
        v = sub[f.name]
        if isinstance(v, list):
            v = tuple(v)
        kwargs[f.name] = v
    return cls(**kwargs)


def config_from_dict(d: dict) -> TrainConfig:
    build = dataclass_from_dict

    return TrainConfig(
        moco=build(MocoConfig, d.get("moco", {})),
        optim=build(OptimConfig, d.get("optim", {})),
        data=build(DataConfig, d.get("data", {})),
        parallel=build(ParallelConfig, d.get("parallel", {})),
        **{
            k: d[k]
            for k in (
                "seed", "workdir", "log_every", "checkpoint_every_epochs",
                "checkpoint_async", "checkpoint_keep", "steps_per_epoch",
                "nan_guard_threshold", "watchdog_timeout",
                "strict_tracing", "recompile_warmup_steps", "sanitize_collectives",
                "sanitize_threads",
                "sinks", "metrics_port", "metrics_host", "health_metrics",
                "obs_probe_every", "fleet_metrics", "alert_rules", "alerts_fatal",
                "device_prefetch", "prefetch_depth", "prefetch_donate",
                "elastic", "heartbeat_timeout", "auto_scale",
            )
            if k in d
        },
    )


def parse_auto_scale(spec: str) -> Optional[int]:
    """Parse the `--auto-scale` spec ("ref_batch=N"); None when unset.
    Same colon-separated key=val shape as the fault/alert grammars so a
    future key (e.g. a BN-statistics-momentum rule) extends in place."""
    if not spec:
        return None
    ref_batch: Optional[int] = None
    for tok in spec.split(":"):
        tok = tok.strip()
        if not tok:
            continue
        k, _, v = tok.partition("=")
        if k == "ref_batch":
            ref_batch = int(v)
        else:
            raise ValueError(f"unknown auto-scale param {k!r} in {spec!r}")
    if ref_batch is None or ref_batch <= 0:
        raise ValueError(f"auto-scale spec {spec!r} needs ref_batch=<positive int>")
    return ref_batch


def apply_auto_scale(config: TrainConfig) -> Tuple[TrainConfig, Optional[dict]]:
    """Derive the LIVE hyperparameters from the reference ones under the
    batch-scaling rules: κ = global_batch / ref_batch, lr' = lr·κ
    (linear), EMA momentum m' = m^κ (the EMA scaling rule — shrinking
    the batch by κ<1 must SLOW the key encoder's drift per step or it
    decouples from the query encoder; arXiv:2307.13813 §3). Identity
    (config, None) when no auto_scale spec is set.

    Always derives from the values IN `config` — callers that rescale
    repeatedly (the elastic loop) must pass the reference config each
    time, never an already-derived one."""
    ref_batch = parse_auto_scale(config.auto_scale)
    if ref_batch is None:
        return config, None
    kappa = config.data.global_batch / ref_batch
    lr = config.optim.lr * kappa
    momentum = config.moco.momentum**kappa
    derived = dataclasses.replace(
        config,
        optim=dataclasses.replace(config.optim, lr=lr),
        moco=dataclasses.replace(config.moco, momentum=momentum),
    )
    info = {
        "ref_batch": ref_batch,
        "kappa": kappa,
        "lr": lr,
        "momentum": momentum,
        "ref_lr": config.optim.lr,
        "ref_momentum": config.moco.momentum,
    }
    return derived, info


class ResumeCompatError(ValueError):
    """The checkpoint being resumed was trained under a structurally
    different config — restoring it into the live model would either
    fail with an opaque shape error or, worse, silently succeed into the
    wrong semantics. Carries a human-readable field-by-field diff."""


# Structural fields a resume must agree on: they determine parameter /
# optimizer-state / queue SHAPES (a mismatch makes the restore template
# wrong). Tunables (lr, epochs, temperature, aug recipe, ...) may change
# across a resume on purpose and are deliberately not listed.
RESUME_COMPAT_FIELDS = {
    "moco": (
        "arch", "dim", "num_negatives", "mlp", "v3", "cifar_stem",
        "vit_pool", "vit_patch_size", "vit_sequence_parallel",
    ),
    "data": ("image_size",),
    # NOTE: parallel.shard_weight_update / zero_stage / num_data are
    # deliberately NOT hard-compat fields anymore: a layout mismatch is
    # "compatible but resharded" — the driver restores into a template
    # of the checkpoint's own layout and converts host-side
    # (core/moco.py:reshard_state), so zero1 -> zero23, sharded ->
    # replicated, and mesh-width changes all resume.
    "parallel": ("num_model",),
}


def resume_compat_diff(saved_extra: dict, config: TrainConfig, num_data: int) -> list[str]:
    """Field-by-field incompatibility diff between a checkpoint's saved
    `extra` (as written by the train driver: `config` + `num_data`) and
    the live run. Empty list = compatible. Unknown/missing saved keys are
    skipped (older checkpoints stay resumable)."""
    diffs = []
    saved_cfg = saved_extra.get("config") or {}
    live = config_to_dict(config)
    for section, fields in RESUME_COMPAT_FIELDS.items():
        saved_sec = saved_cfg.get(section) or {}
        for f in fields:
            if f not in saved_sec:
                continue
            sv, lv = saved_sec[f], live[section][f]
            if isinstance(lv, tuple):
                lv = list(lv)
            if sv != lv:
                diffs.append(f"{section}.{f}: checkpoint={sv!r} != config={lv!r}")
    # num_data under ZeRO used to be a hard incompatibility (the mesh
    # width is baked into the (n, m) shard shapes); since reshard_state
    # it is a resharding case, handled by the driver's layout-aware
    # restore — no diff entry.
    return diffs


def _v2(moco: MocoConfig, **kw) -> MocoConfig:
    return dataclasses.replace(moco, mlp=True, temperature=0.2, **kw)


PRESETS = {
    # BASELINE.json configs[0]: single-process CPU/1-chip smoke
    "cifar_smoke": TrainConfig(
        moco=MocoConfig(arch="resnet18", num_negatives=4096, cifar_stem=True, shuffle="none"),
        optim=OptimConfig(lr=0.03, epochs=10, cos=True),
        data=DataConfig(dataset="cifar10", image_size=32, global_batch=256),
    ),
    # configs[1]: ImageNet-100 v2
    "imagenet100_v2": TrainConfig(
        moco=_v2(MocoConfig()),
        optim=OptimConfig(lr=0.03, epochs=200, cos=True),
        data=DataConfig(dataset="imagefolder", aug_plus=True),
    ),
    # configs[2]: ImageNet-1k v2 200ep, 8-chip DP
    "imagenet_v2": TrainConfig(
        moco=_v2(MocoConfig()),
        optim=OptimConfig(lr=0.03, epochs=200, cos=True),
        data=DataConfig(dataset="imagefolder", aug_plus=True),
    ),
    # configs[3]: pod-scale large-batch + LARS (v4-128-class). Raised to
    # 8192 once layer-granular ZeRO-3 freed the per-chip headroom; the
    # hyperparameters stay declared at the 4096 reference and the
    # scaling-law rules derive the live ones (κ=2: lr×2, momentum^2 —
    # the README "scaling up batch size correctly" runbook), with
    # momentum-statistics BN standing in for cross-replica statistics.
    # NB: LARS needs whole-tensor trust ratios, so THIS preset cannot
    # also turn on the sharded weight update — the ZeRO-3 huge-batch
    # recipe is the vit preset below.
    "imagenet_v2_large_batch": TrainConfig(
        moco=_v2(MocoConfig(), bn_momentum_stats=True),
        optim=OptimConfig(
            optimizer="lars", lr=4.8, weight_decay=1e-6, epochs=200, cos=True, warmup_epochs=10
        ),
        data=DataConfig(dataset="imagefolder", aug_plus=True, global_batch=8192),
        auto_scale="ref_batch=4096",
    ),
    # NOTE (r5): the former `imagenet_v2_eman` preset was DEMOTED to a
    # documented experiment. The EMAN-style key forward
    # (--key-bn-eval / key_bn_running_stats, arXiv:2101.08482 pattern —
    # no key-side BN statistics pass, no Shuffle-BN collectives,
    # zero-comm multi-chip key forwards) remains fully supported as
    # flags, but its measured accuracy arms argue against recommending
    # it as a recipe: the CI-budget deficit (35.6 vs 53.7 kNN) was only
    # HALF-closed by the stats-EMA warmup fix (44.1), and at 4× budget
    # the deficit persists and mildly widens (46.5 vs 59.8 —
    # REPORT.md "EMAN key forward"). Reproduce with:
    #   train.py --preset imagenet_v2 --shuffle none --key-bn-eval
    # BASELINE.json configs[4]: MoCo v3 ViT-B/16, queue-free symmetric
    # loss, AdamW + warmup (arXiv:2104.02057 recipe: lr=1.5e-4·batch/256,
    # wd=0.1, 40-epoch warmup, batch 4096).
    "vit_b16_v3": TrainConfig(
        moco=MocoConfig(
            arch="vit_b16", dim=256, num_negatives=0, momentum=0.99,
            momentum_cos=True, temperature=0.2, v3=True, shuffle="none",
        ),
        optim=OptimConfig(
            optimizer="adamw", lr=2.4e-3, weight_decay=0.1, epochs=300,
            cos=True, warmup_epochs=40,
        ),
        data=DataConfig(dataset="imagefolder", aug_plus=True, global_batch=4096),
    ),
    # Huge-batch v3 on the layer-granular ZeRO-3 memory budget: the
    # vit_b16_v3 recipe declared at its 4096 reference batch, run at
    # 8192 with the scaling-law rules deriving lr/momentum (κ=2) and
    # params + optimizer state persistently sharded, gathered one layer
    # group at a time (transient model memory ≈ two encoder blocks
    # instead of the full tree — the headroom the doubled batch spends).
    # AdamW is elementwise, so the sharded update is eligible (unlike
    # the LARS preset above).
    "vit_b16_v3_huge_batch_zero3": TrainConfig(
        moco=MocoConfig(
            arch="vit_b16", dim=256, num_negatives=0, momentum=0.99,
            momentum_cos=True, temperature=0.2, v3=True, shuffle="none",
        ),
        optim=OptimConfig(
            optimizer="adamw", lr=2.4e-3, weight_decay=0.1, epochs=300,
            cos=True, warmup_epochs=40,
        ),
        data=DataConfig(dataset="imagefolder", aug_plus=True, global_batch=8192),
        parallel=ParallelConfig(
            shard_weight_update=True, zero_stage=3, zero_layer_granular=True
        ),
        auto_scale="ref_batch=4096",
    ),
    # Long-sequence showcase (beyond the reference): 448px inputs give a
    # 784-token ViT-B/16; tokens shard over an 8-way model axis with ring
    # attention (gap pooling, --num-model 8). Sequence parallelism keeps
    # per-chip attention memory at 1/8 of the full sequence.
    "vit_b16_v3_highres_sp": TrainConfig(
        moco=MocoConfig(
            arch="vit_b16", dim=256, num_negatives=0, momentum=0.99,
            momentum_cos=True, temperature=0.2, v3=True, shuffle="none",
            vit_pool="gap", vit_sequence_parallel=True,
        ),
        # lr follows the v3 rule 1.5e-4 * batch/256 at THIS preset's
        # batch of 1024 (not the 4096 of vit_b16_v3 above)
        optim=OptimConfig(
            optimizer="adamw", lr=6e-4, weight_decay=0.1, epochs=300,
            cos=True, warmup_epochs=40,
        ),
        data=DataConfig(
            dataset="imagefolder", aug_plus=True, global_batch=1024, image_size=448
        ),
        parallel=ParallelConfig(num_model=8),
    ),
}
