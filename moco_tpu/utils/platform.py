"""Platform pinning for CLI entry points.

With a remote-TPU PJRT plugin registered at interpreter start (this
environment's sitecustomize), setting ``JAX_PLATFORMS=cpu`` in the
environment alone is not always honored — backend probing can still
contact the remote terminal and hang if it is unreachable. The fix is a
CONFIG-level pin before any backend use (what `tests/conftest.py` and
`__graft_entry__.dryrun_multichip` already do); every CLI calls
:func:`pin_platform_from_env` first so `JAX_PLATFORMS=cpu python
train.py ...` behaves as a user expects.
"""

from __future__ import annotations

import os


def pin_platform_from_env() -> None:
    """Mirror a ``JAX_PLATFORMS`` env request into jax's config, before
    any operation initializes a backend. No-op when the env var is unset
    (the environment's default platform, e.g. the TPU tunnel, is used).
    """
    want = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if want:
        import jax

        jax.config.update("jax_platforms", want)
