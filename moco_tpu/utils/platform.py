"""Platform pinning for CLI entry points.

With a remote-TPU PJRT plugin registered at interpreter start (this
environment's sitecustomize), setting ``JAX_PLATFORMS=cpu`` in the
environment alone is not always honored — backend probing can still
contact the remote terminal and hang if it is unreachable. The fix is a
CONFIG-level pin before any backend use (what `tests/conftest.py` and
`__graft_entry__.dryrun_multichip` already do); every CLI calls
:func:`pin_platform_from_env` first so `JAX_PLATFORMS=cpu python
train.py ...` behaves as a user expects.
"""

from __future__ import annotations

import os


def pin_platform_from_env() -> None:
    """Mirror a ``JAX_PLATFORMS`` env request into jax's config, before
    any operation initializes a backend. No-op when the env var is unset
    (the environment's default platform, e.g. the TPU tunnel, is used).
    """
    want = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if want:
        import jax

        jax.config.update("jax_platforms", want)


def backend_usable(timeout: int = 180) -> bool:
    """Probe the default accelerator backend in a SUBPROCESS with a
    timeout; True when `jax.devices()` succeeds there.

    The remote-TPU tunnel fails two ways: a fast UNAVAILABLE error, or
    an indefinite HANG in backend init (busy chip / wedged lease) that
    no in-process try/except can bound. Callers use a False return to
    pin the CPU platform instead of crashing or hanging. The timed-out
    probe is ABANDONED, never killed — SIGKILLing a TPU client mid-init
    wedges the chip's lease (measured 1h+; see PROFILE.md provenance).

    A CPU-pinned environment short-circuits to True (the caller's
    `pin_platform_from_env` makes CPU init safe and instant).
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return True
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        return proc.wait(timeout=timeout) == 0
    except subprocess.TimeoutExpired:
        return False
