"""Platform pinning for CLI entry points.

With a remote-TPU PJRT plugin registered at interpreter start (this
environment's sitecustomize), setting ``JAX_PLATFORMS=cpu`` in the
environment alone is not always honored — backend probing can still
contact the remote terminal and hang if it is unreachable. The fix is a
CONFIG-level pin before any backend use (what `tests/conftest.py` and
`__graft_entry__.dryrun_multichip` already do); every CLI calls
:func:`pin_platform_from_env` first so `JAX_PLATFORMS=cpu python
train.py ...` behaves as a user expects.
"""

from __future__ import annotations

import os


def pin_platform_from_env() -> None:
    """Mirror a ``JAX_PLATFORMS`` env request into jax's config, before
    any operation initializes a backend. No-op when the env var is unset
    (the environment's default platform, e.g. the TPU tunnel, is used).
    """
    want = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if want:
        import jax

        jax.config.update("jax_platforms", want)


def enable_persistent_compilation_cache(path: str | None = None) -> None:
    """Persistent XLA compilation cache shared by every process on this
    host: a program compiled once (the ~3.5-min r50/224 TPU step; the
    pathologically slow bn_stats_rows variant, PROFILE.md) is a disk hit
    for every later bench leg / chain / driver run instead of a repeat
    compile. Opt-out with MOCO_NO_COMPILE_CACHE=1; failures degrade to
    the uncached behavior silently (older jax may lack the knobs).
    """
    if os.environ.get("MOCO_NO_COMPILE_CACHE") == "1":
        return
    path = path or os.environ.get("MOCO_COMPILE_CACHE_DIR", "/tmp/moco_jax_cache")
    try:
        import jax

        if (
            jax.default_backend() == "cpu"
            and not os.environ.get("MOCO_COMPILE_CACHE_DIR")
        ):
            # CPU runs (the test suite, ablation chains, accelerator-less
            # hosts): compile time is not the bottleneck there, and
            # XLA:CPU's AOT cache loader warns (and threatens SIGILL) on
            # machine-feature mismatches between writer and reader
            # processes on this host. Keyed on the RESOLVED backend —
            # jax.default_backend() initializes it, which every caller
            # was about to do anyway; callers that must not touch a
            # possibly-wedged tunnel (bench.py) gate this call behind
            # their own backend_usable() probe. An explicit
            # MOCO_COMPILE_CACHE_DIR overrides.
            return
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything non-trivial; the default 1s floor would skip
        # nothing we care about, but be explicit for clarity
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def backend_probe(timeout: int = 180) -> tuple[bool, str | None]:
    """(usable, reason-if-not) for the default accelerator backend —
    the reasoned form of :func:`backend_usable`, so callers (bench.py)
    can RECORD why an accelerator leg was skipped instead of silently
    degrading (BENCH r02–r05 all fell back to the CPU smoke with no
    trace of why; the perf trajectory went blind)."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return True, None
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )
    try:
        rc = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        # abandoned, never killed — see backend_usable's docstring
        return False, (
            f"backend probe hung > {timeout}s (jax.devices() never returned; "
            "busy chip or wedged tunnel lease)"
        )
    if rc == 0:
        return True, None
    err = b""
    try:
        if proc.stderr is not None:
            err = proc.stderr.read() or b""
    except Exception:
        pass
    tail = err.decode("utf-8", "replace").strip().splitlines()
    detail = tail[-1][:200] if tail else "no stderr"
    return False, f"backend probe failed (exit {rc}): {detail}"


def backend_usable(timeout: int = 180) -> bool:
    """Probe the default accelerator backend in a SUBPROCESS with a
    timeout; True when `jax.devices()` succeeds there.

    The remote-TPU tunnel fails two ways: a fast UNAVAILABLE error, or
    an indefinite HANG in backend init (busy chip / wedged lease) that
    no in-process try/except can bound. Callers use a False return to
    pin the CPU platform instead of crashing or hanging. The timed-out
    probe is ABANDONED, never killed — SIGKILLing a TPU client mid-init
    wedges the chip's lease (measured 1h+; see PROFILE.md provenance).

    A CPU-pinned environment short-circuits to True (the caller's
    `pin_platform_from_env` makes CPU init safe and instant).
    """
    return backend_probe(timeout)[0]
