"""LR schedules and optimizer builders.

Reference semantics:
- `adjust_learning_rate` (`main_moco.py:~L362-375`): per-EPOCH granularity;
  cosine `lr *= 0.5*(1+cos(pi*epoch/epochs))` when `--cos`, else step decay
  `lr *= 0.1` at each milestone in `--schedule` (default 120,160).
- Pretrain optimizer (`main_moco.py:~L188`): SGD(lr=0.03, momentum=0.9,
  weight_decay=1e-4) — torch applies wd additively to the grad before the
  momentum buffer, reproduced here with `add_decayed_weights` *before*
  `sgd`.
- Linear probe (`main_lincls.py:~L200-210`): SGD(lr=30.0, wd=0).
- LARS/AdamW have no reference recipe (its max batch is 256); they serve
  the pod-scale and v3 presets, with warmup + BN/bias exclusion per the
  large-batch literature.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
import optax

from moco_tpu.utils.config import OptimConfig


def make_lr_schedule(cfg: OptimConfig, steps_per_epoch: int) -> Callable:
    """Per-epoch-granular schedule over the global step, matching
    `adjust_learning_rate` exactly (with optional linear warmup)."""
    total_epochs = cfg.epochs

    def schedule(step):
        epoch = jnp.floor_divide(step, steps_per_epoch).astype(jnp.float32)
        if cfg.cos:
            factor = 0.5 * (1.0 + jnp.cos(math.pi * epoch / total_epochs))
        else:
            milestones = jnp.asarray(cfg.schedule, jnp.float32)
            factor = 0.1 ** jnp.sum(epoch[None] >= milestones)
        lr = cfg.lr * factor
        if cfg.warmup_epochs > 0:
            warm_steps = cfg.warmup_epochs * steps_per_epoch
            warm = cfg.lr * (step + 1) / warm_steps
            lr = jnp.where(step < warm_steps, warm, lr)
        return lr

    return schedule


def _bn_and_bias_mask(params):
    """True for weight-decayable leaves: excludes biases and BN/LN
    scale/bias (standard for LARS; torch SGD in the reference decays
    everything).

    Decayability is decided by the leaf's NAME only — every
    non-decayable leaf in this codebase is literally named 'bias' or
    'scale' — not by ndim: under sharded weight update
    (parallel/zero.py) leaves arrive as 1-D flat shards with the same
    tree paths, and an ndim test would silently disable decay there
    (caught by tests/test_zero.py's adamw parity test)."""

    def decayable(path, x):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return name not in ("bias", "scale")

    return jax.tree_util.tree_map_with_path(decayable, params)


def build_optimizer(cfg: OptimConfig, steps_per_epoch: int) -> optax.GradientTransformation:
    lr = make_lr_schedule(cfg, steps_per_epoch)
    if cfg.optimizer == "sgd":
        chain = []
        if cfg.weight_decay:
            chain.append(optax.add_decayed_weights(cfg.weight_decay))
        chain.append(optax.sgd(lr, momentum=cfg.momentum or None))
        return optax.chain(*chain)
    if cfg.optimizer == "lars":
        return optax.lars(
            lr,
            weight_decay=cfg.weight_decay,
            weight_decay_mask=_bn_and_bias_mask,
            trust_coefficient=cfg.trust_coefficient,
            trust_ratio_mask=_bn_and_bias_mask,
            momentum=cfg.momentum,
        )
    if cfg.optimizer == "adamw":
        return optax.adamw(lr, weight_decay=cfg.weight_decay, mask=_bn_and_bias_mask)
    raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
