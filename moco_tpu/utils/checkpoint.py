"""Checkpoint save/restore (Orbax).

Reference semantics (`main_moco.py:~L195-215, ~L275-280, ~L312-320`,
SURVEY.md §3.5): rank-0 saves `checkpoint_{epoch:04d}.pth.tar` every
epoch with `{'epoch','arch','state_dict','optimizer'}`; `state_dict`
carries both encoders, the queue + pointer, so `--resume` restores the
EMA encoder and the negative dictionary exactly. The linear probe
additionally keeps a `model_best` snapshot (`main_lincls.py:~L250-260`).

TPU-native redesign: the whole `MocoState` pytree (params_q, params_k,
batch_stats, queue, queue_ptr, opt_state, step) plus the root data RNG
and epoch counter is one Orbax StandardSave — multi-host-safe (Orbax
coordinates per-host shard writes; the reference needed the rank-0-only
dance), atomic (tmp dir + rename), with keep-last-N garbage collection
and an optional `best` alias for probe drivers.

Fault tolerance (the robustness layer): a partial or corrupt newest
checkpoint — torn write, truncated blob, unparseable metadata — is
QUARANTINED (moved to `<dir>/quarantine/<step>`) and restore falls back
to the next-older step instead of killing the resume, so a crash during
a write costs at most one checkpoint interval. Save/restore I/O runs
through `moco_tpu.utils.retry` (transient-store errors degrade to a
logged retry), and the driver passes a `validate_extra` hook so a
config-incompatible checkpoint fails fast with a readable diff *before*
a shape-mismatched restore could masquerade as corruption.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Callable, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from moco_tpu.obs.trace import span as obs_span
from moco_tpu.utils import faults, retry


class CheckpointCorruptionError(RuntimeError):
    """Every checkpoint under the directory failed to restore (all
    quarantined) — unlike a merely-missing directory this is never
    silently treated as a fresh start."""


class CheckpointManager:
    """Thin wrapper over `orbax.checkpoint.CheckpointManager` that
    checkpoints an arbitrary state pytree keyed by step/epoch."""

    def __init__(
        self,
        directory: str,
        keep: int = 3,
        save_interval: int = 1,
        async_save: bool = False,
    ):
        """`async_save=True` overlaps checkpoint writes with subsequent
        train steps (Orbax async): `save()` returns once the on-device
        state is snapshotted to host memory; the serialization/write
        happens on a background thread. `restore`/`latest_step`/`close`
        all wait for in-flight saves first, and the driver's preemption
        save must call `wait()` before exiting."""
        self.directory = os.path.abspath(directory)
        self.async_save = async_save
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                # keep<=0 = retain every checkpoint (the reference keeps
                # every per-epoch checkpoint_{epoch:04d}.pth.tar,
                # main_moco.py:~L275-280)
                max_to_keep=keep if keep > 0 else None,
                save_interval_steps=save_interval,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state: Any, extra: Optional[dict] = None, force: bool = False) -> None:
        """Save of the state pytree + JSON-serializable extras — blocking
        by default, overlapped when async_save. `force=True` bypasses the
        save-interval policy (used for the final epoch, which an interval
        of N would otherwise silently skip)."""
        extra = _jsonify(extra or {})

        def _save():
            self._mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state), extra=ocp.args.JsonSave(extra)
                ),
                force=force,
            )
            if not self.async_save:
                self._mgr.wait_until_finished()

        # span duration = what the TRAIN LOOP paid for this save (with
        # async_save that is the host snapshot, not the background write)
        with obs_span("checkpoint_save", step=step, asynchronous=self.async_save):
            retry.retry_call(_save, site="ckpt.save")
        if faults.enabled():  # chaos harness: corrupt this write on request
            faults.on_checkpoint_saved(
                self.directory, step, wait=self._mgr.wait_until_finished
            )

    def wait(self) -> None:
        """Block until any in-flight async save is durable."""
        self._mgr.wait_until_finished()

    def all_steps(self) -> list[int]:
        """Committed step ids, unvalidated (ascending)."""
        self._mgr.wait_until_finished()
        return sorted(self._mgr.all_steps())

    def latest_step(self) -> Optional[int]:
        """Newest step that passes cheap structural validation. A step
        whose directory is visibly partial (missing commit metadata,
        zero-length payload file, unreadable extras) is quarantined and
        the next-older step answers — `restore` then deep-validates by
        actually restoring."""
        self._mgr.wait_until_finished()  # async saves land before counting
        for step in sorted(self._mgr.all_steps(), reverse=True):
            reason = self._structural_defect(step)
            if reason is None:
                return step
            self._quarantine(step, reason)
        return None

    def _structural_defect(self, step: int) -> Optional[str]:
        path = os.path.join(self.directory, str(step))
        if not os.path.isdir(path):
            return "step directory missing"
        if not os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA")):
            return "no commit metadata (partial write)"
        for root, _, names in os.walk(path):
            for name in names:
                fp = os.path.join(root, name)
                try:
                    if os.path.getsize(fp) == 0:
                        return f"zero-length file {os.path.relpath(fp, path)} (torn write)"
                except OSError as e:
                    return f"unreadable file {os.path.relpath(fp, path)}: {e!r}"
        try:
            self._read_extra_step(step)
        except Exception as e:
            return f"extras unreadable: {e!r}"
        return None

    def _read_extra_step(self, step: int) -> dict:
        restored = retry.retry_call(
            self._mgr.restore,
            step,
            args=ocp.args.Composite(extra=ocp.args.JsonRestore()),
            site="ckpt.restore",
        )
        return dict(restored["extra"] or {})

    def _quarantine(self, step: int, reason) -> None:
        """Move a bad step dir to `<dir>/quarantine/<step>` (kept for
        post-mortem, out of Orbax's view) and refresh the manager."""
        src = os.path.join(self.directory, str(step))
        qdir = os.path.join(self.directory, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, str(step))
        suffix = 0
        while os.path.exists(dst):
            suffix += 1
            dst = os.path.join(qdir, f"{step}.{suffix}")
        try:
            os.rename(src, dst)
        except OSError:
            shutil.rmtree(src, ignore_errors=True)  # cross-device fallback
        print(
            f"WARNING: checkpoint step {step} quarantined to {dst}: {reason}",
            flush=True,
        )
        self._mgr.reload()

    def read_extra(self, step: Optional[int] = None) -> dict:
        """Restore only the JSON extras (no state template needed) — lets
        tools discover the training config before building a restore
        template."""
        self._mgr.wait_until_finished()  # async saves land before reading
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        return self._read_extra_step(step)

    def restore(
        self,
        abstract_state: Any,
        step: Optional[int] = None,
        validate_extra: Optional[Callable[[dict], None]] = None,
    ) -> tuple[Any, dict]:
        """Restore into the structure/shardings of `abstract_state`.

        `abstract_state` may be a concrete pytree (freshly created state):
        its shape/dtype/sharding guide the restore, exactly the
        `load_state_dict` pattern of the reference's `--resume`.

        With `step=None`, a corrupt newest checkpoint is quarantined and
        the next-older one restores instead (fallback chain down to the
        oldest); only when EVERY step fails does this raise
        `CheckpointCorruptionError`. An explicit `step` restores exactly
        that step or raises — no silent substitution.

        `validate_extra(extra)` runs before the (expensive) state read;
        it should raise on an incompatible checkpoint (config drift).
        Its exception propagates untouched — incompatibility is a user
        error affecting every step equally, NOT corruption, so nothing
        is quarantined for it.
        """
        self._mgr.wait_until_finished()  # an in-flight async save must land first
        abstract = jax.tree.map(_abstract_leaf, abstract_state)
        explicit = step is not None
        candidates = [step] if explicit else sorted(self._mgr.all_steps(), reverse=True)
        if not candidates:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        failures: list[tuple[int, str]] = []
        for s in candidates:
            try:
                extra = self._read_extra_step(s)
            except Exception as e:
                if explicit:
                    raise
                failures.append((s, repr(e)))
                self._quarantine(s, e)
                continue
            if validate_extra is not None:
                validate_extra(extra)  # incompatibility propagates, no quarantine
            try:
                with obs_span("checkpoint_restore", step=s):
                    restored = retry.retry_call(
                        self._mgr.restore,
                        s,
                        args=ocp.args.Composite(state=ocp.args.StandardRestore(abstract)),
                        site="ckpt.restore",
                    )
            except Exception as e:
                if explicit:
                    raise
                failures.append((s, repr(e)))
                self._quarantine(s, e)
                continue
            if failures:
                print(
                    f"WARNING: restored fallback step {s} after quarantining "
                    f"{[f[0] for f in failures]}",
                    flush=True,
                )
            return restored["state"], extra
        raise CheckpointCorruptionError(
            f"all {len(failures)} checkpoint(s) under {self.directory} failed to "
            f"restore and were quarantined: {failures} — inspect "
            f"{os.path.join(self.directory, 'quarantine')}"
        )

    def close(self) -> None:
        self._mgr.close()


def _abstract_leaf(x):
    """`ocp.utils.to_shape_dtype_struct` that tolerates templates already
    containing `jax.ShapeDtypeStruct` leaves with `sharding=None` (orbax
    0.7's converter assumes a sharding object and crashes on None)."""
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    return ocp.utils.to_shape_dtype_struct(x)


def _jsonify(extra: dict) -> dict:
    out = {}
    for k, v in extra.items():
        if isinstance(v, (np.ndarray, jax.Array)):
            out[k] = np.asarray(v).tolist()
        elif isinstance(v, (np.integer, np.floating)):
            out[k] = v.item()
        else:
            out[k] = v
    return out


def best_exists(directory: str) -> bool:
    """Whether a `model_best` alias exists under `directory` — the one
    place that knows the alias layout (keep save/restore/probe in sync)."""
    return os.path.isdir(os.path.join(os.path.abspath(directory), "best"))


def save_best(directory: str, state: Any, metric: float) -> None:
    """`model_best` alias (`main_lincls.py:~L250-260`): overwrite the
    single best-by-metric snapshot."""
    path = os.path.join(os.path.abspath(directory), "best")
    with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as ckptr:
        ckptr.save(
            path,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                extra=ocp.args.JsonSave({"metric": float(metric)}),
            ),
            force=True,
        )


def restore_best(directory: str, abstract_state: Any) -> tuple[Any, float]:
    path = os.path.join(os.path.abspath(directory), "best")
    abstract = jax.tree.map(_abstract_leaf, abstract_state)
    with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as ckptr:
        out = ckptr.restore(
            path,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract), extra=ocp.args.JsonRestore()
            ),
        )
    return out["state"], float(out["extra"]["metric"])
