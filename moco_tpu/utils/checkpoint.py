"""Checkpoint save/restore (Orbax).

Reference semantics (`main_moco.py:~L195-215, ~L275-280, ~L312-320`,
SURVEY.md §3.5): rank-0 saves `checkpoint_{epoch:04d}.pth.tar` every
epoch with `{'epoch','arch','state_dict','optimizer'}`; `state_dict`
carries both encoders, the queue + pointer, so `--resume` restores the
EMA encoder and the negative dictionary exactly. The linear probe
additionally keeps a `model_best` snapshot (`main_lincls.py:~L250-260`).

TPU-native redesign: the whole `MocoState` pytree (params_q, params_k,
batch_stats, queue, queue_ptr, opt_state, step) plus the root data RNG
and epoch counter is one Orbax StandardSave — multi-host-safe (Orbax
coordinates per-host shard writes; the reference needed the rank-0-only
dance), atomic (tmp dir + rename), with keep-last-N garbage collection
and an optional `best` alias for probe drivers.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin wrapper over `orbax.checkpoint.CheckpointManager` that
    checkpoints an arbitrary state pytree keyed by step/epoch."""

    def __init__(
        self,
        directory: str,
        keep: int = 3,
        save_interval: int = 1,
        async_save: bool = False,
    ):
        """`async_save=True` overlaps checkpoint writes with subsequent
        train steps (Orbax async): `save()` returns once the on-device
        state is snapshotted to host memory; the serialization/write
        happens on a background thread. `restore`/`latest_step`/`close`
        all wait for in-flight saves first, and the driver's preemption
        save must call `wait()` before exiting."""
        self.directory = os.path.abspath(directory)
        self.async_save = async_save
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                # keep<=0 = retain every checkpoint (the reference keeps
                # every per-epoch checkpoint_{epoch:04d}.pth.tar,
                # main_moco.py:~L275-280)
                max_to_keep=keep if keep > 0 else None,
                save_interval_steps=save_interval,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state: Any, extra: Optional[dict] = None, force: bool = False) -> None:
        """Save of the state pytree + JSON-serializable extras — blocking
        by default, overlapped when async_save. `force=True` bypasses the
        save-interval policy (used for the final epoch, which an interval
        of N would otherwise silently skip)."""
        extra = _jsonify(extra or {})
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state), extra=ocp.args.JsonSave(extra)
            ),
            force=force,
        )
        if not self.async_save:
            self._mgr.wait_until_finished()

    def wait(self) -> None:
        """Block until any in-flight async save is durable."""
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        self._mgr.wait_until_finished()  # async saves land before counting
        return self._mgr.latest_step()

    def read_extra(self, step: Optional[int] = None) -> dict:
        """Restore only the JSON extras (no state template needed) — lets
        tools discover the training config before building a restore
        template."""
        self._mgr.wait_until_finished()  # async saves land before reading
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        restored = self._mgr.restore(step, args=ocp.args.Composite(extra=ocp.args.JsonRestore()))
        return dict(restored["extra"] or {})

    def restore(self, abstract_state: Any, step: Optional[int] = None) -> tuple[Any, dict]:
        """Restore into the structure/shardings of `abstract_state`.

        `abstract_state` may be a concrete pytree (freshly created state):
        its shape/dtype/sharding guide the restore, exactly the
        `load_state_dict` pattern of the reference's `--resume`.
        """
        self._mgr.wait_until_finished()  # an in-flight async save must land first
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, abstract_state)
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract), extra=ocp.args.JsonRestore()
            ),
        )
        return restored["state"], dict(restored["extra"] or {})

    def close(self) -> None:
        self._mgr.close()


def _jsonify(extra: dict) -> dict:
    out = {}
    for k, v in extra.items():
        if isinstance(v, (np.ndarray, jax.Array)):
            out[k] = np.asarray(v).tolist()
        elif isinstance(v, (np.integer, np.floating)):
            out[k] = v.item()
        else:
            out[k] = v
    return out


def best_exists(directory: str) -> bool:
    """Whether a `model_best` alias exists under `directory` — the one
    place that knows the alias layout (keep save/restore/probe in sync)."""
    return os.path.isdir(os.path.join(os.path.abspath(directory), "best"))


def save_best(directory: str, state: Any, metric: float) -> None:
    """`model_best` alias (`main_lincls.py:~L250-260`): overwrite the
    single best-by-metric snapshot."""
    path = os.path.join(os.path.abspath(directory), "best")
    with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as ckptr:
        ckptr.save(
            path,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                extra=ocp.args.JsonSave({"metric": float(metric)}),
            ),
            force=True,
        )


def restore_best(directory: str, abstract_state: Any) -> tuple[Any, float]:
    path = os.path.join(os.path.abspath(directory), "best")
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, abstract_state)
    with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as ckptr:
        out = ckptr.restore(
            path,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract), extra=ocp.args.JsonRestore()
            ),
        )
    return out["state"], float(out["extra"]["metric"])
