"""Single source of truth for the repo's stringly-typed contracts.

The fleet (router <-> replicas <-> ingest <-> chaos harnesses) is wired
together by literals: magic exit codes, the port-offset rule, HTTP
routes and their required headers, and fault-grammar site names. Each
of those used to live wherever it was first needed (the stall code in
`utils/watchdog.py`, the kill code in `utils/faults.py`, the rescale
code in `parallel/elastic.py`, the serve-port stride in
`obs/sinks.py`), which is exactly how contracts drift: a test hard-
codes 42, a handler grows a route the router never learns about, a
`slow@site=` spec outlives the hook it targeted.

This module hosts the constants; the original homes re-export them so
existing imports (`from moco_tpu.utils.faults import KILL_EXIT_CODE`)
keep working. mocolint v4 (JX015-JX018, `analysis/contracts.py`) lints
the tree against these registries, and the `--contract-coverage`
runtime arm records which entries actually fire during the smoke legs.

Adding a metric family, HTTP route, or fault site? Ship the registry
entry in the same change (see CONTRIBUTING.md) or JX016/JX017 will flag
the orphan.

Stdlib-only, import-light: this is imported by `utils/faults.py` and
the analyzer alike.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# exit codes
#
# Names kept verbatim from their original homes; the `EXIT_CODES` map is
# what the chaos harnesses and JX018 key on.

STALL_EXIT_CODE = 42  # utils/watchdog.py: watchdog fired, no heartbeat
RESCALE_EXIT_CODE = 75  # parallel/elastic.py: durable save done, relaunch me
KILL_EXIT_CODE = 113  # utils/faults.py: kill@replica / kill@host sudden death

EXIT_CODES = {
    "stall": STALL_EXIT_CODE,
    "rescale": RESCALE_EXIT_CODE,
    "kill": KILL_EXIT_CODE,
}

# ---------------------------------------------------------------------------
# port-offset rule (obs/sinks.py holds the arithmetic; this is the knob)
#
# Prometheus owns `metrics_port + process_index`; the serve endpoint
# claims `serve_port + process_index` and shifts up by the stride when
# the two bases collide. derive_metrics_port / resolve_serve_port in
# obs/sinks.py are the ONLY sanctioned implementations (JX018 flags
# hand-computed offsets anywhere else).

SERVE_PORT_STRIDE = 16

# ---------------------------------------------------------------------------
# HTTP routes
#
# route -> (methods, required request headers, idempotent?, which server
# handles it). "replica" = serve/server.py ServeServer, "router" =
# serve/router.py FleetRouter, "both" = the router proxies or mirrors
# the replica surface. `idempotent` is the retry/hedge contract: the
# router may retry and hedge exactly these routes and nothing else —
# in particular it must NEVER retry /ingest (appends queue rows; the
# fan-out writer in scripts/serve_ingest.py owns its own idempotence
# via row-count reconciliation).
#
# `opt_headers` are the PROPAGATED headers (obs/ctxprop.py): a plain
# client may omit them, but every handler of the route must read them —
# JX016 checks the handler side only, so adding one here never flags
# existing clients.

# distributed-tracing context headers (obs/ctxprop.py mints/parses them)
TRACE_HEADERS = ("X-Trace-Id", "X-Parent-Span")


class Route:
    __slots__ = ("path", "methods", "headers", "opt_headers", "idempotent", "server")

    def __init__(
        self, path, methods, headers=(), opt_headers=(), idempotent=False,
        server="both",
    ):
        self.path = path
        self.methods = tuple(methods)
        self.headers = tuple(headers)
        self.opt_headers = tuple(opt_headers)
        self.idempotent = idempotent
        self.server = server


ROUTES = {
    r.path: r
    for r in (
        Route("/healthz", ("GET",), idempotent=True, server="both"),
        # the Prometheus scrape endpoint (obs/sinks.py PrometheusSink)
        Route("/metrics", ("GET",), idempotent=True, server="metrics"),
        Route("/stats", ("GET",), idempotent=True, server="both"),
        Route("/debug/flight", ("GET",), idempotent=True, server="both"),
        Route("/admin/replicas", ("GET",), idempotent=True, server="router"),
        Route(
            "/embed",
            ("POST",),
            headers=("X-Image-Shape",),
            opt_headers=TRACE_HEADERS,
            idempotent=True,
            server="both",
        ),
        Route(
            "/neighbors",
            ("POST",),
            headers=("X-Image-Shape",),
            opt_headers=TRACE_HEADERS,
            idempotent=True,
            server="both",
        ),
        Route(
            "/ingest",
            ("POST",),
            headers=("X-Rows-Shape",),
            # the source checkpoint step of the posted rows: the replica
            # reads it into its serve/ingest_ckpt_step gauge so encoder/
            # index skew is visible (scripts/serve_ingest.py sends it)
            opt_headers=("X-Ckpt-Step",),
            idempotent=False,
            server="replica",
        ),
        Route("/admin/drain", ("POST",), idempotent=False, server="both"),
        Route("/admin/undrain", ("POST",), idempotent=False, server="router"),
        # served-model identity (step + params digest + last ingest step)
        Route("/admin/model", ("GET",), idempotent=True, server="replica"),
        # one staged-rollout step: retarget the supervisor's checkpoint
        # dir and drain/restart one replica onto it. NOT idempotent (a
        # retry would double-drain a replica mid-swap) — the promotion
        # controller polls /admin/replicas instead of retrying.
        Route("/admin/promote", ("POST",), idempotent=False, server="router"),
    )
}

IDEMPOTENT_ROUTES = tuple(sorted(p for p, r in ROUTES.items() if r.idempotent))
REQUIRED_HEADERS = {p: r.headers for p, r in ROUTES.items() if r.headers}
OPTIONAL_HEADERS = {p: r.opt_headers for p, r in ROUTES.items() if r.opt_headers}


def route_methods(path: str) -> tuple:
    """Declared methods for a route ('' query strings already stripped),
    or () for an undeclared route."""
    r = ROUTES.get(path)
    return r.methods if r else ()


# ---------------------------------------------------------------------------
# fault-grammar sites (utils/faults.py holds the grammar; these are the
# site vocabularies per kind). kill/stall/nan/preempt/ckpt_truncate are
# site-less; diverge sites are dynamic comms tags (per-bucket schedule
# entries like `zero.gather_q.b0`) and are validated at runtime by the
# sanitizer, not here.

SERVE_STAGE_SITES = (
    "serve.ingress",
    "serve.batch_assemble",
    "serve.engine_execute",
    "serve.index_query",
    "serve.scatter",
    "serve.respond",
)

# tsan.make_lock names — the deadlock@site=<lock> fault inverts the
# acquisition order around the named lock.
LOCK_SITES = (
    "data.transfer_stats",
    "fleet.supervisor",
    "obs.comms",
    "obs.flight",
    "obs.prometheus",
    "obs.slo",
    "obs.trace",
    "promote.ledger",
    "router.fleet",
    "router.metrics",
    "serve.index",
    "serve.metrics",
    "utils.retry",
)

FAULT_SITES = {
    "slow": SERVE_STAGE_SITES,
    # "ingest": stalls the replica's /ingest handler before the body
    # read (serve/server.py) — the freshness-SLO chaos lever: rows age
    # past the declared max while the tail pipeline is stuck.
    "delay": ("data.read", "input.h2d", "zero.gather", "ingest"),
    "io": ("data.read",),
    "deadlock": LOCK_SITES,
}

# ---------------------------------------------------------------------------
# runtime contract-coverage gates (analysis/contracts.py recorder)
#
# The serve/* schema validators the serving stack itself must exercise
# in a full smoke (everything explicit under serve/ except the
# bench-only trace-overhead gauge, which only bench.py emits).

SERVE_GATED_VALIDATORS = (
    "serve/ingested_rows",
    "serve/int8",
    "serve/ivf_occupancy",
    "serve/ivf_spill",
    "serve/latency_hist",
    "serve/nprobe",
    "serve/p99_exemplar",
    "serve/p99_exemplar_ms",
    "serve/quant_tier",
    "serve/recall_estimate",
    "serve/slo_objective",
)

# Model-quality / freshness validators a replica with a declared
# freshness objective must exercise (served-model identity, row-age
# gauges, and the freshness burn family's prefix).

QUALITY_GATED_VALIDATORS = (
    "serve/fresh_burn_rate_",
    "serve/fresh_max_age_s",
    "serve/ingest_ckpt_step",
    "serve/model_digest",
    "serve/model_step",
    "serve/row_age_max_s",
    "serve/row_age_mean_s",
)

# The distributed-tracing validators the ROUTER's metric stream must
# exercise in a full fleet smoke (critical-path attribution + the
# hedge-loser cost counter — both only emitted by serve/router.py).

FLEET_GATED_VALIDATORS = (
    "fleet_serve/critpath_",
    "fleet_serve/hedge_wasted_ms",
)

# The promotion pipeline's ledger validators the fleet smoke's
# promotion leg must exercise (serve/promote.py writes them through
# schema.validate_line, so coverage proves real verdict lines landed).

PROMOTION_GATED_VALIDATORS = (
    "fleet_serve/model_skew",
    "promotion/",
    "promotion/digest",
    "promotion/failed_gate",
    "promotion/stage",
    "promotion/verdict",
)

# The scaling-law battery's validators scripts/scaling_smoke.py must
# exercise (per-leg verdict lines + the numeric kappa/drift/peak family
# all flow through schema.validate_line, so coverage proves the battery
# emitted real evidence, not just an exit code).

SCALING_GATED_VALIDATORS = (
    "scaling/",
    "scaling/leg",
    "scaling/verdict",
)
