from moco_tpu.utils.config import (
    DataConfig,
    MocoConfig,
    OptimConfig,
    ParallelConfig,
    PRESETS,
    ResumeCompatError,
    TrainConfig,
    resume_compat_diff,
)
from moco_tpu.utils.schedules import build_optimizer, make_lr_schedule
from moco_tpu.utils.checkpoint import (
    CheckpointCorruptionError,
    CheckpointManager,
    restore_best,
    save_best,
)
from moco_tpu.utils.metrics import (
    AverageMeter,
    MetricWriter,
    ProfilerWindow,
    ProgressMeter,
    is_primary,
    parse_profile_steps,
    print0,
    profiler_trace,
)
from moco_tpu.utils.watchdog import StepWatchdog

__all__ = [
    "CheckpointCorruptionError",
    "ResumeCompatError",
    "StepWatchdog",
    "resume_compat_diff",
    "AverageMeter",
    "CheckpointManager",
    "MetricWriter",
    "ProfilerWindow",
    "ProgressMeter",
    "is_primary",
    "parse_profile_steps",
    "print0",
    "profiler_trace",
    "restore_best",
    "save_best",
    "DataConfig",
    "MocoConfig",
    "OptimConfig",
    "ParallelConfig",
    "PRESETS",
    "TrainConfig",
    "build_optimizer",
    "make_lr_schedule",
]
