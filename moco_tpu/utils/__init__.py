from moco_tpu.utils.config import (
    DataConfig,
    MocoConfig,
    OptimConfig,
    ParallelConfig,
    PRESETS,
    TrainConfig,
)
from moco_tpu.utils.schedules import build_optimizer, make_lr_schedule

__all__ = [
    "DataConfig",
    "MocoConfig",
    "OptimConfig",
    "ParallelConfig",
    "PRESETS",
    "TrainConfig",
    "build_optimizer",
    "make_lr_schedule",
]
