"""Bounded retrying for host-side I/O (exponential backoff + jitter).

The reference's failure story is "restart by hand with `--resume`"
(SURVEY.md §5.3); on preemptible TPU fleets reading datasets and writing
checkpoints over GCS/NFS, transient `OSError`s are routine and must
degrade to a *logged retry*, not an aborted epoch. Every wrapped call
site names itself (`site=`), and the per-site retry counters are
surfaced into `metrics.jsonl` by the train driver on log steps — a flaky
filesystem is observable, not silent.

Defaults are env-tunable (no config plumbing needed for ops knobs):
    MOCO_IO_RETRIES      total attempts per call (default 4)
    MOCO_IO_RETRY_BASE   first backoff in seconds (default 0.2)
    MOCO_IO_RETRY_MAX    backoff ceiling in seconds (default 5.0)

Only `OSError` (and subclasses — `IOError` is an alias) retries by
default: logic errors like a corrupt-cache `ValueError` must propagate
immediately, not burn the backoff budget masking a real bug.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import Counter
from typing import Callable, Optional, Tuple, Type

from moco_tpu.analysis import tsan

_lock = tsan.make_lock("utils.retry")  # traced under --sanitize-threads
_retries: Counter = Counter()  # site -> number of retried failures
_last_error: dict = {}  # site -> repr of the most recent retried error


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def default_attempts() -> int:
    return max(1, int(_env_float("MOCO_IO_RETRIES", 4)))


def retry_call(
    fn: Callable,
    *args,
    site: str,
    attempts: Optional[int] = None,
    base_delay: Optional[float] = None,
    max_delay: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    sleep: Callable[[float], None] = time.sleep,
    **kwargs,
):
    """Call `fn(*args, **kwargs)`, retrying transient failures.

    Backoff before attempt k (1-based retries) is
    `min(max_delay, base_delay * 2**(k-1))` scaled by a uniform [0.5,
    1.5) jitter, so a fleet of workers hitting the same flaky store does
    not retry in lockstep. The final attempt's exception propagates
    unchanged. `sleep` is injectable for tests.
    """
    attempts = attempts if attempts is not None else default_attempts()
    base_delay = base_delay if base_delay is not None else _env_float("MOCO_IO_RETRY_BASE", 0.2)
    max_delay = max_delay if max_delay is not None else _env_float("MOCO_IO_RETRY_MAX", 5.0)
    for attempt in range(attempts):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if attempt == attempts - 1:
                raise
            with _lock:
                _retries[site] += 1
                _last_error[site] = repr(e)
            delay = min(max_delay, base_delay * (2**attempt)) * (0.5 + random.random())
            print(
                f"retry[{site}]: attempt {attempt + 1}/{attempts} failed "
                f"({e!r}); retrying in {delay:.2f}s",
                flush=True,
            )
            sleep(delay)


def snapshot(reset: bool = False) -> dict:
    """Per-site retry counts since process start (or the last reset).
    Empty dict when nothing retried — callers can `if snapshot():`."""
    with _lock:
        out = {k: int(v) for k, v in _retries.items() if v}
        if reset:
            _retries.clear()
            _last_error.clear()
    return out


def last_errors() -> dict:
    with _lock:
        return dict(_last_error)
